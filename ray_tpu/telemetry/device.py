"""Device runtime observability: compilation ledger + HBM memory census.

Everything above the device runtime is instrumented — PR 4 times steps,
PR 12 times RPCs, PR 17 traces the task hot path — but nothing watched
XLA itself, even though the serve engine stakes its design on "the step
program never recompiles" (``serve/_engine.py``) and the KV page arena
is the HBM budget that decides admission.  Pod-scale TPU runs live or
die on compile time and per-replica memory headroom (arXiv:1909.09756),
and HBM occupancy is *the* capacity signal for TPU serving
(PAPERS.md 2605.25645).  This module makes both visible:

``CompilationLedger``
    An instrumented jit/pjit entry point (``device.jit`` /
    ``device.instrument``) plus ``jax.monitoring`` duration hooks.
    Every compile is detected per call via the executable-cache size
    delta (``_cache_size()`` grows exactly when a new input signature
    compiles, and is stable on a cache hit), stamped with trace / lower
    / backend-compile wall times from the monitooring events, a
    fingerprint of the triggering signature, optional executable
    cost/memory analysis, and — on a *re*compile — a **cause diff**
    against the previous compile of the same program: which argument
    changed shape, dtype, weak-type, static value or tree structure.
    A sliding window per program detects **recompile storms** (the
    compiles-per-iteration bug class the new ``jit-per-call`` lint
    flags statically) and publishes an advisory on the "train" pubsub
    topic exactly once per episode.

``DeviceMemoryCensus``
    Samples live device buffers (``jax.live_arrays``) by dtype/shape,
    plus registered owner reports — the serve engine registers its
    ``PageAllocator`` arena occupancy (free / used / shared / COW
    pages) and emergency-vault footprint.  Crossing a configured
    watermark publishes a ``memory_watermark`` advisory with the same
    episode semantics.

Snapshots flush to control-plane KV namespace ``_device`` (keyed
``device:<worker_id>``) over the same rate-limited, never-raises path
as PR-4 telemetry, and surface through ``ray-tpu device-stats``,
``GET /api/device/stats``, Prometheus series (``ray_tpu_compile_seconds``,
``ray_tpu_recompiles_total``, ``ray_tpu_hbm_live_bytes``,
``ray_tpu_kv_pages{state=…}``) and Chrome-trace compile slices.
"""

from __future__ import annotations

import functools
import inspect
import os
import pickle
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..util import metrics as metrics_mod

#: control-plane KV namespace for device snapshots.  Deliberately NOT
#: ``_metrics``: collect_cluster_metrics json-merges every key there.
DEVICE_NS = "_device"
DEVICE_KEY_PREFIX = "device:"

#: jax.monitoring duration events -> ledger duration keys (jax 0.4.x)
_DURATION_EVENTS = {
    "/jax/core/compile/jaxpr_trace_duration": "trace_s",
    "/jax/core/compile/jaxpr_to_mlir_module_duration": "lower_s",
    "/jax/core/compile/backend_compile_duration": "backend_s",
}

_COMPILE_BOUNDARIES = [0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1, 2.5,
                       5, 10, 30, 60, 120, 300]

_tls = threading.local()

# Strong refs so the weakref metric registry keeps these alive.
_metric_lock = threading.Lock()
_metric_cache: Dict[str, Any] = {}


def _get_metric(key: str, factory: Callable[[], Any]) -> Any:
    with _metric_lock:
        m = _metric_cache.get(key)
        if m is None:
            m = _metric_cache[key] = factory()
        return m


def _compile_histogram():
    return _get_metric("compile_hist", lambda: metrics_mod.Histogram(
        "ray_tpu_compile_seconds",
        description="XLA trace+lower+compile wall time per program",
        boundaries=_COMPILE_BOUNDARIES,
        tag_keys=("program",)))


def _recompile_counter():
    return _get_metric("recompile_ctr", lambda: metrics_mod.Counter(
        "ray_tpu_recompiles_total",
        description="Recompiles (2nd+ compile of the same program)",
        tag_keys=("program",)))


def _hbm_gauge():
    return _get_metric("hbm_gauge", lambda: metrics_mod.Gauge(
        "ray_tpu_hbm_live_bytes",
        description="Live device-buffer bytes (jax.live_arrays sample)"))


def _kv_pages_gauge():
    return _get_metric("kv_pages", lambda: metrics_mod.Gauge(
        "ray_tpu_kv_pages",
        description="KV page arena occupancy by state "
                    "(free/used live; shared/cow cumulative)",
        tag_keys=("state",)))


def _default_publish(payload: Dict[str, Any]) -> None:
    """Advisories ride the existing "train" pubsub topic (the same one
    StepAggregator straggler advisories use) so RemediationEngine and
    dashboards need no new subscription."""
    from ray_tpu._private import core as core_mod

    core = core_mod._current_core
    if core is None or getattr(core, "_shutdown", False):
        return
    core.control.call("publish", {"topic": "train", "payload": payload},
                      timeout=5.0)


# ---------------------------------------------------------------------------
# Signature fingerprints + cause diffs
# ---------------------------------------------------------------------------


def _is_arraylike(x: Any) -> bool:
    return hasattr(x, "shape") and hasattr(x, "dtype") \
        and not inspect.isclass(x)


def _leaf_desc(x: Any) -> Dict[str, Any]:
    if _is_arraylike(x):
        return {"kind": "array",
                "shape": tuple(int(s) for s in x.shape),
                "dtype": str(x.dtype),
                "weak_type": bool(getattr(x, "weak_type", False))}
    return {"kind": "static", "value": repr(x)[:80]}


def _describe(x: Any) -> Dict[str, Any]:
    """Bounded structural descriptor of one call argument."""
    if _is_arraylike(x) or x is None or isinstance(
            x, (int, float, bool, complex, str, bytes)):
        return _leaf_desc(x)
    try:
        import jax

        flat, treedef = jax.tree_util.tree_flatten_with_path(x)
        leaves = [{"path": jax.tree_util.keystr(path), **_leaf_desc(leaf)}
                  for path, leaf in flat[:32]]
        return {"kind": "pytree", "num_leaves": len(flat),
                "treedef": str(treedef)[:120], "leaves": leaves}
    except Exception:
        return {"kind": "static", "value": repr(x)[:80]}


def _fmt_desc(d: Dict[str, Any]) -> str:
    if d.get("kind") == "array":
        shape = ",".join(str(s) for s in d.get("shape", ()))
        weak = "~" if d.get("weak_type") else ""
        return f"{weak}{d.get('dtype')}[{shape}]"
    if d.get("kind") == "pytree":
        return f"pytree({d.get('num_leaves')} leaves)"
    return str(d.get("value"))


def _fingerprint(sig: Optional[inspect.Signature], args: Tuple,
                 kwargs: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Per-arg descriptors, named from the wrapped fn's signature when
    it binds (fallback: positional ``argN``)."""
    named: List[Tuple[str, Any]] = []
    if sig is not None:
        try:
            bound = sig.bind_partial(*args, **kwargs)
            named = list(bound.arguments.items())
        except TypeError:
            named = []
    if not named:
        named = [(f"arg{i}", a) for i, a in enumerate(args)]
        named += sorted(kwargs.items())
    return [{"arg": name, **_describe(val)} for name, val in named]


def _diff_entry(name: str, old: Dict[str, Any],
                new: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Field-level diff of one argument's descriptor (old vs new)."""
    if old.get("kind") != new.get("kind"):
        return [{"arg": name, "kind": "type",
                 "old": _fmt_desc(old), "new": _fmt_desc(new)}]
    kind = new.get("kind")
    out: List[Dict[str, Any]] = []
    if kind == "array":
        for field, label in (("shape", "shape"), ("dtype", "dtype"),
                             ("weak_type", "weak_type")):
            if old.get(field) != new.get(field):
                out.append({"arg": name, "kind": label,
                            "old": _fmt_desc(old), "new": _fmt_desc(new)})
        return out
    if kind == "pytree":
        if (old.get("num_leaves") != new.get("num_leaves")
                or old.get("treedef") != new.get("treedef")):
            return [{"arg": name, "kind": "structure",
                     "old": _fmt_desc(old), "new": _fmt_desc(new)}]
        for o_leaf, n_leaf in zip(old.get("leaves", []),
                                  new.get("leaves", [])):
            if o_leaf != n_leaf:
                leaf_name = f"{name}{n_leaf.get('path', '')}"
                out.extend(_diff_entry(leaf_name,
                                       {k: v for k, v in o_leaf.items()
                                        if k != "path"},
                                       {k: v for k, v in n_leaf.items()
                                        if k != "path"}))
        return out
    if old.get("value") != new.get("value"):
        return [{"arg": name, "kind": "static",
                 "old": str(old.get("value")), "new": str(new.get("value"))}]
    return out


def diff_signatures(old: List[Dict[str, Any]],
                    new: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """All per-arg changes between two compile fingerprints."""
    changes: List[Dict[str, Any]] = []
    old_map = {e["arg"]: e for e in old}
    new_names = set()
    for e in new:
        name = e["arg"]
        new_names.add(name)
        o = old_map.get(name)
        if o is None:
            changes.append({"arg": name, "kind": "added",
                            "old": None, "new": _fmt_desc(e)})
        else:
            changes.extend(_diff_entry(name, o, e))
    for e in old:
        if e["arg"] not in new_names:
            changes.append({"arg": e["arg"], "kind": "removed",
                            "old": _fmt_desc(e), "new": None})
    return changes


# ---------------------------------------------------------------------------
# jax.monitoring hookup
# ---------------------------------------------------------------------------

_monitoring_lock = threading.Lock()
_monitoring_installed = False


def _frame_stack() -> List[Dict[str, Any]]:
    st = getattr(_tls, "frames", None)
    if st is None:
        st = _tls.frames = []
    return st


def _install_monitoring() -> None:
    """Attach duration listeners once per process.  The listener fires
    *during* the instrumented call while the compile happens, so the
    durations attach to the innermost open call frame."""
    global _monitoring_installed
    with _monitoring_lock:
        if _monitoring_installed:
            return
        try:
            from jax import monitoring

            def on_duration(event: str, duration: float, **kw) -> None:
                key = _DURATION_EVENTS.get(event)
                if key is None:
                    return
                st = _frame_stack()
                if st:
                    d = st[-1]["durations"]
                    d[key] = d.get(key, 0.0) + float(duration)

            monitoring.register_event_duration_secs_listener(on_duration)
            _monitoring_installed = True
        except Exception:
            # jax absent or too old: cache-size deltas still detect
            # compiles, records just carry no phase durations
            _monitoring_installed = True


# ---------------------------------------------------------------------------
# The ledger
# ---------------------------------------------------------------------------


class _ProgramState:
    """Per-program compile history (owned by the ledger, all access
    under the ledger's lock)."""

    __slots__ = ("name", "compiles", "recompiles", "last_signature",
                 "last_cause", "last_compile_wall", "last_compile_mono",
                 "compile_times", "storm_open", "storm_episodes",
                 "durations_total")

    def __init__(self, name: str):
        self.name = name
        self.compiles = 0
        self.recompiles = 0
        self.last_signature: Optional[List[Dict[str, Any]]] = None
        self.last_cause: Optional[Dict[str, Any]] = None
        self.last_compile_wall = 0.0
        self.last_compile_mono = 0.0
        self.compile_times: deque = deque(maxlen=64)  # mono stamps
        self.storm_open = False
        self.storm_episodes = 0
        self.durations_total: Dict[str, float] = {}


class CompilationLedger:
    """Per-process XLA compilation ledger.

    Thread-safe; the per-call fast path (cache hit) costs one
    ``_cache_size()`` C call and no lock.  Records, program state and
    advisories are guarded by ``_lock``.
    """

    def __init__(self, max_records: int = 256,
                 storm_threshold: Optional[int] = None,
                 storm_window_s: Optional[float] = None,
                 analysis: Optional[bool] = None,
                 publish: Optional[Callable[[Dict[str, Any]], None]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 wall: Callable[[], float] = time.time):
        if storm_threshold is None:
            storm_threshold = int(os.environ.get(
                "RAY_TPU_DEVICE_STORM_THRESHOLD", "4"))
        if storm_window_s is None:
            storm_window_s = float(os.environ.get(
                "RAY_TPU_DEVICE_STORM_WINDOW_S", "30"))
        if analysis is None:
            analysis = os.environ.get(
                "RAY_TPU_DEVICE_ANALYSIS", "0") not in ("0", "", "false")
        self.storm_threshold = max(2, int(storm_threshold))
        self.storm_window_s = float(storm_window_s)
        self.analysis = bool(analysis)
        self._publish = publish or _default_publish
        self._clock = clock
        self._wall = wall
        self._lock = threading.Lock()
        self._programs: Dict[str, _ProgramState] = {}  # guarded-by: _lock
        self._records: deque = deque(maxlen=max(1, int(max_records)))  # guarded-by: _lock
        self._advisories: List[Dict[str, Any]] = []  # guarded-by: _lock
        self._total_compiles = 0   # guarded-by: _lock
        self._total_recompiles = 0  # guarded-by: _lock
        self._drain_idx = 0  # guarded-by: _lock
        self._last_flush = 0.0  # rate limiter state (monotonic)

    # -- instrumentation entry points ---------------------------------

    def instrument(self, jitted: Any, name: Optional[str] = None,
                   analysis: Optional[bool] = None) -> "InstrumentedProgram":
        """Wrap an already-jitted callable so its compiles are recorded
        under ``name``.  Idempotent on already-instrumented programs."""
        if isinstance(jitted, InstrumentedProgram):
            return jitted
        return InstrumentedProgram(jitted, name=name, ledger=self,
                                   analysis=analysis)

    def jit(self, fun: Optional[Callable] = None, *,
            name: Optional[str] = None, analysis: Optional[bool] = None,
            **jit_kwargs) -> Any:
        """Instrumented drop-in for ``jax.jit`` (usable as a decorator
        or a wrap call): the returned program records every compile in
        this ledger."""
        if fun is None:
            return functools.partial(self.jit, name=name,
                                     analysis=analysis, **jit_kwargs)
        import jax

        return self.instrument(jax.jit(fun, **jit_kwargs), name=name,
                               analysis=analysis)

    # -- record path (called from InstrumentedProgram) ----------------

    def _record_compile(self, prog: "InstrumentedProgram", args: Tuple,
                        kwargs: Dict[str, Any], call_s: float,
                        durations: Dict[str, float]) -> None:
        """One detected compile.  Never raises (observability must not
        take down the workload)."""
        try:
            self._record_compile_inner(prog, args, kwargs, call_s,
                                       durations)
        except Exception:
            pass

    def _record_compile_inner(self, prog: "InstrumentedProgram",
                              args: Tuple, kwargs: Dict[str, Any],
                              call_s: float,
                              durations: Dict[str, float]) -> None:
        signature = _fingerprint(prog._sig, args, kwargs)
        compile_s = sum(durations.values()) if durations else call_s
        analysis = None
        if prog._analysis if prog._analysis is not None else self.analysis:
            analysis = _analyze_executable(prog._fn, args, kwargs)
        now_wall, now_mono = self._wall(), self._clock()

        advisory = None
        with self._lock:
            st = self._programs.get(prog.name)
            if st is None:
                st = self._programs[prog.name] = _ProgramState(prog.name)
            st.compiles += 1
            self._total_compiles += 1
            cause: Optional[Dict[str, Any]] = None
            is_recompile = st.compiles > 1
            if is_recompile:
                st.recompiles += 1
                self._total_recompiles += 1
                changes = diff_signatures(st.last_signature or [],
                                          signature)
                cause = {"changes": changes}
                if changes:
                    cause.update({"arg": changes[0]["arg"],
                                  "kind": changes[0]["kind"],
                                  "old": changes[0]["old"],
                                  "new": changes[0]["new"]})
                else:
                    cause["note"] = ("signature-equivalent recompile "
                                     "(sharding/backend or untracked "
                                     "static)")
            st.last_signature = signature
            st.last_cause = cause
            st.last_compile_wall = now_wall
            st.last_compile_mono = now_mono
            for k, v in durations.items():
                st.durations_total[k] = st.durations_total.get(k, 0.0) + v
            rec = {
                "program": prog.name,
                "ts": now_wall,
                "nth_compile": st.compiles,
                "call_s": round(call_s, 6),
                "compile_s": round(compile_s, 6),
                "durations": {k: round(v, 6)
                              for k, v in durations.items()},
                "signature": signature,
                "cause": cause,
            }
            if analysis:
                rec["analysis"] = analysis
            self._records.append(rec)

            # storm detection: threshold compiles inside the sliding
            # window opens an episode; one advisory per episode, re-armed
            # only after the window drains.
            st.compile_times.append(now_mono)
            cutoff = now_mono - self.storm_window_s
            while st.compile_times and st.compile_times[0] < cutoff:
                st.compile_times.popleft()
            if st.storm_open and not st.compile_times:
                st.storm_open = False
            if (not st.storm_open
                    and len(st.compile_times) >= self.storm_threshold):
                st.storm_open = True
                st.storm_episodes += 1
                advisory = {
                    "event": "device_advisory",
                    "kind": "recompile_storm",
                    "program": prog.name,
                    "compiles_in_window": len(st.compile_times),
                    "window_s": self.storm_window_s,
                    "threshold": self.storm_threshold,
                    "cause": cause,
                    "ts": now_wall,
                }
                self._advisories.append(advisory)

        try:
            _compile_histogram().observe(compile_s,
                                         tags={"program": prog.name})
            if is_recompile:
                _recompile_counter().inc(1.0, tags={"program": prog.name})
        except Exception:
            pass
        if advisory is not None:
            try:
                self._publish(advisory)
            except Exception:
                pass
        # piggyback the KV flush on the compile path (rate-limited):
        # a storm flushes itself visible without any cooperating loop
        flush_device_snapshot()

    def push_advisory(self, payload: Dict[str, Any],
                      publish: bool = True) -> None:
        """Record (and optionally publish) an externally-raised device
        advisory — the memory census uses this for watermark events."""
        with self._lock:
            self._advisories.append(payload)
        if publish:
            try:
                self._publish(payload)
            except Exception:
                pass

    # -- read side -----------------------------------------------------

    def counts(self) -> Dict[str, int]:
        """Per-program compile counts — the bench zero-recompile gate
        diffs two of these around the timed region."""
        with self._lock:
            return {name: st.compiles
                    for name, st in self._programs.items()}

    def compiles_since(self, mark: Dict[str, int]) -> Dict[str, int]:
        """Programs that compiled since ``mark = ledger.counts()``."""
        now = self.counts()
        out = {}
        for name, n in now.items():
            delta = n - mark.get(name, 0)
            if delta > 0:
                out[name] = delta
        return out

    def storm_advisories(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [a for a in self._advisories
                    if a.get("kind") == "recompile_storm"]

    def drain_advisories(self) -> List[Dict[str, Any]]:
        """Advisories raised since the last drain — driver loops feed
        these to ``RemediationEngine.observe_advisory`` once per round."""
        with self._lock:
            new = list(self._advisories[self._drain_idx:])
            self._drain_idx = len(self._advisories)
            if len(self._advisories) > 512:  # bound the log
                drop = len(self._advisories) - 256
                del self._advisories[:drop]
                self._drain_idx = max(0, self._drain_idx - drop)
            return new

    def snapshot(self) -> Dict[str, Any]:
        """Picklable ledger state for the ``_device`` KV flush."""
        now_mono = self._clock()
        with self._lock:
            programs = {}
            for name, st in self._programs.items():
                if (st.storm_open and st.compile_times
                        and now_mono - st.compile_times[-1]
                        > self.storm_window_s):
                    st.storm_open = False  # episode drained
                programs[name] = {
                    "compiles": st.compiles,
                    "recompiles": st.recompiles,
                    "last_compile_ts": st.last_compile_wall,
                    "last_cause": st.last_cause,
                    "storm_open": st.storm_open,
                    "storm_episodes": st.storm_episodes,
                    "durations_total_s": {
                        k: round(v, 6)
                        for k, v in st.durations_total.items()},
                }
            return {
                "total_compiles": self._total_compiles,
                "total_recompiles": self._total_recompiles,
                "programs": programs,
                "records": list(self._records),
                "advisories": list(self._advisories),
                "storm_threshold": self.storm_threshold,
                "storm_window_s": self.storm_window_s,
            }

    def reset(self) -> None:
        """Forget all state (tests)."""
        with self._lock:
            self._programs.clear()
            self._records.clear()
            self._advisories.clear()
            self._drain_idx = 0
            self._total_compiles = 0
            self._total_recompiles = 0


def _analyze_executable(jitted: Any, args: Tuple,
                        kwargs: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Opt-in executable cost/memory analysis via the AOT path.  The
    AOT lower→compile does NOT share the jit dispatch cache, so this
    roughly doubles compile cost — off by default, on in tests/bench."""
    try:
        compiled = jitted.lower(*args, **kwargs).compile()
    except Exception:
        return None
    out: Dict[str, Any] = {}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):   # list-of-dicts on jax 0.4.x
            ca = ca[0] if ca else {}
        if isinstance(ca, dict):
            out["cost"] = {k: float(v) for k, v in ca.items()
                           if isinstance(v, (int, float))
                           and k in ("flops", "bytes accessed",
                                     "utilization operand 0",
                                     "optimal_seconds")}
    except Exception:
        pass
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            out["memory"] = {
                "argument_bytes": int(getattr(
                    ma, "argument_size_in_bytes", 0)),
                "output_bytes": int(getattr(
                    ma, "output_size_in_bytes", 0)),
                "temp_bytes": int(getattr(ma, "temp_size_in_bytes", 0)),
                "code_bytes": int(getattr(
                    ma, "generated_code_size_in_bytes", 0)),
            }
    except Exception:
        pass
    return out or None


class InstrumentedProgram:
    """A jitted callable routed through the ledger.

    Transparent: attribute access (``lower``, ``clear_cache``, …)
    proxies to the underlying jitted object, so instrumented programs
    drop into existing call sites unchanged.
    """

    def __init__(self, jitted: Any, name: Optional[str] = None,
                 ledger: Optional["CompilationLedger"] = None,
                 analysis: Optional[bool] = None):
        self._fn = jitted
        wrapped = getattr(jitted, "__wrapped__", None)
        self.name = name or getattr(wrapped, "__qualname__", None) \
            or getattr(jitted, "__name__", None) or repr(jitted)
        self._ledger = ledger
        self._analysis = analysis
        try:
            self._sig: Optional[inspect.Signature] = \
                inspect.signature(wrapped if wrapped is not None else jitted)
        except (TypeError, ValueError):
            self._sig = None
        functools.update_wrapper(self, wrapped or jitted, updated=())
        _install_monitoring()

    def __call__(self, *args, **kwargs):
        led = self._ledger if self._ledger is not None else get_ledger()
        try:
            before = self._fn._cache_size()
        except Exception:
            before = None
        frame = {"durations": {}}
        stack = _frame_stack()
        stack.append(frame)
        t0 = time.perf_counter()
        try:
            out = self._fn(*args, **kwargs)
        finally:
            stack.pop()
        if before is not None:
            try:
                compiled_new = self._fn._cache_size() > before
            except Exception:
                compiled_new = False
            if compiled_new:
                led._record_compile(self, args, kwargs,
                                    time.perf_counter() - t0,
                                    frame["durations"])
        return out

    def __getattr__(self, item):
        return getattr(self._fn, item)

    def __repr__(self):
        return f"<InstrumentedProgram {self.name!r} of {self._fn!r}>"


# ---------------------------------------------------------------------------
# Memory census
# ---------------------------------------------------------------------------


class DeviceMemoryCensus:
    """Samples live device memory and registered owner reports.

    Owners (e.g. the serve engine) register a zero-arg callback
    returning a small dict; a callback reporting a ``pages`` sub-dict
    (``free/used/shared/cow``) feeds the ``ray_tpu_kv_pages`` gauge.
    """

    def __init__(self, watermark_bytes: Optional[int] = None,
                 ledger: Optional[CompilationLedger] = None,
                 wall: Callable[[], float] = time.time):
        if watermark_bytes is None:
            watermark_bytes = int(float(os.environ.get(
                "RAY_TPU_DEVICE_WATERMARK_BYTES", "0")))
        self.watermark_bytes = int(watermark_bytes)
        self._ledger = ledger
        self._wall = wall
        self._lock = threading.Lock()
        self._owners: Dict[str, Callable[[], Dict[str, Any]]] = {}  # guarded-by: _lock
        self._watermark_open = False  # guarded-by: _lock

    def register_owner(self, tag: str,
                       report: Callable[[], Dict[str, Any]]) -> None:
        with self._lock:
            self._owners[tag] = report

    def unregister_owner(self, tag: str) -> None:
        with self._lock:
            self._owners.pop(tag, None)

    def _live_buffers(self) -> Dict[str, Any]:
        total = 0
        count = 0
        by_dtype: Dict[str, int] = {}
        shapes: Dict[Tuple[str, Tuple[int, ...]], Dict[str, Any]] = {}
        try:
            import jax

            for a in jax.live_arrays():
                try:
                    nbytes = int(a.nbytes)
                    dt = str(a.dtype)
                    shp = tuple(int(s) for s in a.shape)
                except Exception:
                    continue
                total += nbytes
                count += 1
                by_dtype[dt] = by_dtype.get(dt, 0) + nbytes
                key = (dt, shp)
                slot = shapes.get(key)
                if slot is None:
                    slot = shapes[key] = {"dtype": dt, "shape": list(shp),
                                          "count": 0, "bytes": 0}
                slot["count"] += 1
                slot["bytes"] += nbytes
        except Exception:
            pass
        top = sorted(shapes.values(), key=lambda s: -s["bytes"])[:12]
        return {"total_bytes": total, "count": count,
                "by_dtype": by_dtype, "top_shapes": top}

    def census(self) -> Dict[str, Any]:
        """One sample: live buffers + owner reports + gauges, plus a
        watermark advisory (once per above-watermark episode)."""
        live = self._live_buffers()
        with self._lock:
            owners = dict(self._owners)
        reports: Dict[str, Dict[str, Any]] = {}
        for tag, cb in owners.items():
            try:
                reports[tag] = dict(cb())
            except Exception:
                reports[tag] = {"error": "owner report failed"}
        try:
            # built-in owner: this process's emergency-vault footprint
            # (elastic/emergency.py) — recovery headroom competes with
            # the KV arena for the same HBM budget
            from ..elastic.emergency import vault_footprint

            vf = vault_footprint()
            if vf.get("entries"):
                reports["emergency_vault"] = vf
        except Exception:
            pass

        try:
            _hbm_gauge().set(float(live["total_bytes"]))
            for rep in reports.values():
                pages = rep.get("pages")
                if isinstance(pages, dict):
                    for state in ("free", "used", "shared", "cow"):
                        if state in pages:
                            _kv_pages_gauge().set(
                                float(pages[state]),
                                tags={"state": state})
        except Exception:
            pass

        advisory = None
        with self._lock:
            if self.watermark_bytes > 0:
                over = live["total_bytes"] >= self.watermark_bytes
                if over and not self._watermark_open:
                    self._watermark_open = True
                    advisory = {
                        "event": "device_advisory",
                        "kind": "memory_watermark",
                        "live_bytes": live["total_bytes"],
                        "watermark_bytes": self.watermark_bytes,
                        "ts": self._wall(),
                    }
                elif (not over and self._watermark_open
                      and live["total_bytes"]
                      < 0.9 * self.watermark_bytes):
                    self._watermark_open = False  # hysteresis re-arm
        if advisory is not None:
            led = self._ledger if self._ledger is not None else get_ledger()
            led.push_advisory(advisory)
        return {"ts": self._wall(), "live": live, "owners": reports,
                "watermark_bytes": self.watermark_bytes}


# ---------------------------------------------------------------------------
# Process singletons + module-level entry points
# ---------------------------------------------------------------------------

_singleton_lock = threading.Lock()
_ledger: Optional[CompilationLedger] = None
_census: Optional[DeviceMemoryCensus] = None


def get_ledger() -> CompilationLedger:
    global _ledger
    with _singleton_lock:
        if _ledger is None:
            _ledger = CompilationLedger()
        return _ledger


def get_census() -> DeviceMemoryCensus:
    global _census
    with _singleton_lock:
        if _census is None:
            _census = DeviceMemoryCensus()
        return _census


def jit(fun: Optional[Callable] = None, *, name: Optional[str] = None,
        analysis: Optional[bool] = None, **jit_kwargs) -> Any:
    """Process-ledger instrumented ``jax.jit`` (decorator or wrap call):

        step = device.jit(step_fn, name="serve.step", donate_argnums=(0,))
    """
    return get_ledger().jit(fun, name=name, analysis=analysis,
                            **jit_kwargs)


def instrument(jitted: Any, name: Optional[str] = None,
               analysis: Optional[bool] = None) -> InstrumentedProgram:
    """Route an already-jitted callable through the process ledger."""
    return get_ledger().instrument(jitted, name=name, analysis=analysis)


def reset_for_tests() -> None:
    """Fresh singletons (unit tests share one process)."""
    global _ledger, _census
    with _singleton_lock:
        _ledger = None
        _census = None


# ---------------------------------------------------------------------------
# KV flush + cluster read side
# ---------------------------------------------------------------------------


def device_snapshot() -> Dict[str, Any]:
    """The local process's full device-observability snapshot."""
    return {
        "ts": time.time(),
        "ledger": get_ledger().snapshot(),
        "memory": get_census().census(),
    }


def flush_device_snapshot(interval_s: float = 2.0,
                          force: bool = False) -> bool:
    """Ship the device snapshot to control-plane KV ns ``_device``
    (rate-limited, never raises — same contract as PR-4 telemetry's
    ``flush_snapshot``)."""
    led = get_ledger()
    now = time.monotonic()
    if not force and interval_s > 0 and \
            now - led._last_flush < interval_s:
        return False
    try:
        from ray_tpu._private import core as core_mod

        from .recorder import _kick_reattach

        core = core_mod._current_core
        if core is None or getattr(core, "_shutdown", False):
            return False
        led._last_flush = now
        cli = core.control
        if getattr(cli, "closed", False):
            _kick_reattach(core, cli)
            return False
        snap = device_snapshot()
        snap["worker_id"] = core.worker_id
        try:
            cli.call("kv_put", {
                "ns": DEVICE_NS,
                "key": f"{DEVICE_KEY_PREFIX}{core.worker_id}",
                "val": pickle.dumps(snap),
            }, timeout=5.0)
        except Exception:
            _kick_reattach(core, cli)
            return False
        return True
    except Exception:
        return False


def collect_device_stats(control_client) -> Dict[str, Any]:
    """Cluster-wide merge of every worker's ``_device`` snapshot — the
    shared read side for the dashboard route, the CLI and the state
    API."""
    workers: Dict[str, Dict[str, Any]] = {}
    try:
        keys = control_client.call(
            "kv_keys", {"ns": DEVICE_NS, "prefix": DEVICE_KEY_PREFIX},
            timeout=10.0) or []
        for k in keys:
            raw = control_client.call(
                "kv_get", {"ns": DEVICE_NS, "key": k}, timeout=10.0)
            if raw is None:
                continue
            try:
                snap = pickle.loads(raw)
            except Exception:
                continue
            wid = snap.get("worker_id") or k[len(DEVICE_KEY_PREFIX):]
            workers[wid] = snap
    except Exception:
        pass

    programs: Dict[str, Dict[str, Any]] = {}
    advisories: List[Dict[str, Any]] = []
    total_compiles = 0
    total_recompiles = 0
    live_bytes = 0
    for wid, snap in workers.items():
        led = snap.get("ledger") or {}
        total_compiles += int(led.get("total_compiles", 0))
        total_recompiles += int(led.get("total_recompiles", 0))
        for name, st in (led.get("programs") or {}).items():
            agg = programs.setdefault(name, {
                "compiles": 0, "recompiles": 0, "storm_episodes": 0,
                "workers": 0, "last_cause": None, "last_compile_ts": 0.0})
            agg["compiles"] += int(st.get("compiles", 0))
            agg["recompiles"] += int(st.get("recompiles", 0))
            agg["storm_episodes"] += int(st.get("storm_episodes", 0))
            agg["workers"] += 1
            if st.get("last_compile_ts", 0.0) >= agg["last_compile_ts"]:
                agg["last_compile_ts"] = st.get("last_compile_ts", 0.0)
                if st.get("last_cause") is not None:
                    agg["last_cause"] = st.get("last_cause")
        for adv in (led.get("advisories") or []):
            advisories.append({**adv, "worker_id": wid})
        mem = snap.get("memory") or {}
        live_bytes += int((mem.get("live") or {}).get("total_bytes", 0))
    advisories.sort(key=lambda a: a.get("ts", 0.0))
    return {
        "workers": workers,
        "programs": programs,
        "advisories": advisories,
        "total_compiles": total_compiles,
        "total_recompiles": total_recompiles,
        "live_bytes": live_bytes,
    }


def compile_trace_events(workers: Dict[str, Dict[str, Any]],
                         pid: int = 90) -> List[Dict[str, Any]]:
    """Chrome-trace complete slices for every recorded compile (one
    thread row per worker; ``timeline.chrome_trace`` appends these)."""
    events: List[Dict[str, Any]] = []
    for tid, (wid, snap) in enumerate(sorted(workers.items())):
        slices: List[Dict[str, Any]] = []
        for rec in (snap.get("ledger") or {}).get("records", []):
            dur_s = rec.get("compile_s") or rec.get("call_s") or 0.0
            ev = {
                "name": f"compile {rec.get('program')}",
                "ph": "X", "pid": pid, "tid": tid,
                "ts": rec.get("ts", 0.0) * 1e6 - dur_s * 1e6,
                "dur": max(1.0, dur_s * 1e6),
                "cat": "compile",
                "args": {
                    "program": rec.get("program"),
                    "nth_compile": rec.get("nth_compile"),
                    "durations": rec.get("durations"),
                },
            }
            cause = rec.get("cause")
            if cause and cause.get("arg") is not None:
                ev["args"]["cause"] = (f"{cause['arg']}: {cause['kind']} "
                                       f"{cause['old']} -> {cause['new']}")
            slices.append(ev)
        if slices:
            # meta rows only for workers that actually compiled, so an
            # empty (e.g. trial-filtered) timeline stays truly empty
            events.append({
                "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                "args": {"name": f"xla-compile {wid[:12]}"}})
            events.extend(slices)
    if events:
        events.insert(0, {"name": "process_name", "ph": "M", "pid": pid,
                          "args": {"name": "xla compiles"}})
    return events
