"""ray_tpu.telemetry: the training flight recorder.

Four cooperating pieces (see COMPONENTS.md):

  * recorder   — per-worker StepTimer: phase-resolved step timing
    (data / compute / collective / checkpoint) with
    ``jax.block_until_ready`` fences, a bounded ring buffer, and
    rate-limited KV snapshot flushes; ``record_collective`` is the hook
    the collective layer reports per-op timing + wire bytes through.
  * goodput    — GoodputAccountant: wall-clock state machine
    (productive / draining / recovering / idle) stamped by the elastic
    subsystem across incarnations.
  * aggregator — driver-side StepAggregator: merges per-round
    cross-worker step records, flags stragglers (busy time >
    multiple × gang median, sustained-N hysteresis) and publishes
    ``straggler_detected`` advisories on the "train" topic.
  * timeline   — Chrome trace-event export for Perfetto, serving
    ``GET /api/train/timeline`` and ``ray-tpu timeline <job>``.
  * device     — XLA compilation ledger (instrumented jit entry point,
    recompile cause diffs, storm advisories) + device-memory census
    (live buffers, KV page arena occupancy); flushes to KV ns
    ``_device`` and serves ``ray-tpu device-stats`` /
    ``GET /api/device/stats``.

Exports resolve lazily (PEP 562) so importing ``ray_tpu`` does not drag
the train stack in.
"""

_EXPORTS = {
    "TelemetryConfig": "config",
    "resolve_telemetry": "config",
    "StepTimer": "recorder",
    "phase": "recorder",
    "set_current_timer": "recorder",
    "current_timer": "recorder",
    "record_collective": "recorder",
    "flush_snapshot": "recorder",
    "TELEMETRY_KEY_PREFIX": "recorder",
    "GoodputAccountant": "goodput",
    "set_current_accountant": "goodput",
    "current_accountant": "goodput",
    "stamp": "goodput",
    "StepAggregator": "aggregator",
    "collect_snapshots": "timeline",
    "collect_device_workers": "timeline",
    "chrome_trace": "timeline",
    "validate_chrome_trace": "timeline",
    "CompilationLedger": "device",
    "DeviceMemoryCensus": "device",
    "InstrumentedProgram": "device",
    "get_ledger": "device",
    "get_census": "device",
    "instrument": "device",
    "device_snapshot": "device",
    "flush_device_snapshot": "device",
    "collect_device_stats": "device",
    "DEVICE_NS": "device",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    try:
        modname = _EXPORTS[name]
    except KeyError:
        raise AttributeError(f"module {__name__!r} has no attribute "
                             f"{name!r}") from None
    import importlib

    mod = importlib.import_module(f".{modname}", __name__)
    val = getattr(mod, name)
    globals()[name] = val
    return val


def __dir__():
    return __all__
