"""Telemetry configuration.

``TelemetryConfig`` rides into workers through ``TrainContext.extra``
(serialized via ``to_dict``), the same channel the elastic subsystem
uses for per-replica batch math, so enabling the flight recorder needs
no new plumbing: ``JaxConfig(telemetry=TelemetryConfig(...))``.
"""

from __future__ import annotations

from dataclasses import dataclass, field, asdict
from typing import Any, Dict, Optional

DEFAULT_RING_SIZE = 512
DEFAULT_FLUSH_INTERVAL_S = 2.0
DEFAULT_STRAGGLER_MULTIPLE = 2.0
DEFAULT_STRAGGLER_SUSTAIN = 3


@dataclass
class TelemetryConfig:
    """Knobs for the training flight recorder.

    Attributes:
        enabled: master switch; telemetry defaults ON (near-zero cost —
            a perf_counter pair per phase and a bounded deque append).
        ring_size: per-worker step-record ring buffer capacity.
        flush_interval_s: min seconds between KV snapshot flushes from a
            worker (0 flushes on every report — used by tests).
        straggler_multiple: a worker is suspect when its busy step time
            exceeds this multiple of the gang median.
        straggler_sustain: consecutive suspect steps before the
            aggregator emits a ``straggler_detected`` advisory
            (hysteresis: one GC pause must not page anyone).
    """

    enabled: bool = True
    ring_size: int = DEFAULT_RING_SIZE
    flush_interval_s: float = DEFAULT_FLUSH_INTERVAL_S
    straggler_multiple: float = DEFAULT_STRAGGLER_MULTIPLE
    straggler_sustain: int = DEFAULT_STRAGGLER_SUSTAIN

    def __post_init__(self):
        if self.ring_size < 1:
            raise ValueError("ring_size must be >= 1")
        if self.flush_interval_s < 0:
            raise ValueError("flush_interval_s must be >= 0")
        if self.straggler_multiple <= 1.0:
            raise ValueError("straggler_multiple must be > 1.0")
        if self.straggler_sustain < 1:
            raise ValueError("straggler_sustain must be >= 1")

    def to_dict(self) -> Dict[str, Any]:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TelemetryConfig":
        known = {k: v for k, v in (d or {}).items()
                 if k in cls.__dataclass_fields__}
        return cls(**known)


def resolve_telemetry(value: Any) -> TelemetryConfig:
    """Normalize the user-facing ``telemetry=`` knob.

    Accepts None (default: enabled), bool, dict, or TelemetryConfig.
    """
    if value is None:
        return TelemetryConfig()
    if isinstance(value, TelemetryConfig):
        return value
    if isinstance(value, bool):
        return TelemetryConfig(enabled=value)
    if isinstance(value, dict):
        return TelemetryConfig.from_dict(value)
    raise TypeError(f"telemetry must be None/bool/dict/TelemetryConfig, "
                    f"got {type(value).__name__}")
