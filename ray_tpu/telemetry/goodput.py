"""Goodput accounting across elastic incarnations.

Goodput = wall-clock fraction spent making forward progress
(state "productive") versus lost to drain notices ("draining"),
shrink/recover cycles ("recovering"), and in-between gaps ("idle").
The elastic subsystem stamps transitions through the module-level
current accountant so BackendExecutor/trainer need no handle plumbing.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

STATES = ("productive", "draining", "recovering", "idle")


class GoodputAccountant:
    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._state = "idle"
        self._t0 = clock()
        self._seg0 = self._t0
        self._seconds: Dict[str, float] = {s: 0.0 for s in STATES}
        self._transitions: List[Dict[str, Any]] = []
        self._incarnations: List[int] = []

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def transition(self, state: str, **meta: Any) -> None:
        if state not in STATES:
            raise ValueError(f"unknown goodput state {state!r}; "
                             f"expected one of {STATES}")
        with self._lock:
            inc = meta.get("incarnation")
            if inc is not None and inc not in self._incarnations:
                self._incarnations.append(inc)
            if state == self._state:
                return
            now = self._clock()
            self._seconds[self._state] += now - self._seg0
            self._seg0 = now
            self._state = state
            self._transitions.append(
                {"ts": now - self._t0, "state": state, **meta})
        self._export_gauge()

    def note_incarnation(self, incarnation: int) -> None:
        with self._lock:
            if incarnation not in self._incarnations:
                self._incarnations.append(incarnation)

    def report(self) -> Dict[str, Any]:
        with self._lock:
            now = self._clock()
            seconds = dict(self._seconds)
            seconds[self._state] += now - self._seg0  # in-progress segment
            wall = now - self._t0
            return {
                "state": self._state,
                "goodput": (seconds["productive"] / wall) if wall > 0
                else 0.0,
                "seconds": {k: round(v, 6) for k, v in seconds.items()},
                "wall_s": round(wall, 6),
                "transitions": list(self._transitions),
                "incarnations": list(self._incarnations),
            }

    def _export_gauge(self) -> None:
        try:
            from . import recorder
            from ..util import metrics as metrics_mod

            g = recorder._get_metric(
                "goodput_gauge", lambda: metrics_mod.Gauge(
                    "ray_tpu_train_goodput",
                    description="Fraction of wall-clock in productive "
                                "training"))
            g.set(self.report()["goodput"])
        except Exception:
            pass


_lock = threading.Lock()
_current: Optional[GoodputAccountant] = None


def set_current_accountant(acct: Optional[GoodputAccountant]) -> None:
    global _current
    with _lock:
        _current = acct


def current_accountant() -> Optional[GoodputAccountant]:
    return _current


def stamp(state: str, **meta: Any) -> None:
    """Transition the current accountant, if any (elastic hooks call
    this so telemetry-off runs cost one attribute read)."""
    acct = _current
    if acct is not None:
        try:
            acct.transition(state, **meta)
        except Exception:
            pass
