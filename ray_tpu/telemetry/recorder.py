"""Per-worker step timing: the flight recorder's write side.

``StepTimer`` splits each training step into phases (data wait, compute,
collective sync, checkpoint) using ``jax.block_until_ready`` fences —
without a fence, XLA's async dispatch attributes device time to whatever
host line happens to block next (the central pitfall called out in the
MLPerf TPU-pod scaling report, arXiv:1909.09756 §4). Records land in a
bounded ring buffer; ``flush_snapshot`` ships them through the same
control-plane KV namespace ``util/metrics`` already uses (keyed
``telemetry:<worker_id>:<incarnation>``), so partition tolerance and
dashboard plumbing come for free.

The collective layer reports into the *current* timer through a
thread-local registry (``record_collective``) so ``collective.py`` /
``xla_group.py`` need no handle threading.
"""

from __future__ import annotations

import pickle
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from ..util import metrics as metrics_mod

# Sub-namespace prefix inside METRICS_NS; collect_cluster_metrics reads
# snap["metrics"] which we keep as [] so plain metric merging is unharmed.
TELEMETRY_KEY_PREFIX = "telemetry:"

# Canonical phase order for timeline rendering; unknown phases append.
# Dotted names are SUB-phases nested under their parent ("collective" is
# also reported as quantize/transfer/dequantize when the collective layer
# measured its stages) — children overlap the parent's time, so summaries
# and the step residual must not double-count them (see step_end).
PHASE_ORDER = ("data", "compute", "collective", "collective.quantize",
               "collective.transfer", "collective.dequantize", "checkpoint",
               "pipeline", "pipeline.fwd", "pipeline.bwd", "pipeline.bwd_w",
               "pipeline.p2p", "pipeline.idle")

_STEP_BOUNDARIES = [0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5,
                    10, 30, 60, 300]

_tls = threading.local()

# Module-level caches hold strong refs so the weakref registry
# (util/metrics._Registry) keeps these alive across flush epochs.
_metric_lock = threading.Lock()
_metric_cache: Dict[str, Any] = {}


def _fence(x: Any) -> None:
    """Block until device work backing ``x`` is done (no-op sans jax)."""
    if x is None:
        return
    try:
        import jax

        jax.block_until_ready(x)
    except Exception:
        pass


def _get_metric(key: str, factory: Callable[[], Any]) -> Any:
    with _metric_lock:
        m = _metric_cache.get(key)
        if m is None:
            m = _metric_cache[key] = factory()
        return m


def _step_histogram():
    return _get_metric("step_hist", lambda: metrics_mod.Histogram(
        "ray_tpu_train_step_phase_seconds",
        description="Per-step train phase durations",
        boundaries=_STEP_BOUNDARIES,
        tag_keys=("phase",)))


def _collective_histogram():
    return _get_metric("coll_hist", lambda: metrics_mod.Histogram(
        "ray_tpu_collective_op_seconds",
        description="Collective op dispatch+sync time",
        boundaries=_STEP_BOUNDARIES,
        tag_keys=("op",)))


def _payload_counter():
    return _get_metric("payload_ctr", lambda: metrics_mod.Counter(
        "ray_tpu_collective_payload_bytes_total",
        description="Logical (fp32-equivalent) bytes moved by collectives",
        tag_keys=("op",)))


def _wire_counter():
    return _get_metric("wire_ctr", lambda: metrics_mod.Counter(
        "ray_tpu_collective_wire_bytes_total",
        description="Wire bytes moved by collectives (post-compression)",
        tag_keys=("op",)))


class _PhaseHandle:
    """Context manager for one phase of the current step."""

    __slots__ = ("_timer", "_name", "_t0", "_fence_on")

    def __init__(self, timer: "StepTimer", name: str):
        self._timer = timer
        self._name = name
        self._t0 = 0.0
        self._fence_on: Optional[Any] = None

    def fence(self, x: Any) -> Any:
        """Fence on ``x`` at phase exit so async device work counts here."""
        self._fence_on = x
        return x

    def __enter__(self) -> "_PhaseHandle":
        self._t0 = self._timer._clock()
        return self

    def __exit__(self, *exc) -> None:
        if self._fence_on is not None:
            _fence(self._fence_on)
            self._fence_on = None
        self._timer.add_phase_time(self._name, self._timer._clock() - self._t0)


class StepTimer:
    """Phase-resolved per-step stopwatch with a bounded ring buffer.

    Typical use inside a train loop (``session.get_session()`` creates
    one per worker and exposes it via ``telemetry.phase(...)``)::

        timer.step_start(step)
        with timer.phase("data"):
            batch = next(it)
        loss, state = train_step(state, batch)   # collective records itself
        rec = timer.step_end(fence=loss)         # residual -> "compute"
    """

    def __init__(self, ring_size: int = 512, rank: int = 0,
                 incarnation: int = 0, trial: str = "",
                 clock: Callable[[], float] = time.perf_counter,
                 wall: Callable[[], float] = time.time):
        self._ring: deque = deque(maxlen=max(1, int(ring_size)))
        self.rank = int(rank)
        self.incarnation = int(incarnation)
        self.trial = trial
        self._clock = clock
        self._lock = threading.Lock()
        self._t0: Optional[float] = None
        self._wall0 = 0.0
        self._step: Optional[int] = None
        self._phases: Dict[str, float] = {}
        self._last_flush = 0.0
        # ONE wall<->monotonic anchor per incarnation: every wall stamp
        # this timer emits is derived from the monotonic clock via this
        # pair, so an NTP step mid-run shifts nothing — Chrome traces
        # and goodput windows stay mutually consistent (phases already
        # used perf_counter; mixing raw time.time() into "ts" let the
        # two clocks skew).
        self._anchor = (wall(), clock())

    def wall_now(self) -> float:
        """Anchor-derived wall time (monotonic progression since the
        one wall reading taken at construction)."""
        anchor_wall, anchor_mono = self._anchor
        return anchor_wall + (self._clock() - anchor_mono)

    # -- step lifecycle ------------------------------------------------

    def step_start(self, step: Optional[int] = None) -> None:
        with self._lock:
            self._t0 = self._clock()
            self._wall0 = self.wall_now()
            self._step = step
            self._phases = {}

    def phase(self, name: str) -> _PhaseHandle:
        return _PhaseHandle(self, name)

    def add_phase_time(self, name: str, seconds: float) -> None:
        if seconds < 0:
            seconds = 0.0
        with self._lock:
            if self._t0 is None:
                return  # between steps (e.g. collectives in group setup)
            self._phases[name] = self._phases.get(name, 0.0) + seconds

    def step_end(self, step: Optional[int] = None,
                 fence: Any = None) -> Optional[Dict[str, Any]]:
        if fence is not None:
            _fence(fence)
        with self._lock:
            if self._t0 is None:
                return None
            dur = self._clock() - self._t0
            phases = dict(self._phases)
            # residual host+device time not claimed by an explicit phase;
            # dotted sub-phases ("collective.quantize") overlap their
            # parent's time and must not be counted twice
            residual = dur - sum(v for k, v in phases.items()
                                 if "." not in k)
            if residual > 0:
                phases["compute"] = phases.get("compute", 0.0) + residual
            rec = {
                "step": self._step if step is None else step,
                "ts": self._wall0,
                "dur": dur,
                "phases": phases,
                "rank": self.rank,
                "incarnation": self.incarnation,
            }
            self._ring.append(rec)
            self._t0 = None
            self._step = None
            self._phases = {}
        try:
            h = _step_histogram()
            for name, secs in phases.items():
                h.observe(secs, tags={"phase": name})
        except Exception:
            pass
        return rec

    # -- read side -----------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "trial": self.trial,
                "rank": self.rank,
                "incarnation": self.incarnation,
                "ring_size": self._ring.maxlen,
                "steps": list(self._ring),
            }

    def aggregate(self) -> Dict[str, Any]:
        """Phase means/totals over the ring (bench/report summary)."""
        with self._lock:
            steps = list(self._ring)
        if not steps:
            return {"steps": 0}
        totals: Dict[str, float] = {}
        for rec in steps:
            for name, secs in rec["phases"].items():
                totals[name] = totals.get(name, 0.0) + secs
        n = len(steps)
        total_dur = sum(r["dur"] for r in steps)
        return {
            "steps": n,
            "step_mean_s": total_dur / n,
            "phase_totals_s": {k: round(v, 6) for k, v in totals.items()},
            "phase_means_s": {k: round(v / n, 6) for k, v in totals.items()},
        }


# -- current-timer registry (thread-local, like session._tls) ----------


class _NoopPhase:
    """Stands in for _PhaseHandle when no timer is active (telemetry
    disabled, or code running outside a train session)."""

    __slots__ = ()

    def fence(self, x: Any) -> Any:
        return x

    def __enter__(self) -> "_NoopPhase":
        return self

    def __exit__(self, *exc) -> None:
        pass


def phase(name: str):
    """User-facing phase marker for the current worker's train loop::

        with ray_tpu.telemetry.phase("data"):
            batch = next(it)

    No-op when telemetry is off, so train loops need no gating."""
    timer = current_timer()
    if timer is None:
        return _NoopPhase()
    return timer.phase(name)


def set_current_timer(timer: Optional[StepTimer]) -> None:
    _tls.timer = timer


def current_timer() -> Optional[StepTimer]:
    return getattr(_tls, "timer", None)


def record_collective(op: str, seconds: float, payload_bytes: float = 0,
                      wire_bytes: Optional[float] = None,
                      breakdown: Optional[Dict[str, float]] = None) -> None:
    """Called by collective/xla_group per op; feeds the current step's
    "collective" phase plus cluster-wide Prometheus series.

    ``breakdown`` carries measured quantize/transfer/dequantize sub-phase
    seconds (the kv backend times its codec/wire stages; the compiled
    backend reports them from mesh_allreduce(profile=True)'s fenced
    stage programs).  Sub-phases land as "collective.<stage>" children —
    NESTED inside the parent "collective" time, not additional to it."""
    timer = current_timer()
    if timer is not None:
        timer.add_phase_time("collective", seconds)
        if breakdown:
            for stage, secs in breakdown.items():
                if secs > 0:
                    timer.add_phase_time(f"collective.{stage}", secs)
    try:
        _collective_histogram().observe(seconds, tags={"op": op})
        if payload_bytes > 0:
            _payload_counter().inc(payload_bytes, tags={"op": op})
            wb = payload_bytes if wire_bytes is None else wire_bytes
            if wb > 0:
                _wire_counter().inc(wb, tags={"op": op})
    except Exception:
        pass


# -- KV flush ----------------------------------------------------------

_reattach_lock = threading.Lock()
_reattach_inflight = False


def _kick_reattach(core, failed_client) -> None:
    """Rebuild the core's control client off the hot path.  The core
    only re-attaches inside ``_control_call`` (user-facing RPCs), so an
    idle driver/worker whose only control traffic is telemetry flushes
    would otherwise stay disconnected forever after a partition heals.
    ``_rebuild_control`` blocks up to the reconnect grace — that wait
    must land on a background thread, never inside session.report()."""
    global _reattach_inflight
    with _reattach_lock:
        if _reattach_inflight:
            return
        _reattach_inflight = True

    def run():
        global _reattach_inflight
        try:
            core._rebuild_control(failed_client)
        except Exception:
            pass
        finally:
            with _reattach_lock:
                _reattach_inflight = False

    threading.Thread(target=run, daemon=True,
                     name="telemetry-reattach").start()


def flush_snapshot(timer: StepTimer, interval_s: float = 2.0,
                   force: bool = False) -> bool:
    """Ship the ring to control-plane KV (rate-limited, never raises —
    a partition flap must not take down the train loop)."""
    now = time.monotonic()
    if not force and interval_s > 0 and \
            now - timer._last_flush < interval_s:
        return False
    try:
        from ray_tpu._private import core as core_mod

        core = core_mod._current_core
        if core is None or getattr(core, "_shutdown", False):
            return False
        timer._last_flush = now
        cli = core.control
        if getattr(cli, "closed", False):
            _kick_reattach(core, cli)
            return False
        # incarnation in the key: an elastic shrink reuses surviving
        # worker processes under new ranks, and the new gang's snapshots
        # must not clobber the pre-shrink ring (the timeline wants both)
        key = (f"{TELEMETRY_KEY_PREFIX}{core.worker_id}"
               f":{timer.incarnation}")
        try:
            cli.call("kv_put", {
                "ns": metrics_mod.METRICS_NS,
                "key": key,
                # anchor-derived stamp: must agree with the ring's
                # per-step "ts" values even across an NTP step
                "val": pickle.dumps({"ts": timer.wall_now(),
                                     "metrics": [],
                                     "telemetry": timer.snapshot()}),
            }, timeout=5.0)
        except Exception:
            # degraded, not dead: fail fast here, heal in the background
            _kick_reattach(core, cli)
            return False
        try:
            # piggyback the device-observability flush on the same
            # rate-limited heartbeat (telemetry/device.py)
            from .device import flush_device_snapshot

            flush_device_snapshot(interval_s=interval_s)
        except Exception:
            pass
        return True
    except Exception:
        return False
