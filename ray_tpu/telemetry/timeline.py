"""Timeline export: ring-buffer snapshots -> Chrome trace-event JSON.

The dashboard's ``GET /api/train/timeline`` and the
``ray-tpu timeline <job>`` CLI both route through here. Output follows
the Trace Event Format ("X" complete events, µs timestamps) so the
payload drops straight into Perfetto / chrome://tracing.
"""

from __future__ import annotations

import pickle
from typing import Any, Dict, List, Optional

from .recorder import PHASE_ORDER, TELEMETRY_KEY_PREFIX
from ..util.metrics import METRICS_NS


def collect_snapshots(control_client,
                      trial: Optional[str] = None) -> List[Dict[str, Any]]:
    """Pull every worker's last flushed ring snapshot from control KV."""
    snaps: List[Dict[str, Any]] = []
    try:
        keys = control_client.call(
            "kv_keys", {"ns": METRICS_NS, "prefix": TELEMETRY_KEY_PREFIX},
            timeout=5.0)
        for k in keys:
            raw = control_client.call(
                "kv_get", {"ns": METRICS_NS, "key": k}, timeout=5.0)
            if not raw:
                continue
            try:
                snap = pickle.loads(raw)
            except Exception:
                continue
            tel = snap.get("telemetry")
            if not isinstance(tel, dict):
                continue
            if trial and tel.get("trial") != trial:
                continue
            tel["worker_id"] = k[len(TELEMETRY_KEY_PREFIX):]
            snaps.append(tel)
    except Exception:
        pass
    return snaps


def collect_remediations(control_client,
                         trial: Optional[str] = None) -> List[Dict[str, Any]]:
    """Pull the trial's cause→action→effect remediation records (see
    elastic/remediation.py) for overlay onto the trace timeline."""
    try:
        from ray_tpu.elastic.remediation import fetch_records

        return fetch_records(control_client, trial or "")
    except Exception:
        return []


def _phase_sorted(phases: Dict[str, float]) -> List[str]:
    known = [p for p in PHASE_ORDER if p in phases]
    extra = sorted(p for p in phases if p not in PHASE_ORDER)
    return known + extra


def _top_level(phases: Dict[str, float]) -> List[str]:
    """Canonical-order top-level phase names (dotted sub-phases are laid
    out nested inside their parent, not on the sequential cursor)."""
    return [p for p in _phase_sorted(phases) if "." not in p]


def _children_of(phases: Dict[str, float], parent: str) -> List[str]:
    pre = parent + "."
    return [p for p in _phase_sorted(phases) if p.startswith(pre)]


def collect_device_workers(control_client) -> Dict[str, Dict[str, Any]]:
    """Pull every worker's ``_device`` snapshot for compile-slice
    overlay (see telemetry/device.py)."""
    try:
        from .device import collect_device_stats

        return collect_device_stats(control_client).get("workers", {})
    except Exception:
        return {}


def chrome_trace(snapshots: List[Dict[str, Any]],
                 remediations: Optional[List[Dict[str, Any]]] = None,
                 device_workers: Optional[Dict[str, Dict[str, Any]]] = None
                 ) -> Dict[str, Any]:
    """Render snapshots as a Chrome trace: one process per worker rank,
    an "X" span per step plus sequential per-phase child spans.
    Remediation records land as global instant events ("i") at their
    cause/action/effect wall timestamps, so the timeline answers "why
    did the cluster change shape right here".  ``device_workers``
    (from ``collect_device_workers``) adds one process of XLA-compile
    slices per worker so a recompile storm is visible against the very
    steps it stalled."""
    events: List[Dict[str, Any]] = []
    for snap in sorted(snapshots, key=lambda s: s.get("rank", 0)):
        rank = snap.get("rank", 0)
        pid = rank
        label = f"worker {rank}"
        if snap.get("trial"):
            label = f"{snap['trial']} / {label}"
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "args": {"name": label}})
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": "train step"}})
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": 1, "args": {"name": "phases"}})
        for rec in snap.get("steps", []):
            ts_us = rec["ts"] * 1e6
            dur_us = max(rec["dur"] * 1e6, 0.001)
            step = rec.get("step")
            events.append({
                "name": f"step {step}" if step is not None else "step",
                "ph": "X", "ts": ts_us, "dur": dur_us,
                "pid": pid, "tid": 0,
                "args": {"step": step, "dur_s": rec["dur"],
                         "incarnation": rec.get("incarnation"),
                         "phases": rec.get("phases", {})},
            })
            # phases have durations, not start offsets — lay them out
            # sequentially in canonical order on a sibling track; dotted
            # sub-phases (collective.quantize/transfer/dequantize) nest
            # INSIDE their parent's span (same tid, contained ts range ->
            # Perfetto renders them as child slices), so the track's
            # sequential cursor never double-counts them
            cursor = ts_us
            phases = rec.get("phases") or {}
            for name in _top_level(phases):
                p_us = max(phases[name] * 1e6, 0.001)
                events.append({
                    "name": name, "ph": "X", "ts": cursor, "dur": p_us,
                    "pid": pid, "tid": 1,
                    "args": {"step": step, "seconds": phases[name]},
                })
                sub_cursor = cursor
                for child in _children_of(phases, name):
                    c_us = max(phases[child] * 1e6, 0.001)
                    # clip to the parent span: measured sub-stages can
                    # overshoot the async parent's dispatch time by a
                    # rounding hair, and an escaping child breaks nesting
                    c_us = min(c_us, cursor + p_us - sub_cursor)
                    if c_us <= 0:
                        break
                    events.append({
                        "name": child, "ph": "X", "ts": sub_cursor,
                        "dur": c_us, "pid": pid, "tid": 1,
                        "args": {"step": step, "seconds": phases[child]},
                    })
                    sub_cursor += c_us
                cursor += p_us
    for rec in remediations or []:
        rid = rec.get("id", "rem")
        kind = (rec.get("action") or {}).get("kind", "remediation")
        marks = [("cause", rec.get("ts"))]
        marks.append(("action", (rec.get("action") or {}).get("ts")))
        marks.append(("effect", (rec.get("effect") or {}).get("ts")))
        for phase, ts in marks:
            if ts is None:
                continue
            events.append({
                "name": f"{rid}:{kind}:{phase}",
                "ph": "i", "ts": float(ts) * 1e6,
                "pid": 0, "tid": 0, "s": "g",  # global-scope instant
                "args": {"remediation": rec, "phase": phase},
            })
    if device_workers:
        try:
            from .device import compile_trace_events

            events.extend(compile_trace_events(device_workers))
        except Exception:
            pass
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def validate_chrome_trace(trace: Dict[str, Any]) -> bool:
    """Structural check used by tests/CLI: is this loadable trace JSON?"""
    if not isinstance(trace, dict) or \
            not isinstance(trace.get("traceEvents"), list):
        return False
    for ev in trace["traceEvents"]:
        if not isinstance(ev, dict) or "ph" not in ev or "name" not in ev:
            return False
        if ev["ph"] == "X" and not ("ts" in ev and "dur" in ev):
            return False
    return True
