"""Collective operations inside compiled DAGs.

Reference parity: ray.dag collective nodes + experimental.collective
(reference: dag/collective_node.py:19,93; experimental/collective/
allreduce.py:21) — an allreduce bound across several actors' outputs,
executed inside the compiled graph without a driver round-trip.  The
reference moves tensors over NCCL channels; here the participants
exchange contributions over the same shm channel mesh the DAG already
uses (host plane).  Device-plane reductions belong to the compiled ICI
collectives (ray_tpu.collective with the xla backend).
"""

from __future__ import annotations

from typing import List

import numpy as np

from .dag_node import ClassMethodNode, CollectiveOutputNode

__all__ = ["allreduce_bind", "REDUCERS"]


def _sum(vals):
    out = vals[0]
    for v in vals[1:]:
        out = out + v
    return out


def _prod(vals):
    out = vals[0]
    for v in vals[1:]:
        out = out * v
    return out


REDUCERS = {
    "sum": _sum,
    "prod": _prod,
    "max": lambda vals: np.maximum.reduce(vals),
    "min": lambda vals: np.minimum.reduce(vals),
}


def allreduce_bind(nodes: List[ClassMethodNode], op: str = "sum"
                   ) -> List[CollectiveOutputNode]:
    """Bind an allreduce across actor-method outputs (reference:
    experimental/collective/allreduce.py:21 `allreduce.bind`).

    Each input node must run on a distinct actor; returns one output
    node per participant, each carrying the fully-reduced value on that
    participant's actor (usable by later same-actor nodes or as DAG
    leaves)."""
    if op not in REDUCERS:
        raise ValueError(f"unknown reduce op {op!r}; "
                         f"have {sorted(REDUCERS)}")
    if not nodes:
        raise ValueError("allreduce needs at least one contributor")
    for n in nodes:
        if not isinstance(n, ClassMethodNode):
            raise TypeError(
                f"allreduce contributors must be actor-method nodes, "
                f"got {n!r}")
    actor_ids = [n.handle._actor_id for n in nodes]
    if len(set(actor_ids)) != len(actor_ids):
        raise ValueError(
            "allreduce contributors must be on distinct actors")
    group = list(nodes)
    return [CollectiveOutputNode(n, group, op) for n in group]
