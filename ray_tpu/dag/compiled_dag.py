"""CompiledDAG: persistent per-actor exec loops over shm channels.

Reference: python/ray/dag/compiled_dag_node.py (CompiledDAG :664,
do_exec_tasks :133, ExecutableTask :345, execute :2118).  Compilation turns
a bound DAG into:

  * one long-running "exec loop" task per participating actor (submitted
    via the __apply__ mechanism, so user classes need no changes), running
    its nodes in topo order every iteration;
  * one SPSC shm ring channel per edge (driver->actor, actor->actor,
    actor->driver) — dag/channel.py over native/shm_channel.cc;
  * a driver facade: ``execute(v)`` writes v into the root channels and
    returns a CompiledDAGRef whose ``get()`` reads the leaf channels.

Pipelining: channels hold `nslots` versions, so up to nslots iterations run
concurrently across stages — this is the substrate for MPMD pipeline
parallelism across TPU slices (each stage actor owns a slice; the channel
carries host-staged activations between them.  Intra-slice tensors should
move via compiled ICI collectives, not channels).

Error semantics: a node exception becomes an error envelope that flows to
the leaf channels; CompiledDAGRef.get() re-raises it.  Teardown closes the
root channels; closure propagates node-to-node and the loops exit.
"""

from __future__ import annotations

import logging
import os
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

import ray_tpu

from .channel import (TAG_ERROR, TAG_INLINE, TAG_STOP, Channel,
                      ChannelClosed, ChannelTimeout)
from .dag_node import (ClassMethodNode, CollectiveOutputNode, DAGNode,
                       InputNode, MultiOutputNode)

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# The exec loop (runs inside each participating actor via __apply__)
# ---------------------------------------------------------------------------

def _dag_exec_loop(actor_self, plan: List[Dict[str, Any]],
                   chan_geometry: Tuple[int, int]) -> bool:
    """Run this actor's nodes forever (until stop/close).

    plan: topo-ordered node descriptors for THIS actor:
      {"method": str, "inputs": [("chan", path) | ("const", value)],
       "outputs": [path, ...]}
    """
    slot_bytes, nslots = chan_geometry
    in_chans: Dict[str, Channel] = {}
    out_chans: Dict[str, Channel] = {}
    for t in plan:
        for kind, src in t["inputs"]:
            if kind == "chan" and src not in in_chans:
                in_chans[src] = Channel(src, slot_bytes, nslots)
        for p in t["outputs"]:
            if p not in out_chans:
                out_chans[p] = Channel(p, slot_bytes, nslots)
    logger.info("dag exec loop up: plan=%s in=%s out=%s",
                [t["method"] for t in plan], list(in_chans),
                list(out_chans))

    def broadcast_stop():
        for c in out_chans.values():
            c.write_stop()
            c.close()

    try:
        while True:
            # Channels are read lazily, at the step that consumes them,
            # in plan (topo) order — NOT all-upfront.  Collectives make
            # the channel graph cyclic across actors (A⇄B contribution
            # exchange); upfront reads would deadlock, while plan-order
            # reads guarantee every contribution is written before the
            # collective step blocks on its peers.  Each channel is
            # still consumed exactly once per iteration (iter_vals).
            iter_vals: Dict[str, Any] = {}
            err: Optional[BaseException] = None
            stop = False

            def read_chan(path: str):
                nonlocal err, stop
                if path in iter_vals:
                    return
                try:
                    tag, v = in_chans[path].read()
                except (ChannelClosed, ChannelTimeout):
                    stop = True
                    iter_vals[path] = (TAG_STOP, None)
                    return
                if tag == TAG_STOP:
                    stop = True
                elif tag == TAG_ERROR and err is None:
                    err = v
                iter_vals[path] = (tag, v)

            node_out: Dict[str, Any] = {}
            for t in plan:
                # resolve inputs first, even under error: every channel
                # must be drained once per iteration to stay aligned
                args = []
                for kind, src in t["inputs"]:
                    if kind == "const":
                        args.append(src)
                    elif kind == "node":
                        args.append(node_out.get(src))
                    else:
                        read_chan(src)
                        if stop:
                            break
                        args.append(iter_vals[src][1])
                if stop:
                    break
                outs = [out_chans[p] for p in t["outputs"]]
                if err is not None:
                    for c in outs:
                        c.write_error(err)
                    continue
                try:
                    if t.get("builtin"):
                        # collective step: reduce own contribution with
                        # the peers' (reference: aDAG collective node —
                        # the reduction runs inside the actor loop)
                        from .collective import REDUCERS

                        out = REDUCERS[t["builtin"]](args)
                    else:
                        method = getattr(actor_self, t["method"])
                        out = method(*args)
                    node_out[t["key"]] = out
                    for c in outs:
                        c.write(out)
                except BaseException as e:  # node failure -> error envelope
                    err = e
                    for c in outs:
                        c.write_error(e)
            if stop:
                broadcast_stop()
                return True
    except BaseException:
        logger.exception("dag exec loop crashed")
        broadcast_stop()
        return False
    finally:
        for c in list(in_chans.values()) + list(out_chans.values()):
            c.release()


# ---------------------------------------------------------------------------
# Driver side
# ---------------------------------------------------------------------------

class CompiledDAGRef:
    """Future for one execute() iteration (reference: CompiledDAGRef)."""

    def __init__(self, dag: "CompiledDAG", seq: int):
        self._dag = dag
        self._seq = seq
        self._consumed = False

    def get(self, timeout: Optional[float] = 300.0):
        return self._dag._read_result(self, timeout)


class CompiledDAG:
    def __init__(self, root: DAGNode, *, buffer_size_bytes: int = 1 << 20,
                 nslots: int = 4):
        self._root = root
        self._slot_bytes = buffer_size_bytes
        self._nslots = nslots
        self._lock = threading.Lock()
        self._seq_submitted = 0
        self._seq_read = 0
        self._results: Dict[int, Any] = {}
        self._torn_down = False

        nodes = root.topo_sort()
        self._input_nodes = [n for n in nodes if isinstance(n, InputNode)]
        if isinstance(root, MultiOutputNode):
            self._leaves = list(root.outputs)
        else:
            self._leaves = [root]
        body = [n for n in nodes
                if isinstance(n, (ClassMethodNode, CollectiveOutputNode))]
        if not any(isinstance(n, ClassMethodNode) for n in body):
            raise ValueError("compiled DAG needs at least one actor node")
        for n in nodes:
            if not isinstance(n, (InputNode, ClassMethodNode,
                                  CollectiveOutputNode, MultiOutputNode)):
                raise TypeError(
                    f"compiled DAGs support actor-method, collective and "
                    f"input nodes only, got {n!r}")

        from ray_tpu._private.api import current_core

        core = current_core()
        store_root = getattr(getattr(core, "store", None), "root", None)
        session_dir = (os.path.dirname(store_root) if store_root
                       else "/dev/shm/ray_tpu_dag")
        base = os.path.join(session_dir, "channels", uuid.uuid4().hex[:12])
        os.makedirs(base, exist_ok=True)
        self._chan_dir = base

        def edge_path(src: DAGNode, dst_desc: str) -> str:
            return os.path.join(base, f"e{src._id}-{dst_desc}")

        # group nodes per actor, build channel edges
        per_actor: Dict[str, Dict[str, Any]] = {}
        consumer_counts: Dict[int, int] = {}
        self._input_chan_paths: List[str] = []
        self._leaf_chan_paths: List[str] = []

        for n in body:
            aid = n.handle._actor_id
            per_actor.setdefault(aid, {"handle": n.handle, "plan": []})

        def dep_input(a: DAGNode, aid: str, consumer: DAGNode):
            """Wire one upstream value into `consumer` on actor `aid`."""
            if a.handle._actor_id == aid:
                # same actor: direct value handoff, no channel
                return ("node", f"n{a._id}")
            p = edge_path(a, f"a{aid[:8]}-{consumer._id}")
            consumer_counts[a._id] = consumer_counts.get(a._id, 0) + 1
            per_actor[a.handle._actor_id].setdefault(
                "extra_out", {}).setdefault(a._id, []).append(p)
            return ("chan", p)

        for n in body:
            aid = n.handle._actor_id
            inputs = []
            if isinstance(n, CollectiveOutputNode):
                # collective step: own contribution by direct handoff,
                # every peer's over a channel (reference: collective_node
                # — the aDAG schedules one send/recv pair per peer)
                for c in n.group:
                    inputs.append(dep_input(c, aid, n))
                per_actor[aid]["plan"].append(
                    {"key": f"n{n._id}", "node_id": n._id,
                     "method": f"allreduce_{n.op}", "builtin": n.op,
                     "inputs": inputs, "outputs": []})
                continue
            for a in list(n.args) + list(n.kwargs.values()):
                if isinstance(a, InputNode):
                    p = edge_path(a, f"a{aid[:8]}-{n._id}")
                    inputs.append(("chan", p))
                    if p not in self._input_chan_paths:
                        self._input_chan_paths.append(p)
                elif isinstance(a, (ClassMethodNode, CollectiveOutputNode)):
                    inputs.append(dep_input(a, aid, n))
                elif isinstance(a, DAGNode):
                    raise TypeError(f"unsupported arg node {a!r}")
                else:
                    inputs.append(("const", a))
            per_actor[aid]["plan"].append(
                {"key": f"n{n._id}", "node_id": n._id, "method": n.method_name,
                 "inputs": inputs, "outputs": []})

        for leaf in self._leaves:
            if not isinstance(leaf, (ClassMethodNode, CollectiveOutputNode)):
                raise TypeError("DAG leaves must be actor-method or "
                                "collective nodes")
            p = edge_path(leaf, "driver")
            self._leaf_chan_paths.append(p)
            aid = leaf.handle._actor_id
            for t in per_actor[aid]["plan"]:
                if t["node_id"] == leaf._id:
                    t["outputs"].append(p)

        for aid, desc in per_actor.items():
            for t in desc["plan"]:
                extra = desc.get("extra_out", {}).get(t["node_id"], [])
                t["outputs"].extend(extra)

        # driver endpoints (create channels before the loops attach)
        geometry = (self._slot_bytes, self._nslots)
        self._input_chans = [Channel(p, *geometry)
                             for p in self._input_chan_paths]
        self._leaf_chans = [Channel(p, *geometry)
                            for p in self._leaf_chan_paths]

        # launch the per-actor loops
        self._loop_refs = []
        for aid, desc in per_actor.items():
            ref = desc["handle"]._actor_call(
                _dag_exec_loop, desc["plan"], geometry)
            self._loop_refs.append(ref)

    # -- execution ----------------------------------------------------------

    def execute(self, *args) -> CompiledDAGRef:
        with self._lock:
            if self._torn_down:
                raise RuntimeError("DAG was torn down")
            value = args[0] if len(args) == 1 else args
            for c in self._input_chans:
                c.write(value, timeout_s=300.0)
            self._seq_submitted += 1
            return CompiledDAGRef(self, self._seq_submitted - 1)

    def _check_loops_alive(self):
        """Surface an exec-loop crash (actor died, channel open failure)
        instead of letting the caller block into a timeout."""
        import ray_tpu

        done, _ = ray_tpu.wait(self._loop_refs,
                               num_returns=len(self._loop_refs),
                               timeout=0.001)
        for r in done:
            try:
                ray_tpu.get(r, timeout=1.0)
                raise RuntimeError(
                    "compiled DAG exec loop exited unexpectedly")
            except (RuntimeError,):
                raise
            except BaseException as e:
                raise RuntimeError(
                    f"compiled DAG exec loop died: {e}") from e

    def _read_leaf(self, c: Channel, timeout: Optional[float]):
        """Read one leaf value, polling in slices so a dead exec loop
        raises its real error instead of a bare ChannelTimeout."""
        deadline = None if timeout is None else \
            (time.monotonic() + timeout)
        while True:
            slice_s = 1.0 if deadline is None else \
                min(1.0, max(deadline - time.monotonic(), 0.01))
            try:
                return c.read(timeout_s=slice_s)
            except ChannelTimeout:
                self._check_loops_alive()
                if deadline is not None and time.monotonic() > deadline:
                    raise

    def _read_result(self, ref: CompiledDAGRef, timeout: Optional[float]):
        with self._lock:
            if ref._consumed:
                return self._results.pop(ref._seq)
            while self._seq_read <= ref._seq:
                outs = []
                for c in self._leaf_chans:
                    tag, v = self._read_leaf(c, timeout)
                    if tag == TAG_STOP:
                        raise ChannelClosed("DAG torn down mid-read")
                    outs.append((tag, v))
                seq = self._seq_read
                self._seq_read += 1
                errs = [v for tag, v in outs if tag == TAG_ERROR]
                if errs:
                    result = errs[0]
                    is_err = True
                else:
                    vals = [v for _, v in outs]
                    result = vals[0] if not isinstance(
                        self._root, MultiOutputNode) else vals
                    is_err = False
                if seq == ref._seq:
                    if is_err:
                        raise result
                    return result
                self._results[seq] = result
        raise RuntimeError("unreachable")

    # -- lifecycle ----------------------------------------------------------

    def teardown(self, timeout_s: float = 10.0):
        with self._lock:
            if self._torn_down:
                return
            self._torn_down = True
        for c in self._input_chans:
            c.write_stop()
            c.close()
        # close leaf channels too: a loop blocked writing an unread result
        # must wake (ChannelClosed) instead of stranding the actor
        for c in self._leaf_chans:
            c.close()
        try:
            ray_tpu.wait(self._loop_refs, num_returns=len(self._loop_refs),
                         timeout=timeout_s)
        except Exception:
            pass
        for c in self._input_chans + self._leaf_chans:
            c.release()
        try:
            import shutil

            shutil.rmtree(self._chan_dir, ignore_errors=True)
        except OSError:
            pass

    def __del__(self):
        try:
            self.teardown(timeout_s=2.0)
        except Exception:
            pass
