"""Python endpoints for native shared-memory ring channels.

Binding over ray_tpu/native/shm_channel.cc — the mutable-object transport
under compiled graphs (reference: experimental/channel/
shared_memory_channel.py over experimental_mutable_object_manager.h).

Value envelope (first byte):
  0x01  inline payload: serialization.dumps_inline bytes follow
  0x02  spilled payload: pickled ObjectRef follows (value was larger than
        the slot; it went through the object store instead)
  0x03  error: pickled exception follows (propagates through the DAG)
  0x04  device tensor: tiny (shape, dtype) header + RAW buffer bytes —
        the jax.Array fast path (see below)
  0x00  stop sentinel (teardown)

Device tensors (the NCCL-channel role, reference:
experimental/channel/torch_tensor_nccl_channel.py): a TPU stage actor
owns its own slice and jax runtime, so a cross-ACTOR edge necessarily
stages through host memory — the TPU in-slice analog of an NCCL device
channel is the *compiled* ppermute pipeline (parallel/pipeline.py), not a
runtime channel.  What the channel CAN eliminate is the serialization
tax: a jax.Array payload moves as one device->shm copy on the writer and
one shm->device copy on the reader (raw dtype bytes, no pickle of the
array data on either side).
"""

from __future__ import annotations

import ctypes
import os
import struct
from typing import Any, Optional, Tuple

import cloudpickle

from ray_tpu._private import serialization

TAG_STOP = 0
TAG_INLINE = 1
TAG_SPILLED = 2
TAG_ERROR = 3
TAG_DEVICE = 4

DEFAULT_SLOT_BYTES = 1 << 20
DEFAULT_NSLOTS = 4

_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    from ray_tpu.native.build import load_library

    lib = load_library("shm_channel", ["shm_channel.cc"])
    lib.rt_chan_open.restype = ctypes.c_void_p
    lib.rt_chan_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                 ctypes.c_uint32]
    lib.rt_chan_close_handle.argtypes = [ctypes.c_void_p]
    lib.rt_chan_slot_size.restype = ctypes.c_uint64
    lib.rt_chan_slot_size.argtypes = [ctypes.c_void_p]
    lib.rt_chan_write_acquire.restype = ctypes.c_int64
    lib.rt_chan_write_acquire.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.rt_chan_write_release.restype = ctypes.c_int
    lib.rt_chan_write_release.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.rt_chan_read_acquire.restype = ctypes.c_int64
    lib.rt_chan_read_acquire.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64), ctypes.c_int64]
    lib.rt_chan_read_release.restype = ctypes.c_int
    lib.rt_chan_read_release.argtypes = [ctypes.c_void_p]
    lib.rt_chan_close.argtypes = [ctypes.c_void_p]
    lib.rt_chan_is_closed.restype = ctypes.c_int
    lib.rt_chan_is_closed.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


class ChannelClosed(Exception):
    pass


class ChannelTimeout(Exception):
    pass


class Channel:
    """One endpoint (this process may use it as writer, reader, or both in
    tests).  SPSC: exactly one writer process and one reader process."""

    def __init__(self, path: str, slot_bytes: int = DEFAULT_SLOT_BYTES,
                 nslots: int = DEFAULT_NSLOTS):
        self.path = path
        self._lib = _load()
        self._chan = self._lib.rt_chan_open(path.encode(), slot_bytes,
                                            nslots)
        if not self._chan:
            raise RuntimeError(f"rt_chan_open failed for {path}")
        self._slot = self._lib.rt_chan_slot_size(self._chan)
        self._fd = os.open(path, os.O_RDWR)
        self._mm = None  # lazily map the whole (small) channel file
        self._closed_handle = False

    def _map(self):
        if self._mm is None:
            import mmap

            size = os.fstat(self._fd).st_size
            self._mm = mmap.mmap(self._fd, size)
        return self._mm

    # -- write -------------------------------------------------------------

    @staticmethod
    def _device_path_enabled(jax) -> bool:
        """Raw-bytes tensor transport pays off when host staging replaces
        a full pickle of device memory (TPU/GPU); on the cpu backend jnp
        arrays already ARE host memory and the extra device_put dispatch
        makes it a net loss — so default on only for real accelerators.
        RAY_TPU_DAG_DEVICE_CHANNEL=1/0 forces either way (tests)."""
        env = os.environ.get("RAY_TPU_DAG_DEVICE_CHANNEL")
        if env is not None:
            return env.strip().lower() not in ("0", "false", "no", "off",
                                               "")
        try:
            return jax.default_backend() != "cpu"
        except Exception:
            return False

    @classmethod
    def _as_device_array(cls, value):
        """The jax.Array fast-path guard (no jax import when unused)."""
        import sys

        jax = sys.modules.get("jax")
        if jax is None or not isinstance(value, jax.Array) \
                or value.is_deleted():
            return None
        # extended dtypes (PRNG keys) have no raw-bytes form: pickle path
        if jax.dtypes.issubdtype(value.dtype, jax.dtypes.extended):
            return None
        if not cls._device_path_enabled(jax):
            return None
        return value

    def write(self, value: Any, timeout_s: Optional[float] = None):
        arr = self._as_device_array(value)
        if arr is not None:
            import numpy as np

            meta = cloudpickle.dumps((tuple(arr.shape), str(arr.dtype)))
            header = struct.pack("<I", len(meta))
            if 1 + len(header) + len(meta) + arr.nbytes <= self._slot:
                # device -> shm in ONE copy (np.asarray is the host
                # staging; zero-copy on the cpu backend, one DMA on TPU)
                self._write_device(header + meta, np.asarray(arr),
                                   timeout_s)
                return
        payload = serialization.dumps_inline(value)
        if 1 + len(payload) > self._slot:
            import ray_tpu

            ref = ray_tpu.put(value)
            # dumps_inline swaps the ref for a SerializedRef marker so the
            # reader re-wraps it with borrower ref-counting intact
            payload = serialization.dumps_inline(ref)
            tag = TAG_SPILLED
        else:
            tag = TAG_INLINE
        self._write_raw(tag, payload, timeout_s)

    def _write_device(self, head: bytes, host_view,
                      timeout_s: Optional[float]):
        import numpy as np

        t_us = -1 if timeout_s is None else int(timeout_s * 1e6)
        off = self._lib.rt_chan_write_acquire(self._chan, t_us)
        if off == -3:
            raise ChannelClosed(self.path)
        if off == -2:
            raise ChannelTimeout(self.path)
        mm = self._map()
        mm[off] = TAG_DEVICE
        pos = off + 1
        mm[pos:pos + len(head)] = head
        pos += len(head)
        # raw dtype bytes straight into the ring slot — no pickle copy
        dst = np.frombuffer(memoryview(mm)[pos:pos + host_view.nbytes],
                            np.uint8)
        dst[:] = host_view.reshape(-1).view(np.uint8)
        self._lib.rt_chan_write_release(
            self._chan, 1 + len(head) + host_view.nbytes)

    def write_error(self, exc: BaseException,
                    timeout_s: Optional[float] = None):
        try:
            payload = cloudpickle.dumps(exc)
        except BaseException:
            payload = cloudpickle.dumps(
                RuntimeError(f"{type(exc).__name__}: {exc}"))
        self._write_raw(TAG_ERROR, payload, timeout_s)

    def write_stop(self, timeout_s: Optional[float] = 1.0):
        try:
            self._write_raw(TAG_STOP, b"", timeout_s)
        except (ChannelClosed, ChannelTimeout):
            pass

    def _write_raw(self, tag: int, payload: bytes,
                   timeout_s: Optional[float]):
        if 1 + len(payload) > self._slot:
            raise ValueError(
                f"payload of {len(payload)}B exceeds channel slot "
                f"{self._slot}B even after spilling")
        t_us = -1 if timeout_s is None else int(timeout_s * 1e6)
        off = self._lib.rt_chan_write_acquire(self._chan, t_us)
        if off == -3:
            raise ChannelClosed(self.path)
        if off == -2:
            raise ChannelTimeout(self.path)
        mm = self._map()
        mm[off] = tag
        mm[off + 1:off + 1 + len(payload)] = payload
        self._lib.rt_chan_write_release(self._chan, 1 + len(payload))

    # -- read --------------------------------------------------------------

    def read(self, timeout_s: Optional[float] = None) -> Tuple[int, Any]:
        """Returns (tag, value).  Raises ChannelClosed / ChannelTimeout."""
        t_us = -1 if timeout_s is None else int(timeout_s * 1e6)
        nbytes = ctypes.c_uint64(0)
        off = self._lib.rt_chan_read_acquire(self._chan,
                                             ctypes.byref(nbytes), t_us)
        if off == -3:
            raise ChannelClosed(self.path)
        if off == -2:
            raise ChannelTimeout(self.path)
        mm = self._map()
        try:
            tag = mm[off]
            if tag == TAG_DEVICE:
                # device tensors transfer straight off the ring slot:
                # ONE shm -> device copy, synchronized before the slot is
                # released for reuse.  The tag is surfaced so tests can
                # observe the fast path; consumers treat any
                # non-STOP/ERROR tag as a value
                import jax
                import numpy as np

                view = memoryview(mm)[off + 1:off + nbytes.value]
                try:
                    (meta_len,) = struct.unpack_from("<I", view, 0)
                    shape, dtype = cloudpickle.loads(
                        bytes(view[4:4 + meta_len]))
                    # stage into an OWNED host buffer (one copy), then
                    # device_put: on cpu device_put may alias its input,
                    # and an alias of ring-slot memory would be
                    # overwritten by the next writer
                    host = np.empty(len(view) - 4 - meta_len, np.uint8)
                    host[:] = np.frombuffer(view, np.uint8,
                                            offset=4 + meta_len)
                    arr = jax.device_put(host.view(dtype).reshape(shape))
                finally:
                    view.release()
                return TAG_DEVICE, arr
            payload = bytes(mm[off + 1:off + nbytes.value])
        finally:
            self._lib.rt_chan_read_release(self._chan)
        if tag == TAG_INLINE:
            return tag, serialization.loads_inline(payload)
        if tag == TAG_SPILLED:
            import ray_tpu

            ref = serialization.loads_inline(payload)
            return TAG_INLINE, ray_tpu.get(ref, timeout=300.0)
        if tag == TAG_ERROR:
            return tag, cloudpickle.loads(payload)
        return TAG_STOP, None

    # -- lifecycle ---------------------------------------------------------

    def close(self):
        """Mark the channel closed (wakes both sides)."""
        if not self._closed_handle:
            self._lib.rt_chan_close(self._chan)

    def release(self):
        if self._closed_handle:
            return
        self._closed_handle = True
        try:
            if self._mm is not None:
                self._mm.close()
            os.close(self._fd)
        except OSError:
            pass
        self._lib.rt_chan_close_handle(self._chan)
        self._chan = None

    def __del__(self):
        try:
            self.release()
        except Exception:
            pass
