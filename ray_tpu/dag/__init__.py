"""ray_tpu.dag: lazily-bound DAGs + compiled multi-actor graphs.

Reference: python/ray/dag/ (2.4k LoC compiled_dag_node.py) +
experimental/channel/.  See compiled_dag.py for the TPU-native design.
"""

from .channel import Channel, ChannelClosed, ChannelTimeout
from .collective import allreduce_bind
from .compiled_dag import CompiledDAG, CompiledDAGRef
from .dag_node import (ClassMethodNode, CollectiveOutputNode, DAGNode,
                       FunctionNode, InputNode, MultiOutputNode)

__all__ = [
    "Channel", "ChannelClosed", "ChannelTimeout", "ClassMethodNode",
    "CollectiveOutputNode", "CompiledDAG", "CompiledDAGRef", "DAGNode",
    "FunctionNode", "InputNode", "MultiOutputNode", "allreduce_bind",
]
