"""DAG node API: lazily-bound task graphs over actors.

Reference: python/ray/dag/ (dag_node.py, class_node.py, input_node.py) —
``actor.method.bind(x)`` builds a node; ``dag.execute(v)`` runs it
interpreted (one actor task per node per call); ``experimental_compile()``
returns a CompiledDAG with persistent per-actor exec loops over
shared-memory channels (compiled_dag.py).
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional, Tuple

_node_counter = itertools.count()


class DAGNode:
    def __init__(self):
        self._id = next(_node_counter)

    # -- graph walking ------------------------------------------------------

    def _upstream(self) -> List["DAGNode"]:
        return []

    def topo_sort(self) -> List["DAGNode"]:
        order: List[DAGNode] = []
        seen = set()

        def walk(n: "DAGNode"):
            if id(n) in seen:
                return
            seen.add(id(n))
            for u in n._upstream():
                walk(u)
            order.append(n)

        walk(self)
        return order

    # -- interpreted execution ---------------------------------------------

    def execute(self, *args, _timeout: Optional[float] = None):
        """Run the DAG once, interpreted: one actor task per node
        (reference: dag_node.py execute)."""
        values: Dict[int, Any] = {}
        for node in self.topo_sort():
            values[node._id] = node._exec_interpreted(values, args)
        return values[self._id]

    def _exec_interpreted(self, values: Dict[int, Any], args: Tuple) -> Any:
        raise NotImplementedError

    def experimental_compile(self, *, buffer_size_bytes: int = 1 << 20,
                             nslots: int = 4):
        from .compiled_dag import CompiledDAG

        return CompiledDAG(self, buffer_size_bytes=buffer_size_bytes,
                           nslots=nslots)


def _resolve(arg: Any, values: Dict[int, Any], input_args: Tuple) -> Any:
    if isinstance(arg, DAGNode):
        v = values[arg._id]
        return v
    return arg


class InputNode(DAGNode):
    """The DAG's runtime argument (reference: dag/input_node.py).  Usable
    as a context manager for parity with the reference:

        with InputNode() as inp:
            dag = a.fwd.bind(inp)
    """

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def _exec_interpreted(self, values, args):
        if len(args) == 1:
            return args[0]
        return args

    def __repr__(self):
        return f"InputNode({self._id})"


class ClassMethodNode(DAGNode):
    def __init__(self, handle, method_name: str, args: Tuple,
                 kwargs: Dict[str, Any]):
        super().__init__()
        self.handle = handle
        self.method_name = method_name
        self.args = args
        self.kwargs = kwargs

    def _upstream(self):
        return [a for a in list(self.args) + list(self.kwargs.values())
                if isinstance(a, DAGNode)]

    def _exec_interpreted(self, values, input_args):
        import ray_tpu

        args = [_resolve(a, values, input_args) for a in self.args]
        kwargs = {k: _resolve(v, values, input_args)
                  for k, v in self.kwargs.items()}
        # upstream interpreted results are ObjectRefs: pass through so the
        # runtime pipelines them (no driver round-trip); input values pass
        # as-is
        method = getattr(self.handle, self.method_name)
        return method.remote(*args, **kwargs)

    def __repr__(self):
        return (f"ClassMethodNode({self.handle._class_name}."
                f"{self.method_name}#{self._id})")


class FunctionNode(DAGNode):
    """A bound stateless task (interpreted mode only)."""

    def __init__(self, remote_fn, args: Tuple, kwargs: Dict[str, Any]):
        super().__init__()
        self.remote_fn = remote_fn
        self.args = args
        self.kwargs = kwargs

    def _upstream(self):
        return [a for a in list(self.args) + list(self.kwargs.values())
                if isinstance(a, DAGNode)]

    def _exec_interpreted(self, values, input_args):
        args = [_resolve(a, values, input_args) for a in self.args]
        kwargs = {k: _resolve(v, values, input_args)
                  for k, v in self.kwargs.items()}
        return self.remote_fn.remote(*args, **kwargs)

    def __repr__(self):
        return f"FunctionNode({self.remote_fn._fn.__name__}#{self._id})"


class CollectiveOutputNode(DAGNode):
    """One participant's output of a cross-actor collective inside a
    compiled DAG (reference: dag/collective_node.py:19,93 — aDAG
    allreduce over NCCL channels; here the reduction data plane is the
    shm channel mesh between the participating actors).

    Built via :func:`ray_tpu.dag.allreduce_bind`; each contributor
    (a ClassMethodNode) yields one CollectiveOutputNode carrying the
    reduced value on that contributor's actor."""

    def __init__(self, contributor: "ClassMethodNode",
                 group: List["ClassMethodNode"], op: str):
        super().__init__()
        self.contributor = contributor
        self.group = group
        self.op = op
        self.handle = contributor.handle

    def _upstream(self):
        return list(self.group)

    def _exec_interpreted(self, values, input_args):
        # interpreted mode: materialize every contribution at the driver
        # and reduce (compiled mode reduces inside the actors)
        import ray_tpu

        from .collective import REDUCERS

        vals = [values[c._id] for c in self.group]
        vals = [ray_tpu.get(v) if isinstance(v, ray_tpu.ObjectRef) else v
                for v in vals]
        return REDUCERS[self.op](vals)

    def __repr__(self):
        return (f"CollectiveOutputNode({self.op}@"
                f"{self.handle._class_name}#{self._id})")


class MultiOutputNode(DAGNode):
    """Bundle several leaves as the DAG output (reference:
    dag/output_node.py)."""

    def __init__(self, outputs: List[DAGNode]):
        super().__init__()
        self.outputs = list(outputs)

    def _upstream(self):
        return list(self.outputs)

    def _exec_interpreted(self, values, input_args):
        return [values[o._id] for o in self.outputs]

    def __repr__(self):
        return f"MultiOutputNode({len(self.outputs)})"
