"""MPMD pipeline parallelism: per-stage jit programs on separate gangs.

The SPMD path (parallel/pipeline.py) compiles ONE program in which every
pp rank holds every stage's schedule — model depth is capped by what a
single compiled program can hold, and the fill-drain loop burns a
(n-1)/(M+n-1) bubble every step.  Here the model is partitioned into
per-stage programs (models/gpt.py partition_stage_params / stage_hidden /
stage_loss), each compiled and run by its own gang scheduler, with
activation and gradient edges flowing over the dag/ device-tensor channel
envelope (dag/channel.py TAG_DEVICE 0x04: one device->shm raw-buffer copy
per hop, no pickle of array data).

Schedules (one `PipelineSchedule` interface):
  fill_drain  all M forwards, then all M backwards (GPipe)
  1f1b        warmup F's, steady (F,B) pairs, drain B's — same bubble as
              fill-drain but activation stash bounded by pipeline depth
  zb          zero-bubble (ZB-H1 family): backward split into Bx (input
              grad only — XLA DCEs the weight-grad einsums) and W (weight
              grads only); W ops run opportunistically whenever the gang
              would otherwise idle in a channel wait, filling the warmup/
              drain bubbles

Bubble measurement: on a host with fewer cores than stages, wall-clock
interleaving is serialization noise, so `replay_bubble` replays the
recorded per-op durations and p2p edge costs in *virtual time* — each
stage gets a dedicated executor, op start = max(executor free, dependency
ready + edge cost) — recovering the schedule's intrinsic bubble structure.
Per-stage metrics land in the flight recorder as dotted sub-phases
(`pipeline.fwd` / `pipeline.bwd` / `pipeline.p2p` / `pipeline.idle`), so
chrome traces show the schedule visually.

Elastic: each stage commits params at step boundaries and snapshots them
through elastic.emergency's peer-replicated vault; a dead stage gang is
respawned from its `EmergencyCheckpoint` while survivors roll back to the
committed step — the pipeline never collapses.
"""

from __future__ import annotations

import collections
import dataclasses
import os
import queue
import shutil
import tempfile
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

SCHEDULES = ("fill_drain", "1f1b", "zb")

_ENV_SPEC = "RAY_TPU_TRAIN_PIPELINE"


@dataclasses.dataclass
class PipelineConfig:
    """MPMD pipeline shape (JaxConfig.pipeline / MPMDPipeline).

    stages: number of pipeline stages (one gang each).
    schedule: "fill_drain" | "1f1b" | "zb".
    microbatches: per-step microbatch count M (default = stages).
    transport: "threads" (in-process gangs, shm channels — tests/bench)
               or "actors" (one ray_tpu actor per stage gang).
    grad_sync_group: when set, each stage syncs its grads through
        GradientSynchronizer over collective group "<name>-s<stage>"
        (per-stage bucketed async allreduce for dp>1 gangs).
    snapshot_every: emergency-vault snapshot cadence in steps.
    """

    stages: int = 2
    schedule: str = "1f1b"
    microbatches: Optional[int] = None
    transport: str = "threads"
    grad_sync_group: Optional[str] = None
    snapshot_every: int = 1
    slot_bytes: int = 8 << 20
    nslots: int = 4

    def __post_init__(self):
        if self.schedule not in SCHEDULES:
            raise ValueError(f"unknown schedule {self.schedule!r}; "
                             f"one of {SCHEDULES}")
        if self.stages < 1:
            raise ValueError(f"stages must be >= 1, got {self.stages}")
        if self.transport not in ("threads", "actors"):
            raise ValueError(f"unknown transport {self.transport!r}")
        if self.microbatches is not None and self.microbatches < 1:
            raise ValueError("microbatches must be >= 1")

    @property
    def num_microbatches(self) -> int:
        return self.microbatches or self.stages

    def to_spec(self) -> str:
        """Env-var form (see from_spec): the train backend publishes this
        to workers as RAY_TPU_TRAIN_PIPELINE."""
        parts = [f"stages={self.stages}", f"schedule={self.schedule}",
                 f"microbatches={self.num_microbatches}",
                 f"transport={self.transport}"]
        if self.grad_sync_group:
            parts.append(f"grad_sync_group={self.grad_sync_group}")
        if self.snapshot_every != 1:
            parts.append(f"snapshot_every={self.snapshot_every}")
        return ",".join(parts)

    @classmethod
    def from_spec(cls, spec: str) -> "PipelineConfig":
        kw: Dict[str, Any] = {}
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(f"bad pipeline spec item {part!r} "
                                 f"in {spec!r}")
            k, v = part.split("=", 1)
            k = k.strip()
            if k in ("stages", "microbatches", "snapshot_every",
                     "slot_bytes", "nslots"):
                kw[k] = int(v)
            elif k in ("schedule", "transport", "grad_sync_group"):
                kw[k] = v.strip()
            else:
                raise ValueError(f"unknown pipeline spec key {k!r}")
        return cls(**kw)

    @classmethod
    def from_env(cls) -> Optional["PipelineConfig"]:
        spec = os.environ.get(_ENV_SPEC, "").strip()
        return cls.from_spec(spec) if spec else None


# ---------------------------------------------------------------------------
# Schedule library


class PipelineSchedule:
    """Per-stage op streams behind one interface.

    ops() returns the ordered (kind, microbatch) list one gang scheduler
    executes: kind in {"F", "B", "Bx", "W"}.  Cross-stage consistency is
    the schedule's contract — stage s emits sends in exactly the order
    stage s±1 posts the matching recvs.
    """

    name = "base"
    split_backward = False  # zb: B split into Bx (input grad) + W (weights)

    def ops(self, stage: int, stages: int, microbatches: int
            ) -> List[Tuple[str, int]]:
        raise NotImplementedError

    @staticmethod
    def theoretical_fill_drain_bubble(stages: int, microbatches: int
                                      ) -> float:
        """(n-1)/(M+n-1): the GPipe bubble both SPMD pipeline.py and the
        fill_drain schedule here pay — the floor MPMD schedules beat."""
        n, m = stages, microbatches
        return (n - 1) / (m + n - 1) if n > 1 else 0.0


class FillDrain(PipelineSchedule):
    name = "fill_drain"

    def ops(self, stage, stages, microbatches):
        # backwards in LIFO order (the GPipe activation stack)
        return ([("F", i) for i in range(microbatches)]
                + [("B", i) for i in reversed(range(microbatches))])


class OneFOneB(PipelineSchedule):
    name = "1f1b"

    def ops(self, stage, stages, microbatches):
        warm = min(microbatches, stages - 1 - stage)
        out = [("F", i) for i in range(warm)]
        b = 0
        for f in range(warm, microbatches):
            out.append(("F", f))
            out.append(("B", b))
            b += 1
        out.extend(("B", i) for i in range(b, microbatches))
        return out


class ZeroBubble(OneFOneB):
    """1F1B skeleton with B split into Bx + W.  The W ops listed at the
    tail are a completeness fallback: the gang scheduler runs pending W's
    early whenever a channel wait would otherwise idle the gang."""

    name = "zb"
    split_backward = True

    def ops(self, stage, stages, microbatches):
        base = super().ops(stage, stages, microbatches)
        out = [("Bx", mb) if kind == "B" else (kind, mb)
               for kind, mb in base]
        out.extend(("W", i) for i in range(microbatches))
        return out


_SCHEDULE_CLASSES = {c.name: c for c in (FillDrain, OneFOneB, ZeroBubble)}


def get_schedule(name: str) -> PipelineSchedule:
    try:
        return _SCHEDULE_CLASSES[name]()
    except KeyError:
        raise ValueError(f"unknown schedule {name!r}; one of {SCHEDULES}")


# ---------------------------------------------------------------------------
# Bubble measurement: virtual-time replay of the recorded event log


def replay_bubble(events_by_stage: List[List[Dict[str, Any]]]
                  ) -> Dict[str, Any]:
    """Replay per-op durations against the schedule's dependency graph.

    Dependencies: F(s,mb) needs F(s-1,mb) + fwd edge cost; B/Bx(s,mb)
    needs B/Bx(s+1,mb) + bwd edge cost (last stage: its own F(mb));
    W(s,mb) needs Bx(s,mb).  Edge cost = measured send dur (writer) +
    recv dur (reader).  Per-stage ops execute in recorded order on a
    dedicated virtual executor, so a W that really ran inside a channel
    wait replays inside the same gap.

    Returns per-stage bubble fractions (1 - busy/span), their mean (the
    headline metric) and max, and the virtual makespan.
    """
    n = len(events_by_stage)
    comp: List[List[Tuple[str, int, float]]] = [[] for _ in range(n)]
    edge_f: List[Dict[int, float]] = [dict() for _ in range(n)]
    edge_b: List[Dict[int, float]] = [dict() for _ in range(n)]
    for s, evs in enumerate(events_by_stage):
        for e in evs:
            k, mb, dur = e["kind"], e.get("mb", -1), e["dur"]
            if k in ("F", "B", "Bx", "W"):
                comp[s].append((k, mb, dur))
            elif k == "send_f":
                edge_f[s][mb] = edge_f[s].get(mb, 0.0) + dur
            elif k == "recv_f" and s > 0:
                edge_f[s - 1][mb] = edge_f[s - 1].get(mb, 0.0) + dur
            elif k == "send_b":
                edge_b[s][mb] = edge_b[s].get(mb, 0.0) + dur
            elif k == "recv_b" and s + 1 < n:
                edge_b[s + 1][mb] = edge_b[s + 1].get(mb, 0.0) + dur

    end: Dict[Tuple[int, str, int], float] = {}

    def dep_ready(s: int, kind: str, mb: int) -> Optional[float]:
        if kind == "F":
            if s == 0:
                return 0.0
            t = end.get((s - 1, "F", mb))
            return None if t is None else t + edge_f[s - 1].get(mb, 0.0)
        if kind in ("B", "Bx"):
            if s == n - 1:
                return end.get((s, "F", mb))
            t = end.get((s + 1, "B", mb))
            return None if t is None else t + edge_b[s + 1].get(mb, 0.0)
        return end.get((s, "B", mb))  # W

    idx = [0] * n
    cursor = [0.0] * n
    first = [None] * n
    last = [0.0] * n
    busy = [0.0] * n
    total = sum(len(c) for c in comp)
    done = 0
    while done < total:
        progressed = False
        for s in range(n):
            while idx[s] < len(comp[s]):
                kind, mb, dur = comp[s][idx[s]]
                dep = dep_ready(s, kind, mb)
                if dep is None:
                    break
                t0 = max(cursor[s], dep)
                t1 = t0 + dur
                cursor[s] = t1
                key = "B" if kind in ("B", "Bx") else kind
                end[(s, key, mb)] = t1
                if first[s] is None:
                    first[s] = t0
                last[s] = t1
                busy[s] += dur
                idx[s] += 1
                done += 1
                progressed = True
        if not progressed:
            missing = [(s, comp[s][idx[s]]) for s in range(n)
                       if idx[s] < len(comp[s])]
            raise RuntimeError(f"replay deadlock; blocked ops: {missing}")

    bubbles = []
    for s in range(n):
        span = last[s] - (first[s] or 0.0)
        bubbles.append(0.0 if span <= 0 else max(0.0, 1 - busy[s] / span))
    return {
        "per_stage": bubbles,
        "mean": sum(bubbles) / max(1, n),
        "max": max(bubbles) if bubbles else 0.0,
        "span_s": max(last) if n else 0.0,
    }


_TRACE_NAMES = {"F": "pipeline.fwd", "B": "pipeline.bwd",
                "Bx": "pipeline.bwd", "W": "pipeline.bwd_w",
                "send_f": "pipeline.p2p", "recv_f": "pipeline.p2p",
                "send_b": "pipeline.p2p", "recv_b": "pipeline.p2p",
                "send_tie": "pipeline.p2p", "recv_tie": "pipeline.p2p",
                "wait": "pipeline.idle"}


def schedule_chrome_trace(events_by_stage: List[List[Dict[str, Any]]]
                          ) -> List[Dict[str, Any]]:
    """Per-op chrome trace (one pid per stage): load in chrome://tracing
    or Perfetto to SEE the schedule — F/B/W slices, p2p edges, idle."""
    out: List[Dict[str, Any]] = []
    t_base = min((e["t0"] for evs in events_by_stage for e in evs),
                 default=0.0)
    for s, evs in enumerate(events_by_stage):
        out.append({"ph": "M", "pid": s, "tid": 0, "name": "process_name",
                    "args": {"name": f"pipeline stage {s}"}})
        for e in evs:
            out.append({
                "ph": "X", "pid": s, "tid": 0,
                "name": _TRACE_NAMES.get(e["kind"], e["kind"]),
                "cat": "pipeline",
                "ts": (e["t0"] - t_base) * 1e6,
                "dur": max(0.01, e["dur"] * 1e6),
                "args": {"kind": e["kind"], "mb": e.get("mb", -1)},
            })
    return out


# ---------------------------------------------------------------------------
# Stage runtime: one gang's programs, params, and scheduler loop


def _add_trees(a, b):
    import jax

    return jax.tree.map(lambda x, y: x + y, a, b)


_PROG_LOCK = threading.Lock()
_PROGRAM_CACHE: Dict[Any, Dict[str, Any]] = {}  # guarded-by: _PROG_LOCK


def _stage_programs(cfg, stage: int, stages: int) -> Dict[str, Any]:
    """fwd/bwd/bwd_x/bwd_w programs for one stage slice, memoized
    process-wide — GPTConfig is frozen/hashable, so (cfg, stage, stages)
    is a stable key and rebuilding a pipeline (elastic recovery on a
    surviving host, repeated construction in one process) reuses the XLA
    executables instead of re-tracing and recompiling every stage."""
    key = (cfg, stage, stages)
    with _PROG_LOCK:
        progs = _PROGRAM_CACHE.get(key)
    if progs is not None:
        return progs

    import jax
    import jax.numpy as jnp

    from ray_tpu.models import gpt
    from ray_tpu.telemetry import device as devtel

    n, s = stages, stage
    first, last = s == 0, s == stages - 1
    tag = f"mpmd.s{s}of{n}"

    if last:
        def full(p, x, tgt):
            def f(p_, x_):
                return gpt.stage_loss(p_, x_, tgt, cfg, s, n)

            if first:  # single-stage pipeline: no input grad needed
                loss, vjp = jax.vjp(lambda p_: f(p_, x), p)
                (dp,) = vjp(jnp.ones_like(loss))
                return x, dp
            loss, vjp = jax.vjp(f, p, x)
            dp, dx = vjp(jnp.ones_like(loss))
            return dx, dp

        progs = {
            "fwd": devtel.instrument(jax.jit(
                lambda p, x, tgt: gpt.stage_loss(p, x, tgt, cfg, s, n)),
                name=f"{tag}.fwd"),
            "bwd": devtel.instrument(jax.jit(full), name=f"{tag}.bwd"),
            # zb split: jit of one output each — XLA dead-code-eliminates
            # the other half's einsums, so Bx carries no weight-grad work
            "bwd_x": devtel.instrument(
                jax.jit(lambda p, x, g: full(p, x, g)[0]),
                name=f"{tag}.bwd_x"),
            "bwd_w": devtel.instrument(
                jax.jit(lambda p, x, g: full(p, x, g)[1]),
                name=f"{tag}.bwd_w"),
        }
    elif first:
        def full0(p, x, g):
            _, vjp = jax.vjp(
                lambda p_: gpt.stage_hidden(p_, x, cfg, s, n), p)
            (dp,) = vjp(g)
            return dp

        bwd0 = devtel.instrument(jax.jit(full0), name=f"{tag}.bwd")
        progs = {
            "fwd": devtel.instrument(
                jax.jit(lambda p, x: gpt.stage_hidden(p, x, cfg, s, n)),
                name=f"{tag}.fwd"),
            "bwd": bwd0,
            "bwd_x": None,  # tokens have no grad; all of B is W work
            "bwd_w": bwd0,
        }
    else:
        def fullm(p, x, g):
            _, vjp = jax.vjp(
                lambda p_, x_: gpt.stage_hidden(p_, x_, cfg, s, n),
                p, x)
            dp, dx = vjp(g)
            return dx, dp

        progs = {
            "fwd": devtel.instrument(
                jax.jit(lambda p, x: gpt.stage_hidden(p, x, cfg, s, n)),
                name=f"{tag}.fwd"),
            "bwd": devtel.instrument(jax.jit(fullm), name=f"{tag}.bwd"),
            "bwd_x": devtel.instrument(
                jax.jit(lambda p, x, g: fullm(p, x, g)[0]),
                name=f"{tag}.bwd_x"),
            "bwd_w": devtel.instrument(
                jax.jit(lambda p, x, g: fullm(p, x, g)[1]),
                name=f"{tag}.bwd_w"),
        }
    with _PROG_LOCK:
        # a concurrent builder may have won the race; keep ITS programs so
        # every runtime shares one executable set
        progs = _PROGRAM_CACHE.setdefault(key, progs)
    return progs


class StageRuntime:
    """One pipeline stage: per-stage jit programs + the gang scheduler.

    Transport-agnostic: the threads transport runs this on a dedicated
    thread (one per gang, distinct virtual devices); the actors transport
    runs it inside a dedicated ray_tpu actor process.  All mutable state
    is owned by the single scheduler thread driving run_step — cross-
    thread traffic happens only through shm channels and the transport's
    queues.
    """

    def __init__(self, cfg, pcfg: PipelineConfig, stage: int, stage_params,
                 tx=None, opt_state=None, device_index: Optional[int] = None,
                 telemetry: bool = False, vault_tag: Optional[str] = None,
                 restore=None, grad_sync=None, incarnation: int = 0):
        import jax

        self.cfg = cfg
        self.pcfg = pcfg
        self.stage = stage
        self.stages = pcfg.stages
        self.M = pcfg.num_microbatches
        self._schedule = get_schedule(pcfg.schedule)
        self._zb = self._schedule.split_backward
        self._tx = tx
        self._device = None
        if device_index is not None:
            devs = jax.local_devices()
            self._device = devs[device_index % len(devs)]
        if restore is not None:
            # fold the lost gang's state back from its emergency shards
            payload = restore.load()[0]
            stage_params = payload["params"]
            opt_state = payload["opt_state"]
        if self._device is not None:
            stage_params = jax.device_put(stage_params, self._device)
            if opt_state is not None:
                opt_state = jax.device_put(opt_state, self._device)
        self._params = stage_params
        self._opt_state = (opt_state if opt_state is not None
                           else (tx.init(stage_params) if tx else None))
        self._committed = (-1, self._params, self._opt_state)
        self._grad_sync = grad_sync
        if grad_sync is None and pcfg.grad_sync_group:
            from ray_tpu.parallel.sharding import GradientSynchronizer

            self._grad_sync = GradientSynchronizer(
                group_name=f"{pcfg.grad_sync_group}-s{stage}")
        self._ckpt = None
        if vault_tag:
            from ray_tpu.elastic.emergency import EmergencyCheckpointer

            self._ckpt = EmergencyCheckpointer(
                vault_tag, rank=stage, world_size=self.stages,
                replication_factor=(1 if pcfg.transport == "actors" else 0),
                keep_steps=2, snapshot_every=pcfg.snapshot_every)
        self._vault_tag = vault_tag
        self._timer = None
        if telemetry:
            from ray_tpu.telemetry.recorder import StepTimer

            self._timer = StepTimer(rank=stage, incarnation=incarnation)
        self._chans: Dict[str, Any] = {}
        self._epoch = -1
        self._make_programs()

    # -- program construction ---------------------------------------------

    def _make_programs(self):
        import jax

        progs = _stage_programs(self.cfg, self.stage, self.stages)
        self._fwd = progs["fwd"]
        self._bwd = progs["bwd"]
        self._bwd_x = progs["bwd_x"]
        self._bwd_w = progs["bwd_w"]

        if self._tx is not None:
            tx = self._tx

            def upd(g, o, p):
                import optax

                updates, o2 = tx.update(g, o, p)
                return optax.apply_updates(p, updates), o2

            from ray_tpu.telemetry import device as devtel

            self._update = devtel.instrument(
                jax.jit(upd), name=f"mpmd.s{self.stage}.update")

    # -- channels -----------------------------------------------------------

    def connect(self, paths: Dict[str, str], epoch: int):
        """(Re-)open this stage's channel endpoints for `epoch` (recovery
        bumps the epoch so survivors drop closed rings and re-attach)."""
        if epoch == self._epoch and self._chans:
            return
        from ray_tpu.dag.channel import Channel

        self.disconnect()
        self._chans = {
            k: Channel(p, slot_bytes=self.pcfg.slot_bytes,
                       nslots=self.pcfg.nslots)
            for k, p in paths.items()}
        self._epoch = epoch

    def disconnect(self):
        for ch in self._chans.values():
            try:
                ch.release()
            except Exception:
                pass
        self._chans = {}

    def abort_step(self):
        """A peer died mid-step: drop partial state, restore the commit."""
        _, self._params, self._opt_state = self._committed
        self.disconnect()
        if self._timer is not None:
            # discard the partial step (a fresh step_start resets phases)
            self._timer.step_start(None)

    # -- the scheduler loop --------------------------------------------------

    def run_step(self, step: int, mbs_in=None, mbs_tgt=None,
                 apply_update: bool = True, return_grads: bool = False,
                 fail_at: Optional[int] = None,
                 deadline_s: float = 180.0) -> Dict[str, Any]:
        import jax
        import numpy as np

        from ray_tpu.dag.channel import (TAG_ERROR, TAG_STOP, ChannelClosed,
                                         ChannelTimeout)

        n, s, M = self.stages, self.stage, self.M
        first, last = s == 0, s == n - 1
        deadline = time.monotonic() + deadline_s
        ev: List[Dict[str, Any]] = []
        stash: Dict[int, Any] = {}
        gstash: Dict[int, Any] = {}
        tgts: Dict[int, Any] = {}
        pending_w: "collections.deque[int]" = collections.deque()
        acc = [None]
        loss_sum = 0.0
        p2p_bytes = 0
        peak_stash = 0
        if self._timer is not None:
            self._timer.step_start(step)

        def put(x):
            return (jax.device_put(x, self._device)
                    if self._device is not None else x)

        def record(kind, mb, t0, t1, **kw):
            ev.append({"kind": kind, "mb": mb, "t0": t0,
                       "dur": t1 - t0, **kw})

        def run_w(mb):
            g = gstash.pop(mb)
            x = stash.pop(mb)
            t0 = time.perf_counter()
            dp = jax.block_until_ready(self._bwd_w(self._params, x, g))
            record("W", mb, t0, time.perf_counter())
            acc[0] = dp if acc[0] is None else _add_trees(acc[0], dp)

        def check_deadline():
            if time.monotonic() > deadline:
                raise ChannelTimeout(
                    f"stage {s} step {step} exceeded {deadline_s}s")

        def recv(chan, kind, mb):
            """Poll-read so channel waits are measured as idle (and, for
            zb, filled with pending W work) separately from the shm->
            device copy of the successful read."""
            nonlocal p2p_bytes
            t_wait0 = time.perf_counter()
            while True:
                # timeout 0 = immediate check: a successful read's duration
                # is then the pure shm->host copy, never hidden peer-wait
                # (which would inflate the replay's edge costs)
                t0 = time.perf_counter()
                try:
                    tag, val = chan.read(timeout_s=0.0)
                    break
                except ChannelTimeout:
                    if self._zb and pending_w:
                        run_w(pending_w.popleft())
                    else:
                        time.sleep(0.0002)
                    check_deadline()
            waited = t0 - t_wait0
            t1 = time.perf_counter()
            if tag == TAG_ERROR:
                raise val if isinstance(val, BaseException) \
                    else RuntimeError(str(val))
            if tag == TAG_STOP:
                raise ChannelClosed(chan.path)
            if waited > 1e-6:
                ev.append({"kind": "wait", "mb": mb, "t0": t_wait0,
                           "dur": waited})
            record(kind, mb, t0, t1)
            return val

        def send(chan, val, kind, mb):
            """Poll-write: backpressure from a full ring is idle, not
            p2p — and zb fills it with W work too."""
            nonlocal p2p_bytes
            t_wait0 = time.perf_counter()
            while True:
                t0 = time.perf_counter()
                try:
                    chan.write(val, timeout_s=0.0)
                    break
                except ChannelTimeout:
                    if self._zb and pending_w:
                        run_w(pending_w.popleft())
                    else:
                        time.sleep(0.0002)
                    check_deadline()
            waited = t0 - t_wait0
            t1 = time.perf_counter()
            if waited > 1e-6:
                ev.append({"kind": "wait", "mb": mb, "t0": t_wait0,
                           "dur": waited})
            nb = int(getattr(val, "nbytes", 0))
            record(kind, mb, t0, t1, bytes=nb)
            p2p_bytes += nb

        ops = self._schedule.ops(s, n, M)
        for op_idx, (kind, mb) in enumerate(ops):
            if fail_at is not None and op_idx == fail_at:
                raise RuntimeError(
                    f"injected gang failure: stage {s} step {step} "
                    f"op {op_idx} ({kind}{mb})")
            if kind == "F":
                if first:
                    x = put(mbs_in[mb])
                else:
                    x = put(recv(self._chans["in"], "recv_f", mb))
                stash[mb] = x
                peak_stash = max(peak_stash, len(stash))
                t0 = time.perf_counter()
                if last:
                    tgts[mb] = put(mbs_tgt[mb])
                    loss = jax.block_until_ready(
                        self._fwd(self._params, x, tgts[mb]))
                    record("F", mb, t0, time.perf_counter())
                    loss_sum += float(loss)
                else:
                    y = jax.block_until_ready(self._fwd(self._params, x))
                    record("F", mb, t0, time.perf_counter())
                    send(self._chans["out"], y, "send_f", mb)
            elif kind == "B":
                if last:
                    g = tgts.pop(mb)
                else:
                    g = put(recv(self._chans["gin"], "recv_b", mb))
                x = stash.pop(mb)
                t0 = time.perf_counter()
                if last or not first:  # the last-stage program returns
                    dx, dp = jax.block_until_ready(  # (dx, dp) even at n==1
                        self._bwd(self._params, x, g))
                else:
                    dp = jax.block_until_ready(self._bwd(self._params, x, g))
                    dx = None
                record("B", mb, t0, time.perf_counter())
                if not first:
                    send(self._chans["gout"], dx, "send_b", mb)
                acc[0] = dp if acc[0] is None else _add_trees(acc[0], dp)
            elif kind == "Bx":
                if last:
                    g = tgts.pop(mb)
                else:
                    g = put(recv(self._chans["gin"], "recv_b", mb))
                gstash[mb] = g
                t0 = time.perf_counter()
                if self._bwd_x is not None:
                    dx = jax.block_until_ready(
                        self._bwd_x(self._params, stash[mb], g))
                record("Bx", mb, t0, time.perf_counter())
                if not first:
                    send(self._chans["gout"], dx, "send_b", mb)
                pending_w.append(mb)
            else:  # "W" — skip if an idle-fill already ran it
                if pending_w:
                    run_w(pending_w.popleft())

        # -- step finalize: grad average, tied-embed exchange, sync, apply
        grads = jax.tree.map(lambda a: a / M, acc[0])
        if self.cfg.tie_embeddings and n > 1 and (first or last):
            # both end stages hold the tied table; exchange partials so
            # each applies the TOTAL grad and the copies stay identical
            if last:
                send(self._chans["tie_out"], grads["embed"], "send_tie", -1)
                grads = dict(grads)
                grads["embed"] = put(recv(self._chans["tie_in"],
                                          "recv_tie", -1))
            else:
                partial = put(recv(self._chans["tie_in"], "recv_tie", -1))
                grads = dict(grads)
                grads["embed"] = grads["embed"] + partial
                send(self._chans["tie_out"], grads["embed"], "send_tie", -1)
        if self._grad_sync is not None:
            grads = self._grad_sync(grads)
        loss = (loss_sum / M) if last else None
        if apply_update and self._tx is not None:
            self._params, self._opt_state = jax.block_until_ready(
                self._update(grads, self._opt_state, self._params))
        if apply_update:
            self._committed = (step, self._params, self._opt_state)
            if self._ckpt is not None:
                self._ckpt.snapshot(
                    {"pipeline": self._vault_tag, "stage": s,
                     "step": step,
                     "params": self._params,
                     "opt_state": self._opt_state}, step=step)

        fwd_t = sum(e["dur"] for e in ev if e["kind"] == "F")
        bwd_t = sum(e["dur"] for e in ev
                    if e["kind"] in ("B", "Bx", "W"))
        p2p_t = sum(e["dur"] for e in ev
                    if e["kind"].startswith(("send_", "recv_")))
        idle_t = sum(e["dur"] for e in ev if e["kind"] == "wait")
        if self._timer is not None:
            self._timer.add_phase_time("pipeline", fwd_t + bwd_t + p2p_t
                                       + idle_t)
            self._timer.add_phase_time("pipeline.fwd", fwd_t)
            self._timer.add_phase_time("pipeline.bwd", bwd_t)
            self._timer.add_phase_time("pipeline.p2p", p2p_t)
            self._timer.add_phase_time("pipeline.idle", idle_t)
            self._timer.step_end(step)

        res: Dict[str, Any] = {
            "stage": s, "step": step, "loss": loss, "events": ev,
            "p2p_bytes": p2p_bytes, "peak_stash": peak_stash,
            "phase_s": {"fwd": fwd_t, "bwd": bwd_t, "p2p": p2p_t,
                        "idle": idle_t},
        }
        if self._timer is not None:
            res["telemetry"] = self._timer.snapshot()
        if return_grads:
            res["grads"] = jax.device_get(grads)
        return res

    def committed_step(self) -> int:
        return self._committed[0]

    def wait_snapshot(self, timeout: float = 10.0) -> bool:
        return self._ckpt.wait_idle(timeout) if self._ckpt else True

    def close(self):
        self.disconnect()
        if self._ckpt is not None:
            self._ckpt.stop()


# ---------------------------------------------------------------------------
# Threads transport: one scheduler thread per gang


class _StageThread:
    """Thread-transport gang handle.  Commands flow through Queues (their
    internal lock is the synchronization); the runtime itself is owned by
    the scheduler thread alone.  A generic exception kills the gang (the
    runtime is dropped, modeling host loss); ChannelClosed means a PEER
    died — the gang aborts the step, restores its commit, and waits for
    a new epoch."""

    def __init__(self, stage: int, make_runtime: Callable[[], StageRuntime]):
        self.stage = stage
        self._make = make_runtime
        self._inbox: "queue.Queue" = queue.Queue()
        self._outbox: "queue.Queue" = queue.Queue()
        self._runtime: Optional[StageRuntime] = None
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=f"mpmd-stage{stage}")
        self._thread.start()

    def _loop(self):
        from ray_tpu.dag.channel import ChannelClosed

        try:
            self._runtime = self._make()
        except BaseException as e:  # noqa: BLE001 — report, don't hang
            self._outbox.put(("failed", -1, e))
            return
        self._outbox.put(("ready", -1, None))
        while True:
            cmd = self._inbox.get()
            if cmd[0] == "stop":
                self._runtime.close()
                self._outbox.put(("stopped", -1, None))
                return
            _, step, paths, epoch, kwargs = cmd
            try:
                self._runtime.connect(paths, epoch)
                res = self._runtime.run_step(step, **kwargs)
                self._outbox.put(("ok", step, res))
            except ChannelClosed as e:
                self._runtime.abort_step()
                self._outbox.put(("aborted", step, e))
            except BaseException as e:  # noqa: BLE001 — gang death
                rt, self._runtime = self._runtime, None
                try:
                    rt.disconnect()
                except Exception:
                    pass
                self._outbox.put(("failed", step, e))
                return

    def submit(self, step, paths, epoch, kwargs):
        self._inbox.put(("step", step, paths, epoch, kwargs))

    def stop(self):
        if self._thread.is_alive():
            self._inbox.put(("stop",))

    def result(self, timeout: float):
        return self._outbox.get(timeout=timeout)

    @property
    def alive(self) -> bool:
        return self._thread.is_alive()


# ---------------------------------------------------------------------------
# Actors transport: one ray_tpu actor per gang (the per-gang scheduler
# actor).  Defined lazily so importing mpmd never requires a cluster.

_STAGE_ACTOR_CLS = None


def _stage_actor_cls():
    global _STAGE_ACTOR_CLS
    if _STAGE_ACTOR_CLS is not None:
        return _STAGE_ACTOR_CLS
    import ray_tpu

    @ray_tpu.remote
    class MPMDStageActor:
        """Per-gang scheduler actor: owns one StageRuntime and drives its
        schedule; activations/grads ride shm channels, NOT actor RPC."""

        def __init__(self, blob):
            import cloudpickle

            kw = cloudpickle.loads(blob)
            self._rt = StageRuntime(**kw)

        def run_step(self, step, paths, epoch, blob):
            import cloudpickle

            from ray_tpu.dag.channel import ChannelClosed

            kwargs = cloudpickle.loads(blob)
            try:
                self._rt.connect(paths, epoch)
                res = self._rt.run_step(step, **kwargs)
                return ("ok", step, res)
            except ChannelClosed as e:
                self._rt.abort_step()
                return ("aborted", step, repr(e))

        def vault_inventory(self):
            from ray_tpu.elastic import emergency

            return emergency._inventory()

        def vault_fetch(self, step, stage):
            from ray_tpu.elastic import emergency

            return emergency._fetch(step, stage)

        def wait_snapshot(self, timeout=10.0):
            return self._rt.wait_snapshot(timeout)

        def close(self):
            self._rt.close()
            return True

    _STAGE_ACTOR_CLS = MPMDStageActor
    return MPMDStageActor


class _StageActorHandle:
    def __init__(self, stage: int, runtime_kwargs: Dict[str, Any]):
        import cloudpickle

        self.stage = stage
        self._actor = _stage_actor_cls().remote(
            cloudpickle.dumps(runtime_kwargs))
        self._pending = None

    def submit(self, step, paths, epoch, kwargs):
        import cloudpickle

        self._pending = self._actor.run_step.remote(
            step, paths, epoch, cloudpickle.dumps(kwargs))

    def result(self, timeout: float):
        import ray_tpu

        try:
            return ray_tpu.get(self._pending, timeout=timeout)
        except Exception as e:
            if "timeout" in type(e).__name__.lower() \
                    or "timeout" in str(e).lower():
                raise queue.Empty() from None  # still running: poll again
            return ("failed", -1, e)  # actor death / RPC error = gang loss

    def stop(self):
        try:
            import ray_tpu

            ray_tpu.get(self._actor.close.remote(), timeout=10)
        except Exception:
            pass

    @property
    def alive(self) -> bool:
        return True


# ---------------------------------------------------------------------------
# Driver


class MPMDPipeline:
    """Driver for an MPMD pipeline over `models/gpt.py`.

    Partitions params into per-stage trees (partition_stage_params),
    spawns one gang per stage (threads or actors transport), wires
    activation/grad channels, and drives steps.  See tests/test_mpmd.py
    and `bench.py --pipeline-only`.
    """

    def __init__(self, cfg, pcfg: PipelineConfig, params=None, key=None,
                 tx=None, telemetry: bool = False, base_dir: Optional[str]
                 = None, grad_sync_factory: Optional[Callable[[int], Any]]
                 = None, auto_recover: bool = True):
        from ray_tpu.models import gpt

        if params is None:
            import jax

            params = gpt.init(key if key is not None
                              else jax.random.PRNGKey(0), cfg)
        self.cfg = cfg
        self.pcfg = pcfg
        self.M = pcfg.num_microbatches
        self._tx = tx
        self._telemetry = telemetry
        self._grad_sync_factory = grad_sync_factory
        self._auto_recover = auto_recover
        self._tag = f"mpmd-{os.getpid()}-{id(self) & 0xffff:x}"
        root = base_dir
        if root is None:
            shm = "/dev/shm" if os.path.isdir("/dev/shm") else None
            root = tempfile.mkdtemp(prefix="mpmd-", dir=shm)
            self._owns_dir = True
        else:
            os.makedirs(root, exist_ok=True)
            self._owns_dir = False
        self._dir = root
        self._epoch = 0
        self._last_step = -1
        self._last_results: List[Dict[str, Any]] = []
        self._fail_next: Dict[int, int] = {}
        self._init_state = gpt.partition_stage_params(params, cfg,
                                                      pcfg.stages)
        self._reg_lock = threading.Lock()
        self._runtimes: Dict[int, StageRuntime] = {}  # guarded-by: _reg_lock
        self._handles: List[Any] = [
            self._spawn(s, self._init_state[s], restore=None)
            for s in range(pcfg.stages)]
        for h in self._handles:
            if self.pcfg.transport == "threads":
                status, _, err = h.result(timeout=300.0)
                if status != "ready":
                    raise RuntimeError(
                        f"stage {h.stage} failed to start") from err

    # -- gang lifecycle ----------------------------------------------------

    def _runtime_kwargs(self, stage: int, stage_params, restore):
        return dict(
            cfg=self.cfg, pcfg=self.pcfg, stage=stage,
            stage_params=stage_params, tx=self._tx,
            device_index=(stage if self.pcfg.transport == "threads"
                          else None),
            telemetry=self._telemetry, vault_tag=self._tag,
            restore=restore, incarnation=self._epoch)

    def _spawn(self, stage: int, stage_params, restore):
        if self.pcfg.transport == "actors":
            kw = self._runtime_kwargs(stage, stage_params, restore)
            import jax

            kw["stage_params"] = jax.device_get(kw["stage_params"])
            return _StageActorHandle(stage, kw)

        def make(stage=stage, restore=restore):
            grad_sync = (self._grad_sync_factory(stage)
                         if self._grad_sync_factory else None)
            rt = StageRuntime(grad_sync=grad_sync,
                              **self._runtime_kwargs(stage, stage_params,
                                                     restore))
            with self._reg_lock:
                self._runtimes[stage] = rt
            return rt

        return _StageThread(stage, make)

    def _paths(self, stage: int) -> Dict[str, str]:
        d = os.path.join(self._dir, f"e{self._epoch}")
        os.makedirs(d, exist_ok=True)
        n = self.pcfg.stages
        p: Dict[str, str] = {}
        if stage > 0:
            p["in"] = os.path.join(d, f"act{stage - 1}")
            p["gout"] = os.path.join(d, f"grad{stage - 1}")
        if stage < n - 1:
            p["out"] = os.path.join(d, f"act{stage}")
            p["gin"] = os.path.join(d, f"grad{stage}")
        if self.cfg.tie_embeddings and n > 1:
            if stage == n - 1:
                p["tie_out"] = os.path.join(d, "tie_a")
                p["tie_in"] = os.path.join(d, "tie_b")
            elif stage == 0:
                p["tie_in"] = os.path.join(d, "tie_a")
                p["tie_out"] = os.path.join(d, "tie_b")
        return p

    def _close_epoch_channels(self):
        """Wake every gang blocked on an epoch-e channel (recovery)."""
        from ray_tpu.dag.channel import Channel

        d = os.path.join(self._dir, f"e{self._epoch}")
        if not os.path.isdir(d):
            return
        for name in os.listdir(d):
            try:
                ch = Channel(os.path.join(d, name),
                             slot_bytes=self.pcfg.slot_bytes,
                             nslots=self.pcfg.nslots)
                ch.close()
                ch.release()
            except Exception:
                pass

    # -- stepping ----------------------------------------------------------

    def _split(self, batch):
        import numpy as np

        if "inputs" in batch:
            inputs, targets = batch["inputs"], batch["targets"]
        else:
            toks = batch["tokens"]
            inputs, targets = toks[:, :-1], toks[:, 1:]
        from ray_tpu.parallel.pipeline import split_microbatches

        inp = np.asarray(split_microbatches(np.asarray(inputs), self.M))
        tgt = np.asarray(split_microbatches(np.asarray(targets), self.M))
        return ([inp[i] for i in range(self.M)],
                [tgt[i] for i in range(self.M)])

    def inject_failure(self, stage: int, op_index: int = 0):
        """Kill stage's gang at op_index of the NEXT step (tests/bench:
        proves a lost gang folds back from emergency checkpoints)."""
        self._fail_next[stage] = op_index

    def step(self, batch, apply_update: bool = True,
             return_grads: bool = False, deadline_s: float = 180.0,
             _retry: bool = False) -> Dict[str, Any]:
        step = self._last_step + 1
        mbs_in, mbs_tgt = self._split(batch)
        n = self.pcfg.stages
        for s, h in enumerate(self._handles):
            kwargs = dict(apply_update=apply_update,
                          return_grads=return_grads,
                          deadline_s=deadline_s,
                          fail_at=self._fail_next.pop(s, None))
            if s == 0:
                kwargs["mbs_in"] = mbs_in
            if s == n - 1:
                kwargs["mbs_tgt"] = mbs_tgt
            h.submit(step, self._paths(s), self._epoch, kwargs)
        statuses: List[Optional[Tuple]] = [None] * n
        t_end = time.monotonic() + deadline_s + 30.0
        failed: List[int] = []
        while any(st is None for st in statuses):
            for s, h in enumerate(self._handles):
                if statuses[s] is not None:
                    continue
                try:
                    out = h.result(timeout=0.2)
                except queue.Empty:
                    continue
                statuses[s] = out
                if out[0] == "failed":
                    failed.append(s)
                    # wake peers blocked on this gang's channels so they
                    # abort instead of timing out
                    self._close_epoch_channels()
            if time.monotonic() > t_end:
                raise TimeoutError(
                    f"pipeline step {step} stuck; statuses="
                    f"{[st and st[0] for st in statuses]}")
        if failed:
            if any(st[0] == "ok" for st in statuses):
                # a gang already committed this step while another died:
                # rolling the committed gang back is not supported, so
                # surface it rather than silently diverge
                raise RuntimeError(
                    f"unrecoverable: gang(s) {failed} died after "
                    f"{sum(st[0] == 'ok' for st in statuses)} gang(s) "
                    f"committed step {step}")
            if _retry or not self._auto_recover:
                errs = [statuses[s][2] for s in failed]
                raise RuntimeError(
                    f"stage gang(s) {failed} died: {errs}") from errs[0]
            self.recover(failed)
            return self.step(batch, apply_update=apply_update,
                             return_grads=return_grads,
                             deadline_s=deadline_s, _retry=True)
        aborted = [s for s, st in enumerate(statuses) if st[0] != "ok"]
        if aborted:
            raise RuntimeError(
                f"gang(s) {aborted} aborted step {step} without a "
                f"detected failure: {[statuses[s][2] for s in aborted]}")
        results = [st[2] for st in statuses]
        self._last_step = step
        self._last_results = results
        out: Dict[str, Any] = {
            "step": step,
            "loss": results[-1]["loss"],
            "p2p_bytes": sum(r["p2p_bytes"] for r in results),
            "peak_stash": [r["peak_stash"] for r in results],
            "events": [r["events"] for r in results],
            "recovered": _retry,
        }
        if return_grads:
            from ray_tpu.models import gpt

            out["grads"] = gpt.merge_stage_trees(
                [r["grads"] for r in results], self.cfg, grads=True,
                tie_summed=True)  # the step's exchange already totalled it
        return out

    def forward_backward(self, batch) -> Tuple[float, Any]:
        """One no-update pass: (loss, full reassembled grad tree) — the
        parity-test surface against loss_fn + jax.grad."""
        res = self.step(batch, apply_update=False, return_grads=True)
        return res["loss"], res["grads"]

    # -- elastic recovery --------------------------------------------------

    def recover(self, dead_stages: List[int]):
        """Respawn dead gangs from their freshest emergency shards; the
        survivors already rolled back to the committed step when their
        channels closed.  Channels are rebuilt under a new epoch."""
        from ray_tpu.elastic import emergency
        from ray_tpu.elastic.emergency import EmergencyCheckpoint

        step = self._last_step
        self._close_epoch_channels()
        self._epoch += 1
        for s in dead_stages:
            restore = None
            with self._reg_lock:
                rt = self._runtimes.pop(s, None)
            if rt is not None:
                rt.wait_snapshot(10.0)
            if step >= 0:
                payload = emergency._fetch(step, s)
                if payload is None and self.pcfg.transport == "actors":
                    import ray_tpu

                    # the dead gang's shard lives in its ring successors'
                    # vaults (EmergencyCheckpointer peer replication)
                    for h in self._handles:
                        if h.stage == s:
                            continue
                        try:
                            payload = ray_tpu.get(
                                h._actor.vault_fetch.remote(step, s),
                                timeout=30)
                        except Exception:
                            continue
                        if payload is not None:
                            break
                if payload is not None:
                    restore = EmergencyCheckpoint(step, self.pcfg.stages,
                                                  {s: payload})
            if restore is None and step >= 0:
                raise RuntimeError(
                    f"no emergency shard for stage {s} at step {step}")
            self._handles[s] = self._spawn(s, self._init_state[s], restore)
            if self.pcfg.transport == "threads":
                status, _, err = self._handles[s].result(timeout=300.0)
                if status != "ready":
                    raise RuntimeError(
                        f"stage {s} respawn failed") from err

    # -- reporting ---------------------------------------------------------

    def bubble_report(self) -> Dict[str, Any]:
        """Measured (virtual-replay) bubble of the LAST step vs the
        fill-drain theoretical floor at the same (n, M)."""
        if not self._last_results:
            raise RuntimeError("no step recorded yet")
        rep = replay_bubble([r["events"] for r in self._last_results])
        rep["theoretical_fill_drain"] = \
            PipelineSchedule.theoretical_fill_drain_bubble(
                self.pcfg.stages, self.M)
        rep["schedule"] = self.pcfg.schedule
        return rep

    def chrome_trace(self) -> List[Dict[str, Any]]:
        if not self._last_results:
            return []
        return schedule_chrome_trace(
            [r["events"] for r in self._last_results])

    def telemetry_snapshots(self) -> List[Dict[str, Any]]:
        return [r["telemetry"] for r in self._last_results
                if "telemetry" in r]

    def close(self):
        for h in self._handles:
            try:
                h.stop()
            except Exception:
                pass
        if self.pcfg.transport == "threads":
            for h in self._handles:
                try:
                    h.result(timeout=10.0)
                except Exception:
                    pass
        if self._owns_dir:
            shutil.rmtree(self._dir, ignore_errors=True)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
