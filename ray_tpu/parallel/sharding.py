"""Logical-axis sharding rules: the FSDP/TP/SP story as partition specs.

The reference delegates sharded training to integrations (torch FSDP /
DeepSpeed via Train, reference: python/ray/train/ — §2.3 of SURVEY.md);
here parameter/optimizer sharding is first-class: parameters carry *logical*
axis names and a rule table maps them to mesh axes, GSPMD-style.  ZeRO-3 ≡
sharding every parameter's largest axis over `fsdp`; TP ≡ sharding
attention-head / mlp axes over `tp`; SP ≡ sharding the sequence axis of
activations over `sp`.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# logical axis -> mesh axis (or tuple of mesh axes, or None = replicate)
DEFAULT_RULES: Dict[str, Any] = {
    "batch": ("dp", "fsdp", "ep"),
    "seq": "sp",
    "embed": "fsdp",       # ZeRO-3: shard params' embed dim over fsdp
    "heads": "tp",
    "kv_heads": "tp",
    "head_dim": None,
    "mlp": "tp",
    "vocab": "tp",
    "layers": "pp",        # stacked-layer leading axis: stage-sharded when pp>1
    "stages": "pp",
    "experts": "ep",
    "conv_in": None,
    "conv_out": "fsdp",
    "norm": None,
}

# Rules for ACTIVATION constraints.  fsdp shards PARAMETER embed dims
# (ZeRO-3: gathered on use); activations keep fsdp on their batch dim, so
# their embed dim must stay unsharded — with the param table, an
# activation spec like ("batch", "seq", "embed") would claim fsdp twice
# (invalid), and before this split the resulting constraint was silently
# dropped, leaving the partitioner free to embed-shard block outputs
# (the "Involuntary full rematerialization" reshard in the r2 dryrun).
ACTIVATION_RULES: Dict[str, Any] = {
    **DEFAULT_RULES,
    "embed": None,
    "conv_out": None,
}


def spec_from_logical(logical: Sequence[Optional[str]],
                      rules: Optional[Dict[str, Any]] = None,
                      mesh: Optional[Mesh] = None) -> P:
    """Map a tuple of logical axis names to a PartitionSpec.

    Axes whose mesh axis has size 1 (or is absent) become None so the same
    model code runs on any mesh shape.
    """
    rules = {**DEFAULT_RULES, **(rules or {})}
    out = []
    used: set = set()
    for name in logical:
        mesh_axes = rules.get(name) if name is not None else None
        if mesh_axes is None:
            out.append(None)
            continue
        if isinstance(mesh_axes, str):
            mesh_axes = (mesh_axes,)
        if mesh is not None:
            mesh_axes = tuple(a for a in mesh_axes
                              if mesh.shape.get(a, 1) > 1)
        # a mesh axis may shard only ONE tensor dim; on a clash the
        # earlier (leftmost, usually batch) dim keeps it — a duplicate
        # spec is invalid and would otherwise void the whole constraint
        mesh_axes = tuple(a for a in mesh_axes if a not in used)
        used.update(mesh_axes)
        if not mesh_axes:
            out.append(None)
        elif len(mesh_axes) == 1:
            out.append(mesh_axes[0])
        else:
            out.append(tuple(mesh_axes))
    while out and out[-1] is None:
        out.pop()
    return P(*out)


class Logical:
    """Annotation carried on parameter pytree leaves at init time."""

    __slots__ = ("axes",)

    def __init__(self, *axes: Optional[str]):
        self.axes = axes


def tree_shardings(logical_tree, mesh: Mesh,
                   rules: Optional[Dict[str, Any]] = None):
    """Map a pytree of Logical annotations to NamedShardings."""
    return jax.tree.map(
        lambda l: NamedSharding(mesh, spec_from_logical(l.axes, rules, mesh)),
        logical_tree, is_leaf=lambda x: isinstance(x, Logical))


def shard_tree(tree, logical_tree, mesh: Mesh,
               rules: Optional[Dict[str, Any]] = None):
    """Place a concrete pytree on the mesh per its logical annotations."""
    sh = tree_shardings(logical_tree, mesh, rules)
    return jax.tree.map(lambda x, s: jax.device_put(x, s), tree, sh)


def _is_float_dtype(dtype) -> bool:
    """True for any real floating dtype INCLUDING the ml_dtypes
    extension floats (bfloat16 etc.), which numpy's issubdtype does not
    recognize as np.floating — without this, bf16 gradients would skip
    the bucketed/error-feedback path entirely."""
    import numpy as np

    if np.issubdtype(dtype, np.floating):
        return True
    try:
        from jax import dtypes as _jd

        return bool(_jd.issubdtype(dtype, np.floating))
    except Exception:
        return False


class GradientSynchronizer:
    """Cross-process gradient sync with optional compressed collectives.

    The compiled SPMD path syncs gradients implicitly (the partitioner
    emits the psum); this is the EXPLICIT path for multi-process dp
    loops on the collective API — each worker computes local grads, then
    `sync(grads)` allreduces every leaf (op="mean" by default) through
    the group's backend, compressed per `compression` / the group
    default / the RAY_TPU_COLLECTIVE_COMPRESSION flag.

    Float gradients are COALESCED into buckets of ~`bucket_bytes`
    (CompressionConfig.bucket_bytes unless overridden here) and each
    bucket is issued through `collective.allreduce_async` the moment it
    fills — so with the incremental `begin()/push()/finish()` API the
    first buckets are in flight while the backward pass is still
    producing the rest, and `__call__` still pipelines bucket k's
    reduce under bucket k+1's quantize.  Bucketing also amortizes the
    per-op rendezvous and lets small leaves ride a compressed bucket
    instead of going uncompressed below `min_size`.

    With `error_feedback` on (the CompressionConfig default), the
    compression residual e_t = g_t - deq(quant(g_t)) is held host-side
    — in the PARAMETER dtype, so bf16 training doesn't double residual
    memory by upcasting to f32 — and re-injected into the next step's
    gradient: the standard EF-SGD construction that keeps compressed
    training convergent instead of accumulating quantization bias.
    Residuals are recomputed locally from the deterministic codec over
    the exact bucket stream that went on the wire (an extra local
    quantize per bucket, no extra wire traffic)."""

    def __init__(self, group_name: str = "default", op: str = "mean",
                 compression=None, bucket_bytes: Optional[int] = None):
        self.group_name = group_name
        self.op = op
        self.compression = compression
        self.bucket_bytes = bucket_bytes
        self._residuals: Optional[dict] = None
        self._stream: Optional[dict] = None

    def reset(self):
        """Drop accumulated error-feedback residuals (e.g. after a
        checkpoint restore on different parameters)."""
        self._residuals = None

    # -- incremental streaming API ---------------------------------------

    def begin(self):
        """Start a sync stream; feed leaves with push(), collect with
        finish().  Push order must match across ranks (it is the
        collective issue order)."""
        import numpy as np

        from ray_tpu.collective.compression import resolve_compression

        cc = resolve_compression(self.compression)
        cap = self.bucket_bytes
        if cap is None:
            cap = cc.bucket_bytes if cc is not None else 4 << 20
        if self._residuals is None:
            self._residuals = {}
        self._stream = {
            "cc": cc,
            "use_ef": cc is not None and cc.error_feedback,
            "cap": max(1, int(cap)),
            "pending": [],        # (slot, x_np) awaiting bucket flush
            "pending_bytes": 0,
            "buckets": [],        # flushed: (handle, corrected, segments)
            "singles": {},        # slot -> handle (non-bucketed leaves)
            "meta": {},           # slot -> (shape, dtype)
            "nslots": 0,
        }
        return self

    def push(self, g) -> int:
        """Enqueue one gradient leaf; returns its slot id.  Issues the
        current bucket's allreduce as soon as it crosses bucket_bytes."""
        import numpy as np

        st = self._stream
        if st is None:
            raise RuntimeError("push() outside begin()/finish() — call "
                               "begin() first (or use __call__)")
        from ray_tpu.collective import collective

        slot = st["nslots"]
        st["nslots"] += 1
        x = np.asarray(g)
        st["meta"][slot] = (x.shape, x.dtype)
        if st["cc"] is not None and _is_float_dtype(x.dtype):
            st["pending"].append((slot, x))
            st["pending_bytes"] += x.size * 4     # bucket carries f32
            if st["pending_bytes"] >= st["cap"]:
                self._flush_bucket()
        else:
            st["singles"][slot] = collective.allreduce_async(
                x, self.group_name, op=self.op, compression=st["cc"])
        return slot

    def _flush_bucket(self):
        import numpy as np

        from ray_tpu.collective import collective

        st = self._stream
        if not st["pending"]:
            return
        parts, segments, off = [], [], 0
        for slot, x in st["pending"]:
            flat = x.reshape(-1).astype(np.float32)
            res = self._residuals.get(slot) if st["use_ef"] else None
            if res is not None:
                flat = flat + res.reshape(-1).astype(np.float32)
            parts.append(flat)
            segments.append((slot, off, off + flat.size))
            off += flat.size
        st["pending"] = []
        st["pending_bytes"] = 0
        corrected = parts[0] if len(parts) == 1 else np.concatenate(parts)
        handle = collective.allreduce_async(corrected, self.group_name,
                                            op=self.op, compression=st["cc"])
        st["buckets"].append((handle, corrected, segments))

    def finish(self) -> list:
        """Flush the tail bucket, await every in-flight reduce, update
        residuals, and return the synced leaves in push order."""
        import numpy as np

        from ray_tpu.collective.compression import compression_residual

        st = self._stream
        if st is None:
            raise RuntimeError("finish() without begin()")
        self._flush_bucket()
        cc = st["cc"]
        out = [None] * st["nslots"]
        for handle, corrected, segments in st["buckets"]:
            reduced = handle.result()
            # did the wire actually compress this bucket?  (mirrors
            # _resolve_op_compression: small buckets go exact)
            compressed = cc is not None and corrected.size >= cc.min_size
            resid = (compression_residual(corrected, cc)
                     if compressed and st["use_ef"] else None)
            for slot, a, b in segments:
                shape, dtype = st["meta"][slot]
                out[slot] = np.asarray(
                    reduced[a:b]).reshape(shape).astype(dtype)
                if resid is not None:
                    # parameter dtype on purpose: bf16 params keep bf16
                    # residuals (half the memory; the re-injection above
                    # upcasts to f32 for the arithmetic)
                    self._residuals[slot] = resid[a:b].reshape(
                        shape).astype(dtype)
                elif st["use_ef"]:
                    # exact (uncompressed) sync consumed whatever
                    # residual was injected
                    self._residuals[slot] = np.zeros(shape, dtype)
        for slot, handle in st["singles"].items():
            shape, dtype = st["meta"][slot]
            out[slot] = np.asarray(handle.result())
        self._stream = None
        return out

    def __call__(self, grads):
        leaves, treedef = jax.tree.flatten(grads)
        self.begin()
        for g in leaves:
            self.push(g)
        return jax.tree.unflatten(treedef, self.finish())


def with_constraint(x, logical: Tuple[Optional[str], ...],
                    rules: Optional[Dict[str, Any]] = None):
    """In-jit sharding constraint by logical axes (uses the ambient mesh)."""
    try:
        mesh = jax.sharding.get_abstract_mesh()  # jax>=0.4.35 in-jit mesh
        concrete = None if mesh is None or mesh.empty else mesh
    except Exception:
        concrete = None
    spec = spec_from_logical(logical, rules, None)
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(concrete, spec) if concrete is not None else spec)
