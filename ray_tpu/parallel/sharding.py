"""Logical-axis sharding rules: the FSDP/TP/SP story as partition specs.

The reference delegates sharded training to integrations (torch FSDP /
DeepSpeed via Train, reference: python/ray/train/ — §2.3 of SURVEY.md);
here parameter/optimizer sharding is first-class: parameters carry *logical*
axis names and a rule table maps them to mesh axes, GSPMD-style.  ZeRO-3 ≡
sharding every parameter's largest axis over `fsdp`; TP ≡ sharding
attention-head / mlp axes over `tp`; SP ≡ sharding the sequence axis of
activations over `sp`.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# logical axis -> mesh axis (or tuple of mesh axes, or None = replicate)
DEFAULT_RULES: Dict[str, Any] = {
    "batch": ("dp", "fsdp", "ep"),
    "seq": "sp",
    "embed": "fsdp",       # ZeRO-3: shard params' embed dim over fsdp
    "heads": "tp",
    "kv_heads": "tp",
    "head_dim": None,
    "mlp": "tp",
    "vocab": "tp",
    "layers": "pp",        # stacked-layer leading axis: stage-sharded when pp>1
    "stages": "pp",
    "experts": "ep",
    "conv_in": None,
    "conv_out": "fsdp",
    "norm": None,
}

# Rules for ACTIVATION constraints.  fsdp shards PARAMETER embed dims
# (ZeRO-3: gathered on use); activations keep fsdp on their batch dim, so
# their embed dim must stay unsharded — with the param table, an
# activation spec like ("batch", "seq", "embed") would claim fsdp twice
# (invalid), and before this split the resulting constraint was silently
# dropped, leaving the partitioner free to embed-shard block outputs
# (the "Involuntary full rematerialization" reshard in the r2 dryrun).
ACTIVATION_RULES: Dict[str, Any] = {
    **DEFAULT_RULES,
    "embed": None,
    "conv_out": None,
}


def spec_from_logical(logical: Sequence[Optional[str]],
                      rules: Optional[Dict[str, Any]] = None,
                      mesh: Optional[Mesh] = None) -> P:
    """Map a tuple of logical axis names to a PartitionSpec.

    Axes whose mesh axis has size 1 (or is absent) become None so the same
    model code runs on any mesh shape.
    """
    rules = {**DEFAULT_RULES, **(rules or {})}
    out = []
    used: set = set()
    for name in logical:
        mesh_axes = rules.get(name) if name is not None else None
        if mesh_axes is None:
            out.append(None)
            continue
        if isinstance(mesh_axes, str):
            mesh_axes = (mesh_axes,)
        if mesh is not None:
            mesh_axes = tuple(a for a in mesh_axes
                              if mesh.shape.get(a, 1) > 1)
        # a mesh axis may shard only ONE tensor dim; on a clash the
        # earlier (leftmost, usually batch) dim keeps it — a duplicate
        # spec is invalid and would otherwise void the whole constraint
        mesh_axes = tuple(a for a in mesh_axes if a not in used)
        used.update(mesh_axes)
        if not mesh_axes:
            out.append(None)
        elif len(mesh_axes) == 1:
            out.append(mesh_axes[0])
        else:
            out.append(tuple(mesh_axes))
    while out and out[-1] is None:
        out.pop()
    return P(*out)


class Logical:
    """Annotation carried on parameter pytree leaves at init time."""

    __slots__ = ("axes",)

    def __init__(self, *axes: Optional[str]):
        self.axes = axes


def tree_shardings(logical_tree, mesh: Mesh,
                   rules: Optional[Dict[str, Any]] = None):
    """Map a pytree of Logical annotations to NamedShardings."""
    return jax.tree.map(
        lambda l: NamedSharding(mesh, spec_from_logical(l.axes, rules, mesh)),
        logical_tree, is_leaf=lambda x: isinstance(x, Logical))


def shard_tree(tree, logical_tree, mesh: Mesh,
               rules: Optional[Dict[str, Any]] = None):
    """Place a concrete pytree on the mesh per its logical annotations."""
    sh = tree_shardings(logical_tree, mesh, rules)
    return jax.tree.map(lambda x, s: jax.device_put(x, s), tree, sh)


class GradientSynchronizer:
    """Cross-process gradient sync with optional compressed collectives.

    The compiled SPMD path syncs gradients implicitly (the partitioner
    emits the psum); this is the EXPLICIT path for multi-process dp
    loops on the collective API — each worker computes local grads, then
    `sync(grads)` allreduces every leaf (op="mean" by default) through
    the group's backend, compressed per `compression` / the group
    default / the RAY_TPU_COLLECTIVE_COMPRESSION flag.

    With `error_feedback` on (the CompressionConfig default), the per-
    parameter compression residual e_t = g_t - deq(quant(g_t)) is held
    host-side and re-injected into the next step's gradient — the
    standard EF-SGD construction that keeps compressed training
    convergent instead of accumulating quantization bias.  Residuals are
    recomputed locally from the deterministic codec (an extra local
    quantize per leaf, no extra wire traffic)."""

    def __init__(self, group_name: str = "default", op: str = "mean",
                 compression=None):
        self.group_name = group_name
        self.op = op
        self.compression = compression
        self._residuals: Optional[list] = None

    def reset(self):
        """Drop accumulated error-feedback residuals (e.g. after a
        checkpoint restore on different parameters)."""
        self._residuals = None

    def __call__(self, grads):
        import numpy as np

        from ray_tpu.collective import collective
        from ray_tpu.collective.compression import (compression_residual,
                                                    resolve_compression)

        cc = resolve_compression(self.compression)
        leaves, treedef = jax.tree.flatten(grads)
        use_ef = cc is not None and cc.error_feedback
        if use_ef and self._residuals is None:
            self._residuals = [np.zeros(np.shape(g), np.float32)
                               for g in leaves]
        synced = []
        for i, g in enumerate(leaves):
            x = np.asarray(g)
            if use_ef and np.issubdtype(x.dtype, np.floating):
                corrected = x.astype(np.float32) + self._residuals[i]
                out = collective.allreduce(corrected, self.group_name,
                                           op=self.op, compression=cc)
                if corrected.size >= cc.min_size:
                    # what this rank's contribution lost to quantization;
                    # deterministic codec => exact local recomputation
                    self._residuals[i] = compression_residual(corrected, cc)
                synced.append(out.astype(x.dtype))
            else:
                synced.append(collective.allreduce(x, self.group_name,
                                                   op=self.op,
                                                   compression=cc))
        return jax.tree.unflatten(treedef, synced)


def with_constraint(x, logical: Tuple[Optional[str], ...],
                    rules: Optional[Dict[str, Any]] = None):
    """In-jit sharding constraint by logical axes (uses the ambient mesh)."""
    try:
        mesh = jax.sharding.get_abstract_mesh()  # jax>=0.4.35 in-jit mesh
        concrete = None if mesh is None or mesh.empty else mesh
    except Exception:
        concrete = None
    spec = spec_from_logical(logical, rules, None)
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(concrete, spec) if concrete is not None else spec)
