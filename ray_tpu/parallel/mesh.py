"""Device mesh management: the TPU-native communication substrate.

Where the reference wires collectives at runtime (NCCL groups over cupy,
reference: python/ray/util/collective/collective_group/nccl_collective_group.py),
the TPU-native design makes the *mesh* the primitive: a
`jax.sharding.Mesh` over ICI (intra-slice) and DCN (cross-slice) axes.
Collectives are compiled into XLA programs via shard_map/pjit over this mesh
— there are no runtime collective calls to manage.

Standard axis names (outer-to-inner, DCN-friendly axes first):

    pp    pipeline stages          (cross-slice OK: p2p only)
    dp    pure data parallel       (cross-slice OK: one allreduce per step)
    fsdp  data parallel + param sharding (ZeRO-3; wants ICI)
    sp    sequence/context parallel (ring attention; wants ICI ring)
    tp    tensor parallel          (wants fastest ICI axis, innermost)
    ep    expert parallel          (all_to_all token dispatch; doubles as
                                    a data axis outside MoE layers)

jax device order for TPU meshes follows the physical torus, so keeping `tp`
innermost places it on the fastest ICI loop — the layout recipe of the
scaling playbook (jax-ml.github.io/scaling-book).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_ORDER = ("pp", "dp", "fsdp", "ep", "sp", "tp")

# Process-default mesh: where device arrays crossing the object plane are
# re-placed on deserialization (serialization.py consults it).  The
# reference has no analog — its GPU tensors move through NCCL groups; here
# placement is a mesh property, so the receiving process declares its mesh
# once and every inbound array lands sharded instead of host-replicated.
_default_mesh: Optional[Mesh] = None


def set_default_mesh(mesh: Optional[Mesh]) -> None:
    """Install (or clear, with None) this process's default mesh."""
    global _default_mesh
    _default_mesh = mesh


def get_default_mesh() -> Optional[Mesh]:
    return _default_mesh


class default_mesh:
    """Context manager: `with default_mesh(mesh): ...`"""

    def __init__(self, mesh: Optional[Mesh]):
        self._mesh = mesh

    def __enter__(self):
        self._prev = get_default_mesh()
        set_default_mesh(self._mesh)
        return self._mesh

    def __exit__(self, *exc):
        set_default_mesh(self._prev)
        return False


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape; -1 on at most one axis means 'fill'."""

    pp: int = 1
    dp: int = 1
    fsdp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1

    def sizes(self) -> Dict[str, int]:
        return {a: getattr(self, a) for a in AXIS_ORDER}

    def resolve(self, n_devices: int) -> "MeshSpec":
        sizes = self.sizes()
        fill = [a for a, s in sizes.items() if s == -1]
        if len(fill) > 1:
            raise ValueError(f"at most one -1 axis allowed: {sizes}")
        known = math.prod(s for s in sizes.values() if s != -1)
        if fill:
            if n_devices % known:
                raise ValueError(
                    f"{n_devices} devices not divisible by {known} for {sizes}")
            sizes[fill[0]] = n_devices // known
        if math.prod(sizes.values()) != n_devices:
            raise ValueError(
                f"mesh {sizes} needs {math.prod(sizes.values())} devices, "
                f"got {n_devices}")
        return MeshSpec(**sizes)

    def build(self, devices: Optional[Sequence] = None) -> Mesh:
        devices = list(devices if devices is not None else jax.devices())
        spec = self.resolve(len(devices))
        shape = tuple(spec.sizes()[a] for a in AXIS_ORDER)
        arr = np.array(devices).reshape(shape)
        return Mesh(arr, AXIS_ORDER)


def make_mesh(*, pp: int = 1, dp: int = 1, fsdp: int = 1, ep: int = 1,
              sp: int = 1, tp: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    return MeshSpec(pp=pp, dp=dp, fsdp=fsdp, ep=ep, sp=sp,
                    tp=tp).build(devices)


def data_axes(mesh: Mesh) -> Tuple[str, ...]:
    """Axes a per-example batch is sharded over."""
    return tuple(a for a in ("dp", "fsdp", "ep")
                 if mesh.shape.get(a, 1) >= 1)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Canonical input-batch sharding: batch over the data axes
    (dp, fsdp, ep — ep doubles as a data axis outside MoE layers), seq
    over sp."""
    return NamedSharding(mesh, P(("dp", "fsdp", "ep"), "sp"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def local_mesh(n: Optional[int] = None, **axes) -> Mesh:
    """Mesh over this process's local devices (single-controller use)."""
    devs = jax.local_devices()
    if n is not None:
        devs = devs[:n]
    if not axes:
        axes = {"dp": len(devs)}
    return make_mesh(devices=devs, **axes)


def slice_groups(devices: Sequence) -> List[List]:
    """Group devices by TPU slice (megascale multi-slice: `slice_index`
    on real hardware; process_index as the proxy on multi-host
    single-slice; contiguous chunks can be forced for simulation)."""
    by: Dict[int, List] = {}
    for d in devices:
        sid = getattr(d, "slice_index", None)
        if sid is None:
            sid = getattr(d, "process_index", 0)
        by.setdefault(sid, []).append(d)
    return [by[k] for k in sorted(by)]


def make_multislice_mesh(*, dcn: Dict[str, int], ici: Dict[str, int],
                         devices: Optional[Sequence] = None,
                         num_slices: Optional[int] = None) -> Mesh:
    """Mesh spanning multiple TPU slices connected over DCN (megascale).

    ``dcn`` assigns exactly one axis to the cross-slice dimension (e.g.
    ``{"dp": 2}`` for 2-slice data parallelism, or ``{"pp": 4}`` for a
    pipeline across 4 slices); ``ici`` is the per-slice mesh shape.  The
    device layout keeps every ICI axis inside one slice, so only the dcn
    axis's collectives (one gradient allreduce per step for dp; p2p
    sends for pp) ride the slow interconnect — the layout recipe of the
    scaling playbook.  ``num_slices`` forces contiguous grouping on
    simulated/CPU meshes where devices carry no slice_index.
    """
    if len(dcn) != 1:
        raise ValueError(f"exactly one DCN axis supported, got {dcn}")
    (dcn_axis, n_dcn), = dcn.items()
    if dcn_axis not in AXIS_ORDER:
        raise ValueError(f"unknown axis {dcn_axis!r}")
    if dcn_axis not in ("pp", "dp", "ep"):
        import warnings

        warnings.warn(
            f"axis {dcn_axis!r} over DCN: fsdp/sp/tp collectives are "
            f"per-layer and will bottleneck on cross-slice bandwidth")
    devices = list(devices if devices is not None else jax.devices())
    if num_slices is not None:
        if len(devices) % num_slices:
            raise ValueError(f"{len(devices)} devices not divisible "
                             f"into {num_slices} slices")
        per = len(devices) // num_slices
        groups = [devices[i * per:(i + 1) * per]
                  for i in range(num_slices)]
    else:
        groups = slice_groups(devices)
    if len(groups) != n_dcn:
        raise ValueError(
            f"dcn={{{dcn_axis}: {n_dcn}}} but found {len(groups)} "
            f"slices (pass num_slices to simulate)")

    ici_sizes = {a: ici.get(a, 1) for a in AXIS_ORDER}
    per_slice = math.prod(ici_sizes.values())
    for g in groups:
        if len(g) != per_slice:
            raise ValueError(
                f"slice has {len(g)} devices, ici shape needs {per_slice}")
    ici_shape = tuple(ici_sizes[a] for a in AXIS_ORDER)
    blocks = [np.array(g).reshape(ici_shape) for g in groups]
    axis_i = AXIS_ORDER.index(dcn_axis)
    # stack slices as the outer factor of the dcn axis: positions that
    # differ only inside a slice stay on ICI
    arr = np.stack(blocks, axis=axis_i)
    full_shape = tuple(
        ici_sizes[a] * (n_dcn if a == dcn_axis else 1)
        for a in AXIS_ORDER)
    return Mesh(arr.reshape(full_shape), AXIS_ORDER)
