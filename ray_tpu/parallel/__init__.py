from .mesh import (AXIS_ORDER, MeshSpec, batch_sharding, data_axes,
                   local_mesh, make_mesh, make_multislice_mesh,
                   replicated, slice_groups)
from .sharding import (DEFAULT_RULES, Logical, shard_tree, spec_from_logical,
                       tree_shardings, with_constraint)

__all__ = [
    "AXIS_ORDER", "MeshSpec", "make_mesh", "make_multislice_mesh",
    "local_mesh", "slice_groups", "batch_sharding",
    "data_axes", "replicated",
    "DEFAULT_RULES", "Logical", "spec_from_logical", "tree_shardings",
    "shard_tree", "with_constraint",
]
