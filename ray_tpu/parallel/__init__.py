from .mesh import (AXIS_ORDER, MeshSpec, batch_sharding, data_axes,
                   default_mesh, get_default_mesh, local_mesh, make_mesh,
                   make_multislice_mesh, replicated, set_default_mesh,
                   slice_groups)
from .mpmd import (SCHEDULES, MPMDPipeline, PipelineConfig, get_schedule,
                   replay_bubble)
from .sharding import (DEFAULT_RULES, GradientSynchronizer, Logical,
                       shard_tree, spec_from_logical, tree_shardings,
                       with_constraint)

__all__ = [
    "AXIS_ORDER", "MeshSpec", "make_mesh", "make_multislice_mesh",
    "local_mesh", "slice_groups", "batch_sharding",
    "data_axes", "replicated",
    "set_default_mesh", "get_default_mesh", "default_mesh",
    "DEFAULT_RULES", "GradientSynchronizer", "Logical", "spec_from_logical",
    "tree_shardings", "shard_tree", "with_constraint",
    "SCHEDULES", "MPMDPipeline", "PipelineConfig", "get_schedule",
    "replay_bubble",
]
