from .mesh import (AXIS_ORDER, MeshSpec, batch_sharding, data_axes,
                   local_mesh, make_mesh, replicated)
from .sharding import (DEFAULT_RULES, Logical, shard_tree, spec_from_logical,
                       tree_shardings, with_constraint)

__all__ = [
    "AXIS_ORDER", "MeshSpec", "make_mesh", "local_mesh", "batch_sharding",
    "data_axes", "replicated",
    "DEFAULT_RULES", "Logical", "spec_from_logical", "tree_shardings",
    "shard_tree", "with_constraint",
]
