"""SPMD pipeline parallelism: GPipe-style microbatching over the `pp` axis.

The reference has no in-tree PP; its substrate is compiled multi-actor
graphs with NCCL p2p channels (reference: dag/compiled_dag_node.py:664,
experimental/channel/torch_tensor_nccl_channel.py — SURVEY.md §2.3).  The
TPU-native equivalent is collective pipelining *inside one compiled
program*: every `pp` rank holds one stage's layers; microbatch activations
rotate stage-to-stage via `lax.ppermute` in a `lax.scan` steady-state loop,
and reverse-mode AD differentiates straight through the rotation (the
transpose of ppermute is the reverse ppermute — backward pipelining for
free).

Schedule: plain GPipe fill-drain over T = M + n - 1 ticks (bubble fraction
(n-1)/T); the scan body is one tick.  Deeper schedules (1F1B, interleaved)
are compiler-level refinements of the same loop.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from ray_tpu._private.jax_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def pipeline_apply(stage_fn: Callable, stage_params, x_mb, mesh: Mesh,
                   axis_name: str = "pp"):
    """Run microbatches through the pipeline.

    stage_fn(local_params, x) -> x  : one stage's computation
    stage_params: pytree with a leading *stage* axis sized pp on every leaf
                  (sharded P(axis_name) outside)
    x_mb: pytree whose leaves are [M, mb, ...] microbatched inputs
          (replicated over pp) — a bare array works as before; a tuple
          lets side outputs (e.g. MoE router aux losses) ride the
          rotation with the activations
    returns: same pytree structure, leaves [M, mb, ...] from the final
             stage (replicated over pp)

    Only `axis_name` goes manual; dp/fsdp/tp/sp stay automatic inside, so
    the stage_fn's own sharding constraints keep working.
    """
    tmap = jax.tree.map
    n = mesh.shape[axis_name]
    if n == 1:
        params_local = tmap(lambda p: p[0], stage_params)
        return jax.lax.map(lambda mb: stage_fn(params_local, mb), x_mb)

    M = jax.tree.leaves(x_mb)[0].shape[0]
    fwd = [(i, (i + 1) % n) for i in range(n)]

    def body(params_local, x_local):
        r = jax.lax.axis_index(axis_name)
        params_sq = tmap(lambda p: p[0], params_local)
        state = tmap(lambda l: jnp.zeros_like(l[0]), x_local)
        out_buf = tmap(jnp.zeros_like, x_local)

        def tick(carry, t):
            state, out_buf = carry
            # stage 0 picks up a fresh microbatch while the fill lasts
            mb_idx = jnp.minimum(t, M - 1)
            fresh = tmap(lambda l: jax.lax.dynamic_index_in_dim(
                l, mb_idx, 0, keepdims=False), x_local)
            inp = tmap(lambda f, s: jnp.where(r == 0, f, s), fresh, state)
            out = stage_fn(params_sq, inp)
            # last stage banks its result for microbatch t-(n-1)
            done_idx = jnp.clip(t - (n - 1), 0, M - 1)
            take = jnp.logical_and(r == n - 1, t >= n - 1)

            def bank(buf, o):
                upd = jax.lax.dynamic_update_index_in_dim(
                    buf, o.astype(buf.dtype), done_idx, 0)
                return jnp.where(take, upd, buf)

            out_buf = tmap(bank, out_buf, out)
            state = tmap(lambda o: jax.lax.ppermute(o, axis_name, fwd),
                         out)
            return (state, out_buf), None

        (state, out_buf), _ = jax.lax.scan(tick, (state, out_buf),
                                           jnp.arange(M + n - 1))
        # replicate final-stage outputs to all pp ranks; psum in f32 (XLA:CPU
        # miscompiles sub-f32 all-reduce in partial-manual regions, and on
        # TPU the f32 cast fuses into the collective anyway)
        mask = (jax.lax.axis_index(axis_name) == n - 1).astype(jnp.float32)
        return tmap(
            lambda b: jax.lax.psum(b.astype(jnp.float32) * mask,
                                   axis_name).astype(b.dtype),
            out_buf)

    return shard_map(
        body,
        mesh=mesh,
        in_specs=(P(axis_name), P()),
        out_specs=P(),
        axis_names=frozenset({axis_name}),
        check_vma=False,
    )(stage_params, x_mb)


def split_microbatches(x, num_microbatches: int):
    """Leaves [B, ...] -> [M, B/M, ...] over any pytree (pipeline_apply
    already accepts pytrees; a bare array is the one-leaf case)."""
    M = int(num_microbatches)

    def split_leaf(path, leaf):
        b = leaf.shape[0] if leaf.ndim else 0
        if leaf.ndim == 0 or b % M:
            where = jax.tree_util.keystr(path) or "<root>"
            raise ValueError(
                f"batch {b if leaf.ndim else '<scalar>'} at leaf {where} "
                f"(shape {tuple(leaf.shape)}) not divisible by "
                f"microbatches {M}")
        return leaf.reshape(M, b // M, *leaf.shape[1:])

    return jax.tree_util.tree_map_with_path(split_leaf, x)


def merge_microbatches(x):
    """Leaves [M, mb, ...] -> [B, ...] (inverse of split_microbatches)."""
    return jax.tree.map(
        lambda l: l.reshape(l.shape[0] * l.shape[1], *l.shape[2:]), x)
