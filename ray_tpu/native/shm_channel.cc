// shm_channel: single-producer single-consumer shared-memory ring channel.
//
// TPU-native analog of the reference's mutable plasma objects — the
// zero-copy transport under compiled graphs (reference:
// src/ray/core_worker/experimental_mutable_object_manager.h WriteAcquire
// :153 / ReadAcquire, experimental/channel/shared_memory_channel.py).
// Semantics match the reference's acquire/release protocol, generalized
// from one slot to a small ring so pipeline stages can run ahead:
//
//   writer: rt_chan_write_acquire -> largest free slot buffer (blocks while
//           the ring is full, i.e. reader is `nslots` versions behind)
//           rt_chan_write_release(nbytes) -> publishes the new version
//   reader: rt_chan_read_acquire -> blocks until an unread version exists,
//           returns (offset, nbytes); rt_chan_read_release frees the slot
//
// Progress uses C++11 atomics on the mapped header + bounded exponential
// backoff (spin -> usleep), no mutex: SPSC needs none, and a crashed peer
// can't strand a lock.  Timeouts return -2 so callers can poll their stop
// flags; a closed channel returns -3 (writer side sets the closed bit).
//
// C ABI for ctypes (no pybind11 in the image).

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x5254434841ULL;  // "RTCHA"
constexpr uint64_t kPage = 4096;

struct ChanHeader {
  uint64_t magic;
  uint64_t slot_size;
  uint32_t nslots;
  uint32_t initialized;
  std::atomic<uint64_t> write_seq;  // versions published
  std::atomic<uint64_t> read_seq;   // versions consumed
  std::atomic<uint32_t> closed;
  uint32_t pad;
  // per-slot payload byte counts
  std::atomic<uint64_t> slot_bytes[64];
};

struct Chan {
  int fd;
  uint8_t* base;
  uint64_t map_len;
  ChanHeader* hdr;
  uint64_t data_off;
};

uint64_t page_round(uint64_t n) { return (n + kPage - 1) & ~(kPage - 1); }

// bounded backoff wait; pred returns true to stop. timeout_us<0 = forever.
template <typename F>
bool wait_until(F pred, int64_t timeout_us) {
  int spins = 0;
  int64_t waited = 0;
  while (!pred()) {
    if (spins < 1024) {
      ++spins;
    } else {
      int64_t us = spins < 4096 ? 50 : 500;
      spins++;
      usleep((useconds_t)us);
      waited += us;
      if (timeout_us >= 0 && waited > timeout_us) return false;
    }
  }
  return true;
}

}  // namespace

extern "C" {

// Create or attach.  nslots <= 64.  Returns NULL on failure.
Chan* rt_chan_open(const char* path, uint64_t slot_size, uint32_t nslots) {
  if (nslots == 0 || nslots > 64) return nullptr;
  slot_size = page_round(slot_size);
  uint64_t data_off = page_round(sizeof(ChanHeader));
  uint64_t total = data_off + slot_size * nslots;

  int fd = open(path, O_RDWR | O_CREAT | O_EXCL, 0600);
  bool creator = fd >= 0;
  if (!creator) {
    if (errno != EEXIST) return nullptr;
    fd = open(path, O_RDWR);
    if (fd < 0) return nullptr;
    ChanHeader probe;
    for (int spin = 0; spin < 50000; ++spin) {
      ssize_t n = pread(fd, &probe, sizeof(uint64_t) * 4, 0);
      if (n >= (ssize_t)(sizeof(uint64_t) * 2) && probe.magic == kMagic)
        break;
      usleep(100);
    }
    struct stat st;
    if (fstat(fd, &st) != 0 || (uint64_t)st.st_size < total) {
      // attach with the creator's geometry
      if (pread(fd, &probe, sizeof(uint64_t) * 4, 0) !=
          (ssize_t)(sizeof(uint64_t) * 4)) {
        close(fd);
        return nullptr;
      }
    }
    if (pread(fd, &probe, sizeof(uint64_t) * 4, 0) ==
        (ssize_t)(sizeof(uint64_t) * 4) && probe.magic == kMagic) {
      slot_size = probe.slot_size;
      nslots = probe.nslots;
      total = data_off + slot_size * nslots;
    }
  } else {
    if (ftruncate(fd, (off_t)total) != 0) {
      close(fd);
      unlink(path);
      return nullptr;
    }
  }

  void* base = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED,
                    fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  Chan* c = new Chan;
  c->fd = fd;
  c->base = (uint8_t*)base;
  c->map_len = total;
  c->hdr = (ChanHeader*)base;
  c->data_off = data_off;
  if (creator) {
    memset(base, 0, data_off);
    c->hdr->slot_size = slot_size;
    c->hdr->nslots = nslots;
    c->hdr->write_seq.store(0);
    c->hdr->read_seq.store(0);
    c->hdr->closed.store(0);
    std::atomic_thread_fence(std::memory_order_seq_cst);
    c->hdr->magic = kMagic;
    c->hdr->initialized = 1;
  } else {
    wait_until([&] { return c->hdr->initialized != 0; }, 5000000);
  }
  return c;
}

void rt_chan_close_handle(Chan* c) {
  if (!c) return;
  munmap(c->base, c->map_len);
  close(c->fd);
  delete c;
}

uint64_t rt_chan_slot_size(Chan* c) { return c ? c->hdr->slot_size : 0; }

// Writer: reserve the next slot.  Returns payload offset, or
// -2 on timeout, -3 if closed.
int64_t rt_chan_write_acquire(Chan* c, int64_t timeout_us) {
  if (!c) return -3;
  ChanHeader* h = c->hdr;
  uint64_t w = h->write_seq.load(std::memory_order_relaxed);
  bool ok = wait_until(
      [&] {
        return h->closed.load(std::memory_order_relaxed) ||
               w - h->read_seq.load(std::memory_order_acquire) < h->nslots;
      },
      timeout_us);
  if (h->closed.load(std::memory_order_relaxed)) return -3;
  if (!ok) return -2;
  return (int64_t)(c->data_off + (w % h->nslots) * h->slot_size);
}

// Writer: publish nbytes written into the acquired slot.
int rt_chan_write_release(Chan* c, uint64_t nbytes) {
  if (!c) return -1;
  ChanHeader* h = c->hdr;
  uint64_t w = h->write_seq.load(std::memory_order_relaxed);
  h->slot_bytes[w % h->nslots].store(nbytes, std::memory_order_relaxed);
  h->write_seq.store(w + 1, std::memory_order_release);
  return 0;
}

// Reader: wait for an unread version.  On success stores nbytes and
// returns the payload offset; -2 on timeout; -3 closed AND drained.
int64_t rt_chan_read_acquire(Chan* c, uint64_t* nbytes, int64_t timeout_us) {
  if (!c) return -3;
  ChanHeader* h = c->hdr;
  uint64_t r = h->read_seq.load(std::memory_order_relaxed);
  bool ok = wait_until(
      [&] {
        return h->write_seq.load(std::memory_order_acquire) > r ||
               h->closed.load(std::memory_order_relaxed);
      },
      timeout_us);
  if (h->write_seq.load(std::memory_order_acquire) <= r) {
    return h->closed.load(std::memory_order_relaxed) ? -3 : -2;
  }
  if (!ok) return -2;
  *nbytes = h->slot_bytes[r % h->nslots].load(std::memory_order_relaxed);
  return (int64_t)(c->data_off + (r % h->nslots) * h->slot_size);
}

// Reader: free the slot for the writer.
int rt_chan_read_release(Chan* c) {
  if (!c) return -1;
  ChanHeader* h = c->hdr;
  h->read_seq.fetch_add(1, std::memory_order_release);
  return 0;
}

void rt_chan_close(Chan* c) {
  if (c) c->hdr->closed.store(1, std::memory_order_release);
}

int rt_chan_is_closed(Chan* c) {
  return c ? (int)c->hdr->closed.load(std::memory_order_relaxed) : 1;
}

}  // extern "C"
