// Sanitizer self-test driver for the native components.
//
// The reference runs its C++ unit tests under ASAN/TSAN bazel configs
// (.bazelrc asan/tsan); here the native pieces are small enough that one
// driver exercises each C ABI end to end and the whole binary is built
// with -fsanitize=address,undefined (ray_tpu/native/build.py --sanitize,
// run by tests/test_native_sanitize.py).  Exit 0 = no assertion failed
// AND no sanitizer report (sanitizers abort non-zero on findings).
//
// Build: g++ -std=c++17 -g -O1 -fsanitize=address,undefined \
//            selftest.cc shm_arena.cc shm_channel.cc sched.cc -lpthread

#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <unistd.h>

// C ABI surfaces (the .cc files define them; declared here rather than
// shared headers because the production consumers are ctypes callers)
struct Arena;
struct Chan;
extern "C" {
Arena* rt_arena_open(const char* path, uint64_t capacity, uint32_t n_entries);
void rt_arena_close(Arena* a);
uint64_t rt_create(Arena* a, const char* id, uint64_t size, int* err,
                   uint32_t flags);
int rt_seal(Arena* a, const char* id);
int rt_abort(Arena* a, const char* id);
uint64_t rt_get(Arena* a, const char* id, uint64_t* size);
int rt_release(Arena* a, const char* id);
int rt_delete(Arena* a, const char* id);
int64_t rt_get_flags(Arena* a, const char* id);
int rt_set_primary(Arena* a, const char* id, int on);
int rt_contains(Arena* a, const char* id);
int64_t rt_size(Arena* a, const char* id);
uint64_t rt_list(Arena* a, char* buf, uint64_t buflen);
void rt_memcpy(void* dst, const void* src, uint64_t n);
void rt_stats(Arena* a, uint64_t* capacity, uint64_t* used, uint64_t* nobj,
              uint64_t* npinned);

Chan* rt_chan_open(const char* path, uint64_t slot_size, uint32_t nslots);
void rt_chan_close_handle(Chan* c);
uint64_t rt_chan_slot_size(Chan* c);
int64_t rt_chan_write_acquire(Chan* c, int64_t timeout_us);
int rt_chan_write_release(Chan* c, uint64_t nbytes);
int64_t rt_chan_read_acquire(Chan* c, uint64_t* nbytes, int64_t timeout_us);
int rt_chan_read_release(Chan* c);
void rt_chan_close(Chan* c);
int rt_chan_is_closed(Chan* c);

void* rsched_create(double spread_threshold, int topk);
void rsched_destroy(void* h);
int rsched_intern(void* h, const char* name);
void rsched_upsert_node(void* h, const char* node_id, const int* ids,
                        const int64_t* totals, int cnt);
void rsched_set_alive(void* h, const char* node_id, int alive);
void rsched_remove_node(void* h, const char* node_id);
void rsched_set_avail(void* h, const char* node_id, const int* ids,
                      const int64_t* avail, int cnt);
int rsched_acquire(void* h, const char* node_id, const int* ids,
                   const int64_t* demand, int cnt);
void rsched_release(void* h, const char* node_id, const int* ids,
                    const int64_t* demand, int cnt);
int rsched_pick(void* h, const int* ids, const int64_t* demand, int cnt,
                int strategy, char* out, int out_cap);
}

static void test_arena(const std::string& dir) {
  std::string path = dir + "/arena.bin";
  unlink(path.c_str());            // a prior aborted run may have left one
  Arena* a = rt_arena_open(path.c_str(), 1 << 20, 64);
  assert(a);
  int err = 7;
  uint64_t off = rt_create(a, "obj-1", 4096, &err, 0);
  assert(off != 0 && err == 0);
  assert(rt_contains(a, "obj-1") == 0);   // unsealed: not yet visible
  assert(rt_seal(a, "obj-1") == 0);
  assert(rt_contains(a, "obj-1") == 1);
  uint64_t size = 0;
  assert(rt_get(a, "obj-1", &size) != 0 && size == 4096);
  assert(rt_size(a, "obj-1") == 4096);
  assert(rt_set_primary(a, "obj-1", 1) == 0);
  assert(rt_get_flags(a, "obj-1") >= 0);
  assert(rt_release(a, "obj-1") == 0);

  // abort path
  assert(rt_create(a, "obj-2", 128, &err, 0) != 0 && err == 0);
  assert(rt_abort(a, "obj-2") == 0);
  assert(rt_contains(a, "obj-2") == 0);

  // fill enough objects to exercise the extent allocator + list
  for (int i = 0; i < 20; ++i) {
    char id[32];
    snprintf(id, sizeof id, "bulk-%d", i);
    assert(rt_create(a, id, 8192, &err, 0) != 0 && err == 0);
    assert(rt_seal(a, id) == 0);
    uint64_t sz = 0;
    assert(rt_get(a, id, &sz) != 0 && sz == 8192);   // pins a reader ref
    assert(rt_release(a, id) == 0);                  // ...and drops it
  }
  char listbuf[4096];
  uint64_t n = rt_list(a, listbuf, sizeof listbuf);
  assert(n >= 21);
  uint64_t cap, used, nobj, npinned;
  rt_stats(a, &cap, &used, &nobj, &npinned);
  assert(nobj == n && used > 0 && cap >= used);
  for (int i = 0; i < 20; i += 2) {
    char id[32];
    snprintf(id, sizeof id, "bulk-%d", i);
    assert(rt_delete(a, id) == 0);
  }
  // memcpy helper on our own buffers
  char srcb[256], dstb[256];
  memset(srcb, 0x5a, sizeof srcb);
  rt_memcpy(dstb, srcb, sizeof dstb);
  assert(memcmp(srcb, dstb, sizeof dstb) == 0);
  rt_arena_close(a);
  unlink(path.c_str());
  printf("arena: ok\n");
}

static void test_chan(const std::string& dir) {
  std::string path = dir + "/chan.bin";
  unlink(path.c_str());
  Chan* w = rt_chan_open(path.c_str(), 4096, 4);
  Chan* r = rt_chan_open(path.c_str(), 4096, 4);
  assert(w && r && rt_chan_slot_size(w) >= 4096);
  for (int round = 0; round < 10; ++round) {
    int64_t woff = rt_chan_write_acquire(w, 1000000);
    assert(woff >= 0);
    assert(rt_chan_write_release(w, 100 + round) == 0);
    uint64_t nbytes = 0;
    int64_t roff = rt_chan_read_acquire(r, &nbytes, 1000000);
    assert(roff >= 0 && nbytes == (uint64_t)(100 + round));
    assert(rt_chan_read_release(r) == 0);
  }
  // fill the ring: the 5th un-read write must time out, not corrupt
  for (int i = 0; i < 4; ++i) {
    assert(rt_chan_write_acquire(w, 1000000) >= 0);
    assert(rt_chan_write_release(w, 1) == 0);
  }
  assert(rt_chan_write_acquire(w, 1000) < 0);
  rt_chan_close(w);
  assert(rt_chan_is_closed(r) == 1);
  rt_chan_close_handle(w);
  rt_chan_close_handle(r);
  unlink(path.c_str());
  printf("chan: ok\n");
}

static void test_sched() {
  void* s = rsched_create(0.5, 2);
  assert(s);
  int cpu = rsched_intern(s, "CPU");
  int tpu = rsched_intern(s, "TPU");
  assert(cpu != tpu && rsched_intern(s, "CPU") == cpu);
  int ids[2] = {cpu, tpu};
  int64_t totals_a[2] = {8, 4};
  int64_t totals_b[2] = {16, 0};
  rsched_upsert_node(s, "node-a", ids, totals_a, 2);
  rsched_upsert_node(s, "node-b", ids, totals_b, 2);
  rsched_set_avail(s, "node-a", ids, totals_a, 2);
  rsched_set_avail(s, "node-b", ids, totals_b, 2);

  int64_t want_tpu[2] = {1, 1};
  char out[64];
  assert(rsched_pick(s, ids, want_tpu, 2, 0, out, sizeof out) == 1);
  assert(std::string(out) == "node-a");   // only node with TPU
  assert(rsched_acquire(s, "node-a", ids, want_tpu, 2) == 1);
  rsched_release(s, "node-a", ids, want_tpu, 2);

  rsched_set_alive(s, "node-a", 0);
  assert(rsched_pick(s, ids, want_tpu, 2, 0, out, sizeof out) == 0);
  rsched_set_alive(s, "node-a", 1);
  rsched_remove_node(s, "node-b");
  assert(rsched_pick(s, ids, want_tpu, 2, 0, out, sizeof out) == 1);
  rsched_destroy(s);
  printf("sched: ok\n");
}

int main(int argc, char** argv) {
  std::string dir = argc > 1 ? argv[1] : "/tmp";
  test_arena(dir);
  test_chan(dir);
  test_sched();
  printf("native selftest: ALL OK\n");
  return 0;
}
