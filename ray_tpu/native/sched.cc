// rsched: native cluster resource scheduler.
//
// TPU-native equivalent of the reference's C++ scheduling core (reference:
// src/ray/raylet/scheduling/cluster_resource_scheduler.h,
// policy/hybrid_scheduling_policy.h:61, policy/bundle_scheduling_policy.h):
// fixed-point resource accounting per node, hybrid pack-then-spread node
// selection with top-k randomization, spread policy, and placement-group
// bundle planning (PACK / SPREAD / STRICT_PACK / STRICT_SPREAD) with
// simulated reservations.
//
// The control plane (Python, _private/control.py) keeps node *metadata*;
// this library owns the hot selection math.  C ABI via ctypes (no pybind11
// in the image).

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Node {
  std::string id;
  bool alive = true;
  std::vector<int64_t> total;  // indexed by interned resource id
  std::vector<int64_t> avail;
};

struct Sched {
  std::mutex mu;
  double spread_threshold = 0.5;
  int topk = 1;
  std::unordered_map<std::string, int> rids;
  std::vector<std::string> rnames;
  std::unordered_map<std::string, int> node_index;
  std::vector<Node> nodes;
  uint64_t rng = 0x9e3779b97f4a7c15ULL;
};

uint64_t next_rand(Sched* s) {
  // xorshift64*
  uint64_t x = s->rng;
  x ^= x >> 12;
  x ^= x << 25;
  x ^= x >> 27;
  s->rng = x;
  return x * 0x2545F4914F6CDD1DULL;
}

int intern(Sched* s, const char* name) {
  auto it = s->rids.find(name);
  if (it != s->rids.end()) return it->second;
  int id = static_cast<int>(s->rnames.size());
  s->rids.emplace(name, id);
  s->rnames.emplace_back(name);
  for (auto& n : s->nodes) {
    n.total.resize(s->rnames.size(), 0);
    n.avail.resize(s->rnames.size(), 0);
  }
  return id;
}

Node* find_node(Sched* s, const char* node_id) {
  auto it = s->node_index.find(node_id);
  if (it == s->node_index.end()) return nullptr;
  return &s->nodes[it->second];
}

bool fits(const Node& n, const int* ids, const int64_t* demand, int cnt) {
  for (int i = 0; i < cnt; ++i) {
    int r = ids[i];
    int64_t have = r < static_cast<int>(n.avail.size()) ? n.avail[r] : 0;
    if (have < demand[i]) return false;
  }
  return true;
}

// Critical-resource utilization after hypothetically placing `demand`
// (reference scores nodes by their most-utilized dimension).
double util_after(const Node& n, const int* ids, const int64_t* demand,
                  int cnt) {
  double u = 0.0;
  for (size_t r = 0; r < n.total.size(); ++r) {
    if (n.total[r] <= 0) continue;
    int64_t used = n.total[r] - n.avail[r];
    for (int i = 0; i < cnt; ++i)
      if (ids[i] == static_cast<int>(r)) used += demand[i];
    double ur = static_cast<double>(used) / static_cast<double>(n.total[r]);
    if (ur > u) u = ur;
  }
  return u;
}

constexpr int kPack = 0;    // hybrid: pack below threshold, then spread
constexpr int kSpread = 1;  // least utilized

// Core single-placement policy over an availability snapshot.
int pick_index(Sched* s, const std::vector<Node>& nodes, const int* ids,
               const int64_t* demand, int cnt, int strategy) {
  struct Cand {
    int idx;
    double util;
  };
  std::vector<Cand> below, above;
  for (size_t i = 0; i < nodes.size(); ++i) {
    const Node& n = nodes[i];
    if (!n.alive || !fits(n, ids, demand, cnt)) continue;
    double u = util_after(n, ids, demand, cnt);
    if (u <= s->spread_threshold)
      below.push_back({static_cast<int>(i), u});
    else
      above.push_back({static_cast<int>(i), u});
  }
  if (below.empty() && above.empty()) return -1;
  if (strategy == kSpread) {
    auto& pool = below.empty() ? above : below;
    auto best = std::min_element(
        pool.begin(), pool.end(),
        [](const Cand& a, const Cand& b) { return a.util < b.util; });
    return best->idx;
  }
  // hybrid pack: busiest node still under the spread threshold; top-k
  // randomization among the k best to avoid herding (reference:
  // hybrid_scheduling_policy.h schedule_top_k_absolute)
  if (!below.empty()) {
    std::sort(below.begin(), below.end(),
              [](const Cand& a, const Cand& b) { return a.util > b.util; });
    int k = std::min<int>(std::max(1, s->topk),
                          static_cast<int>(below.size()));
    return below[next_rand(s) % k].idx;
  }
  auto best = std::min_element(
      above.begin(), above.end(),
      [](const Cand& a, const Cand& b) { return a.util < b.util; });
  return best->idx;
}

}  // namespace

extern "C" {

void* rsched_create(double spread_threshold, int topk) {
  auto* s = new Sched();
  s->spread_threshold = spread_threshold;
  s->topk = topk;
  return s;
}

void rsched_destroy(void* h) { delete static_cast<Sched*>(h); }

int rsched_intern(void* h, const char* name) {
  auto* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  return intern(s, name);
}

// Register or replace a node's capacity; availability resets to total
// minus nothing (caller follows with rsched_set_avail for in-use state).
void rsched_upsert_node(void* h, const char* node_id, const int* ids,
                        const int64_t* totals, int cnt) {
  auto* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->node_index.find(node_id);
  if (it == s->node_index.end()) {
    s->node_index.emplace(node_id, static_cast<int>(s->nodes.size()));
    s->nodes.emplace_back();
    it = s->node_index.find(node_id);
    s->nodes.back().id = node_id;
  }
  Node& n = s->nodes[it->second];
  n.alive = true;
  n.total.assign(s->rnames.size(), 0);
  n.avail.assign(s->rnames.size(), 0);
  for (int i = 0; i < cnt; ++i) {
    if (ids[i] < 0 || ids[i] >= static_cast<int>(s->rnames.size())) continue;
    n.total[ids[i]] = totals[i];
    n.avail[ids[i]] = totals[i];
  }
}

void rsched_set_alive(void* h, const char* node_id, int alive) {
  auto* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  Node* n = find_node(s, node_id);
  if (n) n->alive = alive != 0;
}

void rsched_remove_node(void* h, const char* node_id) {
  auto* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  Node* n = find_node(s, node_id);
  if (n) {
    n->alive = false;
    n->total.assign(n->total.size(), 0);
    n->avail.assign(n->avail.size(), 0);
  }
}

// Overwrite availability (heartbeat ground truth).
void rsched_set_avail(void* h, const char* node_id, const int* ids,
                      const int64_t* avail, int cnt) {
  auto* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  Node* n = find_node(s, node_id);
  if (!n) return;
  n->avail.assign(s->rnames.size(), 0);
  for (int i = 0; i < cnt; ++i) {
    if (ids[i] < 0 || ids[i] >= static_cast<int>(s->rnames.size())) continue;
    n->avail[ids[i]] = avail[i];
  }
}

// Atomic feasibility check + subtract.  Returns 1 on success.
int rsched_acquire(void* h, const char* node_id, const int* ids,
                   const int64_t* demand, int cnt) {
  auto* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  Node* n = find_node(s, node_id);
  if (!n || !n->alive || !fits(*n, ids, demand, cnt)) return 0;
  for (int i = 0; i < cnt; ++i) n->avail[ids[i]] -= demand[i];
  return 1;
}

void rsched_release(void* h, const char* node_id, const int* ids,
                    const int64_t* demand, int cnt) {
  auto* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  Node* n = find_node(s, node_id);
  if (!n) return;
  for (int i = 0; i < cnt; ++i) {
    if (ids[i] < 0 || ids[i] >= static_cast<int>(n->avail.size())) continue;
    n->avail[ids[i]] += demand[i];
    if (n->avail[ids[i]] > n->total[ids[i]])
      n->avail[ids[i]] = n->total[ids[i]];
  }
}

// Pick a node (no reservation).  Returns 1 and writes the node id, or 0.
int rsched_pick(void* h, const int* ids, const int64_t* demand, int cnt,
                int strategy, char* out, int out_cap) {
  auto* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  int idx = pick_index(s, s->nodes, ids, demand, cnt, strategy);
  if (idx < 0) return 0;
  std::snprintf(out, out_cap, "%s", s->nodes[idx].id.c_str());
  return 1;
}

// Pick AND reserve up to `want` placements of one demand in a single
// locked pass (batched lease ramp-up: one crossing of the ctypes
// boundary instead of `want` pick+acquire round-trips).  Each pick
// subtracts the demand from the real books so successive picks spread
// correctly and the availability matches the reservation the caller is
// about to mirror into its own accounting.  Writes node indices (resolve
// via rsched_node_name) into out_indices; returns how many were placed
// (0..want).  Picks the caller rejects must be handed back with
// rsched_release.
int rsched_pick_n(void* h, const int* ids, const int64_t* demand, int cnt,
                  int strategy, int want, int* out_indices) {
  auto* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  int got = 0;
  for (; got < want; ++got) {
    int idx = pick_index(s, s->nodes, ids, demand, cnt, strategy);
    if (idx < 0) break;
    Node& n = s->nodes[idx];
    for (int i = 0; i < cnt; ++i) n.avail[ids[i]] -= demand[i];
    out_indices[got] = idx;
  }
  return got;
}

// Acquire up to `want` copies of one demand on one node atomically.
// Returns how many copies fit (each subtracted); 0 when the node is
// missing, dead, or full.
int rsched_acquire_n(void* h, const char* node_id, const int* ids,
                     const int64_t* demand, int cnt, int want) {
  auto* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  Node* n = find_node(s, node_id);
  if (!n || !n->alive) return 0;
  int got = 0;
  for (; got < want; ++got) {
    if (!fits(*n, ids, demand, cnt)) break;
    for (int i = 0; i < cnt; ++i) n->avail[ids[i]] -= demand[i];
  }
  return got;
}

// Plan placement for a placement group's bundles against a simulated
// snapshot (2-phase commit happens elsewhere; this is the policy step).
// bundles are flattened: offsets[b]..offsets[b+1] index into ids/demands.
// strategy: 0 PACK, 1 SPREAD, 2 STRICT_PACK, 3 STRICT_SPREAD.
// Writes each bundle's chosen node index into out_nodes (index into an
// id table returned via rsched_node_name).  Returns 1 on success.
int rsched_plan_bundles(void* h, const int* ids, const int64_t* demands,
                        const int* offsets, int n_bundles, int strategy,
                        int* out_nodes) {
  auto* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  std::vector<Node> sim = s->nodes;  // snapshot to reserve against

  auto sub = [&](int node, int b) {
    for (int i = offsets[b]; i < offsets[b + 1]; ++i)
      sim[node].avail[ids[i]] -= demands[i];
  };

  if (strategy == 2) {  // STRICT_PACK: all bundles on one node
    for (size_t ni = 0; ni < sim.size(); ++ni) {
      std::vector<Node> trial = sim;
      bool ok = trial[ni].alive;
      for (int b = 0; ok && b < n_bundles; ++b) {
        if (!fits(trial[ni], ids + offsets[b], demands + offsets[b],
                  offsets[b + 1] - offsets[b])) {
          ok = false;
          break;
        }
        for (int i = offsets[b]; i < offsets[b + 1]; ++i)
          trial[ni].avail[ids[i]] -= demands[i];
      }
      if (ok) {
        for (int b = 0; b < n_bundles; ++b) out_nodes[b] = static_cast<int>(ni);
        return 1;
      }
    }
    return 0;
  }

  std::vector<bool> used(sim.size(), false);
  for (int b = 0; b < n_bundles; ++b) {
    const int* bids = ids + offsets[b];
    const int64_t* bdem = demands + offsets[b];
    int cnt = offsets[b + 1] - offsets[b];
    int chosen = -1;
    if (strategy == 3) {  // STRICT_SPREAD: distinct nodes required
      double best_u = 2.0;
      for (size_t ni = 0; ni < sim.size(); ++ni) {
        if (used[ni] || !sim[ni].alive || !fits(sim[ni], bids, bdem, cnt))
          continue;
        double u = util_after(sim[ni], bids, bdem, cnt);
        if (u < best_u) {
          best_u = u;
          chosen = static_cast<int>(ni);
        }
      }
    } else {
      chosen = pick_index(s, sim, bids, bdem, cnt,
                          strategy == 1 ? kSpread : kPack);
    }
    if (chosen < 0) return 0;
    used[chosen] = true;
    sub(chosen, b);
    out_nodes[b] = chosen;
  }
  return 1;
}

// Resolve a node index from rsched_plan_bundles to its id string.
int rsched_node_name(void* h, int index, char* out, int out_cap) {
  auto* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  if (index < 0 || index >= static_cast<int>(s->nodes.size())) return 0;
  std::snprintf(out, out_cap, "%s", s->nodes[index].id.c_str());
  return 1;
}

int64_t rsched_get_avail(void* h, const char* node_id, int rid) {
  auto* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  Node* n = find_node(s, node_id);
  if (!n || rid < 0 || rid >= static_cast<int>(n->avail.size())) return -1;
  return n->avail[rid];
}

}  // extern "C"
