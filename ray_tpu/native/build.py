"""Lazy g++ build + cache for native components.

The reference ships prebuilt native binaries (bazel); we compile on first
use instead — a few hundred ms once per machine — and cache the .so next to
the sources keyed by source mtime, so edits rebuild automatically.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

logger = logging.getLogger(__name__)

_SRC_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_SRC_DIR, "_build")
_lock = threading.Lock()
_cache: dict = {}


def build_extension(name: str, sources: list, extra_flags: list = ()) -> str:
    """Compile sources into _build/lib<name>.so; returns the path.

    Rebuilds when any source is newer than the cached .so.  Raises
    RuntimeError if the compiler fails.
    """
    out = os.path.join(_BUILD_DIR, f"lib{name}.so")
    srcs = [os.path.join(_SRC_DIR, s) for s in sources]
    if os.path.exists(out):
        so_mtime = os.path.getmtime(out)
        if all(os.path.getmtime(s) <= so_mtime for s in srcs):
            return out
    os.makedirs(_BUILD_DIR, exist_ok=True)
    tmp = out + ".tmp.%d" % os.getpid()
    cmd = ["g++", "-O2", "-g", "-std=c++17", "-shared", "-fPIC",
           "-o", tmp, *srcs, "-lpthread", *extra_flags]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        raise RuntimeError(f"native build failed to run: {e}") from e
    if proc.returncode != 0:
        raise RuntimeError(
            f"native build of {name} failed:\n{proc.stderr[-4000:]}")
    os.replace(tmp, out)  # atomic: concurrent builders race benignly
    return out


def load_library(name: str, sources: list) -> ctypes.CDLL:
    """Build (if needed) and dlopen a native component; cached per-process."""
    with _lock:
        if name in _cache:
            return _cache[name]
        path = build_extension(name, sources)
        lib = ctypes.CDLL(path)
        _cache[name] = lib
        return lib
