"""Lazy g++ build + cache for native components.

The reference ships prebuilt native binaries (bazel); we compile on first
use instead — a few hundred ms once per machine — and cache the .so next to
the sources keyed by source mtime, so edits rebuild automatically.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

logger = logging.getLogger(__name__)

_SRC_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_SRC_DIR, "_build")
_lock = threading.Lock()
_cache: dict = {}


def _compile(out: str, srcs: list, flags: list, timeout: float) -> str:
    """mtime-cached g++ compile-and-swap shared by every build target.
    Raises RuntimeError on any failure mode (missing compiler included)."""
    if os.path.exists(out):
        out_mtime = os.path.getmtime(out)
        if all(os.path.getmtime(s) <= out_mtime for s in srcs):
            return out
    os.makedirs(_BUILD_DIR, exist_ok=True)
    tmp = out + ".tmp.%d" % os.getpid()
    cmd = ["g++", *flags, "-o", tmp, *srcs, "-lpthread"]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout)
    except (OSError, subprocess.TimeoutExpired) as e:
        raise RuntimeError(f"native build failed to run: {e}") from e
    if proc.returncode != 0:
        raise RuntimeError(
            f"native build of {os.path.basename(out)} failed:\n"
            f"{proc.stderr[-4000:]}")
    os.replace(tmp, out)  # atomic: concurrent builders race benignly
    return out


def build_extension(name: str, sources: list, extra_flags: list = ()) -> str:
    """Compile sources into _build/lib<name>.so; returns the path.

    Rebuilds when any source is newer than the cached .so.  Raises
    RuntimeError if the compiler fails.
    """
    return _compile(
        os.path.join(_BUILD_DIR, f"lib{name}.so"),
        [os.path.join(_SRC_DIR, s) for s in sources],
        ["-O2", "-g", "-std=c++17", "-shared", "-fPIC", *extra_flags],
        timeout=120)


def build_sanitized_selftest() -> str:
    """Build the ASAN+UBSAN self-test binary (reference: the C++ tests'
    bazel asan/tsan configs in .bazelrc); returns the binary path.
    Rebuilds when any native source is newer."""
    sources = ["selftest.cc", "shm_arena.cc", "shm_channel.cc", "sched.cc"]
    return _compile(
        os.path.join(_BUILD_DIR, "native_selftest_san"),
        [os.path.join(_SRC_DIR, s) for s in sources],
        ["-std=c++17", "-g", "-O1", "-fno-omit-frame-pointer",
         "-fsanitize=address,undefined", "-fno-sanitize-recover=all"],
        timeout=300)


def load_library(name: str, sources: list) -> ctypes.CDLL:
    """Build (if needed) and dlopen a native component; cached per-process."""
    with _lock:
        if name in _cache:
            return _cache[name]
        path = build_extension(name, sources)
        lib = ctypes.CDLL(path)
        _cache[name] = lib
        return lib


if __name__ == "__main__":
    import sys

    if "--sanitize" in sys.argv:
        path = build_sanitized_selftest()
        print(path)
        rc = subprocess.run([path, "/tmp"]).returncode
        sys.exit(rc)
    print("usage: python -m ray_tpu.native.build --sanitize")
