"""Native (C++) runtime components.

Built lazily with g++ on first use; cached under ``_build/``.  Each
component degrades gracefully to a pure-Python fallback when the toolchain
is unavailable (CI images always have g++).
"""

from .build import build_extension, load_library  # noqa: F401
