"""ctypes wrapper for the native cluster scheduler (sched.cc).

Mirrors the reference's C++ scheduling core surface
(ClusterResourceScheduler / HybridSchedulingPolicy /
BundleSchedulingPolicy) for the Python control plane.  Resources use the
same fixed-point integers as _private/common.normalize_resources.
"""

from __future__ import annotations

import ctypes
import logging
import threading
from typing import Dict, List, Optional, Sequence

from .build import build_extension

logger = logging.getLogger(__name__)

PACK = 0
SPREAD = 1
STRICT_PACK = 2
STRICT_SPREAD = 3

_lib = None
_lib_lock = threading.Lock()


def _load():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        path = build_extension("rsched", ["sched.cc"])
        lib = ctypes.CDLL(path)
        lib.rsched_create.restype = ctypes.c_void_p
        lib.rsched_create.argtypes = [ctypes.c_double, ctypes.c_int]
        lib.rsched_destroy.argtypes = [ctypes.c_void_p]
        lib.rsched_intern.restype = ctypes.c_int
        lib.rsched_intern.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        I = ctypes.POINTER(ctypes.c_int)
        Q = ctypes.POINTER(ctypes.c_int64)
        lib.rsched_upsert_node.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, I, Q, ctypes.c_int]
        lib.rsched_set_alive.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
        lib.rsched_remove_node.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rsched_set_avail.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, I, Q, ctypes.c_int]
        lib.rsched_acquire.restype = ctypes.c_int
        lib.rsched_acquire.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, I, Q, ctypes.c_int]
        lib.rsched_release.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, I, Q, ctypes.c_int]
        lib.rsched_pick.restype = ctypes.c_int
        lib.rsched_pick.argtypes = [
            ctypes.c_void_p, I, Q, ctypes.c_int, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_int]
        lib.rsched_pick_n.restype = ctypes.c_int
        lib.rsched_pick_n.argtypes = [
            ctypes.c_void_p, I, Q, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, I]
        lib.rsched_acquire_n.restype = ctypes.c_int
        lib.rsched_acquire_n.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, I, Q, ctypes.c_int,
            ctypes.c_int]
        lib.rsched_plan_bundles.restype = ctypes.c_int
        lib.rsched_plan_bundles.argtypes = [
            ctypes.c_void_p, I, Q, I, ctypes.c_int, ctypes.c_int, I]
        lib.rsched_node_name.restype = ctypes.c_int
        lib.rsched_node_name.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int]
        lib.rsched_get_avail.restype = ctypes.c_int64
        lib.rsched_get_avail.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
        _lib = lib
        return lib


class ClusterScheduler:
    """Native node-selection + resource accounting engine."""

    def __init__(self, spread_threshold: float = 0.5, topk: int = 1):
        self._lib = _load()
        self._h = self._lib.rsched_create(spread_threshold, topk)
        self._rids: Dict[str, int] = {}
        self._lock = threading.Lock()

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.rsched_destroy(self._h)
                self._h = None
        except Exception:
            pass

    def _rid(self, name: str) -> int:
        rid = self._rids.get(name)
        if rid is None:
            rid = self._lib.rsched_intern(self._h, name.encode())
            self._rids[name] = rid
        return rid

    def _pack(self, res: Dict[str, int]):
        n = len(res)
        ids = (ctypes.c_int * n)()
        vals = (ctypes.c_int64 * n)()
        with self._lock:
            for i, (k, v) in enumerate(res.items()):
                ids[i] = self._rid(k)
                vals[i] = int(v)
        return ids, vals, n

    def upsert_node(self, node_id: str, total: Dict[str, int]):
        ids, vals, n = self._pack(total)
        self._lib.rsched_upsert_node(self._h, node_id.encode(), ids, vals, n)

    def remove_node(self, node_id: str):
        self._lib.rsched_remove_node(self._h, node_id.encode())

    def set_alive(self, node_id: str, alive: bool):
        self._lib.rsched_set_alive(self._h, node_id.encode(), int(alive))

    def set_available(self, node_id: str, avail: Dict[str, int]):
        ids, vals, n = self._pack(avail)
        self._lib.rsched_set_avail(self._h, node_id.encode(), ids, vals, n)

    def acquire(self, node_id: str, demand: Dict[str, int]) -> bool:
        ids, vals, n = self._pack(demand)
        return bool(self._lib.rsched_acquire(self._h, node_id.encode(),
                                             ids, vals, n))

    def release(self, node_id: str, demand: Dict[str, int]):
        ids, vals, n = self._pack(demand)
        self._lib.rsched_release(self._h, node_id.encode(), ids, vals, n)

    def pick(self, demand: Dict[str, int],
             strategy: int = PACK) -> Optional[str]:
        ids, vals, n = self._pack(demand)
        out = ctypes.create_string_buffer(256)
        ok = self._lib.rsched_pick(self._h, ids, vals, n, strategy, out, 256)
        return out.value.decode() if ok else None

    def pick_n(self, demand: Dict[str, int], count: int,
               strategy: int = PACK) -> List[str]:
        """Pick AND reserve up to `count` placements of `demand` in one
        native call.  Unlike pick(), every returned node has the demand
        already subtracted from the native books — a pick the caller
        rejects must be handed back via release().  Returned names may
        repeat (one node can host several leases)."""
        if count <= 0:
            return []
        ids, vals, n = self._pack(demand)
        out = (ctypes.c_int * count)()
        got = self._lib.rsched_pick_n(self._h, ids, vals, n, strategy,
                                      count, out)
        names: List[str] = []
        buf = ctypes.create_string_buffer(256)
        for i in range(got):
            if self._lib.rsched_node_name(self._h, out[i], buf, 256):
                names.append(buf.value.decode())
        return names

    def acquire_n(self, node_id: str, demand: Dict[str, int],
                  count: int) -> int:
        """Atomically acquire up to `count` copies of `demand` on one
        node; returns how many fit (each already subtracted)."""
        if count <= 0:
            return 0
        ids, vals, n = self._pack(demand)
        return int(self._lib.rsched_acquire_n(
            self._h, node_id.encode(), ids, vals, n, count))

    def plan_bundles(self, bundles: Sequence[Dict[str, int]],
                     strategy: int = PACK) -> Optional[List[str]]:
        nb = len(bundles)
        flat_ids: List[int] = []
        flat_vals: List[int] = []
        offsets = [0]
        with self._lock:
            for b in bundles:
                for k, v in b.items():
                    flat_ids.append(self._rid(k))
                    flat_vals.append(int(v))
                offsets.append(len(flat_ids))
        ids = (ctypes.c_int * max(1, len(flat_ids)))(*flat_ids)
        vals = (ctypes.c_int64 * max(1, len(flat_vals)))(*flat_vals)
        offs = (ctypes.c_int * (nb + 1))(*offsets)
        out = (ctypes.c_int * max(1, nb))()
        ok = self._lib.rsched_plan_bundles(self._h, ids, vals, offs, nb,
                                           strategy, out)
        if not ok:
            return None
        names = []
        buf = ctypes.create_string_buffer(256)
        for i in range(nb):
            if not self._lib.rsched_node_name(self._h, out[i], buf, 256):
                return None
            names.append(buf.value.decode())
        return names

    def available(self, node_id: str, resource: str) -> int:
        return int(self._lib.rsched_get_avail(self._h, node_id.encode(),
                                              self._rid(resource)))


def try_create(spread_threshold: float = 0.5,
               topk: int = 1) -> Optional[ClusterScheduler]:
    """Build-or-None: callers fall back to the Python policy on failure."""
    try:
        return ClusterScheduler(spread_threshold, topk)
    except Exception as e:  # toolchain missing etc.
        logger.warning("native scheduler unavailable: %s", e)
        return None
