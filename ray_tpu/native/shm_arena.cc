// shm_arena: node-local shared-memory object arena (plasma equivalent).
//
// TPU-native redesign of the reference's plasma store (reference:
// src/ray/object_manager/plasma/store.h, object_lifecycle_manager.h,
// eviction_policy.h, plasma_allocator.h).  Instead of a store *process*
// that clients talk to over a unix socket with fd passing (plasma/fling.h),
// the whole store is a single mmap-backed arena file that every process on
// the node maps directly:
//
//   * allocation / table ops take a robust process-shared mutex held for
//     microseconds — there is no store round-trip on any path;
//   * object payloads are page-aligned, so a reader maps just its object
//     (offset-aligned mmap) and reads zero-copy;
//   * readers register (pid, count) pins; eviction validates pins with
//     kill(pid, 0) so crashed readers cannot leak pins forever (the role
//     plasma's client-socket-disconnect cleanup plays);
//   * LRU eviction of sealed, unpinned objects runs inline in the
//     allocating process when the arena is full (reference:
//     plasma/eviction_policy.h LRUCache), instead of in a store daemon.
//
// All allocator metadata (object table + free-extent list) lives in the
// arena header region, never interleaved with payload bytes, so a crashed
// writer cannot corrupt block linkage.  The file is sparse: pages cost
// physical memory only once touched.
//
// C ABI only — consumed from Python via ctypes (no pybind11 in the image).

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>

#include <fcntl.h>
#include <pthread.h>
#include <signal.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x524159545055ULL;  // "RAYTPU"
constexpr uint32_t kVersion = 3;  // 3: Header gained high_water
constexpr uint64_t kPage = 4096;
constexpr uint32_t kMaxReaders = 8;
constexpr uint32_t kIdLen = 64;  // incl. NUL

// entry states
constexpr uint32_t kEmpty = 0;
constexpr uint32_t kCreated = 1;  // allocated, being written
constexpr uint32_t kSealed = 2;
constexpr uint32_t kTomb = 3;  // deleted; probe chains continue through it

// entry flags
// Primary copy: the only in-memory copy of an owned object.  Never a
// victim of LRU eviction — it must be spilled to disk (and the flag
// cleared) before its memory can be reclaimed, mirroring plasma's
// pinned-primary rule (reference: local_object_manager.h pinned_objects_;
// eviction only reaps secondary copies).
constexpr uint32_t kFlagPrimary = 1;
// rt_create-only flag (never stored in Entry.flags): fail instead of
// growing the touched region past high_water — the caller can free
// grace-delayed garbage and retry warm before paying first-touch cost.
constexpr uint32_t kFlagWarmOnly = 1u << 30;

struct Reader {
  uint32_t pid;
  int32_t count;
};

struct Entry {
  uint64_t hash;      // 0 means look at state (empty vs tomb)
  uint32_t state;
  uint32_t creator_pid;
  uint32_t flags;
  uint32_t pad0;
  uint64_t off;       // payload offset in arena (page aligned)
  uint64_t size;      // payload bytes (allocated extent = page-rounded)
  uint64_t lru_tick;  // larger = more recently used
  Reader readers[kMaxReaders];
  char id[kIdLen];
};

struct Extent {
  uint64_t off;
  uint64_t len;
};

struct Header {
  uint64_t magic;
  uint32_t version;
  uint32_t initialized;  // set last by creator
  uint64_t capacity;     // total file size
  uint64_t data_off;     // start of payload region
  uint64_t data_len;
  uint32_t n_entries;
  uint32_t n_extents_max;
  uint64_t table_off;    // Entry[n_entries]
  uint64_t extents_off;  // Extent[n_extents_max], sorted by off
  uint32_t n_extents;
  uint32_t pad0;
  uint64_t lru_clock;
  uint64_t bytes_used;
  uint64_t n_objects;
  uint64_t n_evictions;
  uint64_t high_water;   // max data offset ever handed out (see extent_alloc)
  pthread_mutex_t mu;
};

struct Arena {
  int fd;
  uint8_t* base;
  uint64_t map_len;  // header + table + extents only (payload mapped by users)
  Header* hdr;
  Entry* table;
  Extent* extents;
};

uint64_t fnv1a(const char* s) {
  uint64_t h = 1469598103934665603ULL;
  for (; *s; ++s) {
    h ^= (uint8_t)*s;
    h *= 1099511628211ULL;
  }
  return h ? h : 1;  // 0 is the empty marker
}

uint64_t page_round(uint64_t n) { return (n + kPage - 1) & ~(kPage - 1); }

// ---- locking ---------------------------------------------------------------

int lock(Arena* a) {
  int rc = pthread_mutex_lock(&a->hdr->mu);
  if (rc == EOWNERDEAD) {
    // A process died holding the lock.  Table mutations are single-store
    // writes (state flips) or array ops completed under the lock; recover
    // by dropping unsealed entries owned by dead creators.
    pthread_mutex_consistent(&a->hdr->mu);
    for (uint32_t i = 0; i < a->hdr->n_entries; ++i) {
      Entry& e = a->table[i];
      if (e.state == kCreated && e.creator_pid != 0 &&
          kill((pid_t)e.creator_pid, 0) != 0 && errno == ESRCH) {
        e.state = kTomb;  // extent leaks until destroy; rare + bounded
      }
    }
    rc = 0;
  }
  return rc;
}

void unlock(Arena* a) { pthread_mutex_unlock(&a->hdr->mu); }

// ---- free-extent allocator (metadata in header region) ---------------------

// Insert [off, off+len) into the sorted extent list, coalescing neighbors.
void extent_free(Header* h, Extent* ex, uint64_t off, uint64_t len) {
  uint32_t n = h->n_extents;
  uint32_t i = 0;
  while (i < n && ex[i].off < off) ++i;
  bool merge_prev = i > 0 && ex[i - 1].off + ex[i - 1].len == off;
  bool merge_next = i < n && off + len == ex[i].off;
  if (merge_prev && merge_next) {
    ex[i - 1].len += len + ex[i].len;
    memmove(&ex[i], &ex[i + 1], (n - i - 1) * sizeof(Extent));
    h->n_extents = n - 1;
  } else if (merge_prev) {
    ex[i - 1].len += len;
  } else if (merge_next) {
    ex[i].off = off;
    ex[i].len += len;
  } else {
    if (n >= h->n_extents_max) return;  // can't record; leak (bounded)
    memmove(&ex[i + 1], &ex[i], (n - i) * sizeof(Extent));
    ex[i].off = off;
    ex[i].len = len;
    h->n_extents = n + 1;
  }
}

// Allocation of a page-rounded length; returns 0 on failure.
//
// Warm-first policy: prefer extents that start below the high-water mark
// (space that has been allocated before — its pages are already faulted
// in and zeroed), and only grow into virgin tail space when no recycled
// extent fits.  A plain lowest-offset first-fit over a large arena
// marches through cold pages for the whole first cycle (every put pays
// first-touch zero-fill, about half of memcpy bandwidth); with this
// policy the touched working set stays as small as the live set needs.
uint64_t extent_take(Header* h, Extent* ex, uint32_t i, uint64_t len) {
  uint64_t off = ex[i].off;
  ex[i].off += len;
  ex[i].len -= len;
  if (ex[i].len == 0) {
    memmove(&ex[i], &ex[i + 1], (h->n_extents - i - 1) * sizeof(Extent));
    h->n_extents--;
  }
  if (off + len > h->high_water) h->high_water = off + len;
  return off;
}

uint64_t extent_alloc(Header* h, Extent* ex, uint64_t len, bool warm_only) {
  for (uint32_t i = 0; i < h->n_extents; ++i) {
    if (ex[i].len >= len && ex[i].off < h->high_water)
      return extent_take(h, ex, i, len);
  }
  if (warm_only) return 0;
  for (uint32_t i = 0; i < h->n_extents; ++i) {
    if (ex[i].len >= len) return extent_take(h, ex, i, len);
  }
  return 0;
}

// ---- object table ----------------------------------------------------------

Entry* find_entry(Arena* a, const char* id, uint64_t h) {
  uint32_t mask = a->hdr->n_entries - 1;
  uint32_t i = (uint32_t)h & mask;
  for (uint32_t probes = 0; probes < a->hdr->n_entries; ++probes) {
    Entry& e = a->table[i];
    if (e.state == kEmpty) return nullptr;
    if (e.state != kTomb && e.hash == h && strncmp(e.id, id, kIdLen) == 0)
      return &e;
    i = (i + 1) & mask;
  }
  return nullptr;
}

Entry* find_slot(Arena* a, const char* id, uint64_t h) {
  uint32_t mask = a->hdr->n_entries - 1;
  uint32_t i = (uint32_t)h & mask;
  Entry* tomb = nullptr;
  for (uint32_t probes = 0; probes < a->hdr->n_entries; ++probes) {
    Entry& e = a->table[i];
    if (e.state == kEmpty) return tomb ? tomb : &e;
    if (e.state == kTomb) {
      if (!tomb) tomb = &e;
    } else if (e.hash == h && strncmp(e.id, id, kIdLen) == 0) {
      return &e;  // caller checks state
    }
    i = (i + 1) & mask;
  }
  return tomb;
}

bool pinned(Entry& e) {
  for (uint32_t r = 0; r < kMaxReaders; ++r) {
    if (e.readers[r].count > 0) {
      if (kill((pid_t)e.readers[r].pid, 0) != 0 && errno == ESRCH) {
        e.readers[r].count = 0;  // crashed reader: reclaim the pin
        e.readers[r].pid = 0;
      } else {
        return true;
      }
    }
  }
  return false;
}

void drop_object(Arena* a, Entry* e) {
  extent_free(a->hdr, a->extents, e->off, page_round(e->size ? e->size : 1));
  a->hdr->bytes_used -= page_round(e->size ? e->size : 1);
  a->hdr->n_objects--;
  e->state = kTomb;
  e->creator_pid = 0;
  memset(e->readers, 0, sizeof(e->readers));
}

// Evict sealed, unpinned objects in LRU order until `need` bytes can be
// allocated; returns the allocated offset or 0.
uint64_t alloc_with_eviction(Arena* a, uint64_t need, bool warm_only) {
  uint64_t off = extent_alloc(a->hdr, a->extents, need, warm_only);
  // warm_only is a cheap probe: never evict for it — if the probe fails,
  // the caller frees its own garbage and retries, and only the final
  // unconstrained create should spend cached copies on making room
  if (warm_only) return off;
  while (off == 0) {
    Entry* victim = nullptr;
    for (uint32_t i = 0; i < a->hdr->n_entries; ++i) {
      Entry& e = a->table[i];
      if (e.state == kSealed && !(e.flags & kFlagPrimary) && !pinned(e) &&
          (!victim || e.lru_tick < victim->lru_tick))
        victim = &e;
    }
    if (!victim) return 0;
    drop_object(a, victim);
    a->hdr->n_evictions++;
    off = extent_alloc(a->hdr, a->extents, need, warm_only);
  }
  return off;
}

}  // namespace

extern "C" {

// Create (or attach to, if it already exists) the arena at `path`.
// `capacity` is the payload (data region) size — table/extent metadata is
// allocated on top.  n_entries must be a power of two.  NULL on failure.
Arena* rt_arena_open(const char* path, uint64_t capacity, uint32_t n_entries) {
  if (n_entries == 0 || (n_entries & (n_entries - 1))) return nullptr;
  uint64_t table_off = page_round(sizeof(Header));
  uint64_t extents_off = page_round(table_off + n_entries * sizeof(Entry));
  uint32_t n_extents_max = n_entries;
  uint64_t data_off = page_round(extents_off + n_extents_max * sizeof(Extent));
  uint64_t data_len = page_round(capacity < (64 << 10) ? (64 << 10) : capacity);
  capacity = data_off + data_len;

  int fd = open(path, O_RDWR | O_CREAT | O_EXCL, 0600);
  bool creator = fd >= 0;
  if (!creator) {
    if (errno != EEXIST) return nullptr;
    fd = open(path, O_RDWR);
    if (fd < 0) return nullptr;
    // wait for the creator to finish initializing
    Header probe;
    for (int spin = 0; spin < 50000; ++spin) {
      ssize_t n = pread(fd, &probe, sizeof(probe), 0);
      if (n == (ssize_t)sizeof(probe) && probe.magic == kMagic &&
          probe.initialized)
        break;
      usleep(100);
    }
    // reject attaches across layout versions: Entry's stride changed in
    // v2, so a mismatched attacher would misread the whole entry table
    if (pread(fd, &probe, sizeof(probe), 0) != (ssize_t)sizeof(probe) ||
        probe.magic != kMagic || !probe.initialized ||
        probe.version != kVersion) {
      close(fd);
      return nullptr;
    }
    table_off = probe.table_off;
    extents_off = probe.extents_off;
    n_entries = probe.n_entries;
    n_extents_max = probe.n_extents_max;
    data_off = probe.data_off;
    capacity = probe.capacity;
  } else {
    if (ftruncate(fd, (off_t)capacity) != 0) {
      close(fd);
      unlink(path);
      return nullptr;
    }
  }

  uint64_t map_len = data_off;  // metadata only; payloads mapped per-object
  void* base =
      mmap(nullptr, map_len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  Arena* a = new Arena;
  a->fd = fd;
  a->base = (uint8_t*)base;
  a->map_len = map_len;
  a->hdr = (Header*)base;
  a->table = (Entry*)(a->base + table_off);
  a->extents = (Extent*)(a->base + extents_off);

  if (creator) {
    Header* h = a->hdr;
    memset(h, 0, sizeof(Header));
    h->magic = kMagic;
    h->version = kVersion;
    h->capacity = capacity;
    h->data_off = data_off;
    h->data_len = capacity - data_off;
    h->n_entries = n_entries;
    h->n_extents_max = n_extents_max;
    h->table_off = table_off;
    h->extents_off = extents_off;
    h->n_extents = 1;
    a->extents[0].off = data_off;
    a->extents[0].len = capacity - data_off;
    pthread_mutexattr_t at;
    pthread_mutexattr_init(&at);
    pthread_mutexattr_setpshared(&at, PTHREAD_PROCESS_SHARED);
    pthread_mutexattr_setrobust(&at, PTHREAD_MUTEX_ROBUST);
    pthread_mutex_init(&h->mu, &at);
    pthread_mutexattr_destroy(&at);
    __sync_synchronize();
    h->initialized = 1;
  }
  return a;
}

void rt_arena_close(Arena* a) {
  if (!a) return;
  munmap(a->base, a->map_len);
  close(a->fd);
  delete a;
}

// Allocate an object of `size` bytes.  Returns the payload offset
// (page aligned) or 0 on failure.  errno-style result via *err:
//   0 ok, 1 exists (created or sealed), 2 out of memory/ids.
uint64_t rt_create(Arena* a, const char* id, uint64_t size, int* err,
                   uint32_t flags) {
  *err = 2;
  if (!a) return 0;
  if (strlen(id) >= kIdLen) return 0;
  uint64_t h = fnv1a(id);
  if (lock(a) != 0) return 0;
  Entry* e = find_slot(a, id, h);
  if (!e) {
    unlock(a);
    return 0;  // table full
  }
  if (e->state == kCreated || e->state == kSealed) {
    // Re-create of an existing copy: upgrade to primary if requested —
    // lineage recovery can recompute an object on a node that held a
    // pulled (evictable) copy, and the recomputed object is now the
    // primary.  Never downgrade here.
    if (flags & kFlagPrimary) e->flags |= kFlagPrimary;
    *err = 1;
    unlock(a);
    return 0;
  }
  uint64_t need = page_round(size ? size : 1);
  uint64_t off = alloc_with_eviction(a, need, flags & kFlagWarmOnly);
  if (off == 0) {
    unlock(a);
    return 0;
  }
  memset(e, 0, sizeof(Entry));
  e->hash = h;
  e->state = kCreated;
  e->flags = flags & ~kFlagWarmOnly;
  e->creator_pid = (uint32_t)getpid();
  e->off = off;
  e->size = size;
  e->lru_tick = ++a->hdr->lru_clock;
  strncpy(e->id, id, kIdLen - 1);
  a->hdr->bytes_used += need;
  a->hdr->n_objects++;
  *err = 0;
  unlock(a);
  return off;
}

int rt_seal(Arena* a, const char* id) {
  if (!a) return -1;
  uint64_t h = fnv1a(id);
  if (lock(a) != 0) return -1;
  Entry* e = find_entry(a, id, h);
  int rc = -1;
  if (e && e->state == kCreated) {
    e->state = kSealed;
    e->lru_tick = ++a->hdr->lru_clock;
    rc = 0;
  } else if (e && e->state == kSealed) {
    rc = 0;
  }
  unlock(a);
  return rc;
}

// Abort an unsealed create (crash cleanup / failed write).
int rt_abort(Arena* a, const char* id) {
  if (!a) return -1;
  uint64_t h = fnv1a(id);
  if (lock(a) != 0) return -1;
  Entry* e = find_entry(a, id, h);
  int rc = -1;
  if (e && e->state == kCreated) {
    drop_object(a, e);
    rc = 0;
  }
  unlock(a);
  return rc;
}

// Pin + locate a sealed object.  Returns payload offset (0 if absent);
// *size receives the byte size.  Caller must rt_release when done.
uint64_t rt_get(Arena* a, const char* id, uint64_t* size) {
  if (!a) return 0;
  uint64_t h = fnv1a(id);
  if (lock(a) != 0) return 0;
  Entry* e = find_entry(a, id, h);
  if (!e || e->state != kSealed) {
    unlock(a);
    return 0;
  }
  uint32_t pid = (uint32_t)getpid();
  int free_slot = -1;
  bool pinned_here = false;
  for (uint32_t r = 0; r < kMaxReaders; ++r) {
    if (e->readers[r].count > 0 && e->readers[r].pid == pid) {
      e->readers[r].count++;
      pinned_here = true;
      break;
    }
    if (free_slot < 0 && e->readers[r].count <= 0) free_slot = (int)r;
  }
  if (!pinned_here) {
    if (free_slot < 0) {
      // reader slots exhausted: reclaim slots of dead pids
      for (uint32_t r = 0; r < kMaxReaders; ++r) {
        if (kill((pid_t)e->readers[r].pid, 0) != 0 && errno == ESRCH) {
          free_slot = (int)r;
          break;
        }
      }
    }
    if (free_slot < 0) {
      unlock(a);
      return 0;  // too many concurrent reader processes
    }
    e->readers[free_slot].pid = pid;
    e->readers[free_slot].count = 1;
  }
  e->lru_tick = ++a->hdr->lru_clock;
  *size = e->size;
  uint64_t off = e->off;
  unlock(a);
  return off;
}

int rt_release(Arena* a, const char* id) {
  if (!a) return -1;
  uint64_t h = fnv1a(id);
  if (lock(a) != 0) return -1;
  Entry* e = find_entry(a, id, h);
  int rc = -1;
  if (e) {
    uint32_t pid = (uint32_t)getpid();
    for (uint32_t r = 0; r < kMaxReaders; ++r) {
      if (e->readers[r].pid == pid && e->readers[r].count > 0) {
        if (--e->readers[r].count == 0) e->readers[r].pid = 0;
        rc = 0;
        break;
      }
    }
  }
  unlock(a);
  return rc;
}

// Delete a sealed object (frees space immediately if unpinned; pinned
// objects are dropped from the table and their extent freed when the
// allocator next needs space and the pins are gone — here we simply skip).
int rt_delete(Arena* a, const char* id) {
  if (!a) return -1;
  uint64_t h = fnv1a(id);
  if (lock(a) != 0) return -1;
  Entry* e = find_entry(a, id, h);
  int rc = -1;
  if (e && (e->state == kSealed || e->state == kCreated)) {
    if (!pinned(*e)) {
      drop_object(a, e);
      rc = 0;
    } else {
      // demote: stays readable by pinners, invisible to get() latecomers?
      // Simplest correct behavior: keep sealed, let eviction reap it.
      rc = 1;
    }
  }
  unlock(a);
  return rc;
}

// Flags of a live entry, or -1 if absent.
int64_t rt_get_flags(Arena* a, const char* id) {
  if (!a) return -1;
  uint64_t h = fnv1a(id);
  if (lock(a) != 0) return -1;
  Entry* e = find_entry(a, id, h);
  int64_t rc =
      (e && (e->state == kSealed || e->state == kCreated)) ? e->flags : -1;
  unlock(a);
  return rc;
}

// Set/clear the primary-copy flag (spill manager clears it once the
// object's bytes are safe on disk, making the entry evictable/deletable).
int rt_set_primary(Arena* a, const char* id, int on) {
  if (!a) return -1;
  uint64_t h = fnv1a(id);
  if (lock(a) != 0) return -1;
  Entry* e = find_entry(a, id, h);
  int rc = -1;
  if (e && (e->state == kSealed || e->state == kCreated)) {
    if (on)
      e->flags |= kFlagPrimary;
    else
      e->flags &= ~kFlagPrimary;
    rc = 0;
  }
  unlock(a);
  return rc;
}

// 1 if sealed, 0 otherwise.
int rt_contains(Arena* a, const char* id) {
  if (!a) return 0;
  uint64_t h = fnv1a(id);
  if (lock(a) != 0) return 0;
  Entry* e = find_entry(a, id, h);
  int rc = (e && e->state == kSealed) ? 1 : 0;
  unlock(a);
  return rc;
}

// Size of a sealed object, or -1.
int64_t rt_size(Arena* a, const char* id) {
  if (!a) return -1;
  uint64_t h = fnv1a(id);
  if (lock(a) != 0) return -1;
  Entry* e = find_entry(a, id, h);
  int64_t rc = (e && e->state == kSealed) ? (int64_t)e->size : -1;
  unlock(a);
  return rc;
}

// Write NUL-separated ids of sealed objects into buf; returns count.
uint64_t rt_list(Arena* a, char* buf, uint64_t buflen) {
  if (!a) return 0;
  if (lock(a) != 0) return 0;
  uint64_t count = 0, w = 0;
  for (uint32_t i = 0; i < a->hdr->n_entries; ++i) {
    Entry& e = a->table[i];
    if (e.state == kSealed) {
      uint64_t n = strlen(e.id) + 1;
      if (w + n > buflen) break;
      memcpy(buf + w, e.id, n);
      w += n;
      count++;
    }
  }
  unlock(a);
  return count;
}

// Plain memcpy exposed for the Python write path: a ctypes foreign call
// RELEASES the GIL, so concurrent putters' payload copies overlap on
// separate cores — a memoryview slice-assign of the same bytes holds the
// GIL for the whole copy and serializes every writer in the process
// (the multi-client put-bandwidth collapse in the r2 bench table).
void rt_memcpy(void* dst, const void* src, uint64_t n) {
  memcpy(dst, src, n);
}

void rt_stats(Arena* a, uint64_t* capacity, uint64_t* used, uint64_t* nobj,
              uint64_t* nevict) {
  if (!a) return;
  if (lock(a) != 0) return;
  *capacity = a->hdr->data_len;
  *used = a->hdr->bytes_used;
  *nobj = a->hdr->n_objects;
  *nevict = a->hdr->n_evictions;
  unlock(a);
}

}  // extern "C"
