"""Pure-functional jax environments: TPU-native rollouts.

The reference samples with Python gymnasium loops on CPU EnvRunner actors
(reference: rllib/env/single_agent_env_runner.py).  The TPU-native design
goes further: an environment is a pair of pure functions

    reset(rng)           -> (state, obs)
    step(state, action)  -> (state, obs, reward, done)

so a whole rollout is one `lax.scan` — sampling compiles onto the
accelerator with zero host round-trips (the gymnax/brax pattern), and
vectorization is `vmap` instead of subprocess pools.  Gymnasium envs
remain supported host-side via env_runner.GymEnvRunner for API parity.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp


class JaxEnv:
    """Stateless env description; state is an explicit pytree."""

    #: dict with obs_dim / num_actions / max_episode_steps
    spec: Dict[str, Any]

    def reset(self, rng) -> Tuple[Any, jnp.ndarray]:
        raise NotImplementedError

    def step(self, state, action) -> Tuple[Any, jnp.ndarray, jnp.ndarray,
                                           jnp.ndarray]:
        raise NotImplementedError


class CartPoleState(NamedTuple):
    x: jnp.ndarray
    x_dot: jnp.ndarray
    theta: jnp.ndarray
    theta_dot: jnp.ndarray
    t: jnp.ndarray
    rng: jnp.ndarray


class CartPole(JaxEnv):
    """CartPole-v1 dynamics (matches gymnasium classic_control cartpole:
    same constants, Euler integration, termination bounds), as pure jax.
    """

    GRAVITY = 9.8
    MASSCART = 1.0
    MASSPOLE = 0.1
    TOTAL_MASS = MASSCART + MASSPOLE
    LENGTH = 0.5
    POLEMASS_LENGTH = MASSPOLE * LENGTH
    FORCE_MAG = 10.0
    TAU = 0.02
    THETA_LIMIT = 12 * 2 * jnp.pi / 360
    X_LIMIT = 2.4

    spec = {"obs_dim": 4, "num_actions": 2, "max_episode_steps": 500}

    def reset(self, rng):
        rng, sub = jax.random.split(rng)
        vals = jax.random.uniform(sub, (4,), minval=-0.05, maxval=0.05)
        state = CartPoleState(vals[0], vals[1], vals[2], vals[3],
                              jnp.zeros((), jnp.int32), rng)
        return state, self._obs(state)

    def _obs(self, s: CartPoleState):
        return jnp.stack([s.x, s.x_dot, s.theta, s.theta_dot])

    def step(self, s: CartPoleState, action):
        force = jnp.where(action == 1, self.FORCE_MAG, -self.FORCE_MAG)
        costheta = jnp.cos(s.theta)
        sintheta = jnp.sin(s.theta)
        temp = (force + self.POLEMASS_LENGTH * s.theta_dot ** 2 * sintheta) \
            / self.TOTAL_MASS
        thetaacc = (self.GRAVITY * sintheta - costheta * temp) / (
            self.LENGTH * (4.0 / 3.0
                           - self.MASSPOLE * costheta ** 2 / self.TOTAL_MASS))
        xacc = temp - self.POLEMASS_LENGTH * thetaacc * costheta \
            / self.TOTAL_MASS
        x = s.x + self.TAU * s.x_dot
        x_dot = s.x_dot + self.TAU * xacc
        theta = s.theta + self.TAU * s.theta_dot
        theta_dot = s.theta_dot + self.TAU * thetaacc
        t = s.t + 1
        done = (
            (jnp.abs(x) > self.X_LIMIT)
            | (jnp.abs(theta) > self.THETA_LIMIT)
            | (t >= self.spec["max_episode_steps"])
        )
        # auto-reset on done (vectorized envs never sit idle)
        rng, sub = jax.random.split(s.rng)
        reset_vals = jax.random.uniform(sub, (4,), minval=-0.05, maxval=0.05)
        new = CartPoleState(
            jnp.where(done, reset_vals[0], x),
            jnp.where(done, reset_vals[1], x_dot),
            jnp.where(done, reset_vals[2], theta),
            jnp.where(done, reset_vals[3], theta_dot),
            jnp.where(done, 0, t), rng)
        return new, self._obs(new), jnp.ones(()), done


class PendulumState(NamedTuple):
    th: jnp.ndarray
    thdot: jnp.ndarray
    t: jnp.ndarray
    rng: jnp.ndarray


class Pendulum(JaxEnv):
    """Pendulum-v1 dynamics (gymnasium classic_control pendulum: same
    constants, semi-implicit Euler, ±8 rad/s speed clip), as pure jax.
    The canonical continuous-control benchmark: obs [cos th, sin th,
    thdot], one torque action in [-2, 2], reward
    -(angle^2 + 0.1 thdot^2 + 0.001 u^2); 200-step episodes
    (truncation only, auto-reset)."""

    MAX_SPEED = 8.0
    MAX_TORQUE = 2.0
    DT = 0.05
    G = 10.0
    M = 1.0
    L = 1.0

    # time_limit_only: done is truncation, never a terminal state —
    # value-based learners must not cut bootstrap targets on it
    spec = {"obs_dim": 3, "action_dim": 1,
            "action_low": -2.0, "action_high": 2.0,
            "max_episode_steps": 200, "time_limit_only": True}

    def reset(self, rng):
        rng, sub = jax.random.split(rng)
        vals = jax.random.uniform(sub, (2,),
                                  minval=jnp.asarray([-jnp.pi, -1.0]),
                                  maxval=jnp.asarray([jnp.pi, 1.0]))
        state = PendulumState(vals[0], vals[1],
                              jnp.zeros((), jnp.int32), rng)
        return state, self._obs(state)

    def _obs(self, s: PendulumState):
        return jnp.stack([jnp.cos(s.th), jnp.sin(s.th), s.thdot])

    def step(self, s: PendulumState, action):
        u = jnp.clip(jnp.reshape(action, ()), -self.MAX_TORQUE,
                     self.MAX_TORQUE)
        th_norm = ((s.th + jnp.pi) % (2 * jnp.pi)) - jnp.pi
        cost = th_norm ** 2 + 0.1 * s.thdot ** 2 + 0.001 * u ** 2
        thdot = s.thdot + (3 * self.G / (2 * self.L) * jnp.sin(s.th)
                           + 3.0 / (self.M * self.L ** 2) * u) * self.DT
        thdot = jnp.clip(thdot, -self.MAX_SPEED, self.MAX_SPEED)
        th = s.th + thdot * self.DT
        t = s.t + 1
        done = t >= self.spec["max_episode_steps"]
        rng, sub = jax.random.split(s.rng)
        reset_vals = jax.random.uniform(
            sub, (2,), minval=jnp.asarray([-jnp.pi, -1.0]),
            maxval=jnp.asarray([jnp.pi, 1.0]))
        new = PendulumState(
            jnp.where(done, reset_vals[0], th),
            jnp.where(done, reset_vals[1], thdot),
            jnp.where(done, 0, t), rng)
        return new, self._obs(new), -cost, done


_REGISTRY: Dict[str, Callable[[], JaxEnv]] = {
    "CartPole-v1": CartPole,
    "Pendulum-v1": Pendulum,
}


def register_env(name: str, ctor: Callable[[], JaxEnv]):
    _REGISTRY[name] = ctor


def make_env(name: str) -> JaxEnv:
    if name not in _REGISTRY:
        raise KeyError(f"unknown jax env {name!r}; "
                       f"registered: {sorted(_REGISTRY)}")
    return _REGISTRY[name]()


@partial(jax.jit, static_argnums=(0, 1, 4))
def rollout(env: JaxEnv, policy_fn, params, carry, num_steps: int):
    """Vectorized on-device rollout: (states, rngs) x num_steps scan.

    policy_fn(params, obs, rng) -> (action, extras) — typically
    RLModule.forward_exploration.  carry = (env_states, obs, rng) from a
    previous call (or `init_carry`), so sampling is continuous across
    batch boundaries like the reference's EnvRunner.

    Returns (new_carry, batch) where batch arrays are [T, B, ...]:
    obs, action, reward, done, plus whatever extras policy_fn emits.
    """
    def one_step(carry, _):
        states, obs, rng = carry
        rng, act_rng = jax.random.split(rng)
        action, extras = policy_fn(params, obs, act_rng)
        states, next_obs, reward, done = jax.vmap(env.step)(states, action)
        out = {"obs": obs, "action": action, "reward": reward,
               "done": done, **extras}
        return (states, next_obs, rng), out

    carry, batch = jax.lax.scan(one_step, carry, None, length=num_steps)
    return carry, batch


def init_carry(env: JaxEnv, rng, num_envs: int):
    rngs = jax.random.split(rng, num_envs + 1)
    states, obs = jax.vmap(env.reset)(rngs[1:])
    return states, obs, rngs[0]
