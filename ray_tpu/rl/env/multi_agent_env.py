"""Multi-agent environment protocol + runner.

Analog of the reference's MultiAgentEnv (reference:
rllib/env/multi_agent_env.py:32) and MultiAgentEnvRunner
(rllib/env/multi_agent_env_runner.py): dict-keyed parallel stepping —
every live agent submits an action each step and receives its own
observation/reward, with per-agent termination.

The runner maps agents onto POLICIES via `policy_mapping_fn` and emits
one [T, B_agents, ...] batch PER POLICY, so a learner per policy trains
on exactly its own experience (reference:
rl_module/multi_rl_module.py MultiRLModule).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu


class MultiAgentEnv:
    """Parallel multi-agent env (reference: multi_agent_env.py:32).

    Subclasses define:
      possible_agents: list of agent ids
      observation_spec(agent) -> {"obs_dim": int}
      action_spec(agent) -> {"num_actions": int}
      reset(seed) -> obs_dict
      step(action_dict) -> (obs_dict, reward_dict, terminated_dict,
                            truncated_dict, info_dict); the special key
                            "__all__" in terminated ends the episode.
    """

    possible_agents: List[str] = []

    def observation_spec(self, agent: str) -> Dict[str, int]:
        raise NotImplementedError

    def action_spec(self, agent: str) -> Dict[str, int]:
        raise NotImplementedError

    def reset(self, seed: Optional[int] = None) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def step(self, actions: Dict[str, int]):
        raise NotImplementedError


# -- env registry (reference: tune.register_env) ----------------------------

_ENV_REGISTRY: Dict[str, Callable[[], MultiAgentEnv]] = {}


def register_env(name: str, creator: Callable[[], MultiAgentEnv]) -> None:
    _ENV_REGISTRY[name] = creator


def make_multi_agent_env(name_or_creator) -> MultiAgentEnv:
    if callable(name_or_creator):
        return name_or_creator()
    creator = _ENV_REGISTRY.get(name_or_creator)
    if creator is None:
        raise ValueError(f"no registered multi-agent env "
                         f"{name_or_creator!r}; register_env() it first")
    return creator()


class CooperativeMatchEnv(MultiAgentEnv):
    """Tiny cooperative debug env (the reference's MultiAgentCartPole
    role): each agent sees its own one-hot target; the TEAM earns +1 only
    when every agent outputs its own target.  Distinct observations per
    agent force distinct policies."""

    def __init__(self, num_agents: int = 2, num_targets: int = 4,
                 episode_len: int = 8):
        self.possible_agents = [f"agent_{i}" for i in range(num_agents)]
        self.k = num_targets
        self.episode_len = episode_len
        self._rng = np.random.default_rng(0)
        self._t = 0
        self._targets: Dict[str, int] = {}

    def observation_spec(self, agent: str) -> Dict[str, int]:
        return {"obs_dim": self.k}

    def action_spec(self, agent: str) -> Dict[str, int]:
        return {"num_actions": self.k}

    def _obs(self) -> Dict[str, np.ndarray]:
        return {a: np.eye(self.k, dtype=np.float32)[self._targets[a]]
                for a in self.possible_agents}

    def reset(self, seed: Optional[int] = None) -> Dict[str, np.ndarray]:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._t = 0
        self._targets = {a: int(self._rng.integers(self.k))
                         for a in self.possible_agents}
        return self._obs()

    def step(self, actions: Dict[str, int]):
        self._t += 1
        all_correct = all(int(actions[a]) == self._targets[a]
                          for a in self.possible_agents)
        reward = 1.0 if all_correct else 0.0
        self._targets = {a: int(self._rng.integers(self.k))
                         for a in self.possible_agents}
        done = self._t >= self.episode_len
        obs = self._obs()
        rewards = {a: reward for a in self.possible_agents}
        terms = {a: done for a in self.possible_agents}
        terms["__all__"] = done
        truncs = {a: False for a in self.possible_agents}
        truncs["__all__"] = False
        return obs, rewards, terms, truncs, {}


register_env("coop_match", CooperativeMatchEnv)


class MultiAgentEnvRunner:
    """Samples B env copies in lockstep; emits one batch per POLICY.

    Synchronous parallel protocol: every agent acts each step (the
    reference's env-runner also drives the env check/parallel API).
    Episodes auto-reset on "__all__".
    """

    def __init__(self, env_name: str, policies: List[str],
                 policy_mapping_fn: Callable[[str], str],
                 module_spec: Dict[str, Any], num_envs: int = 4,
                 seed: int = 0):
        import jax

        from ray_tpu.rl.core.multi_rl_module import MultiRLModule

        self.envs = [make_multi_agent_env(env_name)
                     for _ in range(num_envs)]
        self.num_envs = num_envs
        self.agents = list(self.envs[0].possible_agents)
        self.policy_mapping_fn = policy_mapping_fn
        self.policies = list(policies)
        # per-policy spec from any agent mapped to it
        specs = {}
        for pid in self.policies:
            agents = [a for a in self.agents
                      if policy_mapping_fn(a) == pid]
            if not agents:
                raise ValueError(f"policy {pid!r} maps to no agent")
            a0 = agents[0]
            specs[pid] = {**self.envs[0].observation_spec(a0),
                          **self.envs[0].action_spec(a0)}
        self.module = MultiRLModule(
            specs, hidden=module_spec.get("hidden", (64, 64)))
        self.params = self.module.init(jax.random.PRNGKey(seed))
        self.rng = jax.random.PRNGKey(seed + 1)
        self.obs = [env.reset(seed=seed + i)
                    for i, env in enumerate(self.envs)]
        self._returns = np.zeros(num_envs)
        self._completed: List[float] = []
        self._steps_sampled = 0

    def env_spec(self) -> Dict[str, Any]:
        return {pid: dict(self.module.specs[pid])
                for pid in self.policies}

    def set_weights(self, params):
        self.params = params

    def get_weights(self):
        return self.params

    def sample(self, num_steps: int) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp

        # per-policy rows, each step: obs/action/... stacked over the
        # (env, agent) pairs that policy controls
        per_policy_agents = {
            pid: [a for a in self.agents
                  if self.policy_mapping_fn(a) == pid]
            for pid in self.policies}
        rows: Dict[str, List[Dict[str, np.ndarray]]] = {
            pid: [] for pid in self.policies}
        for _ in range(num_steps):
            self.rng, step_rng = jax.random.split(self.rng)
            actions_per_env: List[Dict[str, int]] = [
                {} for _ in range(self.num_envs)]
            for pid in self.policies:
                agents = per_policy_agents[pid]
                obs = np.stack([self.obs[e][a]
                                for e in range(self.num_envs)
                                for a in agents])
                act, extras = self.module.forward_exploration(
                    pid, self.params, jnp.asarray(obs), step_rng)
                act = np.asarray(act)
                i = 0
                for e in range(self.num_envs):
                    for a in agents:
                        actions_per_env[e][a] = int(act[i])
                        i += 1
                rows[pid].append(
                    {"obs": obs, "action": act,
                     **{k: np.asarray(v) for k, v in extras.items()}})
            step_reward = np.zeros(
                (self.num_envs, len(self.agents)), np.float32)
            step_done = np.zeros((self.num_envs, len(self.agents)),
                                 bool)
            for e, env in enumerate(self.envs):
                obs, rew, term, trunc, _ = env.step(actions_per_env[e])
                for ai, a in enumerate(self.agents):
                    step_reward[e, ai] = rew.get(a, 0.0)
                    step_done[e, ai] = bool(term.get(a)) or \
                        bool(trunc.get(a))
                self._returns[e] += sum(rew.values())
                if term.get("__all__") or trunc.get("__all__"):
                    self._completed.append(float(self._returns[e]))
                    self._returns[e] = 0.0
                    obs = env.reset()
                self.obs[e] = obs
            # attach this step's reward/done per policy (its agents)
            for pid in self.policies:
                idx = [self.agents.index(a)
                       for a in per_policy_agents[pid]]
                rows[pid][-1]["reward"] = \
                    step_reward[:, idx].reshape(-1)
                rows[pid][-1]["done"] = step_done[:, idx].reshape(-1)
        batches = {}
        for pid in self.policies:
            batch = {k: np.stack([r[k] for r in rows[pid]])
                     for k in rows[pid][0]}
            # bootstrap value of the post-rollout obs (GAE tail)
            agents = per_policy_agents[pid]
            final_obs = np.stack([self.obs[e][a]
                                  for e in range(self.num_envs)
                                  for a in agents])
            batch["final_vf"] = np.asarray(self.module.value(
                pid, self.params, jnp.asarray(final_obs)))
            batches[pid] = batch
        self._steps_sampled += num_steps * self.num_envs
        done, self._completed = self._completed, []
        stats = {"episodes_this_iter": len(done),
                 "env_steps_sampled": self._steps_sampled}
        if done:
            stats["episode_return_mean"] = float(np.mean(done))
        return {"batches": batches, "stats": stats}


class MultiAgentEnvRunnerGroup:
    """N remote multi-agent runners + weight broadcast (reference:
    env_runner_group.py over MultiAgentEnvRunner)."""

    def __init__(self, *, env_name, policies, policy_mapping_fn,
                 module_spec, num_runners: int = 0,
                 num_envs_per_runner: int = 4, seed: int = 0):
        self.local = num_runners == 0
        # mapping fns / env creators travel as actor-constructor args:
        # register their driver-only modules for by-value pickling or the
        # runner actor dies unpickling them
        from ray_tpu._private.common import _ensure_picklable_by_value

        _ensure_picklable_by_value(policy_mapping_fn)
        if callable(env_name):
            _ensure_picklable_by_value(env_name)
        kwargs = dict(env_name=env_name, policies=policies,
                      policy_mapping_fn=policy_mapping_fn,
                      module_spec=module_spec,
                      num_envs=num_envs_per_runner)
        if self.local:
            self.runner = MultiAgentEnvRunner(seed=seed, **kwargs)
            self.actors = []
        else:
            Remote = ray_tpu.remote(MultiAgentEnvRunner)
            self.actors = [Remote.remote(seed=seed + 1000 * i, **kwargs)
                           for i in range(num_runners)]

    def env_spec(self):
        if self.local:
            return self.runner.env_spec()
        return ray_tpu.get(self.actors[0].env_spec.remote())

    def sample(self, num_steps: int):
        if self.local:
            return [self.runner.sample(num_steps)]
        return ray_tpu.get([a.sample.remote(num_steps)
                            for a in self.actors])

    def sync_weights(self, params):
        if self.local:
            self.runner.set_weights(params)
        else:
            ref = ray_tpu.put(params)
            ray_tpu.get([a.set_weights.remote(ref) for a in self.actors])

    def stop(self):
        for a in self.actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
