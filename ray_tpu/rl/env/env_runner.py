"""EnvRunners: sampling actors.

Analog of the reference's EnvRunner/EnvRunnerGroup (reference:
rllib/env/env_runner.py, rllib/env/env_runner_group.py): a group of actors
each owning vectorized environments, sampling with the current policy
weights, returning batches to the algorithm.

Two runner kinds:
  * JaxEnvRunner — pure-jax envs, fully jitted lax.scan rollouts (the
    TPU-native path; sampling itself compiles).
  * GymEnvRunner — gymnasium envs stepped host-side (API-parity path for
    external envs the reference supports).

Both return batches as a dict of numpy [T, B, ...] arrays plus episode
stats, so learners consume one format.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu


class _EpisodeTracker:
    """Accumulates per-env episode return/length across batch boundaries."""

    def __init__(self, num_envs: int):
        self.returns = np.zeros(num_envs)
        self.lengths = np.zeros(num_envs, np.int64)
        self.completed: List[float] = []

    def update(self, rewards: np.ndarray, dones: np.ndarray):
        # rewards/dones: [T, B]
        for t in range(rewards.shape[0]):
            self.returns += rewards[t]
            self.lengths += 1
            for i in np.nonzero(dones[t])[0]:
                self.completed.append(float(self.returns[i]))
                self.returns[i] = 0.0
                self.lengths[i] = 0

    def pop_stats(self) -> Dict[str, float]:
        done, self.completed = self.completed, []
        if not done:
            return {"episodes_this_iter": 0}
        return {
            "episodes_this_iter": len(done),
            "episode_return_mean": float(np.mean(done)),
            "episode_return_max": float(np.max(done)),
            "episode_return_min": float(np.min(done)),
        }


class JaxEnvRunner:
    """Sampling over pure-jax envs; the rollout is one compiled scan.

    `env_to_module` (a ConnectorV2/pipeline) runs on observations INSIDE
    the jitted scan, so it must be traceable; stateful host-side
    connectors (NormalizeObs) belong on GymEnvRunner.
    """

    def __init__(self, env_name: str, module_spec: Dict[str, Any],
                 num_envs: int = 8, seed: int = 0,
                 explore_kwargs: Optional[Dict[str, Any]] = None,
                 env_to_module=None):
        import jax

        from ray_tpu.rl.core.rl_module import module_for_env
        from ray_tpu.rl.env import jax_env

        self.env = jax_env.make_env(env_name)
        self.module = module_for_env(self.env.spec,
                                     kind=module_spec.get("kind", "policy"),
                                     **module_spec.get("kwargs", {}),
                                     hidden=module_spec.get("hidden",
                                                            (64, 64)))
        self.num_envs = num_envs
        self.explore_kwargs = explore_kwargs or {}
        if env_to_module is not None and not env_to_module.traceable:
            raise ValueError(
                "JaxEnvRunner connectors run inside the jitted rollout "
                f"scan and must be traceable; {env_to_module!r} is not "
                "(use GymEnvRunner for stateful connectors like "
                "NormalizeObs)")
        self.env_to_module = env_to_module
        self.params = self.module.init(jax.random.PRNGKey(seed))
        self.carry = jax_env.init_carry(self.env, jax.random.PRNGKey(seed + 1),
                                        num_envs)
        self.tracker = _EpisodeTracker(num_envs)
        self._steps_sampled = 0
        self._build_policy_fn()

    def _build_policy_fn(self):
        # one closure instance per (module, explore_kwargs): it is a static
        # jit arg of rollout(), so a fresh closure per sample() would
        # retrace every call
        kwargs = dict(self.explore_kwargs)
        module = self.module
        e2m = self.env_to_module

        def policy_fn(params, obs, rng):
            if e2m is not None:
                obs = e2m(obs)
            return module.forward_exploration(params, obs, rng, **kwargs)

        self._policy_fn = policy_fn

    def set_explore(self, **kwargs):
        """Update exploration params (e.g. epsilon decay); retraces once."""
        self.explore_kwargs.update(kwargs)
        self._build_policy_fn()

    def set_weights(self, params):
        self.params = params

    def get_weights(self):
        return self.params

    def env_spec(self) -> Dict[str, Any]:
        return dict(self.env.spec)

    def sample(self, num_steps: int) -> Dict[str, Any]:
        """num_steps per env; returns [T, B, ...] numpy batch + stats."""
        import jax

        from ray_tpu.rl.env.jax_env import rollout

        self.carry, batch = rollout(self.env, self._policy_fn, self.params,
                                    self.carry, num_steps)
        if self.env_to_module is not None:
            # the rollout records RAW env obs; the policy sampled from
            # TRANSFORMED obs (the connector runs inside policy_fn) — the
            # learner must see the same representation actions came from,
            # or importance ratios / value targets are silently wrong.
            # Connectors are written against [B, ...]; collapse [T, B]
            # so FlattenObs-style shape ops see one batch axis.
            obs = batch["obs"]
            tb = obs.shape[:2]
            flat = self.env_to_module(obs.reshape(-1, *obs.shape[2:]))
            batch["obs"] = flat.reshape(*tb, *flat.shape[1:])
        # bootstrap value for the obs after the last step (GAE tail)
        final_obs = self.carry[1]
        if self.env_to_module is not None:
            final_obs = self.env_to_module(final_obs)
        if hasattr(self.module, "value"):
            batch["final_vf"] = self.module.value(self.params, final_obs)
        batch = jax.tree_util.tree_map(np.asarray, batch)
        self.tracker.update(batch["reward"], batch["done"])
        self._steps_sampled += num_steps * self.num_envs
        stats = self.tracker.pop_stats()
        stats["env_steps_sampled"] = self._steps_sampled
        return {"batch": batch, "stats": stats}


def fixed_shape_batch(env, module, params, rng, num_envs: int,
                      num_steps: int) -> Dict[str, np.ndarray]:
    """One deterministic fixed-shape trajectory batch from a FRESH
    vectorized carry.

    Unlike JaxEnvRunner.sample (which threads env state across calls for
    continuous sampling), the batch here is a pure function of
    (env, module, params, rng, shapes): no hidden state survives the
    call.  That purity is what lets a replacement Podracer actor gang
    regenerate, bit for bit, the batches its dead predecessor owed the
    learner (see rl/podracer.py) — respawn cost is O(1), not O(history).

    Returns a dict of [B, T, ...] numpy arrays (batch-major, the order
    ImpalaLearner.compute_loss consumes) for obs/action/reward/done/logp
    plus final_vf [B] (the V-trace bootstrap tail).
    """
    from ray_tpu.rl.env import jax_env

    carry = jax_env.init_carry(env, rng, num_envs)
    # module.forward_exploration hashes stably across accesses (same
    # bound method), so rollout's static policy_fn arg never retraces
    carry, batch = jax_env.rollout(env, module.forward_exploration,
                                   params, carry, num_steps)
    out = {k: np.swapaxes(np.asarray(batch[k]), 0, 1)
           for k in ("obs", "action", "reward", "done", "logp")}
    out["final_vf"] = np.asarray(module.value(params, carry[1]))
    return out


class GymEnvRunner:
    """Host-side gymnasium sampling (reference:
    single_agent_env_runner.py with gym.vector.SyncVectorEnv)."""

    def __init__(self, env_name: str, module_spec: Dict[str, Any],
                 num_envs: int = 8, seed: int = 0,
                 explore_kwargs: Optional[Dict[str, Any]] = None,
                 env_to_module=None, module_to_env=None):
        import gymnasium as gym
        import jax

        from ray_tpu.rl.connectors import (default_env_to_module,
                                           default_module_to_env)
        from ray_tpu.rl.core.rl_module import module_for_env

        self.envs = gym.vector.SyncVectorEnv(
            [lambda: gym.make(env_name) for _ in range(num_envs)])
        obs_space = self.envs.single_observation_space
        act_space = self.envs.single_action_space
        self.spec = {"obs_dim": int(np.prod(obs_space.shape)),
                     "max_episode_steps": 0}
        if hasattr(act_space, "n"):                 # Discrete
            self.spec["num_actions"] = int(act_space.n)
        else:                                       # Box (continuous)
            low = np.asarray(act_space.low, np.float64).reshape(-1)
            high = np.asarray(act_space.high, np.float64).reshape(-1)
            if not (np.isfinite(low).all() and np.isfinite(high).all()):
                raise ValueError(
                    f"Box action space has non-finite bounds "
                    f"(low={low}, high={high}): the squashed-Gaussian "
                    f"policy needs a bounded range — wrap the env with "
                    f"a RescaleAction/ClipAction wrapper")
            self.spec.update(
                # per-dimension bounds (lists: specs cross process
                # boundaries) — collapsing to scalars would mis-scale
                # heterogeneous spaces like CarRacing's [steer, gas,
                # brake]
                action_dim=int(np.prod(act_space.shape)),
                action_low=low.tolist(),
                action_high=high.tolist())
        self.module = module_for_env(self.spec,
                                     kind=module_spec.get("kind", "policy"),
                                     **module_spec.get("kwargs", {}),
                                     hidden=module_spec.get("hidden",
                                                            (64, 64)))
        self.num_envs = num_envs
        self.explore_kwargs = explore_kwargs or {}
        # obs/action handling as composable pipelines (reference:
        # connectors/env_to_module/, module_to_env/) — not hardcoded here
        self.env_to_module = (env_to_module if env_to_module is not None
                              else default_env_to_module())
        self.module_to_env = (module_to_env if module_to_env is not None
                              else default_module_to_env())
        self.params = self.module.init(jax.random.PRNGKey(seed))
        self.rng = jax.random.PRNGKey(seed + 1)
        self.obs, _ = self.envs.reset(seed=seed)
        self.tracker = _EpisodeTracker(num_envs)
        self._steps_sampled = 0

    def set_explore(self, **kwargs):
        self.explore_kwargs.update(kwargs)

    def set_weights(self, params):
        self.params = params

    def get_weights(self):
        return self.params

    def env_spec(self) -> Dict[str, Any]:
        return dict(self.spec)

    def sample(self, num_steps: int) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp

        rows = []
        for _ in range(num_steps):
            self.rng, act_rng = jax.random.split(self.rng)
            obs = jnp.asarray(self.env_to_module(self.obs))
            action, extras = self.module.forward_exploration(
                self.params, obs, act_rng, **self.explore_kwargs)
            action_np = self.module_to_env(action)
            next_obs, reward, term, trunc, _ = self.envs.step(action_np)
            done = np.logical_or(term, trunc)
            rows.append({"obs": np.asarray(obs), "action": action_np,
                         "reward": np.asarray(reward, np.float32),
                         "done": done,
                         # terminated vs truncated matters to value
                         # learners: a time-limit hit must not cut the
                         # bootstrap target (gymnasium's own distinction)
                         "terminated": np.asarray(term, bool),
                         **{k: np.asarray(v) for k, v in extras.items()}})
            self.obs = next_obs
        batch = {k: np.stack([r[k] for r in rows]) for k in rows[0]}
        if hasattr(self.module, "value"):
            # bootstrap obs goes through the SAME pipeline the recorded
            # obs did (the value net was trained on transformed obs);
            # no_update so stateful filters don't double-count it when
            # the next sample() transforms it again
            fin = self.env_to_module(self.obs, {"no_update": True})
            batch["final_vf"] = np.asarray(self.module.value(
                self.params, jnp.asarray(fin, jnp.float32)))
        self.tracker.update(batch["reward"], batch["done"])
        self._steps_sampled += num_steps * self.num_envs
        stats = self.tracker.pop_stats()
        stats["env_steps_sampled"] = self._steps_sampled
        return {"batch": batch, "stats": stats}


def make_runner(kind: str, **kwargs):
    return (JaxEnvRunner if kind == "jax" else GymEnvRunner)(**kwargs)


class EnvRunnerGroup:
    """N remote runner actors + weight broadcast (reference:
    rllib/env/env_runner_group.py EnvRunnerGroup.sync_weights)."""

    def __init__(self, *, env_name: str, module_spec: Dict[str, Any],
                 num_runners: int = 2, num_envs_per_runner: int = 8,
                 runner_kind: str = "jax", seed: int = 0,
                 explore_kwargs: Optional[Dict[str, Any]] = None,
                 local: bool = False, env_to_module=None,
                 module_to_env=None):
        conn_kw: Dict[str, Any] = {}
        if env_to_module is not None:
            conn_kw["env_to_module"] = env_to_module
        if module_to_env is not None:
            if runner_kind == "jax":
                # jax rollouts feed actions straight back into the jitted
                # env step — there is no host boundary for this hook, and
                # silently dropping it would train differently than the
                # same config on the gym runner
                raise ValueError(
                    "module_to_env connectors are not supported with "
                    "runner_kind='jax' (actions never cross a host "
                    "boundary inside the compiled rollout); use "
                    "runner_kind='gym' or drop the connector")
            conn_kw["module_to_env"] = module_to_env
        self.local = local or num_runners == 0
        if self.local:
            self.runner = make_runner(
                runner_kind, env_name=env_name, module_spec=module_spec,
                num_envs=num_envs_per_runner, seed=seed,
                explore_kwargs=explore_kwargs, **conn_kw)
            self.actors = []
        else:
            RemoteRunner = ray_tpu.remote(
                JaxEnvRunner if runner_kind == "jax" else GymEnvRunner)
            self.actors = [
                RemoteRunner.remote(
                    env_name=env_name, module_spec=module_spec,
                    num_envs=num_envs_per_runner, seed=seed + 1000 * i,
                    explore_kwargs=explore_kwargs, **conn_kw)
                for i in range(num_runners)
            ]

    def env_spec(self) -> Dict[str, Any]:
        if self.local:
            return self.runner.env_spec()
        return ray_tpu.get(self.actors[0].env_spec.remote())

    def sample(self, num_steps: int) -> List[Dict[str, Any]]:
        if self.local:
            return [self.runner.sample(num_steps)]
        return ray_tpu.get([a.sample.remote(num_steps)
                            for a in self.actors])

    def sync_weights(self, params):
        if self.local:
            self.runner.set_weights(params)
        else:
            ref = ray_tpu.put(params)
            ray_tpu.get([a.set_weights.remote(ref) for a in self.actors])

    def set_explore(self, **kwargs):
        if self.local:
            self.runner.set_explore(**kwargs)
        else:
            ray_tpu.get([a.set_explore.remote(**kwargs)
                         for a in self.actors])

    def stop(self):
        for a in self.actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
