"""Module-to-env connectors: action postprocessing between the module's
output and env.step (reference: rllib/connectors/module_to_env/ —
get_actions.py, unsquash_and_clip_actions.py, listify_data_for_vector_env).
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from .connector import ConnectorPipeline, ConnectorV2


class ToNumpy(ConnectorV2):
    """Device arrays -> host numpy for env.step (the gym boundary)."""

    traceable = False

    def __call__(self, action: Any, ctx: Optional[dict] = None) -> Any:
        return np.asarray(action)


class ClipActions(ConnectorV2):
    """Clip continuous actions into the env's bounds (reference:
    unsquash_and_clip_actions.py clip mode)."""

    def __init__(self, low: float = -1.0, high: float = 1.0):
        self.low, self.high = low, high

    def __call__(self, action: Any, ctx: Optional[dict] = None) -> Any:
        return action.clip(self.low, self.high)

    def __repr__(self):
        return f"ClipActions[{self.low}, {self.high}]"


class UnbatchToInt(ConnectorV2):
    """Discrete actions to the integer dtype vector envs expect."""

    traceable = False

    def __call__(self, action: Any, ctx: Optional[dict] = None) -> Any:
        return np.asarray(action).astype(np.int64, copy=False)


def default_module_to_env() -> ConnectorPipeline:
    return ConnectorPipeline(ToNumpy())
