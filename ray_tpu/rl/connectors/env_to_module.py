"""Env-to-module connectors: observation preprocessing before the
module's forward pass (reference: rllib/connectors/env_to_module/ —
flatten_observations.py, mean_std_filter.py, numpy_to_tensor.py).
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from .connector import ConnectorPipeline, ConnectorV2


class ObsToFloat32(ConnectorV2):
    """Cast observations to float32 (reference: numpy_to_tensor.py role —
    the module's input dtype contract)."""

    def __call__(self, obs: Any, ctx: Optional[dict] = None) -> Any:
        if isinstance(obs, np.ndarray):
            return obs.astype(np.float32, copy=False)
        import jax.numpy as jnp

        return jnp.asarray(obs, jnp.float32)


class FlattenObs(ConnectorV2):
    """Flatten per-step observation trees/arrays to [B, -1] vectors
    (reference: flatten_observations.py)."""

    def __call__(self, obs: Any, ctx: Optional[dict] = None) -> Any:
        return obs.reshape(obs.shape[0], -1)


class ClipObs(ConnectorV2):
    def __init__(self, low: float = -10.0, high: float = 10.0):
        self.low, self.high = low, high

    def __call__(self, obs: Any, ctx: Optional[dict] = None) -> Any:
        return obs.clip(self.low, self.high)

    def __repr__(self):
        return f"ClipObs[{self.low}, {self.high}]"


class NormalizeObs(ConnectorV2):
    """Running mean/std observation filter (reference:
    mean_std_filter.py MeanStdObservationFilter — Welford accumulation,
    update during sampling, frozen at evaluation).

    Stateful, therefore host-side only (gym runner path): the jitted
    jax-env rollout cannot mutate Python state mid-scan.
    """

    traceable = False

    def __init__(self, eps: float = 1e-8, update: bool = True):
        self.eps = eps
        self.update = update
        self._count = 0.0
        self._mean: Optional[np.ndarray] = None
        self._m2: Optional[np.ndarray] = None

    def __call__(self, obs: Any, ctx: Optional[dict] = None) -> Any:
        x = np.asarray(obs, np.float32)
        flat = x.reshape(-1, x.shape[-1]) if x.ndim > 1 else x[None]
        if self.update and not (ctx or {}).get("no_update"):
            # Chan's batched merge: one vectorized update per call, not a
            # Python loop per sample (this sits on the hot sampling path)
            n_b = float(flat.shape[0])
            mean_b = flat.mean(0)
            m2_b = ((flat - mean_b) ** 2).sum(0)
            if self._mean is None:
                self._count, self._mean, self._m2 = n_b, mean_b, m2_b
            else:
                n_a = self._count
                delta = mean_b - self._mean
                tot = n_a + n_b
                self._mean = self._mean + delta * (n_b / tot)
                self._m2 = self._m2 + m2_b + delta ** 2 * (n_a * n_b / tot)
                self._count = tot
        if self._mean is None or self._count < 2:
            return x
        std = np.sqrt(self._m2 / (self._count - 1) + self.eps)
        return (x - self._mean) / std

    def state(self) -> dict:
        return {"count": self._count, "mean": self._mean, "m2": self._m2}


def default_env_to_module() -> ConnectorPipeline:
    """The default stack every runner starts from (reference:
    env_to_module_pipeline.py defaults); users splice into it via
    insert_before/after."""
    return ConnectorPipeline(ObsToFloat32())
