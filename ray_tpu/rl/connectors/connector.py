"""ConnectorV2 base + pipeline (reference:
rllib/connectors/connector_v2.py ConnectorV2,
rllib/connectors/connector_pipeline_v2.py ConnectorPipeline).

A connector is a small callable transforming a batch (obs on the way
into the module, actions on the way out); a pipeline composes them in
order and supports insertion/removal by class — the reference's key
property, letting users splice custom preprocessing into the default
stack without forking the runner.

TPU note: connectors used on the jitted JaxEnvRunner rollout path run
INSIDE a lax.scan, so they must be jax-traceable (pure array ops, no
Python state mutation).  `traceable` declares that; the stateful ones
(NormalizeObs) are host-side only and the runner enforces it.
"""

from __future__ import annotations

from typing import Any, List, Optional, Type


class ConnectorV2:
    """One transformation step; subclasses override __call__."""

    #: safe to run inside jit/scan (pure function of its inputs)
    traceable: bool = True

    def __call__(self, data: Any, ctx: Optional[dict] = None) -> Any:
        raise NotImplementedError

    def __repr__(self):
        return type(self).__name__


class ConnectorPipeline(ConnectorV2):
    """Ordered composition of connectors (reference:
    connector_pipeline_v2.py — prepend/append/insert_after/remove)."""

    def __init__(self, *connectors: ConnectorV2):
        self.connectors: List[ConnectorV2] = list(connectors)

    @property
    def traceable(self) -> bool:  # type: ignore[override]
        return all(c.traceable for c in self.connectors)

    def __call__(self, data: Any, ctx: Optional[dict] = None) -> Any:
        for c in self.connectors:
            data = c(data, ctx)
        return data

    # -- mutation (reference API names) ---------------------------------

    def prepend(self, connector: ConnectorV2) -> "ConnectorPipeline":
        self.connectors.insert(0, connector)
        return self

    def append(self, connector: ConnectorV2) -> "ConnectorPipeline":
        self.connectors.append(connector)
        return self

    def _index_of(self, cls: Type[ConnectorV2]) -> int:
        for i, c in enumerate(self.connectors):
            if isinstance(c, cls):
                return i
        raise ValueError(f"no {cls.__name__} in pipeline {self}")

    def insert_after(self, cls: Type[ConnectorV2],
                     connector: ConnectorV2) -> "ConnectorPipeline":
        self.connectors.insert(self._index_of(cls) + 1, connector)
        return self

    def insert_before(self, cls: Type[ConnectorV2],
                      connector: ConnectorV2) -> "ConnectorPipeline":
        self.connectors.insert(self._index_of(cls), connector)
        return self

    def remove(self, cls: Type[ConnectorV2]) -> "ConnectorPipeline":
        del self.connectors[self._index_of(cls)]
        return self

    def __repr__(self):
        inner = " -> ".join(repr(c) for c in self.connectors)
        return f"ConnectorPipeline[{inner}]"
