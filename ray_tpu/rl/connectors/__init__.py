"""Connector pipelines (reference: rllib/connectors/connector_v2.py,
env_to_module/, module_to_env/ — obs/action preprocessing as composable,
inspectable pipelines instead of logic hardcoded in env runners)."""

from .connector import ConnectorPipeline, ConnectorV2
from .env_to_module import (ClipObs, FlattenObs, NormalizeObs, ObsToFloat32,
                            default_env_to_module)
from .module_to_env import (ClipActions, ToNumpy, UnbatchToInt,
                            default_module_to_env)

__all__ = [
    "ConnectorV2", "ConnectorPipeline",
    "ObsToFloat32", "FlattenObs", "NormalizeObs", "ClipObs",
    "default_env_to_module",
    "ClipActions", "ToNumpy", "UnbatchToInt", "default_module_to_env",
]
