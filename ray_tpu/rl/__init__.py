"""RL library (reference: rllib/) — jax-first RL on ray_tpu actors.

New-API-stack shape mirrors the reference: RLModule (network), Learner /
LearnerGroup (updates), EnvRunner / EnvRunnerGroup (sampling), Algorithm
(the loop, also a Tune trainable).  TPU-native twist: pure-jax envs make
the entire rollout one compiled `lax.scan` (see env/jax_env.py).
"""

from .algorithms.algorithm import Algorithm, AlgorithmConfig
from .algorithms.appo import APPO, APPOConfig
from .algorithms.cql import CQL, CQLConfig
from .algorithms.dqn import DQN, DQNConfig
from .algorithms.dreamerv3 import DreamerV3, DreamerV3Config
from .algorithms.impala import (IMPALA, Impala, ImpalaConfig,
                                make_impala_learner)
from .algorithms.marwil import BC, BCConfig, MARWIL, MARWILConfig
from .algorithms.ppo import PPO, PPOConfig
from .algorithms.sac import SAC, SACConfig
from .core.learner import Learner, LearnerGroup
from .core.rl_module import (DiscretePolicyModule, QModule, RLModule,
                             module_for_env)
from .algorithms.multi_agent_ppo import MultiAgentPPO, MultiAgentPPOConfig
from .core.multi_rl_module import MultiRLModule
from .env.env_runner import EnvRunnerGroup, GymEnvRunner, JaxEnvRunner
from .env.jax_env import CartPole, JaxEnv, make_env, register_env
from .env.multi_agent_env import (CooperativeMatchEnv, MultiAgentEnv,
                                  MultiAgentEnvRunner,
                                  MultiAgentEnvRunnerGroup)
from .env.multi_agent_env import register_env as register_multi_agent_env
from .podracer import (AnakinConfig, ChaosEvent, ChaosSchedule, Sebulba,
                       SebulbaConfig, run_anakin, run_sebulba)
from .utils.replay_buffer import ReplayBuffer
from . import connectors
from .offline import OfflineData, record_rollouts

__all__ = [
    "Algorithm", "AlgorithmConfig", "PPO", "PPOConfig", "DQN", "DQNConfig",
    "Impala", "IMPALA", "ImpalaConfig", "SAC", "SACConfig",
    "MARWIL", "MARWILConfig", "BC", "BCConfig",
    "APPO", "APPOConfig", "CQL", "CQLConfig",
    "DreamerV3", "DreamerV3Config",
    "Learner", "LearnerGroup", "RLModule", "DiscretePolicyModule", "QModule",
    "module_for_env", "EnvRunnerGroup", "JaxEnvRunner", "GymEnvRunner",
    "JaxEnv", "CartPole", "make_env", "register_env", "ReplayBuffer",
    "MultiAgentEnv", "MultiAgentEnvRunner", "MultiAgentEnvRunnerGroup",
    "MultiAgentPPO", "MultiAgentPPOConfig", "MultiRLModule",
    "CooperativeMatchEnv", "register_multi_agent_env",
    "connectors", "OfflineData", "record_rollouts",
    "AnakinConfig", "ChaosEvent", "ChaosSchedule", "Sebulba",
    "SebulbaConfig", "make_impala_learner", "run_anakin", "run_sebulba",
]
