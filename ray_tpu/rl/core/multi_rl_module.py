"""MultiRLModule: one RLModule per policy id.

Analog of the reference's MultiRLModule (reference:
rllib/core/rl_module/multi_rl_module.py): a dict of policy_id ->
RLModule whose params pytree is {policy_id: module_params} — so a
multi-policy checkpoint is still a single pytree save, and each
policy's forward passes stay independently jittable.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence

import jax

from .rl_module import DiscretePolicyModule


class MultiRLModule:
    def __init__(self, specs: Dict[str, Dict[str, int]],
                 hidden: Sequence[int] = (64, 64)):
        self.specs = dict(specs)
        self.modules: Dict[str, DiscretePolicyModule] = {
            pid: DiscretePolicyModule(s["obs_dim"], s["num_actions"],
                                      hidden)
            for pid, s in specs.items()}

    def __getitem__(self, policy_id: str) -> DiscretePolicyModule:
        return self.modules[policy_id]

    def policy_ids(self):
        return list(self.modules)

    def init(self, rng) -> Dict[str, Any]:
        keys = jax.random.split(rng, len(self.modules))
        return {pid: m.init(k)
                for (pid, m), k in zip(sorted(self.modules.items()), keys)}

    def forward_exploration(self, policy_id: str, params, obs, rng):
        return self.modules[policy_id].forward_exploration(
            params[policy_id], obs, rng)

    def forward_inference(self, policy_id: str, params, obs):
        return self.modules[policy_id].forward_inference(
            params[policy_id], obs)

    def value(self, policy_id: str, params, obs):
        return self.modules[policy_id].value(params[policy_id], obs)
