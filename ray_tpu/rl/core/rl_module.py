"""RLModule: the neural-network policy/value container.

Analog of the reference's new-API-stack RLModule (reference:
rllib/core/rl_module/rl_module.py) redesigned jax-first: a module is a
bundle of pure functions over a params pytree — no framework Module
objects cross process boundaries, only arrays — so the same module runs
under jit/vmap/scan on TPU, and checkpointing is a pytree save.

Three forward passes mirror the reference's contract:
  forward_exploration(params, obs, rng) -> action, logp, extras (sampling)
  forward_inference(params, obs)        -> deterministic action
  forward_train(params, batch)          -> dists/values for the loss
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _init_linear(rng, n_in: int, n_out: int, scale: float = None):
    w_key, _ = jax.random.split(rng)
    scale = scale if scale is not None else math.sqrt(2.0 / n_in)
    return {
        "w": jax.random.normal(w_key, (n_in, n_out), jnp.float32) * scale,
        "b": jnp.zeros((n_out,), jnp.float32),
    }


def _linear(p, x):
    return x @ p["w"] + p["b"]


def _mlp_init(rng, sizes: Sequence[int], out_scale: float = 0.01):
    params = []
    keys = jax.random.split(rng, len(sizes) - 1)
    for i, (a, b) in enumerate(zip(sizes[:-1], sizes[1:])):
        last = i == len(sizes) - 2
        params.append(_init_linear(keys[i], a, b,
                                   out_scale if last else None))
    return params


def _mlp_apply(params, x):
    for i, p in enumerate(params):
        x = _linear(p, x)
        if i < len(params) - 1:
            x = jnp.tanh(x)
    return x


class RLModule:
    """Base: subclasses define init() and the forward fns as pure fns."""

    def __init__(self, obs_dim: int, num_actions: int,
                 hidden: Sequence[int] = (64, 64)):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.hidden = tuple(hidden)

    def init(self, rng) -> Any:
        raise NotImplementedError

    def forward_exploration(self, params, obs, rng):
        raise NotImplementedError

    def forward_inference(self, params, obs):
        raise NotImplementedError


class DiscretePolicyModule(RLModule):
    """Separate policy and value MLP heads over a shared input
    (reference: rllib default MLP RLModule for PG algorithms)."""

    def init(self, rng):
        pi_rng, vf_rng = jax.random.split(rng)
        sizes = (self.obs_dim, *self.hidden)
        return {
            "pi": _mlp_init(pi_rng, (*sizes, self.num_actions)),
            "vf": _mlp_init(vf_rng, (*sizes, 1), out_scale=1.0),
        }

    # -- pure functions (safe under jit) -----------------------------------

    def logits(self, params, obs):
        return _mlp_apply(params["pi"], obs)

    def value(self, params, obs):
        return _mlp_apply(params["vf"], obs)[..., 0]

    def forward_exploration(self, params, obs, rng):
        logits = self.logits(params, obs)
        action = jax.random.categorical(rng, logits)
        logp = jax.nn.log_softmax(logits)
        logp_a = jnp.take_along_axis(logp, action[..., None],
                                     axis=-1)[..., 0]
        return action, {"logp": logp_a, "vf": self.value(params, obs)}

    def forward_inference(self, params, obs):
        return jnp.argmax(self.logits(params, obs), axis=-1)


class QModule(RLModule):
    """State-action value net for DQN-family algorithms
    (reference: rllib/algorithms/dqn/ default module)."""

    def init(self, rng):
        q_rng, t_rng = jax.random.split(rng)
        sizes = (self.obs_dim, *self.hidden, self.num_actions)
        q = _mlp_init(q_rng, sizes, out_scale=0.01)
        return {"q": q, "target_q": jax.tree_util.tree_map(jnp.copy, q)}

    def q_values(self, params, obs, target: bool = False):
        return _mlp_apply(params["target_q" if target else "q"], obs)

    def forward_exploration(self, params, obs, rng, epsilon: float = 0.05):
        q = self.q_values(params, obs)
        greedy = jnp.argmax(q, axis=-1)
        rand_rng, pick_rng = jax.random.split(rng)
        random_a = jax.random.randint(rand_rng, greedy.shape, 0,
                                      self.num_actions)
        explore = jax.random.uniform(pick_rng, greedy.shape) < epsilon
        return jnp.where(explore, random_a, greedy), {}

    def forward_inference(self, params, obs):
        return jnp.argmax(self.q_values(params, obs), axis=-1)


# algorithm-owned module kinds register here (e.g. SAC's policy+twin-Q)
MODULE_REGISTRY: Dict[str, type] = {}


def module_for_env(env_spec: Dict[str, Any], kind: str = "policy",
                   hidden: Sequence[int] = (64, 64), **kwargs) -> RLModule:
    if "action_dim" in env_spec and "num_actions" not in env_spec:
        # continuous (Box) action space: dispatch to the kind's
        # continuous-action module (e.g. sac -> sac_continuous)
        cls = MODULE_REGISTRY.get(f"{kind}_continuous")
        if cls is None:
            raise ValueError(
                f"algorithm kind {kind!r} has no continuous-action "
                f"module registered (env spec: {sorted(env_spec)})")
        return cls(env_spec["obs_dim"], env_spec["action_dim"], hidden,
                   low=env_spec.get("action_low", -1.0),
                   high=env_spec.get("action_high", 1.0), **kwargs)
    cls = MODULE_REGISTRY.get(kind) or (
        DiscretePolicyModule if kind == "policy" else QModule)
    return cls(env_spec["obs_dim"], env_spec["num_actions"], hidden,
               **kwargs)
