"""Learner: gradient updates over an RLModule.

Analog of the reference's Learner (reference:
rllib/core/learner/learner.py): owns params + optimizer state, applies a
loss over batches.  Jax-first: the whole update (loss, grad, optimizer,
metrics) is one jitted function; data-parallel scaling is a mesh axis with
`psum` of gradients inside the compiled step (the reference reaches DDP
through torch; here the collective is compiled into the step itself via
shard_map when the learner group spans devices).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from .rl_module import RLModule


class Learner:
    """Single-device learner.  Subclasses define compute_loss(params,
    batch, rng) -> (loss, metrics)."""

    def __init__(self, module: RLModule, *, lr: float = 3e-4,
                 grad_clip: Optional[float] = 0.5, seed: int = 0,
                 optimizer: Optional[optax.GradientTransformation] = None):
        self.module = module
        tx = optimizer or optax.adam(lr)
        if grad_clip is not None:
            tx = optax.chain(optax.clip_by_global_norm(grad_clip), tx)
        self.tx = tx
        self.params = module.init(jax.random.PRNGKey(seed))
        self.opt_state = tx.init(self._trainable(self.params))
        self.rng = jax.random.PRNGKey(seed + 17)
        self._update_fn = self._build_update()

    # -- overridables ------------------------------------------------------

    def compute_loss(self, params, batch, rng) -> Tuple[jnp.ndarray,
                                                        Dict[str, Any]]:
        raise NotImplementedError

    def _trainable(self, params):
        """Subset of params the optimizer touches (e.g. excludes DQN's
        target net, which moves by polyak/periodic copy instead)."""
        return params

    def _merge(self, params, trained):
        """Inverse of _trainable."""
        return trained

    def extra_update(self, params, metrics):
        """Post-gradient param surgery (target-net sync etc.)."""
        return params

    # -- the jitted update -------------------------------------------------

    def _build_update(self):
        @jax.jit
        def update(params, opt_state, batch, rng):
            def loss_fn(trained):
                full = self._merge(params, trained)
                return self.compute_loss(full, batch, rng)

            (loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(self._trainable(params))
            updates, opt_state = self.tx.update(
                grads, opt_state, self._trainable(params))
            trained = optax.apply_updates(self._trainable(params), updates)
            params = self._merge(params, trained)
            metrics["loss"] = loss
            metrics["grad_norm"] = optax.global_norm(grads)
            return params, opt_state, metrics

        return update

    def update(self, batch: Dict[str, Any]) -> Dict[str, float]:
        self.rng, step_rng = jax.random.split(self.rng)
        batch = jax.tree_util.tree_map(jnp.asarray, batch)
        self.params, self.opt_state, metrics = self._update_fn(
            self.params, self.opt_state, batch, step_rng)
        self.params = self.extra_update(self.params, metrics)
        return {k: float(v) for k, v in metrics.items()}

    # -- weights -----------------------------------------------------------

    def get_weights(self):
        return self.params

    def set_weights(self, params):
        self.params = params

    def state(self) -> Dict[str, Any]:
        return {"params": self.params, "opt_state": self.opt_state}

    def load_state(self, state: Dict[str, Any]):
        self.params = state["params"]
        self.opt_state = state["opt_state"]


class LearnerGroup:
    """1..N learners (reference: rllib/core/learner/learner_group.py:80,
    built on Train's BackendExecutor :54,:151).

    local mode: one in-process learner.  remote mode: N learner actors;
    each update shards the batch, learners compute grads on their shard,
    and weights are averaged (the all-reduce rides our collective layer
    when learners share a mesh; host-side mean otherwise).
    """

    def __init__(self, learner_factory: Callable[[], Learner],
                 num_learners: int = 0):
        self.local = num_learners == 0
        if self.local:
            self.learner = learner_factory()
            self.actors = []
        else:
            import ray_tpu

            @ray_tpu.remote
            class LearnerActor:
                def __init__(self, factory, shard_idx: int):
                    self.learner = factory()
                    self.shard_idx = shard_idx

                def update(self, batch):
                    return self.learner.update(batch)

                def get_weights(self):
                    return self.learner.get_weights()

                def set_weights(self, w):
                    self.learner.set_weights(w)

                def state(self):
                    return self.learner.state()

                def load_state(self, s):
                    self.learner.load_state(s)

            self.actors = [LearnerActor.remote(learner_factory, i)
                           for i in range(num_learners)]

    def update(self, batch: Dict[str, Any]) -> Dict[str, float]:
        if self.local:
            return self.learner.update(batch)
        import numpy as np

        import ray_tpu

        n = len(self.actors)
        shards = [
            {k: v[i::n] for k, v in batch.items()} for i in range(n)
        ]
        metrics = ray_tpu.get([a.update.remote(s)
                               for a, s in zip(self.actors, shards)])
        # average weights across learners (grad-mean equivalent for equal
        # shards under identical init)
        weights = ray_tpu.get([a.get_weights.remote() for a in self.actors])
        mean_w = jax.tree_util.tree_map(
            lambda *xs: np.mean(np.stack(xs), axis=0), *weights)
        ray_tpu.get([a.set_weights.remote(mean_w) for a in self.actors])
        out = {}
        for k in metrics[0]:
            out[k] = float(np.mean([m[k] for m in metrics]))
        return out

    def get_weights(self):
        if self.local:
            return self.learner.get_weights()
        import ray_tpu

        return ray_tpu.get(self.actors[0].get_weights.remote())

    def set_weights(self, w):
        if self.local:
            self.learner.set_weights(w)
        else:
            import ray_tpu

            ray_tpu.get([a.set_weights.remote(w) for a in self.actors])

    def state(self):
        if self.local:
            return self.learner.state()
        import ray_tpu

        return ray_tpu.get(self.actors[0].state.remote())

    def load_state(self, s):
        if self.local:
            self.learner.load_state(s)
        else:
            import ray_tpu

            ray_tpu.get([a.load_state.remote(s) for a in self.actors])

    def stop(self):
        import ray_tpu

        for a in self.actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
