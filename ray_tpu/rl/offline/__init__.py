"""Offline-RL data path (reference: rllib/offline/ — OfflineData reads
ray.data datasets of logged experience into learners; offline_env_runner
records rollouts back out as files)."""

from .offline_data import (OfflineData, record_rollouts,
                           resolve_offline_data)

__all__ = ["OfflineData", "record_rollouts", "resolve_offline_data"]
