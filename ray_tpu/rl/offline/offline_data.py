"""File-backed offline experience for CQL/BC/MARWIL.

Analog of the reference's OfflineData (reference:
rllib/offline/offline_data.py:22 — wraps ray.data reads of logged
episodes; rllib/offline/offline_env_runner.py writes rollouts to
parquet).  Here the same two directions ride ray_tpu.data:

  * record_rollouts(...) — [T, B] rollout dicts -> flat transition rows
    -> parquet/json shards (local dir or any fsspec URI, so a TPU pod
    can log experience straight to the shared object store).
  * OfflineData(paths) — lazily reads those files back as a
    ray_tpu.data Dataset and yields flat numpy transition batches for
    learner updates.

Columns are the flat transition schema {obs, action, reward, done,
next_obs} (+ optionally "return"); multi-dim obs are stored as fixed
shape tensor columns (ray_tpu.data blocks handle ndarray columns
natively).
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, Iterator, List, Optional, Union

import numpy as np


def _flatten_rollout(batch: Dict[str, Any],
                     gamma: Optional[float]) -> Dict[str, np.ndarray]:
    """[T, B] rollout arrays -> flat transitions; adds discounted
    reward-to-go as "return" when gamma is given (MARWIL/BC), and
    next_obs (CQL/TD-style) always."""
    obs = np.asarray(batch["obs"])
    rewards = np.asarray(batch["reward"], np.float32)
    dones = np.asarray(batch["done"], bool)
    T = rewards.shape[0]
    flat = lambda a: np.asarray(a)[:T - 1].reshape(  # noqa: E731
        -1, *np.asarray(a).shape[2:])
    out = {
        "obs": flat(obs),
        "next_obs": obs[1:].reshape(-1, *obs.shape[2:]),
        "action": flat(batch["action"]),
        "reward": flat(rewards),
        "done": flat(dones),
    }
    if gamma is not None:
        returns = np.zeros_like(rewards)
        acc = np.zeros(rewards.shape[1], np.float32)
        for t in range(T - 1, -1, -1):
            acc = rewards[t] + gamma * acc * (~dones[t])
            returns[t] = acc
        out["return"] = returns[:T - 1].reshape(-1)
    return out


def record_rollouts(batches: Iterable[Dict[str, Any]], path: str, *,
                    file_format: str = "parquet",
                    gamma: Optional[float] = 0.99) -> List[str]:
    """Write rollout batches (as returned by EnvRunner.sample, [T, B])
    to transition files under `path`; returns the written file paths
    (reference: offline_env_runner.py writing episodes via ray.data)."""
    from ray_tpu import data as rd

    written: List[str] = []
    for batch in batches:
        flat = batch if "next_obs" in batch else _flatten_rollout(batch,
                                                                  gamma)
        ds = rd.read_datasource(
            rd.BlocksDatasource([_to_block(flat)]))
        writer = getattr(ds, f"write_{file_format}")
        written.extend(writer(path))
    return written


def _to_block(flat: Dict[str, np.ndarray]):
    from ray_tpu.data.block import batch_to_block

    return batch_to_block({k: np.asarray(v) for k, v in flat.items()})


class OfflineData:
    """Lazy reader of logged experience (reference:
    rllib/offline/offline_data.py:22 OfflineData).

    `source` is file path(s) (parquet/json — local or fsspec URI), or an
    existing ray_tpu.data.Dataset.
    """

    def __init__(self, source: Union[str, List[str], Any], *,
                 file_format: str = "parquet"):
        self._source = source
        self._format = file_format
        self._ds = None

    @property
    def dataset(self):
        if self._ds is None:
            from ray_tpu import data as rd

            src = self._source
            if isinstance(src, (str, list, tuple)):
                reader = getattr(rd, f"read_{self._format}")
                self._ds = reader(src)
            else:
                self._ds = src  # already a Dataset
        return self._ds

    def iter_transition_batches(
            self, batch_size: int = 256, *,
            shuffle_seed: Optional[int] = None
    ) -> Iterator[Dict[str, np.ndarray]]:
        """Flat numpy transition batches for learner updates."""
        kw = {}
        if shuffle_seed is not None:
            kw = {"local_shuffle_buffer_size": 4 * batch_size,
                  "local_shuffle_seed": shuffle_seed}
        for b in self.dataset.iter_batches(batch_size=batch_size,
                                           batch_format="numpy", **kw):
            yield {k: np.asarray(v) for k, v in b.items()}

    def materialize(self, batch_size: int = 256) -> List[Dict[str, np.ndarray]]:
        return list(self.iter_transition_batches(batch_size))


def resolve_offline_data(data: Any, *, gamma: float,
                         batch_size: int = 256,
                         want_return: bool = False
                         ) -> List[Dict[str, np.ndarray]]:
    """Normalize every accepted offline-data shape into a list of flat
    numpy batches: file path(s), OfflineData, ray_tpu.data Dataset, or
    the legacy in-memory iterable of rollout/transition dicts."""
    if data is None:
        return []
    first = (data[0] if isinstance(data, (list, tuple)) and data else data)
    if isinstance(first, str):
        # sniff the format from the ACTUAL files (a directory of .json
        # shards carries no suffix on the dir path itself)
        from ray_tpu._private import fileio

        files = fileio.expand_paths(data)
        fmt = "json" if files[0].endswith((".json", ".jsonl")) \
            else "parquet"
        data = OfflineData(data, file_format=fmt)
    if isinstance(data, OfflineData):
        batches = data.materialize(batch_size)
    elif hasattr(data, "iter_batches"):       # a ray_tpu.data Dataset
        batches = OfflineData(data).materialize(batch_size)
    else:
        batches = []
        for item in data:
            if "next_obs" not in item and "return" not in item:
                item = _flatten_rollout(item, gamma)
            batches.append({k: np.asarray(v) for k, v in item.items()})
    if want_return:
        for b in batches:
            if "return" not in b:
                raise ValueError(
                    "MARWIL/BC offline data needs a 'return' column; "
                    "record_rollouts(gamma=...) writes it")
    return batches
