"""Replay buffer (reference: rllib/utils/replay_buffers/) — flat numpy
ring buffer over transition dicts; uniform sampling."""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np


class ReplayBuffer:
    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = capacity
        self._storage: Optional[Dict[str, np.ndarray]] = None
        self._idx = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self):
        return self._size

    def add_batch(self, batch: Dict[str, np.ndarray]):
        """batch: dict of [N, ...] transition arrays."""
        n = len(next(iter(batch.values())))
        if self._storage is None:
            self._storage = {
                k: np.zeros((self.capacity, *v.shape[1:]), v.dtype)
                for k, v in batch.items()
            }
        for k, v in batch.items():
            idx = (self._idx + np.arange(n)) % self.capacity
            self._storage[k][idx] = v
        self._idx = (self._idx + n) % self.capacity
        self._size = min(self._size + n, self.capacity)

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, self._size, batch_size)
        return {k: v[idx] for k, v in self._storage.items()}
