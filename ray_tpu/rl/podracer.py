"""Podracer architectures: Anakin and Sebulba (arXiv 2104.06272).

Two TPU-native actor–learner topologies over the existing IMPALA
V-trace learner (rl/algorithms/impala.py):

  * **Anakin** — the single-gang form: environment stepping and the
    learner update live in ONE compiled program; the whole training run
    is a single ``lax.scan`` of (rollout -> V-trace update) with zero
    host round-trips per update.  The speed-of-light baseline.

  * **Sebulba** — the decomposed form and the hard one: N *elastic*
    actor gangs step vectorized ``jax_env`` environments and stream
    fixed-shape trajectory batches to a learner gang over the
    streaming-generator protocol (bounded in-flight window with
    explicit backpressure, per-batch ``policy_version`` stamps).
    V-trace clips the importance weights, so the learner absorbs the
    bounded staleness — which is exactly what lets actor gangs die,
    drain, and regrow without ever stalling the learner.

Determinism under chaos — the design invariant everything else hangs
off: consumption is round-robin over ``num_gangs`` FIXED logical slots
(update ``t`` consumes slot ``t % G``, sequence ``t // G``), every
batch is produced from a FRESH per-batch env carry with
``rng = f(sample_seed, slot, seq)`` and the params at exactly
``policy_version = max(0, t - staleness_bound)``.  Batch content is
therefore a pure function of (seed, slot, seq, params-history) — a
replacement gang (incarnation + 1) regenerates, bit for bit, the
batches its dead predecessor owed, so the final learner params depend
only on the seed, never on the chaos schedule.

``ChaosSchedule`` turns the run into a sustained chaos workload: hard
actor-gang kills (the streaming consumer surfaces ``ActorDiedError``
instead of hanging), ``straggler_multiple``-tripping slowdowns
(StepAggregator detects, RemediationEngine quarantines, the respawn
sheds the slow host), and preemption notices (PreemptionWatcher ->
``report_draining`` -> graceful retire) — while goodput-predicted
resume width (elastic/resume.py) and run-state goodput publishing (the
autoscaler GoodputPolicy's input) act on every recovery.
"""

from __future__ import annotations

import hashlib
import logging
import os
import threading
import time
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

logger = logging.getLogger(__name__)


def _to_numpy(params):
    return jax.tree_util.tree_map(lambda x: np.asarray(x), params)


def params_digest(params) -> str:
    """Stable content hash of a params pytree (bitwise-reproducibility
    checks across chaos runs)."""
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(params):
        a = np.asarray(leaf)
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def _learner_factory(env_spec: Dict[str, Any], hidden: Tuple[int, ...],
                     hp: Dict[str, Any]):
    """Module-level so LearnerGroup's remote learner actors can pickle
    the factory by reference."""
    from ray_tpu.rl.algorithms.impala import make_impala_learner

    return make_impala_learner(env_spec, hidden=tuple(hidden), **hp)


# ---------------------------------------------------------------------------
# Anakin: the whole training loop as one compiled scan
# ---------------------------------------------------------------------------


@dataclass
class AnakinConfig:
    env_name: str = "CartPole-v1"
    num_envs: int = 64
    rollout_len: int = 16
    num_updates: int = 100
    hidden: Tuple[int, ...] = (32, 32)
    lr: float = 3e-4
    gamma: float = 0.99
    vf_coeff: float = 0.5
    entropy_coeff: float = 0.01
    clip_rho: float = 1.0
    clip_c: float = 1.0
    seed: int = 0


def run_anakin(cfg: AnakinConfig) -> Dict[str, Any]:
    """Single-gang Podracer: rollout + V-trace update fused into one
    jitted ``lax.scan`` over ``num_updates`` — sampling never leaves
    the device, the analog of Anakin's replicated pmap loop on a
    single host.  Returns final params, per-update metric curves, and
    steady-state throughput (timed on a second, compile-free call)."""
    import jax.numpy as jnp

    from ray_tpu.rl.algorithms.impala import make_impala_learner
    from ray_tpu.rl.env import jax_env

    env = jax_env.make_env(cfg.env_name)
    learner = make_impala_learner(
        env.spec, hidden=cfg.hidden, gamma=cfg.gamma, vf_coeff=cfg.vf_coeff,
        entropy_coeff=cfg.entropy_coeff, clip_rho=cfg.clip_rho,
        clip_c=cfg.clip_c, lr=cfg.lr, seed=cfg.seed)
    module = learner.module
    update_fn = learner._update_fn  # jitted pure fn: inlines under jit

    def one_update(carry, _):
        params, opt_state, env_carry, rng = carry
        rng, up_rng = jax.random.split(rng)
        env_carry, batch = jax_env.rollout(
            env, module.forward_exploration, params, env_carry,
            cfg.rollout_len)
        upd = {k: jnp.swapaxes(batch[k], 0, 1)
               for k in ("obs", "action", "reward", "done", "logp")}
        upd["final_vf"] = module.value(params, env_carry[1])
        params, opt_state, metrics = update_fn(params, opt_state, upd, up_rng)
        return (params, opt_state, env_carry, rng), metrics

    from ray_tpu.telemetry import device as devtel

    # one fused program per Anakin run; the ledger pins the "second call
    # is compile-free" claim the steady-state timing below relies on
    @devtel.jit(name="rl.anakin.train")  # jax-ok — once per Anakin run
    def train(params, opt_state, env_carry, rng):
        return jax.lax.scan(one_update, (params, opt_state, env_carry, rng),
                            None, length=cfg.num_updates)

    env_carry0 = jax_env.init_carry(env, jax.random.PRNGKey(cfg.seed + 1),
                                    cfg.num_envs)
    args = (learner.params, learner.opt_state, env_carry0,
            jax.random.PRNGKey(cfg.seed + 17))

    t0 = time.monotonic()
    (params, _, _, _), metrics = train(*args)
    jax.block_until_ready(params)
    first_s = time.monotonic() - t0
    t1 = time.monotonic()
    (params, _, _, _), metrics = train(*args)  # compile-free, same result
    jax.block_until_ready(params)
    run_s = max(time.monotonic() - t1, 1e-9)

    env_steps = cfg.num_updates * cfg.rollout_len * cfg.num_envs
    np_params = _to_numpy(params)
    return {
        "params": np_params,
        "params_digest": params_digest(np_params),
        "metrics": {k: np.asarray(v) for k, v in metrics.items()},
        "final_loss": float(np.asarray(metrics["loss"])[-1]),
        "env_steps": env_steps,
        "env_steps_per_s": env_steps / run_s,
        "updates_per_s": cfg.num_updates / run_s,
        "compile_s": max(first_s - run_s, 0.0),
        "run_s": run_s,
    }


# ---------------------------------------------------------------------------
# Chaos schedule
# ---------------------------------------------------------------------------


@dataclass
class ChaosEvent:
    at_update: int
    kind: str              # "kill" | "straggle" | "preempt"
    slot: int = 0
    #: straggle: injected per-batch delay seconds; preempt: grace_s
    value: float = 0.0


class ChaosSchedule:
    """Deterministic fault injections keyed to learner update indices.
    Seeded from ``RAY_TPU_CHAOS_SEED`` so two runs of the same schedule
    inject the same faults — and the determinism invariant above means
    the learner params match bitwise anyway."""

    def __init__(self, events: Sequence[ChaosEvent] = ()):
        self.events: List[ChaosEvent] = sorted(events,
                                               key=lambda e: e.at_update)
        self.fired: List[ChaosEvent] = []
        self._i = 0

    def due(self, t: int) -> List[ChaosEvent]:
        out = []
        while self._i < len(self.events) \
                and self.events[self._i].at_update <= t:
            ev = self.events[self._i]
            self._i += 1
            self.fired.append(ev)
            out.append(ev)
        return out

    @classmethod
    def sustained(cls, num_updates: int, num_gangs: int, *,
                  kills: int = 1, stragglers: int = 1, preemptions: int = 1,
                  straggle_delay_s: float = 0.25, grace_s: float = 30.0,
                  seed: Optional[int] = None) -> "ChaosSchedule":
        """A sustained schedule: the requested faults spread evenly
        through the run, kinds and victim slots drawn from the chaos
        seed (env ``RAY_TPU_CHAOS_SEED`` when ``seed`` is None)."""
        if seed is None:
            seed = int(os.environ.get("RAY_TPU_CHAOS_SEED", "0"))
        rng = np.random.default_rng(seed)
        kinds = (["straggle"] * stragglers + ["kill"] * kills
                 + ["preempt"] * preemptions)
        rng.shuffle(kinds)
        span = max(1, num_updates // (len(kinds) + 1))
        events = []
        for i, kind in enumerate(kinds):
            events.append(ChaosEvent(
                at_update=span * (i + 1), kind=kind,
                slot=int(rng.integers(num_gangs)),
                value=straggle_delay_s if kind == "straggle" else grace_s))
        return cls(events)


# ---------------------------------------------------------------------------
# Sebulba: actor gangs streaming to the learner gang
# ---------------------------------------------------------------------------


@dataclass
class SebulbaConfig:
    env_name: str = "CartPole-v1"
    num_gangs: int = 2
    num_envs: int = 8
    rollout_len: int = 16
    num_updates: int = 24
    #: max learner-vs-behavior version lag a batch may carry; None ->
    #: 2 * num_gangs (one full round of run-ahead per gang)
    staleness_bound: Optional[int] = None
    #: streaming-generator backpressure: max unconsumed items in flight
    #: per gang stream
    window: int = 2
    #: a learner inter-batch wait above this counts as a stall
    #: (availability = fraction of waits under it)
    stall_bound_s: float = 30.0
    min_gangs: int = 1
    num_learners: int = 0          # 0 = in-process learner
    hidden: Tuple[int, ...] = (32, 32)
    lr: float = 3e-4
    gamma: float = 0.99
    vf_coeff: float = 0.5
    entropy_coeff: float = 0.01
    clip_rho: float = 1.0
    clip_c: float = 1.0
    seed: int = 0
    trial: str = "sebulba_00000"
    name: str = "podracer"
    #: wall floor per produced batch — stabilizes the straggler median
    #: on jittery CI hosts (0 = produce at full speed)
    min_produce_s: float = 0.0
    straggler_multiple: float = 2.0
    straggler_sustain: int = 2
    remediation_max_episodes: int = 1
    remediation_cooldown_s: float = 0.0
    remediation_effect_window: int = 2
    remediation_recover_tolerance: float = 0.15
    quarantine_grace_s: float = 30.0
    drain_grace_s: float = 30.0
    debounce_s: float = 0.0
    weights_wait_s: float = 120.0
    get_timeout_s: float = 120.0
    #: test hook: probe(stage, info) fires synchronously at probe points
    #: ("goodput_dip" after a death publishes a dipped goodput)
    probe: Optional[Callable[[str, Dict[str, Any]], None]] = None


class _GangWorker:
    """One actor gang: vectorized env stepping on the gang's host,
    streaming fixed-shape batches.  Async control methods (put_weights /
    inject_delay / ping) run on the actor's event loop concurrently
    with the live ``stream()`` generator, which the worker drains on
    its stream-executor thread — weight pushes land WHILE a batch is
    being produced."""

    def __init__(self, slot: int, incarnation: int, start_seq: int,
                 spec: Dict[str, Any]):
        from ray_tpu.rl.core.rl_module import DiscretePolicyModule
        from ray_tpu.rl.env import jax_env

        self._slot = int(slot)
        self._incarnation = int(incarnation)
        self._start_seq = int(start_seq)
        self._spec = dict(spec)
        self._env = jax_env.make_env(spec["env_name"])
        self._module = DiscretePolicyModule(self._env.spec["obs_dim"],
                                            self._env.spec["num_actions"],
                                            tuple(spec["hidden"]))
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # version -> params pytree
        self._weights: Dict[int, Any] = {}   # guarded-by: _lock
        # injected straggler delay seconds
        self._delay = 0.0   # guarded-by: _lock

    async def put_weights(self, version: int, params) -> bool:
        with self._cv:
            self._weights[int(version)] = params
            self._cv.notify_all()
        return True

    async def put_weights_many(self, versions: Dict[int, Any]) -> int:
        with self._cv:
            for v, p in versions.items():
                self._weights[int(v)] = p
            self._cv.notify_all()
        return len(versions)

    async def inject_delay(self, seconds: float) -> bool:
        with self._lock:
            self._delay = float(seconds)
        return True

    async def ping(self) -> str:
        return "ok"

    async def node_id(self) -> Optional[str]:
        return os.environ.get("RAY_TPU_NODE_ID")

    def _wait_weights(self, version: int, timeout: float):
        deadline = time.monotonic() + timeout
        with self._cv:
            while version not in self._weights:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise RuntimeError(
                        f"gang {self._slot} timed out waiting for weights "
                        f"v{version} ({timeout:.0f}s)")
                self._cv.wait(min(remaining, 0.5))
            # needed versions are nondecreasing in seq: older ones are dead
            for old in [v for v in self._weights if v < version]:
                del self._weights[old]
            return self._weights[version]

    def stream(self):
        """The gang's batch stream.  seq s of slot k feeds learner
        update t = s*G + k using params at exactly
        v = max(0, t - staleness_bound) — the fixed-staleness scheme
        that makes every batch regenerable by a replacement gang."""
        from ray_tpu.rl.env.env_runner import fixed_shape_batch

        spec = self._spec
        G = spec["num_gangs"]
        D = spec["staleness_bound"]
        # warm the compiled rollout on shape-identical throwaway params
        # BEFORE the produce loop: compile time never lands in produce_s,
        # so a replacement gang's first batch doesn't read as a straggler
        # to the remediation effect watch (and env_steps_per_s measures
        # stepping, not tracing)
        warm = self._module.init(jax.random.PRNGKey(0))
        fixed_shape_batch(self._env, self._module, warm,
                          jax.random.PRNGKey(0), spec["num_envs"],
                          spec["rollout_len"])
        seq = self._start_seq
        while True:
            t = seq * G + self._slot
            if t >= spec["num_updates"]:
                return
            version = max(0, t - D)
            params = self._wait_weights(version, spec["weights_wait_s"])
            t0 = time.monotonic()
            rng = jax.random.fold_in(
                jax.random.PRNGKey(spec["sample_seed"]),
                self._slot * 1_000_003 + seq)
            batch = fixed_shape_batch(self._env, self._module, params, rng,
                                      spec["num_envs"], spec["rollout_len"])
            elapsed = time.monotonic() - t0
            if elapsed < spec["min_produce_s"]:
                time.sleep(spec["min_produce_s"] - elapsed)
            with self._lock:
                delay = self._delay
            if delay > 0:
                time.sleep(delay)  # the injected straggler
            yield {
                "gang": self._slot,
                "incarnation": self._incarnation,
                "seq": seq,
                "policy_version": version,
                "produce_s": time.monotonic() - t0,
                "batch": batch,
            }
            seq += 1


class Sebulba:
    """The Sebulba supervisor: staffs ``num_gangs`` actor gangs, runs
    the learner consumption loop, and drives every robustness subsystem
    at once — PreemptionWatcher drains, RemediationEngine quarantines,
    goodput-predicted resume width on each regrow, and run-state
    goodput publishing for the autoscaler's GoodputPolicy."""

    def __init__(self, cfg: SebulbaConfig,
                 chaos: Optional[ChaosSchedule] = None):
        self.cfg = cfg
        self.chaos = chaos or ChaosSchedule()
        self._D = (cfg.staleness_bound if cfg.staleness_bound is not None
                   else 2 * cfg.num_gangs)
        if cfg.num_gangs < 2:
            raise ValueError("Sebulba needs >= 2 actor gangs (the "
                             "straggler median needs a quorum)")

    # -- gang lifecycle ----------------------------------------------------

    def _gang_spec(self) -> Dict[str, Any]:
        cfg = self.cfg
        return {
            "env_name": cfg.env_name, "hidden": tuple(cfg.hidden),
            "num_gangs": cfg.num_gangs, "staleness_bound": self._D,
            "num_updates": cfg.num_updates, "num_envs": cfg.num_envs,
            "rollout_len": cfg.rollout_len,
            "sample_seed": cfg.seed + 777,
            "min_produce_s": cfg.min_produce_s,
            "weights_wait_s": cfg.weights_wait_s,
        }

    def _spawn(self, slot: int, incarnation: int, start_seq: int):
        import ray_tpu

        h = self._RemoteGang.remote(slot, incarnation, start_seq,
                                    self._spec)
        nid = ray_tpu.get(h.node_id.remote(),
                          timeout=self.cfg.get_timeout_s)
        # replay the retained params history: a replacement gang must be
        # able to regenerate every batch it owes, which needs every
        # version from its next seq's v(t) upward
        ray_tpu.get(h.put_weights_many.remote(dict(self._versions)),
                    timeout=self.cfg.get_timeout_s)
        gen = h.stream.options(
            num_returns="streaming",
            _generator_backpressure_num_objects=self.cfg.window).remote()
        self._handles[slot] = h
        self._gens[slot] = gen
        self._nodes[slot] = nid
        self._incs[slot] = incarnation

    def _respawn(self, slot: int, t: int):
        """Regrow a dead/retired slot at the next unconsumed seq.  The
        width decision genuinely consults the goodput history
        (elastic/resume.choose_width); the fixed-slot determinism
        scheme still staffs every logical slot, and the decision is
        recorded so callers can see the predictor at work."""
        now = time.monotonic()
        from ray_tpu.elastic.resume import choose_width

        self._history.end(rounds=t, now=now)
        width = choose_width(self.cfg.num_gangs, self.cfg.min_gangs,
                             self.cfg.num_gangs, 1, self._history)
        self._resume_widths.append(int(width))
        self._era += 1
        self._spawn(slot, self._incs[slot] + 1, self._next_seq[slot])
        self._respawns += 1
        self._history.begin(self._era, width=self.cfg.num_gangs, rounds=t,
                            now=time.monotonic())
        self._publish_goodput(t, staffed=self.cfg.num_gangs)

    def _retire(self, slot: int, t: int, reason: str):
        """Coordinated retirement (drain / quarantine): stop the stream,
        kill the gang, regrow with incarnation + 1."""
        import ray_tpu

        try:
            ray_tpu.cancel(self._gens[slot])
        except Exception:
            pass
        try:
            ray_tpu.kill(self._handles[slot])
        except Exception:
            pass
        self._note_death(slot, t, reason)
        self._respawn(slot, t)

    def _note_death(self, slot: int, t: int, kind: str):
        self.deaths.append({"slot": slot, "at_update": t, "kind": kind,
                            "incarnation": self._incs[slot]})
        self._publish_goodput(t, staffed=self.cfg.num_gangs - 1)
        if self.cfg.probe is not None:
            try:
                self.cfg.probe("goodput_dip", {
                    "slot": slot, "at_update": t, "kind": kind,
                    "goodput": self._goodput_trace[-1]})
            except Exception:
                logger.exception("probe hook failed")

    # -- control-plane integration ----------------------------------------

    def _publish_goodput(self, t: int, staffed: int):
        from ray_tpu.train.backend import publish_run_state

        goodput = staffed / float(self.cfg.num_gangs)
        self._goodput_trace.append(goodput)
        publish_run_state(
            self.cfg.trial, "RUNNING", name=self.cfg.name,
            workers=staffed, rounds=t,
            metrics=self._last_metrics,
            telemetry={"goodput": {"goodput": goodput},
                       "stragglers": self._agg.summary(),
                       "remediations": self._eng.summary()})

    def _on_preempt_notice(self, notice):
        """PreemptionWatcher callback (fired inline from poll_once):
        report the gang's node draining, retire the gang gracefully at
        the batch boundary, regrow, then clear the notice source."""
        slot = self._preempt_victim
        node = self._nodes[slot]
        t = self._t
        try:
            self._core.control.call("report_draining", {
                "node_id": node,
                "grace_s": notice.grace_s or self.cfg.drain_grace_s,
                "reason": notice.reason}, timeout=10.0)
            self._drained_nodes.add(node)
        except Exception:
            logger.exception("report_draining failed")
        self.drains.append({"slot": slot, "at_update": t, "node": node,
                            "reason": notice.reason})
        self._retire(slot, t, "drain")
        self._src.clear()

    def _enforce(self, decision: Dict[str, Any], t: int, round_idx: int):
        """RemediationEngine said quarantine: bench the gang's node on
        the control plane, retire + regrow the gang (the replacement
        has no injected delay, so the effect watch recovers)."""
        slot = int(decision["rank"])
        node = self._nodes[slot]
        try:
            self._core.control.call("report_quarantine", {
                "node_id": node, "grace_s": self.cfg.quarantine_grace_s,
                "reason": decision["reason"]}, timeout=10.0)
            self._quarantined_nodes.add(node)
        except Exception:
            logger.exception("report_quarantine failed")
        self._eng.note_enforced(decision, node_id=node)
        self._retire(slot, t, "quarantine")
        self._eng.note_recovered(new_world=self.cfg.num_gangs,
                                 step=round_idx)

    def _fire_chaos(self, ev: ChaosEvent, t: int):
        import ray_tpu

        if ev.kind == "kill":
            # hard kill: no drain, no warning — the consumer discovers
            # it when the stream errors
            try:
                ray_tpu.kill(self._handles[ev.slot])
            except Exception:
                pass
        elif ev.kind == "straggle":
            try:
                ray_tpu.get(
                    self._handles[ev.slot].inject_delay.remote(ev.value),
                    timeout=self.cfg.get_timeout_s)
            except Exception:
                pass
        elif ev.kind == "preempt":
            self._preempt_victim = ev.slot
            self._src.trigger(reason=f"chaos-preempt-u{t}",
                              grace_s=ev.value or self.cfg.drain_grace_s)
        else:
            raise ValueError(f"unknown chaos kind {ev.kind!r}")

    # -- the consumption loop ----------------------------------------------

    def _consume(self, slot: int, t: int) -> Dict[str, Any]:
        """Next batch from the slot's stream; on producer death, respawn
        at the next unconsumed seq and retry — regeneration, not loss."""
        import ray_tpu

        t0 = time.monotonic()
        attempts = 0
        while True:
            try:
                ref = next(self._gens[slot])
                item = ray_tpu.get(ref, timeout=self.cfg.get_timeout_s)
                self._waits.append(time.monotonic() - t0)
                return item
            except StopIteration:
                err: Any = "stream exhausted early"
            except Exception as e:
                err = e
            attempts += 1
            if attempts > 5:
                raise RuntimeError(
                    f"slot {slot} failed {attempts} consecutive respawns "
                    f"at update {t}: {err}")
            logger.warning("slot %d stream failed at update %d (%s); "
                           "respawning", slot, t, err)
            self._note_death(slot, t, "stream-error")
            self._respawn(slot, t)

    def _broadcast(self, version: int):
        import ray_tpu

        params = self._versions[version]
        refs = [(s, h.put_weights.remote(version, params))
                for s, h in self._handles.items()]
        for s, r in refs:
            try:
                ray_tpu.get(r, timeout=self.cfg.get_timeout_s)
            except Exception:
                # dead gang: the consume path respawns it (with the full
                # retained history replayed), so a lost push is benign
                logger.debug("weight push v%d to slot %d failed", version, s)

    def _prune_versions(self, t: int):
        floor = t - self._D - 2 * self.cfg.num_gangs - 4
        for v in [v for v in self._versions if v < floor]:
            del self._versions[v]

    def run(self) -> Dict[str, Any]:
        import ray_tpu
        from ray_tpu._private.api import current_core
        from ray_tpu.elastic import ElasticConfig
        from ray_tpu.elastic.preemption import (FakePreemptionSource,
                                                PreemptionWatcher)
        from ray_tpu.elastic.remediation import RemediationEngine
        from ray_tpu.elastic.resume import IncarnationHistory
        from ray_tpu.rl.core.learner import LearnerGroup
        from ray_tpu.rl.env import jax_env
        from ray_tpu.telemetry import StepAggregator, TelemetryConfig
        from ray_tpu.train.backend import publish_run_state

        cfg = self.cfg
        G = cfg.num_gangs
        self._core = current_core()
        self._spec = self._gang_spec()
        self._RemoteGang = ray_tpu.remote(_GangWorker)

        env = jax_env.make_env(cfg.env_name)
        hp = dict(gamma=cfg.gamma, vf_coeff=cfg.vf_coeff,
                  entropy_coeff=cfg.entropy_coeff, clip_rho=cfg.clip_rho,
                  clip_c=cfg.clip_c, lr=cfg.lr, seed=cfg.seed)
        learners = LearnerGroup(
            partial(_learner_factory, dict(env.spec), tuple(cfg.hidden), hp),
            cfg.num_learners)

        self._agg = StepAggregator(
            TelemetryConfig(straggler_multiple=cfg.straggler_multiple,
                            straggler_sustain=cfg.straggler_sustain),
            trial=cfg.trial)
        self._eng = RemediationEngine(
            ElasticConfig(
                remediation_mode="enforce",
                remediation_confirm_rounds=1,
                remediation_cooldown_s=cfg.remediation_cooldown_s,
                remediation_max_episodes=cfg.remediation_max_episodes,
                remediation_effect_window=cfg.remediation_effect_window,
                remediation_recover_tolerance=(
                    cfg.remediation_recover_tolerance),
                quarantine_grace_s=cfg.quarantine_grace_s),
            trial=cfg.trial)
        self._src = FakePreemptionSource()
        self._watcher = PreemptionWatcher(self._src, self._on_preempt_notice,
                                          debounce_s=cfg.debounce_s)
        self._history = IncarnationHistory()

        self._versions: Dict[int, Any] = {0: _to_numpy(learners.get_weights())}
        self._handles: Dict[int, Any] = {}
        self._gens: Dict[int, Any] = {}
        self._nodes: Dict[int, Optional[str]] = {}
        self._incs: Dict[int, int] = {s: -1 for s in range(G)}
        self._next_seq = [0] * G
        self._era = 0
        self._respawns = 0
        self._resume_widths: List[int] = []
        self._goodput_trace: List[float] = []
        self._waits: List[float] = []
        self._last_metrics: Optional[Dict[str, float]] = None
        self._preempt_victim = 0
        self._drained_nodes: set = set()
        self._quarantined_nodes: set = set()
        self.deaths: List[Dict[str, Any]] = []
        self.drains: List[Dict[str, Any]] = []
        consumed: List[Tuple[int, int, int, int]] = []
        consumed_keys: set = set()
        staleness: List[int] = []
        produce_last: Dict[int, float] = {}
        produce_total = 0.0
        metrics: Dict[str, float] = {}

        t_start = time.monotonic()
        error: Optional[BaseException] = None
        try:
            for slot in range(G):
                self._spawn(slot, 0, 0)
            self._history.begin(self._era, width=G, rounds=0,
                                now=time.monotonic())
            self._publish_goodput(0, staffed=G)

            for t in range(cfg.num_updates):
                self._t = t
                slot = t % G
                for ev in self.chaos.due(t):
                    self._fire_chaos(ev, t)
                self._watcher.poll_once()

                item = self._consume(slot, t)
                # exactly-once, in-order, staleness-bounded — the
                # invariants chaos must not break
                if item["seq"] != self._next_seq[slot]:
                    raise AssertionError(
                        f"slot {slot} yielded seq {item['seq']}, expected "
                        f"{self._next_seq[slot]} (ordering broken)")
                key = (slot, item["seq"])
                if key in consumed_keys:
                    raise AssertionError(
                        f"duplicate batch {key} (exactly-once broken)")
                consumed_keys.add(key)
                self._next_seq[slot] += 1
                lag = t - item["policy_version"]
                if not 0 <= lag <= self._D:
                    raise AssertionError(
                        f"staleness {lag} outside [0, {self._D}] at "
                        f"update {t}")
                staleness.append(lag)
                consumed.append((slot, item["incarnation"], item["seq"],
                                 item["policy_version"]))
                produce_last[slot] = item["produce_s"]
                produce_total += item["produce_s"]

                metrics = learners.update(item["batch"])
                self._last_metrics = metrics
                self._versions[t + 1] = _to_numpy(learners.get_weights())
                self._prune_versions(t)
                self._broadcast(t + 1)

                if (t + 1) % G == 0:
                    round_idx = (t + 1) // G - 1
                    self._agg.ingest_round([
                        {"step": round_idx, "ts": 0.0,
                         "dur": produce_last.get(s, 0.0),
                         "phases": {"compute": produce_last.get(s, 0.0)},
                         "rank": s, "incarnation": self._incs[s]}
                        for s in range(G)])
                    try:
                        from ray_tpu.telemetry import device as _devtel

                        for adv in _devtel.get_ledger().drain_advisories():
                            self._eng.observe_advisory(adv)
                    except Exception:
                        pass
                    decision = self._eng.observe_round(self._agg)
                    if decision is not None:
                        self._enforce(decision, t, round_idx)
                    self._publish_goodput(t + 1, staffed=G)
            self._history.end(rounds=cfg.num_updates, now=time.monotonic())
        except BaseException as e:
            error = e
            raise
        finally:
            for h in self._handles.values():
                try:
                    ray_tpu.kill(h)
                except Exception:
                    pass
            for node, method in [(n, "report_draining")
                                 for n in self._drained_nodes] + \
                                [(n, "report_quarantine")
                                 for n in self._quarantined_nodes]:
                try:
                    self._core.control.call(method, {
                        "node_id": node, "cancel": True}, timeout=10.0)
                except Exception:
                    pass
            learners.stop()
            publish_run_state(
                cfg.trial, "ERRORED" if error else "FINISHED",
                name=cfg.name, workers=G, rounds=cfg.num_updates,
                metrics=self._last_metrics,
                telemetry={"goodput": {"goodput": 1.0},
                           "remediations": self._eng.summary()})

        elapsed = max(time.monotonic() - t_start, 1e-9)
        samples = cfg.num_updates * cfg.num_envs * cfg.rollout_len
        final = self._versions[cfg.num_updates]
        stal = np.asarray(staleness)
        waits = np.asarray(self._waits)
        return {
            "params": final,
            "params_digest": params_digest(final),
            "updates": cfg.num_updates,
            "learner_samples": samples,
            "learner_samples_per_s": samples / elapsed,
            "env_steps_per_s": samples / max(produce_total, 1e-9),
            "staleness": {"bound": self._D, "max": int(stal.max()),
                          "p99": float(np.percentile(stal, 99)),
                          "mean": float(stal.mean())},
            "availability": float(np.mean(waits <= cfg.stall_bound_s)),
            "wait_p99_s": float(np.percentile(waits, 99)),
            "consumed": consumed,
            "deaths": self.deaths,
            "drains": self.drains,
            "respawns": self._respawns,
            "resume_widths": self._resume_widths,
            "incarnations": dict(self._incs),
            "remediation": self._eng.summary(),
            "remediation_records": list(self._eng.records),
            "goodput_trace": self._goodput_trace,
            "notices": {"fired": self._watcher.notices_fired,
                        "suppressed": self._watcher.notices_suppressed},
            "chaos_fired": [(e.kind, e.at_update, e.slot)
                            for e in self.chaos.fired],
            "quarantined_nodes": sorted(self._quarantined_nodes),
            "drained_nodes": sorted(self._drained_nodes),
            "final_metrics": metrics,
            "elapsed_s": elapsed,
        }


def run_sebulba(cfg: SebulbaConfig,
                chaos: Optional[ChaosSchedule] = None) -> Dict[str, Any]:
    return Sebulba(cfg, chaos).run()
