"""APPO: asynchronous PPO — IMPALA's architecture with PPO's loss.

Analog of the reference's APPO (reference: rllib/algorithms/appo/appo.py,
torch/appo_torch_learner.py): sampling uses possibly-stale behavior
weights (like IMPALA), the learner corrects off-policyness with V-trace
and optimizes the PPO clipped surrogate against a periodically-updated
*target* policy network, plus a KL penalty pulling the learner policy
toward the target (reference: appo.py `use_kl_loss`, `kl_coeff`,
`target_network_update_freq`).

Jax-first: V-trace is the same reverse `lax.scan` as IMPALA's; the
target-network refresh is param surgery in `extra_update`, outside the
jitted gradient step.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rl.core.learner import Learner, LearnerGroup
from ray_tpu.rl.core.rl_module import DiscretePolicyModule

from .algorithm import Algorithm, AlgorithmConfig
from .impala import vtrace


class APPOLearner(Learner):
    def __init__(self, module: DiscretePolicyModule, *,
                 gamma: float = 0.99, clip_param: float = 0.3,
                 vf_coeff: float = 0.5, entropy_coeff: float = 0.01,
                 kl_coeff: float = 0.2, clip_rho: float = 1.0,
                 clip_c: float = 1.0,
                 target_update_freq: int = 4, **kwargs):
        self.gamma = gamma
        self.clip_param = clip_param
        self.vf_coeff = vf_coeff
        self.entropy_coeff = entropy_coeff
        self.kl_coeff = kl_coeff
        self.clip_rho = clip_rho
        self.clip_c = clip_c
        self.target_update_freq = target_update_freq
        self._updates_since_target = 0
        super().__init__(module, **kwargs)
        # target policy: a frozen copy refreshed every N updates
        self.params = {**self.params,
                       "target_pi": jax.tree_util.tree_map(
                           jnp.copy, self.params["pi"])}

    def _trainable(self, params):
        return {"pi": params["pi"], "vf": params["vf"]}

    def _merge(self, params, trained):
        return {**trained, "target_pi": params["target_pi"]}

    def compute_loss(self, params, batch, rng):
        # batch arrives [B, T]; V-trace wants time-major
        batch = dict(batch)
        for k in ("obs", "action", "reward", "done", "logp"):
            batch[k] = jnp.swapaxes(batch[k], 0, 1)
        logits = self.module.logits(params, batch["obs"])
        logp_all = jax.nn.log_softmax(logits)
        a = batch["action"][..., None].astype(jnp.int32)
        target_logp = jnp.take_along_axis(logp_all, a, axis=-1)[..., 0]
        values = self.module.value(params, batch["obs"])

        # V-trace targets use the *target network's* action probabilities
        # as the stable "current" policy (reference: APPO computes
        # advantages against the target net so the surrogate's anchor
        # doesn't move every SGD step)
        tgt_logits = self._target_logits(params, batch["obs"])
        tgt_logp_all = jax.nn.log_softmax(tgt_logits)
        tgt_logp = jnp.take_along_axis(tgt_logp_all, a, axis=-1)[..., 0]

        vs, pg_adv = vtrace(batch["logp"], tgt_logp, batch["reward"],
                            batch["done"], values, batch["final_vf"],
                            self.gamma, self.clip_rho, self.clip_c)

        # PPO clipped surrogate with the behavior policy in the ratio
        ratio = jnp.exp(target_logp - batch["logp"])
        surr = jnp.minimum(
            ratio * pg_adv,
            jnp.clip(ratio, 1 - self.clip_param,
                     1 + self.clip_param) * pg_adv)
        pi_loss = -jnp.mean(surr)
        vf_loss = 0.5 * jnp.mean((values - vs) ** 2)
        entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
        # KL(target || learner): keeps the learner near the anchor
        kl = jnp.mean(jnp.sum(
            jnp.exp(tgt_logp_all) * (tgt_logp_all - logp_all), axis=-1))
        loss = pi_loss + self.vf_coeff * vf_loss \
            - self.entropy_coeff * entropy + self.kl_coeff * kl
        return loss, {"pi_loss": pi_loss, "vf_loss": vf_loss,
                      "entropy": entropy, "kl": kl,
                      "mean_ratio": jnp.mean(ratio)}

    def _target_logits(self, params, obs):
        from ray_tpu.rl.core.rl_module import _mlp_apply

        return jax.lax.stop_gradient(
            _mlp_apply(params["target_pi"], obs))

    def extra_update(self, params, metrics):
        self._updates_since_target += 1
        if self._updates_since_target >= self.target_update_freq:
            self._updates_since_target = 0
            params = {**params,
                      "target_pi": jax.tree_util.tree_map(
                          jnp.copy, params["pi"])}
        return params


class APPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 5e-4
        self.clip_param = 0.3
        self.vf_coeff = 0.5
        self.entropy_coeff = 0.01
        self.kl_coeff = 0.2
        self.clip_rho = 1.0
        self.clip_c = 1.0
        self.target_update_freq = 4

    algo_cls = None


class APPO(Algorithm):
    module_kind = "policy"

    def _setup(self):
        cfg: APPOConfig = self.config

        def factory():
            module = DiscretePolicyModule(self.env_spec["obs_dim"],
                                          self.env_spec["num_actions"],
                                          cfg.hidden)
            return APPOLearner(
                module, gamma=cfg.gamma, clip_param=cfg.clip_param,
                vf_coeff=cfg.vf_coeff, entropy_coeff=cfg.entropy_coeff,
                kl_coeff=cfg.kl_coeff, clip_rho=cfg.clip_rho,
                clip_c=cfg.clip_c,
                target_update_freq=cfg.target_update_freq,
                lr=cfg.lr, seed=cfg.seed)

        self.learner_group = LearnerGroup(factory, cfg.num_learners)
        self.runners.sync_weights(self.learner_group.get_weights())

    def training_step(self) -> Dict[str, Any]:
        cfg: APPOConfig = self.config
        results = self.runners.sample(cfg.rollout_len)
        batch, stats = self._merge_runner_results(results)
        update_batch = {
            k: np.swapaxes(np.asarray(batch[k]), 0, 1)
            for k in ("obs", "action", "reward", "done", "logp")
        }
        update_batch["final_vf"] = np.asarray(batch["final_vf"])
        metrics = self.learner_group.update(update_batch)
        self.runners.sync_weights(self.learner_group.get_weights())
        metrics.update(stats)
        return metrics


APPOConfig.algo_cls = APPO
