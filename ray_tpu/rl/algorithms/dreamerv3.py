"""DreamerV3: model-based RL via a learned world model.

Analog of the reference's DreamerV3 (reference:
rllib/algorithms/dreamerv3/dreamerv3.py, torch/dreamerv3_torch_learner.py,
utils/ — RSSM world model, imagination rollouts, actor-critic on
dreamed trajectories).  Compact jax-first variant with the same moving
parts, sized for vector-obs envs:

  * RSSM: GRU deterministic state + straight-through categorical
    stochastic latent; prior (h -> z) and posterior (h, embed -> z)
  * heads: decoder (obs recon), reward (symlog MSE), continue (bernoulli)
  * KL balancing with free bits (v3's stop-grad two-sided KL)
  * imagination: `lax.scan` rollout of H steps under the actor through
    the prior dynamics — the whole dream is one compiled program
  * actor: REINFORCE on lambda-returns (v3's discrete-action estimator),
    critic: symlog regression with an EMA-free lite target (stop-grad)

Everything trains under a single jitted update (the reference uses one
optimizer per component; the lite variant shares one Adam — the
stop-gradient structure is what matters for correctness).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rl.core.learner import Learner, LearnerGroup
from ray_tpu.rl.core.rl_module import (MODULE_REGISTRY, RLModule, _mlp_apply,
                                       _mlp_init)

from .algorithm import Algorithm, AlgorithmConfig


def symlog(x):
    return jnp.sign(x) * jnp.log1p(jnp.abs(x))


def symexp(x):
    return jnp.sign(x) * (jnp.exp(jnp.abs(x)) - 1.0)


def _gru_init(rng, n_in: int, n_hidden: int):
    r1, r2 = jax.random.split(rng)
    scale_x = 1.0 / np.sqrt(n_in)
    scale_h = 1.0 / np.sqrt(n_hidden)
    return {
        "wx": jax.random.uniform(r1, (n_in, 3 * n_hidden),
                                 minval=-scale_x, maxval=scale_x),
        "wh": jax.random.uniform(r2, (n_hidden, 3 * n_hidden),
                                 minval=-scale_h, maxval=scale_h),
        "b": jnp.zeros((3 * n_hidden,)),
    }


def _gru(p, h, x):
    gates = x @ p["wx"] + h @ p["wh"] + p["b"]
    r, u, c = jnp.split(gates, 3, axis=-1)
    r = jax.nn.sigmoid(r)
    u = jax.nn.sigmoid(u)
    c = jnp.tanh(r * c)
    return u * h + (1 - u) * c


def _st_categorical(rng, logits):
    """Straight-through one-hot sample (v3's unimix omitted for lite)."""
    idx = jax.random.categorical(rng, logits)
    one_hot = jax.nn.one_hot(idx, logits.shape[-1])
    probs = jax.nn.softmax(logits)
    return one_hot + probs - jax.lax.stop_gradient(probs)


class DreamerModule(RLModule):
    """World model + actor + critic parameter bundle.

    Latent state = (h deterministic, z stochastic one-hot); the feature
    vector fed to heads/actor/critic is concat(h, z)."""

    def __init__(self, obs_dim: int, num_actions: int, hidden=(64, 64), *,
                 deter: int = 128, classes: int = 32):
        super().__init__(obs_dim, num_actions, hidden)
        self.deter = deter
        self.classes = classes

    @property
    def feat_dim(self):
        return self.deter + self.classes

    def init(self, rng):
        ks = jax.random.split(rng, 8)
        h = self.hidden
        return {
            "encoder": _mlp_init(ks[0], (self.obs_dim, *h, h[-1]),
                                 out_scale=1.0),
            "gru": _gru_init(ks[1], self.classes + self.num_actions,
                             self.deter),
            "prior": _mlp_init(ks[2], (self.deter, *h, self.classes),
                               out_scale=1.0),
            "posterior": _mlp_init(
                ks[3], (self.deter + h[-1], *h, self.classes),
                out_scale=1.0),
            "decoder": _mlp_init(ks[4], (self.feat_dim, *h, self.obs_dim),
                                 out_scale=1.0),
            "reward": _mlp_init(ks[5], (self.feat_dim, *h, 1)),
            "cont": _mlp_init(ks[6], (self.feat_dim, *h, 1), out_scale=1.0),
            "actor": _mlp_init(ks[7], (self.feat_dim, *h,
                                       self.num_actions)),
            "critic": _mlp_init(jax.random.fold_in(rng, 99),
                                (self.feat_dim, *h, 1), out_scale=0.01),
        }

    # -- world model pieces -------------------------------------------------

    def encode(self, params, obs):
        return _mlp_apply(params["encoder"], obs)

    def dynamics_step(self, params, h, z, action_onehot):
        return _gru(params["gru"], h,
                    jnp.concatenate([z, action_onehot], axis=-1))

    def prior_logits(self, params, h):
        return _mlp_apply(params["prior"], h)

    def posterior_logits(self, params, h, embed):
        return _mlp_apply(params["posterior"],
                          jnp.concatenate([h, embed], axis=-1))

    def feat(self, h, z):
        return jnp.concatenate([h, z], axis=-1)

    # -- policy API (used by env runners) -----------------------------------

    def logits(self, params, obs):
        """Stateless policy view for the runner: posterior latent from a
        zero GRU state.  Dreaming/training uses the recurrent path; this
        keeps the plain EnvRunner protocol working without carried
        state (lite simplification of the reference's stateful
        EnvRunner)."""
        B = obs.shape[:-1]
        h = jnp.zeros((*B, self.deter))
        embed = self.encode(params, obs)
        post = self.posterior_logits(params, h, embed)
        z = jax.nn.softmax(post)
        return _mlp_apply(params["actor"], self.feat(h, z))

    def value(self, params, obs):
        B = obs.shape[:-1]
        h = jnp.zeros((*B, self.deter))
        embed = self.encode(params, obs)
        z = jax.nn.softmax(self.posterior_logits(params, h, embed))
        return _mlp_apply(params["critic"], self.feat(h, z))[..., 0]

    def forward_exploration(self, params, obs, rng):
        logits = self.logits(params, obs)
        action = jax.random.categorical(rng, logits)
        logp = jax.nn.log_softmax(logits)
        logp_a = jnp.take_along_axis(logp, action[..., None],
                                     axis=-1)[..., 0]
        return action, {"logp": logp_a, "vf": self.value(params, obs)}

    def forward_inference(self, params, obs):
        return jnp.argmax(self.logits(params, obs), axis=-1)


MODULE_REGISTRY["dreamer"] = DreamerModule


class DreamerLearner(Learner):
    def __init__(self, module: DreamerModule, *, gamma: float = 0.99,
                 lam: float = 0.95, horizon: int = 15,
                 kl_scale: float = 1.0, free_bits: float = 1.0,
                 entropy_coeff: float = 3e-3, **kwargs):
        self.gamma = gamma
        self.lam = lam
        self.horizon = horizon
        self.kl_scale = kl_scale
        self.free_bits = free_bits
        self.entropy_coeff = entropy_coeff
        super().__init__(module, **kwargs)

    # -- world-model observation (posterior scan over the sequence) --------

    def _observe(self, params, obs_seq, action_seq, rng):
        """obs [T,B,D], action [T,B] -> posterior features + KL loss."""
        m = self.module
        T, B = action_seq.shape
        embed = m.encode(params, obs_seq)
        a_onehot = jax.nn.one_hot(action_seq, m.num_actions)
        h0 = jnp.zeros((B, m.deter))
        z0 = jnp.zeros((B, m.classes))
        rngs = jax.random.split(rng, T)

        def step(carry, xs):
            h, z = carry
            emb_t, a_t, rng_t = xs
            h = m.dynamics_step(params, h, z, a_t)
            prior = m.prior_logits(params, h)
            post = m.posterior_logits(params, h, emb_t)
            z = _st_categorical(rng_t, post)
            return (h, z), (h, z, prior, post)

        _, (hs, zs, priors, posts) = jax.lax.scan(
            step, (h0, z0), (embed, a_onehot, rngs))

        # KL balancing (v3): dyn loss trains the prior toward the (frozen)
        # posterior; rep loss nudges the posterior toward the (frozen)
        # prior; both clipped below by free bits
        def cat_kl(p_logits, q_logits):
            p = jax.nn.softmax(p_logits)
            return jnp.sum(p * (jax.nn.log_softmax(p_logits)
                                - jax.nn.log_softmax(q_logits)), axis=-1)

        dyn = cat_kl(jax.lax.stop_gradient(posts), priors)
        rep = cat_kl(posts, jax.lax.stop_gradient(priors))
        kl = 0.5 * jnp.maximum(dyn, self.free_bits).mean() \
            + 0.1 * jnp.maximum(rep, self.free_bits).mean()
        return hs, zs, kl

    # -- imagination --------------------------------------------------------

    def _imagine(self, params, h0, z0, rng):
        """Roll the prior dynamics H steps under the actor.  Dynamics are
        stop-grad for the actor (REINFORCE estimator, v3 discrete)."""
        m = self.module
        p_sg = jax.lax.stop_gradient(params)

        def step(carry, rng_t):
            h, z = carry
            feat = m.feat(h, z)
            logits = _mlp_apply(params["actor"], feat)
            a_rng, z_rng = jax.random.split(rng_t)
            action = jax.random.categorical(a_rng, logits)
            logp = jnp.take_along_axis(
                jax.nn.log_softmax(logits), action[..., None],
                axis=-1)[..., 0]
            entropy = -jnp.sum(jax.nn.softmax(logits)
                               * jax.nn.log_softmax(logits), axis=-1)
            a_onehot = jax.nn.one_hot(action, m.num_actions)
            h = m.dynamics_step(p_sg, h, z, a_onehot)
            z = _st_categorical(z_rng, m.prior_logits(p_sg, h))
            z = jax.lax.stop_gradient(z)
            return (h, z), (feat, logp, entropy)

        rngs = jax.random.split(rng, self.horizon)
        (hT, zT), (feats, logps, entropies) = jax.lax.scan(
            step, (h0, z0), rngs)
        last_feat = m.feat(hT, zT)
        return feats, logps, entropies, last_feat

    # -- loss ---------------------------------------------------------------

    def compute_loss(self, params, batch, rng):
        m = self.module
        # batch arrives [B, T]; observe scans over time
        obs = jnp.swapaxes(batch["obs"], 0, 1)
        action = jnp.swapaxes(batch["action"], 0, 1).astype(jnp.int32)
        reward = jnp.swapaxes(batch["reward"], 0, 1)
        done = jnp.swapaxes(batch["done"], 0, 1).astype(jnp.float32)

        obs_rng, img_rng = jax.random.split(rng)
        hs, zs, kl = self._observe(params, obs, action, obs_rng)
        feats = m.feat(hs, zs)

        recon = _mlp_apply(params["decoder"], feats)
        recon_loss = jnp.mean(jnp.sum((recon - symlog(obs)) ** 2, axis=-1))
        pred_r = _mlp_apply(params["reward"], feats)[..., 0]
        reward_loss = jnp.mean((pred_r - symlog(reward)) ** 2)
        cont_logit = _mlp_apply(params["cont"], feats)[..., 0]
        cont_target = 1.0 - done
        cont_loss = jnp.mean(
            jnp.maximum(cont_logit, 0) - cont_logit * cont_target
            + jnp.log1p(jnp.exp(-jnp.abs(cont_logit))))
        wm_loss = recon_loss + reward_loss + cont_loss + self.kl_scale * kl

        # ---- dream from every posterior state (flattened T*B starts)
        h0 = jax.lax.stop_gradient(hs.reshape(-1, m.deter))
        z0 = jax.lax.stop_gradient(zs.reshape(-1, m.classes))
        feats_i, logps_i, ent_i, last_feat = self._imagine(
            params, h0, z0, img_rng)

        r_i = symexp(_mlp_apply(
            jax.lax.stop_gradient(params)["reward"], feats_i)[..., 0])
        c_i = jax.nn.sigmoid(_mlp_apply(
            jax.lax.stop_gradient(params)["cont"], feats_i)[..., 0])
        v_i = _mlp_apply(params["critic"], feats_i)[..., 0]
        v_last = _mlp_apply(params["critic"], last_feat)[..., 0]

        # lambda-returns over the dream (v3 eq. 7), all stop-grad values
        disc = self.gamma * c_i
        v_sg = jax.lax.stop_gradient(v_i)

        def lam_step(acc, xs):
            r_t, d_t, v_next = xs
            acc = r_t + d_t * ((1 - self.lam) * v_next + self.lam * acc)
            return acc, acc

        v_next_seq = jnp.concatenate(
            [v_sg[1:], jax.lax.stop_gradient(v_last)[None]], axis=0)
        _, returns = jax.lax.scan(
            lam_step, jax.lax.stop_gradient(v_last),
            (r_i, disc, v_next_seq), reverse=True)
        returns = jax.lax.stop_gradient(returns)

        critic_loss = jnp.mean((v_i - returns) ** 2)
        adv = returns - v_sg
        adv = adv / (jnp.std(adv) + 1e-3)  # v3 return normalization (lite)
        actor_loss = -jnp.mean(jax.lax.stop_gradient(adv) * logps_i) \
            - self.entropy_coeff * jnp.mean(ent_i)

        loss = wm_loss + actor_loss + 0.5 * critic_loss
        return loss, {"wm_loss": wm_loss, "recon_loss": recon_loss,
                      "reward_loss": reward_loss, "kl": kl,
                      "actor_loss": actor_loss,
                      "critic_loss": critic_loss,
                      "dream_return": jnp.mean(returns)}


class DreamerV3Config(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 3e-4
        self.horizon = 15
        self.lam = 0.95
        self.kl_scale = 1.0
        self.free_bits = 1.0
        self.entropy_coeff = 3e-3
        self.deter = 128
        self.classes = 32
        self.rollout_len = 64

    algo_cls = None


class DreamerV3(Algorithm):
    module_kind = "dreamer"

    def _module_kwargs(self):
        return {"deter": self.config.deter, "classes": self.config.classes}

    def _setup(self):
        cfg: DreamerV3Config = self.config

        def factory():
            module = DreamerModule(self.env_spec["obs_dim"],
                                   self.env_spec["num_actions"],
                                   cfg.hidden, deter=cfg.deter,
                                   classes=cfg.classes)
            return DreamerLearner(
                module, gamma=cfg.gamma, lam=cfg.lam,
                horizon=cfg.horizon, kl_scale=cfg.kl_scale,
                free_bits=cfg.free_bits,
                entropy_coeff=cfg.entropy_coeff,
                lr=cfg.lr, seed=cfg.seed)

        self.learner_group = LearnerGroup(factory, cfg.num_learners)
        self.runners.sync_weights(self.learner_group.get_weights())

    def training_step(self) -> Dict[str, Any]:
        cfg: DreamerV3Config = self.config
        results = self.runners.sample(cfg.rollout_len)
        batch, stats = self._merge_runner_results(results)
        update_batch = {
            k: np.swapaxes(np.asarray(batch[k]), 0, 1)
            for k in ("obs", "action", "reward", "done")
        }
        metrics = self.learner_group.update(update_batch)
        self.runners.sync_weights(self.learner_group.get_weights())
        metrics.update(stats)
        return metrics


DreamerV3Config.algo_cls = DreamerV3
