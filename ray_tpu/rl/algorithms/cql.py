"""CQL: conservative Q-learning for offline RL.

Analog of the reference's CQL (reference: rllib/algorithms/cql/cql.py,
torch/cql_torch_learner.py — SAC's learner plus the conservative
regularizer).  Discrete-action variant (CQL(H), Kumar et al. 2020
eq. 4): the critic loss adds

    E_s[ logsumexp_a Q(s, a) - Q(s, a_data) ]

which pushes down Q on out-of-distribution actions and up on dataset
actions — exact (no sampled actions) in the discrete case, and a dense
[batch, actions] logsumexp is the TPU-friendly shape.

Offline data comes the same way as MARWIL/BC: any iterable of sample
dicts with {obs, action, reward, done, next_obs}.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rl.core.learner import Learner, LearnerGroup
from ray_tpu.rl.core.rl_module import QModule

from .algorithm import Algorithm, AlgorithmConfig


class CQLLearner(Learner):
    def __init__(self, module: QModule, *, gamma: float = 0.99,
                 cql_alpha: float = 1.0, tau: float = 0.005, **kwargs):
        self.gamma = gamma
        self.cql_alpha = cql_alpha
        self.tau = tau
        super().__init__(module, **kwargs)

    def _trainable(self, params):
        return {"q": params["q"]}

    def _merge(self, params, trained):
        return {**trained, "target_q": params["target_q"]}

    def compute_loss(self, params, batch, rng):
        q_all = self.module.q_values(params, batch["obs"])
        a = batch["action"][..., None].astype(jnp.int32)
        q_data = jnp.take_along_axis(q_all, a, axis=-1)[..., 0]
        # double-DQN style target from the frozen net
        next_q_online = self.module.q_values(params, batch["next_obs"])
        next_a = jnp.argmax(next_q_online, axis=-1)[..., None]
        next_q_target = self.module.q_values(params, batch["next_obs"],
                                             target=True)
        next_q = jnp.take_along_axis(next_q_target, next_a, axis=-1)[..., 0]
        nonterminal = 1.0 - batch["done"].astype(jnp.float32)
        td_target = jax.lax.stop_gradient(
            batch["reward"] + self.gamma * nonterminal * next_q)
        bellman = 0.5 * jnp.mean((q_data - td_target) ** 2)
        # the conservative term: logsumexp over all actions minus the
        # dataset action's Q (CQL(H), exact for discrete actions)
        conservative = jnp.mean(
            jax.scipy.special.logsumexp(q_all, axis=-1) - q_data)
        loss = bellman + self.cql_alpha * conservative
        return loss, {"bellman_loss": bellman,
                      "cql_loss": conservative,
                      "mean_q_data": jnp.mean(q_data),
                      "mean_q_max": jnp.mean(jnp.max(q_all, axis=-1))}

    def extra_update(self, params, metrics):
        # polyak target update (SAC-style, reference cql keeps SAC's)
        new_target = jax.tree_util.tree_map(
            lambda t, o: (1 - self.tau) * t + self.tau * o,
            params["target_q"], params["q"])
        return {**params, "target_q": new_target}


def transitions_from_rollout(batch: Dict[str, Any]) -> Dict[str, np.ndarray]:
    """[T, B] rollout arrays -> flat {obs, action, reward, done, next_obs}
    transitions (next_obs shifted along T; the last step of each column
    is dropped since its successor is unknown)."""
    obs = np.asarray(batch["obs"])
    flat = lambda a: a.reshape(-1, *a.shape[2:])  # noqa: E731
    return {
        "obs": flat(obs[:-1]),
        "next_obs": flat(obs[1:]),
        "action": flat(np.asarray(batch["action"])[:-1]),
        "reward": flat(np.asarray(batch["reward"], np.float32)[:-1]),
        "done": flat(np.asarray(batch["done"], bool)[:-1]),
    }


class CQLConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 3e-4
        self.cql_alpha = 1.0
        self.tau = 0.005
        self.num_epochs = 1
        self.minibatch_size = 256
        #: offline experience: iterable of flat transition dicts
        #: ({obs, action, reward, done, next_obs}) or [T,B] rollouts
        self.offline_data: Optional[Iterable[Dict[str, Any]]] = None

    algo_cls = None

    def offline(self, data: Iterable[Dict[str, Any]]):
        self.offline_data = data
        return self


class CQL(Algorithm):
    """Offline when config.offline_data is set; otherwise trains
    conservatively on its own rollouts (smoke mode)."""

    module_kind = "q"

    def _setup(self):
        cfg: CQLConfig = self.config

        def factory():
            module = QModule(self.env_spec["obs_dim"],
                             self.env_spec["num_actions"], cfg.hidden)
            return CQLLearner(module, gamma=cfg.gamma,
                              cql_alpha=cfg.cql_alpha, tau=cfg.tau,
                              lr=cfg.lr, seed=cfg.seed)

        self.learner_group = LearnerGroup(factory, cfg.num_learners)
        self.runners.sync_weights(self.learner_group.get_weights())
        from ray_tpu.rl.offline import resolve_offline_data

        # file paths / OfflineData / Dataset / legacy in-memory iterable
        # all land here as flat numpy transition batches (reference:
        # offline_data.py:22 feeds ray.data into the learner)
        self._offline: List[Dict[str, np.ndarray]] = resolve_offline_data(
            cfg.offline_data, gamma=cfg.gamma,
            batch_size=cfg.minibatch_size)
        self._rng = np.random.RandomState(cfg.seed)

    def _offline_minibatches(self):
        cfg: CQLConfig = self.config
        all_idx = [(i, j) for i, d in enumerate(self._offline)
                   for j in range(0, len(d["obs"]), cfg.minibatch_size)]
        self._rng.shuffle(all_idx)
        for i, j in all_idx:
            d = self._offline[i]
            yield {k: v[j:j + cfg.minibatch_size] for k, v in d.items()}

    def training_step(self) -> Dict[str, Any]:
        cfg: CQLConfig = self.config
        metrics: Dict[str, float] = {}
        if self._offline:
            for _ in range(cfg.num_epochs):
                for mb in self._offline_minibatches():
                    metrics = self.learner_group.update(mb)
            self.runners.sync_weights(self.learner_group.get_weights())
            return metrics
        results = self.runners.sample(cfg.rollout_len)
        batch, stats = self._merge_runner_results(results)
        metrics = self.learner_group.update(transitions_from_rollout(batch))
        self.runners.sync_weights(self.learner_group.get_weights())
        metrics.update(stats)
        return metrics


CQLConfig.algo_cls = CQL
