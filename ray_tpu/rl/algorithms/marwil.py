"""MARWIL + BC: offline policy learning from logged experience.

Analog of the reference's MARWIL/BC (reference:
rllib/algorithms/marwil/marwil.py, torch/marwil_torch_learner.py;
rllib/algorithms/bc/bc.py — BC is MARWIL with beta=0): exponentially
advantage-weighted behavior cloning with a value baseline.  Offline data
comes from any iterable of sample dicts (e.g. a ray_tpu.data Dataset of
episodes or rollouts recorded by an EnvRunnerGroup).
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rl.core.learner import Learner, LearnerGroup
from ray_tpu.rl.core.rl_module import DiscretePolicyModule

from .algorithm import Algorithm, AlgorithmConfig


class MarwilLearner(Learner):
    def __init__(self, module: DiscretePolicyModule, *, beta: float = 1.0,
                 vf_coeff: float = 1.0, advantage_clip: float = 10.0,
                 **kwargs):
        self.beta = beta
        self.vf_coeff = vf_coeff
        self.advantage_clip = advantage_clip
        super().__init__(module, **kwargs)

    def compute_loss(self, params, batch, rng):
        logits = self.module.logits(params, batch["obs"])
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, batch["action"][..., None].astype(jnp.int32),
            axis=-1)[..., 0]
        value = self.module.value(params, batch["obs"])
        returns = batch["return"]
        vf_loss = jnp.mean((value - returns) ** 2)
        if self.beta == 0.0:
            # plain behavior cloning
            weights = jnp.ones_like(logp)
        else:
            adv = jax.lax.stop_gradient(returns - value)
            # normalize advantages by their running scale (reference keeps
            # a moving average; per-batch rms is the jit-friendly analog)
            rms = jnp.sqrt(jnp.mean(adv ** 2) + 1e-8)
            weights = jnp.exp(jnp.clip(self.beta * adv / rms,
                                       -self.advantage_clip,
                                       self.advantage_clip))
            weights = jax.lax.stop_gradient(weights)
        pi_loss = -jnp.mean(weights * logp)
        loss = pi_loss + self.vf_coeff * vf_loss * (self.beta != 0.0)
        entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
        return loss, {"pi_loss": pi_loss, "vf_loss": vf_loss,
                      "entropy": entropy,
                      "mean_weight": jnp.mean(weights)}


def episodes_to_batch(batch: Dict[str, np.ndarray],
                      gamma: float) -> Dict[str, np.ndarray]:
    """[T, B] rollout arrays -> flat {obs, action, return} with
    discounted reward-to-go computed per column, resetting at dones."""
    rewards = np.asarray(batch["reward"], np.float32)
    dones = np.asarray(batch["done"], bool)
    T = rewards.shape[0]
    returns = np.zeros_like(rewards)
    acc = np.zeros(rewards.shape[1], np.float32)
    for t in range(T - 1, -1, -1):
        acc = rewards[t] + gamma * acc * (~dones[t])
        returns[t] = acc
    flat = lambda a: np.asarray(a).reshape(-1, *np.asarray(a).shape[2:])  # noqa
    return {"obs": flat(batch["obs"]),
            "action": flat(batch["action"]),
            "return": returns.reshape(-1)}


class MARWILConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.beta = 1.0
        self.vf_coeff = 1.0
        self.lr = 1e-3
        self.num_epochs = 1
        self.minibatch_size = 512
        #: offline experience: list of flat sample dicts
        #: ({obs, action, return}) or [T,B] rollout dicts
        self.offline_data: Optional[Iterable[Dict[str, Any]]] = None

    algo_cls = None

    def offline(self, data: Iterable[Dict[str, Any]]):
        self.offline_data = data
        return self


class MARWIL(Algorithm):
    """Offline when config.offline_data is set; otherwise clones its own
    rollouts (useful as a smoke test / for on-policy distillation)."""

    module_kind = "policy"

    def _setup(self):
        cfg: MARWILConfig = self.config

        def factory():
            module = DiscretePolicyModule(self.env_spec["obs_dim"],
                                          self.env_spec["num_actions"],
                                          cfg.hidden)
            return MarwilLearner(module, beta=cfg.beta,
                                 vf_coeff=cfg.vf_coeff,
                                 lr=cfg.lr, seed=cfg.seed)

        self.learner_group = LearnerGroup(factory, cfg.num_learners)
        self.runners.sync_weights(self.learner_group.get_weights())
        from ray_tpu.rl.offline import resolve_offline_data

        # file paths / OfflineData / Dataset / legacy in-memory iterable
        # (reference: offline_data.py:22 feeds ray.data into the learner)
        self._offline: List[Dict[str, np.ndarray]] = resolve_offline_data(
            cfg.offline_data, gamma=cfg.gamma,
            batch_size=cfg.minibatch_size, want_return=True)
        self._rng = np.random.RandomState(cfg.seed)

    def _offline_minibatches(self):
        cfg: MARWILConfig = self.config
        data = self._offline
        all_idx = [(i, j) for i, d in enumerate(data)
                   for j in range(0, len(d["obs"]), cfg.minibatch_size)]
        self._rng.shuffle(all_idx)
        for i, j in all_idx:
            d = data[i]
            yield {k: v[j:j + cfg.minibatch_size] for k, v in d.items()}

    def training_step(self) -> Dict[str, Any]:
        cfg: MARWILConfig = self.config
        metrics: Dict[str, float] = {}
        if self._offline:
            for _ in range(cfg.num_epochs):
                for mb in self._offline_minibatches():
                    metrics = self.learner_group.update(mb)
            self.runners.sync_weights(self.learner_group.get_weights())
            return metrics
        # no dataset: clone own behavior (BC smoke mode)
        results = self.runners.sample(cfg.rollout_len)
        batch, stats = self._merge_runner_results(results)
        flat = episodes_to_batch(batch, cfg.gamma)
        metrics = self.learner_group.update(flat)
        self.runners.sync_weights(self.learner_group.get_weights())
        metrics.update(stats)
        return metrics


MARWILConfig.algo_cls = MARWIL


class BCConfig(MARWILConfig):
    """Behavior cloning = MARWIL with beta=0 (reference: bc.py)."""

    def __init__(self):
        super().__init__()
        self.beta = 0.0


class BC(MARWIL):
    pass


BCConfig.algo_cls = BC
