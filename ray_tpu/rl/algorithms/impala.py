"""IMPALA: importance-weighted actor-critic with V-trace.

Analog of the reference's IMPALA (reference: rllib/algorithms/impala/
impala.py, torch/vtrace_torch_v2.py): actors sample with a (possibly
stale) behavior policy; the learner corrects off-policyness with V-trace
truncated importance weights.  Jax-first: V-trace is one `lax.scan` over
the reversed time axis inside the jitted update — no per-step host loop.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rl.core.learner import Learner, LearnerGroup
from ray_tpu.rl.core.rl_module import DiscretePolicyModule

from .algorithm import Algorithm, AlgorithmConfig


def vtrace(behavior_logp, target_logp, rewards, dones, values, final_value,
           gamma, clip_rho: float = 1.0, clip_c: float = 1.0):
    """V-trace targets + policy-gradient advantages over [T, B] arrays
    (Espeholt et al. 2018, eq. 1) as a reverse lax.scan."""
    rho = jnp.exp(target_logp - behavior_logp)
    rho_bar = jnp.minimum(rho, clip_rho)
    c_bar = jnp.minimum(rho, clip_c)
    nonterminal = 1.0 - dones.astype(jnp.float32)
    next_values = jnp.concatenate(
        [values[1:], final_value[None]], axis=0)
    deltas = rho_bar * (rewards + gamma * next_values * nonterminal - values)

    def step(acc, xs):
        delta, c, nt = xs
        acc = delta + gamma * c * nt * acc
        return acc, acc

    _, vs_minus_v = jax.lax.scan(
        step, jnp.zeros_like(final_value),
        (deltas, c_bar, nonterminal), reverse=True)
    vs = vs_minus_v + values
    next_vs = jnp.concatenate([vs[1:], final_value[None]], axis=0)
    pg_adv = rho_bar * (rewards + gamma * next_vs * nonterminal - values)
    return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv)


class ImpalaLearner(Learner):
    def __init__(self, module: DiscretePolicyModule, *,
                 gamma: float = 0.99, vf_coeff: float = 0.5,
                 entropy_coeff: float = 0.01, clip_rho: float = 1.0,
                 clip_c: float = 1.0, **kwargs):
        self.gamma = gamma
        self.vf_coeff = vf_coeff
        self.entropy_coeff = entropy_coeff
        self.clip_rho = clip_rho
        self.clip_c = clip_c
        super().__init__(module, **kwargs)

    def compute_loss(self, params, batch, rng):
        # batch arrives [B, T] (batch-major so LearnerGroup's axis-0
        # sharding splits episodes, not time); V-trace wants time-major
        batch = dict(batch)
        for k in ("obs", "action", "reward", "done", "logp"):
            batch[k] = jnp.swapaxes(batch[k], 0, 1)
        logits = self.module.logits(params, batch["obs"])
        logp_all = jax.nn.log_softmax(logits)
        target_logp = jnp.take_along_axis(
            logp_all, batch["action"][..., None].astype(jnp.int32),
            axis=-1)[..., 0]
        values = self.module.value(params, batch["obs"])
        vs, pg_adv = vtrace(batch["logp"], target_logp, batch["reward"],
                            batch["done"], values, batch["final_vf"],
                            self.gamma, self.clip_rho, self.clip_c)
        pi_loss = -jnp.mean(pg_adv * target_logp)
        vf_loss = 0.5 * jnp.mean((values - vs) ** 2)
        entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
        loss = pi_loss + self.vf_coeff * vf_loss \
            - self.entropy_coeff * entropy
        return loss, {"pi_loss": pi_loss, "vf_loss": vf_loss,
                      "entropy": entropy,
                      "mean_rho": jnp.mean(
                          jnp.exp(target_logp - batch["logp"]))}


def make_impala_learner(env_spec: Dict[str, Any],
                        hidden=(64, 64), **hyperparams) -> ImpalaLearner:
    """A standalone ImpalaLearner over the default discrete policy
    module — the piece Podracer shares with the Impala Algorithm without
    dragging in runner groups (see rl/podracer.py).  ``hyperparams``
    pass through to ImpalaLearner/Learner (gamma, vf_coeff,
    entropy_coeff, clip_rho, clip_c, lr, grad_clip, seed, ...)."""
    module = DiscretePolicyModule(env_spec["obs_dim"],
                                  env_spec["num_actions"], hidden)
    return ImpalaLearner(module, **hyperparams)


class ImpalaConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 5e-4
        self.vf_coeff = 0.5
        self.entropy_coeff = 0.01
        self.clip_rho = 1.0
        self.clip_c = 1.0

    algo_cls = None  # set below


class Impala(Algorithm):
    module_kind = "policy"

    def _setup(self):
        cfg: ImpalaConfig = self.config

        def factory():
            module = DiscretePolicyModule(self.env_spec["obs_dim"],
                                          self.env_spec["num_actions"],
                                          cfg.hidden)
            return ImpalaLearner(module, gamma=cfg.gamma,
                                 vf_coeff=cfg.vf_coeff,
                                 entropy_coeff=cfg.entropy_coeff,
                                 clip_rho=cfg.clip_rho, clip_c=cfg.clip_c,
                                 lr=cfg.lr, seed=cfg.seed)

        self.learner_group = LearnerGroup(factory, cfg.num_learners)
        self.runners.sync_weights(self.learner_group.get_weights())

    def training_step(self) -> Dict[str, Any]:
        cfg: ImpalaConfig = self.config
        results = self.runners.sample(cfg.rollout_len)
        batch, stats = self._merge_runner_results(results)
        # [T, B] -> [B, T] so every key (incl. final_vf [B]) shards along
        # episodes under LearnerGroup's axis-0 split
        update_batch = {
            k: np.swapaxes(np.asarray(batch[k]), 0, 1)
            for k in ("obs", "action", "reward", "done", "logp")
        }
        update_batch["final_vf"] = np.asarray(batch["final_vf"])
        metrics = self.learner_group.update(update_batch)
        self.runners.sync_weights(self.learner_group.get_weights())
        metrics.update(stats)
        return metrics


ImpalaConfig.algo_cls = Impala
IMPALA = Impala
IMPALAConfig = ImpalaConfig
