"""PPO: clipped-surrogate policy optimization with GAE.

Analog of the reference's PPO (reference: rllib/algorithms/ppo/ppo.py,
ppo_learner.py, torch/ppo_torch_learner.py) jax-first: GAE runs as a
`lax.scan` over the time axis and the whole minibatch epoch loop executes
as jitted updates on device.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rl.core.learner import Learner, LearnerGroup
from ray_tpu.rl.core.rl_module import DiscretePolicyModule

from .algorithm import Algorithm, AlgorithmConfig


def compute_gae(rewards, dones, values, final_value, gamma, lam):
    """[T, B] arrays -> (advantages, value targets) via reverse scan
    (reference: rllib general_advantage_estimation connector)."""
    def step(carry, xs):
        next_adv, next_value = carry
        reward, done, value = xs
        nonterminal = 1.0 - done
        delta = reward + gamma * next_value * nonterminal - value
        adv = delta + gamma * lam * nonterminal * next_adv
        return (adv, value), adv

    (_, _), advs = jax.lax.scan(
        step, (jnp.zeros_like(final_value), final_value),
        (rewards, dones.astype(jnp.float32), values), reverse=True)
    return advs, advs + values


class PPOLearner(Learner):
    def __init__(self, module: DiscretePolicyModule, *,
                 clip_param: float = 0.2, vf_coeff: float = 0.5,
                 entropy_coeff: float = 0.0, **kwargs):
        self.clip_param = clip_param
        self.vf_coeff = vf_coeff
        self.entropy_coeff = entropy_coeff
        super().__init__(module, **kwargs)

    def compute_loss(self, params, batch, rng):
        logits = self.module.logits(params, batch["obs"])
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, batch["action"][..., None].astype(jnp.int32),
            axis=-1)[..., 0]
        ratio = jnp.exp(logp - batch["logp_old"])
        adv = batch["advantage"]
        surrogate = jnp.minimum(
            ratio * adv,
            jnp.clip(ratio, 1 - self.clip_param, 1 + self.clip_param) * adv)
        pi_loss = -jnp.mean(surrogate)
        value = self.module.value(params, batch["obs"])
        vf_loss = jnp.mean((value - batch["value_target"]) ** 2)
        entropy = -jnp.mean(
            jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
        loss = pi_loss + self.vf_coeff * vf_loss \
            - self.entropy_coeff * entropy
        return loss, {"pi_loss": pi_loss, "vf_loss": vf_loss,
                      "entropy": entropy,
                      "clip_frac": jnp.mean(
                          (jnp.abs(ratio - 1) > self.clip_param)
                          .astype(jnp.float32))}


def ppo_update_on_batch(learner_group, batch, cfg, rng) -> Dict[str, float]:
    """GAE -> advantage normalization -> minibatched epoch loop: the PPO
    update procedure shared by single- and multi-agent PPO (one owner —
    a fix here reaches both paths)."""
    adv, vtarg = compute_gae(
        jnp.asarray(batch["reward"]), jnp.asarray(batch["done"]),
        jnp.asarray(batch["vf"]), jnp.asarray(batch["final_vf"]),
        cfg.gamma, cfg.gae_lambda)
    adv = np.asarray(adv).reshape(-1)
    adv = (adv - adv.mean()) / (adv.std() + 1e-8)
    flat = {
        "obs": np.asarray(batch["obs"]).reshape(-1, batch["obs"].shape[-1]),
        "action": np.asarray(batch["action"]).reshape(-1),
        "logp_old": np.asarray(batch["logp"]).reshape(-1),
        "advantage": adv,
        "value_target": np.asarray(vtarg).reshape(-1),
    }
    n = flat["obs"].shape[0]
    metrics: Dict[str, float] = {}
    for _ in range(cfg.num_epochs):
        perm = rng.permutation(n)
        for lo in range(0, n, cfg.minibatch_size):
            idx = perm[lo:lo + cfg.minibatch_size]
            metrics = learner_group.update(
                {k: v[idx] for k, v in flat.items()})
    return metrics


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.clip_param = 0.2
        self.vf_coeff = 0.5
        self.entropy_coeff = 0.0
        self.gae_lambda = 0.95
        self.num_epochs = 4
        self.minibatch_size = 256
        self.lr = 3e-4


class PPO(Algorithm):
    module_kind = "policy"

    def _setup(self):
        cfg: PPOConfig = self.config

        def factory():
            module = DiscretePolicyModule(self.env_spec["obs_dim"],
                                          self.env_spec["num_actions"],
                                          cfg.hidden)
            return PPOLearner(module, clip_param=cfg.clip_param,
                              vf_coeff=cfg.vf_coeff,
                              entropy_coeff=cfg.entropy_coeff,
                              lr=cfg.lr, seed=cfg.seed)

        self.learner_group = LearnerGroup(factory, cfg.num_learners)
        self.runners.sync_weights(self.learner_group.get_weights())

    def training_step(self) -> Dict[str, Any]:
        cfg: PPOConfig = self.config
        results = self.runners.sample(cfg.rollout_len)
        batch, stats = self._merge_runner_results(results)
        rng = np.random.default_rng(cfg.seed + self.iteration)
        metrics = ppo_update_on_batch(self.learner_group, batch, cfg, rng)
        self.runners.sync_weights(self.learner_group.get_weights())
        return {**stats, **metrics}


PPOConfig.algo_cls = PPO
