"""DQN: double Q-learning with target network + replay.

Analog of the reference's DQN (reference: rllib/algorithms/dqn/dqn.py,
torch/dqn_torch_learner.py): epsilon-greedy sampling into a replay
buffer; double-DQN targets; periodic target-net hard sync.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rl.core.learner import Learner, LearnerGroup
from ray_tpu.rl.core.rl_module import QModule
from ray_tpu.rl.utils.replay_buffer import ReplayBuffer

from .algorithm import Algorithm, AlgorithmConfig


class DQNLearner(Learner):
    def __init__(self, module: QModule, *, gamma: float = 0.99,
                 target_update_freq: int = 100, **kwargs):
        self.gamma = gamma
        self.target_update_freq = target_update_freq
        self._updates = 0
        super().__init__(module, **kwargs)

    # optimizer trains only the online net; the target net syncs by copy
    def _trainable(self, params):
        return params["q"]

    def _merge(self, params, trained):
        return {"q": trained, "target_q": params["target_q"]}

    def compute_loss(self, params, batch, rng):
        q = self.module.q_values(params, batch["obs"])
        q_a = jnp.take_along_axis(
            q, batch["action"][..., None].astype(jnp.int32), axis=-1)[..., 0]
        # double DQN: online net picks, target net evaluates
        next_q_online = self.module.q_values(params, batch["next_obs"])
        next_a = jnp.argmax(next_q_online, axis=-1)
        next_q_target = self.module.q_values(params, batch["next_obs"],
                                             target=True)
        next_q = jnp.take_along_axis(next_q_target, next_a[..., None],
                                     axis=-1)[..., 0]
        target = batch["reward"] + self.gamma * next_q \
            * (1.0 - batch["done"].astype(jnp.float32))
        target = jax.lax.stop_gradient(target)
        loss = jnp.mean(optax_huber(q_a - target))
        return loss, {"q_mean": jnp.mean(q_a), "target_mean":
                      jnp.mean(target)}

    def extra_update(self, params, metrics):
        self._updates += 1
        if self._updates % self.target_update_freq == 0:
            params = {"q": params["q"],
                      "target_q": jax.tree_util.tree_map(
                          jnp.copy, params["q"])}
        return params


def optax_huber(x, delta: float = 1.0):
    abs_x = jnp.abs(x)
    return jnp.where(abs_x <= delta, 0.5 * x ** 2,
                     delta * (abs_x - 0.5 * delta))


class DQNConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 1e-3
        self.buffer_capacity = 50_000
        self.learn_starts = 1000
        self.target_update_freq = 200
        self.epsilon = 1.0
        self.epsilon_final = 0.05
        self.epsilon_decay_iters = 30
        self.updates_per_iter = 64
        self.train_batch_size = 64
        self.rollout_len = 64


class DQN(Algorithm):
    module_kind = "q"

    def _explore_kwargs(self):
        return {"epsilon": float(self.config.epsilon)}

    def _setup(self):
        cfg: DQNConfig = self.config

        def factory():
            module = QModule(self.env_spec["obs_dim"],
                             self.env_spec["num_actions"], cfg.hidden)
            return DQNLearner(module, gamma=cfg.gamma,
                              target_update_freq=cfg.target_update_freq,
                              lr=cfg.lr, grad_clip=10.0, seed=cfg.seed)

        self.learner_group = LearnerGroup(factory, cfg.num_learners)
        self.buffer = ReplayBuffer(cfg.buffer_capacity, seed=cfg.seed)
        self.runners.sync_weights(self.learner_group.get_weights())

    def _epsilon(self) -> float:
        cfg: DQNConfig = self.config
        frac = min(1.0, self.iteration / max(1, cfg.epsilon_decay_iters))
        return float(cfg.epsilon + frac * (cfg.epsilon_final - cfg.epsilon))

    def training_step(self) -> Dict[str, Any]:
        cfg: DQNConfig = self.config
        self.runners.set_explore(epsilon=self._epsilon())
        results = self.runners.sample(cfg.rollout_len)
        batch, stats = self._merge_runner_results(results)

        # [T, B] -> flat transitions with next_obs via time shift;
        # the final step of each rollout bootstraps next iteration
        obs = np.asarray(batch["obs"])          # [T, B, D]
        next_obs = np.roll(obs, -1, axis=0)
        valid = np.ones(obs.shape[:2], bool)
        valid[-1] = False                        # unknown next_obs
        # done steps auto-reset: next_obs is the new episode start, so the
        # (1 - done) mask in the loss already ignores it — keep them.
        flat_idx = valid.reshape(-1)
        flatten = lambda a: a.reshape(-1, *a.shape[2:])[flat_idx]  # noqa
        self.buffer.add_batch({
            "obs": flatten(obs),
            "next_obs": flatten(next_obs),
            "action": flatten(np.asarray(batch["action"])),
            "reward": flatten(np.asarray(batch["reward"])),
            "done": flatten(np.asarray(batch["done"])),
        })

        metrics: Dict[str, float] = {}
        if len(self.buffer) >= cfg.learn_starts:
            for _ in range(cfg.updates_per_iter):
                metrics = self.learner_group.update(
                    self.buffer.sample(cfg.train_batch_size))
            self.runners.sync_weights(self.learner_group.get_weights())
        metrics["epsilon"] = self._epsilon()
        metrics["buffer_size"] = len(self.buffer)
        return {**stats, **metrics}


DQNConfig.algo_cls = DQN
