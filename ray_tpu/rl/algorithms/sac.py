"""SAC: soft actor-critic with twin Q-nets and learned temperature,
for BOTH action-space families.

Analog of the reference's SAC (reference: rllib/algorithms/sac/sac.py,
torch/sac_torch_learner.py):

  * continuous (Box) — the canonical SAC: SquashedGaussian policy with
    reparameterized sampling through twin Q(s, a) critics (reference:
    sac.py:320-322 requires SquashedGaussian for bounded continuous
    spaces); demonstrated learning on Pendulum in the suite.
  * discrete — the Christodoulou 2019 variant: soft value and policy
    objectives as exact expectations over the action set — everything
    stays a dense matmul over [batch, actions], the TPU-friendly shape.

The algorithm picks the module/learner pair from the env spec
(action_dim => continuous, num_actions => discrete).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rl.core.learner import Learner, LearnerGroup
from ray_tpu.rl.core.rl_module import (MODULE_REGISTRY, RLModule, _mlp_apply,
                                       _mlp_init, module_for_env)
from ray_tpu.rl.utils.replay_buffer import ReplayBuffer

from .algorithm import Algorithm, AlgorithmConfig


class SACModule(RLModule):
    """Policy + twin soft-Q nets (+ polyak targets + log temperature)."""

    def init(self, rng):
        pi_rng, q1_rng, q2_rng = jax.random.split(rng, 3)
        sizes = (self.obs_dim, *self.hidden)
        q1 = _mlp_init(q1_rng, (*sizes, self.num_actions), out_scale=0.01)
        q2 = _mlp_init(q2_rng, (*sizes, self.num_actions), out_scale=0.01)
        return {
            "pi": _mlp_init(pi_rng, (*sizes, self.num_actions)),
            "q1": q1,
            "q2": q2,
            "target_q1": jax.tree_util.tree_map(jnp.copy, q1),
            "target_q2": jax.tree_util.tree_map(jnp.copy, q2),
            "log_alpha": jnp.zeros(()),
        }

    def logits(self, params, obs):
        return _mlp_apply(params["pi"], obs)

    def q_values(self, params, obs, which: str):
        return _mlp_apply(params[which], obs)

    def forward_exploration(self, params, obs, rng):
        action = jax.random.categorical(rng, self.logits(params, obs))
        return action, {}

    def forward_inference(self, params, obs):
        return jnp.argmax(self.logits(params, obs), axis=-1)


MODULE_REGISTRY["sac"] = SACModule


class SACContinuousModule(RLModule):
    """Squashed-Gaussian policy + twin Q(s,a) critics for Box action
    spaces (reference: rllib/algorithms/sac/sac.py:320-322 — continuous
    spaces get a SquashedGaussian distribution; torch/sac_torch_learner
    uses the reparameterized sample).  Actions are tanh-squashed and
    affine-mapped to [low, high]; log-probs carry the tanh + scale
    Jacobian corrections."""

    LOG_STD_MIN = -20.0
    LOG_STD_MAX = 2.0

    def __init__(self, obs_dim: int, action_dim: int, hidden=(64, 64), *,
                 low=-1.0, high=1.0):
        super().__init__(obs_dim, action_dim, hidden)
        self.action_dim = action_dim
        # scalar or per-dimension bounds (heterogeneous Boxes like
        # [steer, gas, brake] scale each dim independently)
        self.low = jnp.broadcast_to(jnp.asarray(low, jnp.float32),
                                    (action_dim,))
        self.high = jnp.broadcast_to(jnp.asarray(high, jnp.float32),
                                     (action_dim,))
        if not (bool(jnp.all(jnp.isfinite(self.low)))
                and bool(jnp.all(jnp.isfinite(self.high)))):
            raise ValueError("squashed-Gaussian SAC needs finite action "
                             f"bounds; got low={low} high={high}")
        self.scale = (self.high - self.low) / 2.0
        self.center = (self.high + self.low) / 2.0

    def init(self, rng):
        pi_rng, q1_rng, q2_rng = jax.random.split(rng, 3)
        pi_sizes = (self.obs_dim, *self.hidden, 2 * self.action_dim)
        q_sizes = (self.obs_dim + self.action_dim, *self.hidden, 1)
        q1 = _mlp_init(q1_rng, q_sizes, out_scale=0.01)
        q2 = _mlp_init(q2_rng, q_sizes, out_scale=0.01)
        return {
            "pi": _mlp_init(pi_rng, pi_sizes),
            "q1": q1,
            "q2": q2,
            "target_q1": jax.tree_util.tree_map(jnp.copy, q1),
            "target_q2": jax.tree_util.tree_map(jnp.copy, q2),
            "log_alpha": jnp.zeros(()),
        }

    def pi_dist(self, params, obs):
        out = _mlp_apply(params["pi"], obs)
        mu, log_std = jnp.split(out, 2, axis=-1)
        log_std = jnp.clip(log_std, self.LOG_STD_MIN, self.LOG_STD_MAX)
        return mu, log_std

    def sample_and_logp(self, params, obs, rng):
        """Reparameterized squashed sample -> (env-scaled action [B, A],
        log-prob [B])."""
        mu, log_std = self.pi_dist(params, obs)
        std = jnp.exp(log_std)
        u = mu + std * jax.random.normal(rng, mu.shape)
        a = jnp.tanh(u)
        logp = jnp.sum(
            -0.5 * (((u - mu) / std) ** 2 + 2 * log_std
                    + jnp.log(2 * jnp.pi))
            - jnp.log(1.0 - a ** 2 + 1e-6)
            - jnp.log(self.scale), axis=-1)
        return a * self.scale + self.center, logp

    def q_values(self, params, obs, action, which: str):
        """Q(s, a) with the action normalized back to [-1, 1] (the net
        should not have to learn the env's scale)."""
        a_n = (action - self.center) / self.scale
        return _mlp_apply(params[which],
                          jnp.concatenate([obs, a_n], axis=-1))[..., 0]

    def forward_exploration(self, params, obs, rng):
        action, _ = self.sample_and_logp(params, obs, rng)
        return action, {}

    def forward_inference(self, params, obs):
        mu, _ = self.pi_dist(params, obs)
        return jnp.tanh(mu) * self.scale + self.center


MODULE_REGISTRY["sac_continuous"] = SACContinuousModule


class SACLearner(Learner):
    def __init__(self, module: SACModule, *, gamma: float = 0.99,
                 tau: float = 0.005, target_entropy: float = None,
                 **kwargs):
        self.gamma = gamma
        self.tau = tau
        # default target entropy: 0.98 * max entropy (discrete-SAC paper)
        self.target_entropy = (target_entropy if target_entropy is not None
                               else 0.98 * float(np.log(module.num_actions)))
        super().__init__(module, **kwargs)

    def _trainable(self, params):
        return {"pi": params["pi"], "q1": params["q1"], "q2": params["q2"],
                "log_alpha": params["log_alpha"]}

    def _merge(self, params, trained):
        return {**trained, "target_q1": params["target_q1"],
                "target_q2": params["target_q2"]}

    def compute_loss(self, params, batch, rng):
        m: SACModule = self.module
        alpha = jax.lax.stop_gradient(jnp.exp(params["log_alpha"]))
        logits = m.logits(params, batch["obs"])
        logp = jax.nn.log_softmax(logits)
        probs = jnp.exp(logp)

        # soft target value from the *next* state, exact over actions
        next_logp = jax.nn.log_softmax(m.logits(params, batch["next_obs"]))
        next_probs = jnp.exp(next_logp)
        next_q = jnp.minimum(
            m.q_values(params, batch["next_obs"], "target_q1"),
            m.q_values(params, batch["next_obs"], "target_q2"))
        next_v = jnp.sum(next_probs * (next_q - alpha * next_logp), axis=-1)
        target = batch["reward"] + self.gamma * next_v \
            * (1.0 - batch["done"].astype(jnp.float32))
        target = jax.lax.stop_gradient(target)

        a_idx = batch["action"][..., None].astype(jnp.int32)
        q1_a = jnp.take_along_axis(
            m.q_values(params, batch["obs"], "q1"), a_idx, axis=-1)[..., 0]
        q2_a = jnp.take_along_axis(
            m.q_values(params, batch["obs"], "q2"), a_idx, axis=-1)[..., 0]
        q_loss = 0.5 * (jnp.mean((q1_a - target) ** 2)
                        + jnp.mean((q2_a - target) ** 2))

        # policy: minimize E_s[ sum_a pi(a|s) (alpha log pi - min Q) ]
        q_min = jax.lax.stop_gradient(jnp.minimum(
            m.q_values(params, batch["obs"], "q1"),
            m.q_values(params, batch["obs"], "q2")))
        pi_loss = jnp.mean(jnp.sum(probs * (alpha * logp - q_min), axis=-1))

        # temperature: drive policy entropy toward target_entropy
        entropy = -jnp.sum(probs * logp, axis=-1)
        alpha_loss = jnp.mean(params["log_alpha"] * jax.lax.stop_gradient(
            entropy - self.target_entropy))

        loss = q_loss + pi_loss + alpha_loss
        return loss, {"q_loss": q_loss, "pi_loss": pi_loss,
                      "alpha": jnp.exp(params["log_alpha"]),
                      "entropy": jnp.mean(entropy)}

    def extra_update(self, params, metrics):
        # polyak target sync inside host callback (cheap tree op)
        tau = self.tau
        mix = lambda t, o: jax.tree_util.tree_map(  # noqa: E731
            lambda a, b: (1 - tau) * a + tau * b, t, o)
        params["target_q1"] = mix(params["target_q1"], params["q1"])
        params["target_q2"] = mix(params["target_q2"], params["q2"])
        return params


class SACContinuousLearner(Learner):
    """Continuous-action SAC losses: reparameterized policy gradient
    through min-Q, twin-critic TD targets with entropy bonus, learned
    temperature toward target entropy -|A| (the SAC paper default)."""

    def __init__(self, module: SACContinuousModule, *, gamma: float = 0.99,
                 tau: float = 0.005, target_entropy: float = None,
                 **kwargs):
        self.gamma = gamma
        self.tau = tau
        self.target_entropy = (target_entropy if target_entropy is not None
                               else -float(module.action_dim))
        super().__init__(module, **kwargs)

    _trainable = SACLearner._trainable
    _merge = SACLearner._merge
    extra_update = SACLearner.extra_update

    def compute_loss(self, params, batch, rng):
        m: SACContinuousModule = self.module
        next_rng, pi_rng = jax.random.split(rng)
        alpha = jax.lax.stop_gradient(jnp.exp(params["log_alpha"]))

        # critic target: r + gamma (min target-Q(s', a') - alpha logp')
        next_a, next_logp = m.sample_and_logp(params, batch["next_obs"],
                                              next_rng)
        next_q = jnp.minimum(
            m.q_values(params, batch["next_obs"], next_a, "target_q1"),
            m.q_values(params, batch["next_obs"], next_a, "target_q2"))
        target = batch["reward"] + self.gamma \
            * (next_q - alpha * next_logp) \
            * (1.0 - batch["done"].astype(jnp.float32))
        target = jax.lax.stop_gradient(target)

        action = batch["action"]
        if action.ndim == 1:
            action = action[..., None]
        q1 = m.q_values(params, batch["obs"], action, "q1")
        q2 = m.q_values(params, batch["obs"], action, "q2")
        q_loss = 0.5 * (jnp.mean((q1 - target) ** 2)
                        + jnp.mean((q2 - target) ** 2))

        # actor: reparameterized sample through min-Q (critics frozen)
        pi_a, logp = m.sample_and_logp(params, batch["obs"], pi_rng)
        q_min = jnp.minimum(
            m.q_values(jax.lax.stop_gradient(params), batch["obs"],
                       pi_a, "q1"),
            m.q_values(jax.lax.stop_gradient(params), batch["obs"],
                       pi_a, "q2"))
        pi_loss = jnp.mean(alpha * logp - q_min)

        # temperature: entropy (-logp) toward target_entropy
        alpha_loss = jnp.mean(
            params["log_alpha"]
            * jax.lax.stop_gradient(-logp - self.target_entropy))

        loss = q_loss + pi_loss + alpha_loss
        return loss, {"q_loss": q_loss, "pi_loss": pi_loss,
                      "alpha": jnp.exp(params["log_alpha"]),
                      "entropy": -jnp.mean(logp)}


class SACConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 3e-4
        self.tau = 0.005
        self.buffer_capacity = 50_000
        self.learn_starts = 500
        self.updates_per_iter = 32
        self.train_batch_size = 128
        self.rollout_len = 64
        self.target_entropy = None

    algo_cls = None


class SAC(Algorithm):
    module_kind = "sac"

    def _setup(self):
        cfg: SACConfig = self.config

        def factory():
            # module_for_env owns the continuous-vs-discrete dispatch
            # (and the action-bound defaults) — the learner follows the
            # module type, so runner and learner can't desynchronize
            module = module_for_env(self.env_spec, "sac",
                                    hidden=cfg.hidden)
            learner_cls = (SACContinuousLearner
                           if isinstance(module, SACContinuousModule)
                           else SACLearner)
            return learner_cls(module, gamma=cfg.gamma, tau=cfg.tau,
                               target_entropy=cfg.target_entropy,
                               lr=cfg.lr, seed=cfg.seed)

        self.learner_group = LearnerGroup(factory, cfg.num_learners)
        self.buffer = ReplayBuffer(cfg.buffer_capacity, seed=cfg.seed)
        self.runners.sync_weights(self.learner_group.get_weights())

    def training_step(self) -> Dict[str, Any]:
        cfg: SACConfig = self.config
        results = self.runners.sample(cfg.rollout_len)
        batch, stats = self._merge_runner_results(results)

        obs = np.asarray(batch["obs"])          # [T, B, D]
        next_obs = np.roll(obs, -1, axis=0)
        done = np.asarray(batch["done"], bool)
        # terminated vs truncated: a TRUNCATION boundary must neither
        # cut the TD target (the state isn't terminal) nor bootstrap
        # through the auto-reset (next_obs is the NEXT episode's reset
        # state) — dropping those transitions is the unbiased option.
        # Runners that report `terminated` give the split directly
        # (gym); time_limit_only jax envs are all-truncation.
        if "terminated" in batch:
            terminated = np.asarray(batch["terminated"], bool)
        elif self.env_spec.get("time_limit_only"):
            terminated = np.zeros_like(done)
        else:
            terminated = done
        valid = np.ones(obs.shape[:2], bool)
        valid[-1] = False
        valid &= ~(done & ~terminated)
        flat_idx = valid.reshape(-1)
        flatten = lambda a: a.reshape(-1, *a.shape[2:])[flat_idx]  # noqa
        self.buffer.add_batch({
            "obs": flatten(obs),
            "next_obs": flatten(next_obs),
            "action": flatten(np.asarray(batch["action"])),
            "reward": flatten(np.asarray(batch["reward"])),
            "done": flatten(terminated),
        })

        metrics: Dict[str, float] = {}
        if len(self.buffer) >= cfg.learn_starts:
            for _ in range(cfg.updates_per_iter):
                metrics = self.learner_group.update(
                    self.buffer.sample(cfg.train_batch_size))
            self.runners.sync_weights(self.learner_group.get_weights())
        metrics.update(stats)
        return metrics


SACConfig.algo_cls = SAC
