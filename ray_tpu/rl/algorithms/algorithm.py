"""Algorithm: the top-level RL training loop object.

Analog of the reference's Algorithm (reference:
rllib/algorithms/algorithm.py — a Tune Trainable driving an
EnvRunnerGroup for sampling and a LearnerGroup for updates).  Here:

    config = PPOConfig().environment("CartPole-v1").env_runners(2)
    algo = config.build()
    for _ in range(n):
        result = algo.train()      # sample -> update -> sync weights

Tune integration mirrors the reference (Algorithm IS the trainable):
``config.to_trainable()`` returns a function trainable that reports each
train() result, so Tuner(PPOConfig()...to_trainable(), ...) works with
schedulers/searchers unchanged.
"""

from __future__ import annotations

import copy
import pickle
import time
from typing import Any, Callable, Dict, Optional

from ray_tpu.rl.env.env_runner import EnvRunnerGroup


class AlgorithmConfig:
    """Fluent config (reference: algorithm_config.py)."""

    def __init__(self):
        self.env_name: Optional[str] = None
        self.num_env_runners = 0          # 0 = local sampling
        self.num_envs_per_runner = 8
        self.runner_kind = "jax"          # "jax" | "gym"
        self.num_learners = 0             # 0 = local learner
        self.rollout_len = 128            # steps per env per iteration
        #: zero-arg factories for connector pipelines (reference:
        #: AlgorithmConfig.env_runners(env_to_module_connector=...))
        self.env_to_module_connector = None
        self.module_to_env_connector = None
        self.lr = 3e-4
        self.gamma = 0.99
        self.seed = 0
        self.hidden = (64, 64)
        self.train_batch_size = 1024
        self.extra: Dict[str, Any] = {}

    # -- fluent setters (reference naming) ---------------------------------

    def environment(self, env: str):
        self.env_name = env
        return self

    def env_runners(self, num_env_runners: int = 0, *,
                    num_envs_per_runner: int = 8,
                    runner_kind: str = "jax",
                    env_to_module_connector=None,
                    module_to_env_connector=None):
        self.num_env_runners = num_env_runners
        self.num_envs_per_runner = num_envs_per_runner
        self.runner_kind = runner_kind
        if env_to_module_connector is not None:
            self.env_to_module_connector = env_to_module_connector
        if module_to_env_connector is not None:
            self.module_to_env_connector = module_to_env_connector
        return self

    def learners(self, num_learners: int = 0):
        self.num_learners = num_learners
        return self

    def training(self, **kwargs):
        for k, v in kwargs.items():
            if hasattr(self, k):
                setattr(self, k, v)
            else:
                self.extra[k] = v
        return self

    def debugging(self, seed: int = 0):
        self.seed = seed
        return self

    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    # -- build -------------------------------------------------------------

    algo_cls: Optional[type] = None

    def build(self) -> "Algorithm":
        if self.env_name is None:
            raise ValueError("config.environment(...) not set")
        return self.algo_cls(self)

    def to_trainable(self) -> Callable:
        """Function trainable for Tune: config dict entries override
        attributes (so Tune param_space can sweep lr etc.)."""
        base = self.copy()

        def rl_trainable(tune_config: Dict[str, Any]):
            from ray_tpu.train.session import report

            cfg = base.copy()
            stop_iters = int(tune_config.pop("training_iterations", 10))
            cfg.training(**tune_config)
            algo = cfg.build()
            try:
                for _ in range(stop_iters):
                    report(algo.train())
            finally:
                algo.stop()

        return rl_trainable


def merge_batches(batches) -> Dict[str, Any]:
    """Concat same-keyed [T, B, ...] batches along B ([B...] tails like
    final_vf along axis 0) — the one batch-merge rule for runner results
    in single- and multi-agent algorithms."""
    import numpy as np

    merged = {}
    for k in batches[0]:
        arrs = [b[k] for b in batches]
        axis = 1 if arrs[0].ndim >= 2 else 0
        merged[k] = np.concatenate(arrs, axis=axis) if len(arrs) > 1 \
            else arrs[0]
    return merged


class Algorithm:
    """Base training loop; subclasses implement training_step()."""

    #: module kind for the runner group ("policy" | "q")
    module_kind = "policy"

    def __init__(self, config: AlgorithmConfig):
        self.config = config
        self.iteration = 0
        # connector factories (reference: env_to_module_connector config
        # arg): built once here, pickled per remote runner — so stateful
        # connectors (NormalizeObs) end up with independent state per
        # runner actor
        e2m = config.env_to_module_connector
        m2e = config.module_to_env_connector
        self.runners = EnvRunnerGroup(
            env_name=config.env_name,
            module_spec={"kind": self.module_kind, "hidden": config.hidden,
                         "kwargs": self._module_kwargs()},
            num_runners=config.num_env_runners,
            num_envs_per_runner=config.num_envs_per_runner,
            runner_kind=config.runner_kind,
            seed=config.seed,
            explore_kwargs=self._explore_kwargs(),
            env_to_module=e2m() if e2m else None,
            module_to_env=m2e() if m2e else None,
        )
        self.env_spec = self.runners.env_spec()
        self._setup()
        self._last_stats: Dict[str, Any] = {}

    # -- overridables ------------------------------------------------------

    def _explore_kwargs(self) -> Dict[str, Any]:
        return {}

    def _module_kwargs(self) -> Dict[str, Any]:
        """Extra ctor kwargs for the runner-side module — must match the
        learner's module so synced weights apply (e.g. Dreamer latent
        sizes)."""
        return {}

    def _setup(self):
        raise NotImplementedError

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    # -- public API (reference: Algorithm.train/save/restore/stop) ---------

    def train(self) -> Dict[str, Any]:
        t0 = time.monotonic()
        metrics = self.training_step()
        self.iteration += 1
        metrics["training_iteration"] = self.iteration
        metrics["time_this_iter_s"] = time.monotonic() - t0
        return metrics

    def save(self, path: str):
        with open(path, "wb") as f:
            pickle.dump({"iteration": self.iteration,
                         "learner_state": self.learner_group.state()}, f)

    def restore(self, path: str):
        with open(path, "rb") as f:
            state = pickle.load(f)
        self.iteration = state["iteration"]
        self.learner_group.load_state(state["learner_state"])
        self.runners.sync_weights(self.learner_group.get_weights())

    def stop(self):
        self.runners.stop()
        if hasattr(self, "learner_group"):
            self.learner_group.stop()

    # -- helpers -----------------------------------------------------------

    def _merge_runner_results(self, results) -> Dict[str, Any]:
        """Concat [T,B] batches along B; merge episode stats."""
        import numpy as np

        merged = merge_batches([r["batch"] for r in results])
        stats: Dict[str, Any] = {}
        rets = [r["stats"].get("episode_return_mean") for r in results
                if r["stats"].get("episodes_this_iter", 0) > 0]
        stats["episodes_this_iter"] = sum(
            r["stats"].get("episodes_this_iter", 0) for r in results)
        if rets:
            stats["episode_return_mean"] = float(np.mean(rets))
        stats["env_steps_sampled"] = sum(
            r["stats"].get("env_steps_sampled", 0) for r in results)
        return merged, stats
