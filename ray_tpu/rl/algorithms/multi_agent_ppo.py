"""Multi-agent PPO: one PPO learner per policy over shared experience.

Analog of the reference's multi-agent stack (reference:
rllib/env/multi_agent_env.py:32 + rllib/core/rl_module/multi_rl_module.py
+ ppo trained per policy via policies/policy_mapping_fn in
AlgorithmConfig.multi_agent()): agents map onto policies, each policy
trains a separate clipped-surrogate PPO loss on exactly the transitions
its agents generated.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.rl.core.learner import LearnerGroup
from ray_tpu.rl.core.rl_module import DiscretePolicyModule
from ray_tpu.rl.env.multi_agent_env import MultiAgentEnvRunnerGroup

from .algorithm import Algorithm, AlgorithmConfig, merge_batches
from .ppo import PPOLearner, ppo_update_on_batch


class MultiAgentPPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.clip_param = 0.2
        self.vf_coeff = 0.5
        self.entropy_coeff = 0.0
        self.gae_lambda = 0.95
        self.num_epochs = 4
        self.minibatch_size = 256
        self.lr = 3e-4
        self.policies: List[str] = []
        self.policy_mapping_fn: Optional[Callable[[str], str]] = None

    def multi_agent(self, *, policies: List[str],
                    policy_mapping_fn: Callable[[str], str]
                    ) -> "MultiAgentPPOConfig":
        """(reference: AlgorithmConfig.multi_agent)"""
        self.policies = list(policies)
        self.policy_mapping_fn = policy_mapping_fn
        return self


class MultiAgentPPO(Algorithm):
    def __init__(self, config: MultiAgentPPOConfig):
        if not config.policies or config.policy_mapping_fn is None:
            raise ValueError(
                "MultiAgentPPO needs config.multi_agent(policies=..., "
                "policy_mapping_fn=...)")
        self.config = config
        self.iteration = 0
        self.runners = MultiAgentEnvRunnerGroup(
            env_name=config.env_name,
            policies=config.policies,
            policy_mapping_fn=config.policy_mapping_fn,
            module_spec={"hidden": config.hidden},
            num_runners=config.num_env_runners,
            num_envs_per_runner=config.num_envs_per_runner,
            seed=config.seed,
        )
        self.env_spec = self.runners.env_spec()  # {pid: spec}
        self._setup()
        self._last_stats: Dict[str, Any] = {}

    def _setup(self):
        cfg = self.config
        self.learner_groups: Dict[str, LearnerGroup] = {}
        for pid in cfg.policies:
            spec = self.env_spec[pid]

            def factory(spec=spec):
                module = DiscretePolicyModule(
                    spec["obs_dim"], spec["num_actions"], cfg.hidden)
                return PPOLearner(module, clip_param=cfg.clip_param,
                                  vf_coeff=cfg.vf_coeff,
                                  entropy_coeff=cfg.entropy_coeff,
                                  lr=cfg.lr, seed=cfg.seed)

            self.learner_groups[pid] = LearnerGroup(factory,
                                                    cfg.num_learners)
        self.runners.sync_weights(self._weights())

    def _weights(self) -> Dict[str, Any]:
        return {pid: g.get_weights()
                for pid, g in self.learner_groups.items()}

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        results = self.runners.sample(cfg.rollout_len)
        stats: Dict[str, Any] = {
            "episodes_this_iter": sum(
                r["stats"].get("episodes_this_iter", 0)
                for r in results),
            "env_steps_sampled": sum(
                r["stats"].get("env_steps_sampled", 0)
                for r in results)}
        rets = [r["stats"]["episode_return_mean"] for r in results
                if "episode_return_mean" in r["stats"]]
        if rets:
            stats["episode_return_mean"] = float(np.mean(rets))

        rng = np.random.default_rng(cfg.seed + self.iteration)
        for pid, group in self.learner_groups.items():
            batch = merge_batches([r["batches"][pid] for r in results])
            metrics = ppo_update_on_batch(group, batch, cfg, rng)
            for k, v in metrics.items():
                stats[f"{pid}/{k}"] = v
        self.runners.sync_weights(self._weights())
        return stats

    def save(self, path: str):
        import pickle

        with open(path, "wb") as f:
            pickle.dump({"iteration": self.iteration,
                         "learner_state": {
                             pid: g.state()
                             for pid, g in self.learner_groups.items()}},
                        f)

    def restore(self, path: str):
        import pickle

        with open(path, "rb") as f:
            state = pickle.load(f)
        self.iteration = state["iteration"]
        for pid, s in state["learner_state"].items():
            self.learner_groups[pid].load_state(s)
        self.runners.sync_weights(self._weights())

    def stop(self):
        self.runners.stop()
        for g in self.learner_groups.values():
            g.stop()


MultiAgentPPOConfig.algo_cls = MultiAgentPPO
