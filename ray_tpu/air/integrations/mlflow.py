"""MLflow logging callback (reference:
python/ray/air/integrations/mlflow.py MLflowLoggerCallback — one mlflow
run per trial, params logged once, metrics per result).

Every call is targeted by run_id: TuneController runs trials
CONCURRENTLY, and mlflow's fluent module-level API routes through a
single global "active run" — interleaved trials would log into each
other's runs.  The real library is therefore wrapped in an
MlflowClient-backed adapter; injected fakes implement the same
run_id-explicit surface (see _FakeMlflow in tests/test_air_integrations.py):

    start_run(run_name, tags) -> run (with .info.run_id)
    log_params(params, run_id)
    log_metrics(metrics, step, run_id)
    end_run(run_id)
"""

from __future__ import annotations

import numbers
from typing import Any, Dict, Optional

from ray_tpu.tune.tune_controller import Callback


class _ClientAdapter:
    """run_id-targeted adapter over the real mlflow module (reference:
    air/integrations/mlflow.py _MLflowLoggerUtil, which likewise keeps
    an MlflowClient and passes run ids explicitly)."""

    def __init__(self, mlflow, tracking_uri: Optional[str],
                 experiment_name: Optional[str]):
        if tracking_uri:
            mlflow.set_tracking_uri(tracking_uri)
        self._client = mlflow.tracking.MlflowClient(tracking_uri)
        self._exp_id = "0"
        if experiment_name:
            exp = self._client.get_experiment_by_name(experiment_name)
            self._exp_id = (exp.experiment_id if exp is not None
                            else self._client.create_experiment(
                                experiment_name))

    def start_run(self, run_name, tags):
        return self._client.create_run(
            self._exp_id, tags={**(tags or {}),
                                "mlflow.runName": run_name})

    def log_params(self, params, run_id):
        for k, v in params.items():
            self._client.log_param(run_id, k, v)

    def log_metrics(self, metrics, step, run_id):
        for k, v in metrics.items():
            self._client.log_metric(run_id, k, v, step=step)

    def end_run(self, run_id):
        self._client.set_terminated(run_id)


def _resolve_mlflow(injected, tracking_uri, experiment_name):
    if injected is not None:
        if tracking_uri and hasattr(injected, "set_tracking_uri"):
            injected.set_tracking_uri(tracking_uri)
        if experiment_name and hasattr(injected, "set_experiment"):
            injected.set_experiment(experiment_name)
        return injected
    try:
        import mlflow  # type: ignore
    except ImportError:
        raise ImportError(
            "MLflowLoggerCallback needs the mlflow library (not bundled "
            "in this environment) or an injected mlflow-shaped object: "
            "MLflowLoggerCallback(mlflow=fake)") from None
    return _ClientAdapter(mlflow, tracking_uri, experiment_name)


class MLflowLoggerCallback(Callback):
    """reference: air/integrations/mlflow.py MLflowLoggerCallback."""

    def __init__(self, tracking_uri: Optional[str] = None,
                 experiment_name: Optional[str] = None, *, mlflow=None,
                 tags: Optional[Dict[str, str]] = None):
        self._mlflow = _resolve_mlflow(mlflow, tracking_uri,
                                       experiment_name)
        self.tags = tags or {}
        self._run_ids: Dict[str, Any] = {}

    def _run_id(self, trial):
        rid = self._run_ids.get(trial.trial_id)
        if rid is None:
            run = self._mlflow.start_run(run_name=trial.trial_id,
                                         tags=self.tags)
            rid = getattr(getattr(run, "info", None), "run_id",
                          trial.trial_id)
            self._run_ids[trial.trial_id] = rid
            if trial.config:
                self._mlflow.log_params(dict(trial.config), run_id=rid)
        return rid

    def on_trial_result(self, trial, result: Dict[str, Any]):
        rid = self._run_id(trial)
        metrics = {k: float(v) for k, v in result.items()
                   if isinstance(v, numbers.Number)
                   and not isinstance(v, bool)}
        self._mlflow.log_metrics(
            metrics, step=int(result.get("training_iteration") or 0),
            run_id=rid)

    def on_trial_complete(self, trial):
        rid = self._run_ids.pop(trial.trial_id, None)
        if rid is not None:
            self._mlflow.end_run(run_id=rid)

    on_trial_error = on_trial_complete
