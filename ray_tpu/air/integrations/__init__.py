"""Experiment-tracking logger callbacks (reference:
python/ray/air/integrations/wandb.py:453 WandbLoggerCallback,
python/ray/air/integrations/mlflow.py MlflowLoggerCallback,
python/ray/tune/logger/tensorboardx.py TBXLoggerCallback).

All three attach via ``RunConfig(callbacks=[...])`` (or directly on a
Tuner) and are duck-typed over their client libraries: pass a fake
module/client for tests, or install the real library — resolution order
is (injected object) > (importable library) > loud ImportError.
"""

from .mlflow import MLflowLoggerCallback
from .tbx import TBXLoggerCallback
from .wandb import WandbLoggerCallback

MlflowLoggerCallback = MLflowLoggerCallback  # reference spelling

__all__ = ["MLflowLoggerCallback", "MlflowLoggerCallback",
           "TBXLoggerCallback", "WandbLoggerCallback"]
