"""Weights & Biases logging callback (reference:
python/ray/air/integrations/wandb.py:453 WandbLoggerCallback — one wandb
run per trial, config logged once, metrics streamed per result).
"""

from __future__ import annotations

import numbers
from typing import Any, Dict, Optional

from ray_tpu.tune.tune_controller import Callback


def _resolve_wandb(injected):
    if injected is not None:
        return injected
    try:
        import wandb  # type: ignore

        return wandb
    except ImportError:
        raise ImportError(
            "WandbLoggerCallback needs the wandb library (not bundled in "
            "this environment) or an injected wandb-shaped object: "
            "WandbLoggerCallback(project=..., wandb=fake)") from None


class WandbLoggerCallback(Callback):
    """reference: air/integrations/wandb.py:453.

    `wandb` injects a module-shaped object with init(...)->run (run has
    .log/.finish) — the exact surface the real library exposes — so
    tests (and air-gapped clusters with a local relay) run without the
    dependency.
    """

    def __init__(self, project: Optional[str] = None,
                 group: Optional[str] = None, *, wandb=None,
                 excludes: Optional[list] = None, log_config: bool = True,
                 **init_kwargs):
        self._wandb = _resolve_wandb(wandb)
        self.project = project
        self.group = group
        self.excludes = set(excludes or ())
        self.log_config = log_config
        self.init_kwargs = init_kwargs
        self._runs: Dict[str, Any] = {}

    def _run(self, trial):
        run = self._runs.get(trial.trial_id)
        if run is None:
            # reinit="create_new" (wandb >= 0.19): each trial gets an
            # INDEPENDENT run handle — TuneController interleaves trial
            # results in one process, and legacy reinit=True would
            # finish trial A's active run when trial B starts.  All
            # logging below goes through the returned handle, never the
            # module-level fluent API, for the same reason.
            run = self._wandb.init(
                project=self.project, group=self.group,
                name=trial.trial_id, reinit="create_new",
                config=(dict(trial.config) if self.log_config else None),
                **self.init_kwargs)
            self._runs[trial.trial_id] = run
        return run

    def on_trial_result(self, trial, result: Dict[str, Any]):
        payload = {k: v for k, v in result.items()
                   if k not in self.excludes
                   and isinstance(v, numbers.Number)
                   and not isinstance(v, bool)}
        self._run(trial).log(payload,
                             step=result.get("training_iteration"))

    def on_trial_complete(self, trial):
        run = self._runs.pop(trial.trial_id, None)
        if run is not None:
            run.finish()

    on_trial_error = on_trial_complete
