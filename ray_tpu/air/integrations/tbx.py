"""TensorBoard logging callback (reference:
python/ray/tune/logger/tensorboardx.py TBXLoggerCallback — one
SummaryWriter per trial, scalars per result, flushed on complete).
"""

from __future__ import annotations

import json
import numbers
import os
from typing import Any, Dict, Optional

from ray_tpu.tune.tune_controller import Callback


class _FileSummaryWriter:
    """Dependency-free SummaryWriter stand-in: one JSONL event file per
    trial.  Not the TF event format, but the same information — and the
    fallback keeps the callback usable (and testable) in hermetic
    environments without tensorboardX."""

    def __init__(self, logdir: str):
        os.makedirs(logdir, exist_ok=True)
        self._f = open(os.path.join(logdir, "events.ray_tpu.jsonl"), "a")

    def add_scalar(self, tag: str, value, global_step: Optional[int] = None):
        self._f.write(json.dumps({"tag": tag, "value": float(value),
                                  "step": global_step}) + "\n")

    def flush(self):
        self._f.flush()

    def close(self):
        self._f.close()


def _resolve_writer_cls():
    try:
        from tensorboardX import SummaryWriter  # type: ignore

        return SummaryWriter
    except ImportError:
        try:
            from torch.utils.tensorboard import SummaryWriter  # type: ignore

            return SummaryWriter
        except ImportError:
            return _FileSummaryWriter


class TBXLoggerCallback(Callback):
    """Logs every numeric result field as a scalar, stepped by
    training_iteration (reference: tensorboardx.py:71 log_trial_result).

    `summary_writer_cls` overrides writer resolution (tests inject a
    recording fake; default tries tensorboardX, then torch's copy, then
    the JSONL stand-in).
    """

    def __init__(self, summary_writer_cls=None):
        self._writer_cls = summary_writer_cls or _resolve_writer_cls()
        self._writers: Dict[str, Any] = {}

    def _writer(self, trial):
        w = self._writers.get(trial.trial_id)
        if w is None:
            logdir = trial.trial_dir
            if "://" in (logdir or ""):
                # remote trial dirs: write locally under ~/.ray_tpu_tbx
                # (tbx writers need a real filesystem)
                logdir = os.path.expanduser(
                    os.path.join("~/.ray_tpu_tbx", trial.trial_id))
            w = self._writers[trial.trial_id] = self._writer_cls(logdir)
        return w

    def on_trial_result(self, trial, result: Dict[str, Any]):
        w = self._writer(trial)
        step = result.get("training_iteration")
        for k, v in result.items():
            if isinstance(v, numbers.Number) and not isinstance(v, bool):
                w.add_scalar(f"ray/tune/{k}", v, step)
        w.flush()

    def on_trial_complete(self, trial):
        w = self._writers.pop(trial.trial_id, None)
        if w is not None:
            w.close()

    on_trial_error = on_trial_complete
