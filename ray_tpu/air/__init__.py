"""ray_tpu.air: shared train/tune runtime pieces (reference:
python/ray/air/ — RunConfig & co. live in train/config.py here; this
package carries the experiment-tracking integrations)."""

from ray_tpu.train.config import (CheckpointConfig, FailureConfig,
                                  RunConfig, ScalingConfig)

from . import integrations

__all__ = ["CheckpointConfig", "FailureConfig", "RunConfig",
           "ScalingConfig", "integrations"]
