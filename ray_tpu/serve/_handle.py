"""DeploymentHandle: typed Python calls into a deployment.

Reference: python/ray/serve/handle.py DeploymentHandle/DeploymentResponse —
``handle.remote(*a)`` routes through the same p2c router as HTTP and
returns a `DeploymentResponse` future; handles pickle by (app, deployment)
name so they can be shipped into other replicas for model composition, and
`await response` works inside async replicas without blocking their loop.
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict, Optional

import ray_tpu

from ._router import get_router


class DeploymentResponse:
    def __init__(self, ref, done_cb=None):
        self._ref = ref
        self._done_cb = done_cb
        self._result = None
        self._have_result = False

    def result(self, timeout_s: Optional[float] = 300.0):
        if not self._have_result:
            try:
                self._result = ray_tpu.get(self._ref, timeout=timeout_s)
            finally:
                self._fire_done()
            self._have_result = True
        return self._result

    def _to_object_ref(self):
        return self._ref

    def _fire_done(self):
        if self._done_cb is not None:
            cb, self._done_cb = self._done_cb, None
            cb()

    def __await__(self):
        loop = asyncio.get_event_loop()
        fut = loop.run_in_executor(None, self.result)
        return fut.__await__()

    def __del__(self):
        # dropped without .result(): still release the router's inflight slot
        self._fire_done()


class DeploymentResponseGenerator:
    """Streaming response: iterate to receive items as the deployment
    yields them (reference: handle.py DeploymentResponseGenerator).
    Sync iteration blocks per item; `async for` hops via an executor."""

    def __init__(self, gen, done_cb=None):
        self._gen = gen
        self._done_cb = done_cb

    def _fire_done(self):
        if self._done_cb is not None:
            cb, self._done_cb = self._done_cb, None
            cb()

    def __iter__(self):
        try:
            for ref in self._gen:
                yield ray_tpu.get(ref, timeout=300.0)
        finally:
            self._fire_done()

    async def __aiter__(self):
        loop = asyncio.get_event_loop()
        it = iter(self)
        sentinel = object()
        while True:
            item = await loop.run_in_executor(
                None, lambda: next(it, sentinel))
            if item is sentinel:
                return
            yield item

    def __del__(self):
        self._fire_done()


class DeploymentHandle:
    def __init__(self, deployment_name: str, app_name: str,
                 method_name: Optional[str] = None,
                 multiplexed_model_id: Optional[str] = None,
                 stream: bool = False):
        self.deployment_name = deployment_name
        self.app_name = app_name
        self._method_name = method_name
        self._multiplexed_model_id = multiplexed_model_id
        self._stream = stream

    def options(self, *, method_name: Optional[str] = None,
                multiplexed_model_id: Optional[str] = None,
                stream: Optional[bool] = None) -> "DeploymentHandle":
        return DeploymentHandle(
            self.deployment_name, self.app_name,
            method_name=method_name or self._method_name,
            multiplexed_model_id=(multiplexed_model_id
                                  or self._multiplexed_model_id),
            stream=self._stream if stream is None else stream)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return self.options(method_name=name)

    def remote(self, *args, **kwargs):
        router = get_router(self.app_name, self.deployment_name)
        metadata: Dict[str, Any] = {}
        if self._multiplexed_model_id:
            metadata["multiplexed_model_id"] = self._multiplexed_model_id
        if self._stream:
            gen, done = router.assign_streaming(self._method_name, args,
                                                kwargs, metadata)
            return DeploymentResponseGenerator(gen, done)
        ref, done = router.assign(self._method_name, args, kwargs, metadata)
        return DeploymentResponse(ref, done)

    def __reduce__(self):
        return (DeploymentHandle,
                (self.deployment_name, self.app_name, self._method_name,
                 self._multiplexed_model_id, self._stream))

    def __repr__(self):
        return (f"DeploymentHandle(app={self.app_name!r}, "
                f"deployment={self.deployment_name!r})")
