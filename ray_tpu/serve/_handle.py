"""DeploymentHandle: typed Python calls into a deployment.

Reference: python/ray/serve/handle.py DeploymentHandle/DeploymentResponse —
``handle.remote(*a)`` routes through the same p2c router as HTTP and
returns a `DeploymentResponse` future; handles pickle by (app, deployment)
name so they can be shipped into other replicas for model composition, and
`await response` works inside async replicas without blocking their loop.

Responses resolve through the router's replay core (_router.py): a
replica that dies mid-call is ejected and the request replayed on a
survivor, transparently to the caller.  Streaming responses registered
with a resume continuation (``options(resume="llm_tokens")``) continue
from the last item the client received instead of restarting.
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict, Optional

from ._router import get_router


class DeploymentResponse:
    def __init__(self, router, sub):
        self._router = router
        self._sub = sub
        self._result = None
        self._have_result = False

    def result(self, timeout_s: Optional[float] = 300.0):
        if not self._have_result:
            self._result = self._router.call(self._sub,
                                             timeout_s=timeout_s)
            self._have_result = True
        return self._result

    def _to_object_ref(self):
        return self._sub.ref

    def _fire_done(self):
        self._sub.fire_done()

    def __await__(self):
        loop = asyncio.get_event_loop()
        fut = loop.run_in_executor(None, self.result)
        return fut.__await__()

    def __del__(self):
        # dropped without .result(): still release the router's inflight slot
        self._fire_done()


class DeploymentResponseGenerator:
    """Streaming response: iterate to receive items as the deployment
    yields them (reference: handle.py DeploymentResponseGenerator).
    Sync iteration blocks per item; `async for` hops via an executor.
    Abandoning the iteration (break / close / GC before exhaustion)
    releases the router's in-flight slot instead of inflating the
    replica's load score forever."""

    def __init__(self, router, sub):
        self._router = router
        self._sub = sub
        self._it = None

    def _fire_done(self):
        self._sub.fire_done()

    def close(self):
        """Abandon the stream: close the underlying iterator (its
        finally releases the in-flight slot)."""
        it, self._it = self._it, None
        if it is not None:
            try:
                it.close()
            except Exception:
                pass
        self._fire_done()

    def __iter__(self):
        if self._it is None:
            self._it = self._router.iter_stream(self._sub)
        return self._it

    async def __aiter__(self):
        loop = asyncio.get_event_loop()
        it = iter(self)
        sentinel = object()
        try:
            while True:
                item = await loop.run_in_executor(
                    None, lambda: next(it, sentinel))
                if item is sentinel:
                    return
                yield item
        finally:
            # a client that stops consuming (disconnect, early break in
            # `async for`) must release the in-flight slot NOW, not when
            # the GC eventually finds the generator
            await loop.run_in_executor(None, self.close)

    def __del__(self):
        self.close()


class DeploymentHandle:
    def __init__(self, deployment_name: str, app_name: str,
                 method_name: Optional[str] = None,
                 multiplexed_model_id: Optional[str] = None,
                 stream: bool = False, resume: Optional[str] = None):
        self.deployment_name = deployment_name
        self.app_name = app_name
        self._method_name = method_name
        self._multiplexed_model_id = multiplexed_model_id
        self._stream = stream
        self._resume = resume

    def options(self, *, method_name: Optional[str] = None,
                multiplexed_model_id: Optional[str] = None,
                stream: Optional[bool] = None,
                resume: Optional[str] = None) -> "DeploymentHandle":
        return DeploymentHandle(
            self.deployment_name, self.app_name,
            method_name=method_name or self._method_name,
            multiplexed_model_id=(multiplexed_model_id
                                  or self._multiplexed_model_id),
            stream=self._stream if stream is None else stream,
            resume=resume or self._resume)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return self.options(method_name=name)

    def remote(self, *args, **kwargs):
        router = get_router(self.app_name, self.deployment_name)
        metadata: Dict[str, Any] = {}
        if self._multiplexed_model_id:
            metadata["multiplexed_model_id"] = self._multiplexed_model_id
        if self._resume:
            metadata["resume"] = self._resume
        sub = router.submit(self._method_name, args, kwargs, metadata,
                            streaming=self._stream)
        if self._stream:
            return DeploymentResponseGenerator(router, sub)
        return DeploymentResponse(router, sub)

    def __reduce__(self):
        return (DeploymentHandle,
                (self.deployment_name, self.app_name, self._method_name,
                 self._multiplexed_model_id, self._stream, self._resume))

    def __repr__(self):
        return (f"DeploymentHandle(app={self.app_name!r}, "
                f"deployment={self.deployment_name!r})")
