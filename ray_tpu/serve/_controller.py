"""ServeController: the serve control-plane actor.

Reference: python/ray/serve/_private/controller.py:84 ServeController and
deployment_state.py:1245 DeploymentState — a singleton actor holding target
state (apps -> deployments -> target replica counts) and a reconcile loop
that starts/stops replica actors, health-checks them, autoscales from
replica queue metrics, and serves the routing table to proxies/handles.

Config fan-out is pull-based: proxies and handles poll
``get_routing_table(version)`` / ``get_replica_table(...)`` cheaply and
re-pull on version bumps (the role LongPollHost plays in the reference,
long_poll.py:177).
"""

from __future__ import annotations

import logging
import math
import threading
import time
import traceback
from typing import Any, Dict, List, Optional

import ray_tpu

from ._common import (APP_RUNNING, DEPLOY_FAILED, DEPLOYING, RUNNING,
                      STARTING, ApplicationStatus, AutoscalingConfig,
                      DeploymentStatus, ReplicaStatus)
from ._replica import Replica

logger = logging.getLogger(__name__)

RECONCILE_PERIOD_S = 0.25


class _ReplicaState:
    def __init__(self, replica_id: str, handle):
        self.replica_id = replica_id
        self.handle = handle
        self.state = STARTING
        self.ready_ref = None
        self.ongoing = 0
        self.model_ids: List[str] = []
        self.engine: Optional[Dict[str, Any]] = None  # decode-engine stats
        self.last_health_ts = time.time()
        self.health_ref = None       # in-flight check_health probe
        self.health_fired_ts = 0.0   # when that probe was submitted
        self.metrics_ref = None
        self.node_id: Optional[str] = None   # placement, for drain marks
        self.draining = False        # node preemption/quarantine advisory
        self.drain_deadline = 0.0    # wall time the node goes away


class _DeploymentState:
    def __init__(self, app_name: str, spec: Dict[str, Any]):
        self.app_name = app_name
        self.spec = spec  # serialized deployment info
        self.target_num_replicas = spec["num_replicas"]
        self.replicas: Dict[str, _ReplicaState] = {}
        self.next_replica_no = 0
        self.autoscaling = (AutoscalingConfig.from_dict(
            spec["autoscaling_config"]) if spec.get("autoscaling_config")
            else None)
        self.last_scale_up = 0.0
        self.last_scale_down = 0.0
        self.message = ""

    @property
    def name(self) -> str:
        return self.spec["name"]


class ServeController:
    def __init__(self, http_host: str = "127.0.0.1", http_port: int = 8000):
        from ray_tpu._private.config import cfg

        c = cfg()
        self._apps: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.RLock()
        self._routing_version = 0
        self._replica_version = 0
        self._http_host = http_host
        self._http_port = http_port
        self._proxy = None
        self._rpc_proxy = None
        self._grpc_proxy = None
        self._shutdown = False
        self._health_period = c.serve_health_check_period_s
        self._health_timeout = c.serve_health_check_timeout_s
        self._drain_grace = c.serve_drain_grace_s
        # preemption advisories: node_id -> wall-clock deadline the node
        # goes away.  Fed by the pubsub edge (h_report_draining /
        # h_report_quarantine events) and re-derived level-triggered from
        # get_nodes so a missed push cannot strand a mark forever.
        self._unsafe_nodes: Dict[str, float] = {}  # guarded-by: _lock
        self._safe_node_exists = True  # guarded-by: _lock
        self._last_node_sync = 0.0
        try:
            from ray_tpu._private.api import current_core

            current_core().add_push_handler("pub:node", self._on_node_event)
        except Exception:
            # single-process / test harness without a control plane: the
            # level-triggered sync (or nothing) covers it
            logger.debug("node-event subscription unavailable",
                         exc_info=True)
        self._reconciler = threading.Thread(target=self._reconcile_loop,
                                            name="serve-reconcile",
                                            daemon=True)
        self._reconciler.start()

    # -- preemption advisories ----------------------------------------------

    def _on_node_event(self, payload: Dict[str, Any]):
        """Pubsub edge: drain/quarantine advisories land here the moment
        the control plane publishes them (reference: the drain listener in
        train/backend_executor.py) — the reconcile tick then pre-starts
        replacements before the node's deadline instead of after its
        death."""
        try:
            event = payload.get("event")
            view = payload.get("node") or {}
            nid = view.get("node_id")
            if not nid:
                return
            if event in ("draining", "quarantined"):
                grace = payload.get("grace_s")
                deadline = time.time() + (float(grace)
                                          if grace else self._drain_grace)
                with self._lock:
                    self._unsafe_nodes[nid] = deadline
            elif event in ("drain_canceled", "quarantine_cleared",
                           "removed"):
                with self._lock:
                    self._unsafe_nodes.pop(nid, None)
        except Exception:
            logger.debug("node event ignored", exc_info=True)

    def _sync_node_state(self):
        """Level-triggered reconciliation of the unsafe-node map against
        get_nodes (≤1/s): catches advisories published before this
        controller subscribed, prunes marks for nodes that drained away or
        had the advisory cleared, and resolves replica -> node placement
        for drain marking.  All control calls run OUTSIDE the lock."""
        now = time.time()
        if now - self._last_node_sync < 1.0:
            return
        self._last_node_sync = now
        try:
            from ray_tpu._private.api import current_core

            core = current_core()
            views = core.control.call("get_nodes", {}, timeout=5.0)
        except Exception:
            return
        fresh: Dict[str, float] = {}
        safe = False
        live_ids = set()
        for v in views or []:
            nid = v.get("node_id")
            if not nid:
                continue
            live_ids.add(nid)
            if v.get("state") != "ALIVE" or v.get("disconnected"):
                continue
            unsafe = False
            if v.get("draining"):
                rem = v.get("draining_remaining_s")
                fresh[nid] = now + (float(rem) if rem is not None
                                    else self._drain_grace)
                unsafe = True
            if v.get("quarantined"):
                rem = v.get("quarantine_remaining_s")
                dl = now + (float(rem) if rem is not None
                            else self._drain_grace)
                fresh[nid] = max(fresh.get(nid, 0.0), dl)
                unsafe = True
            if not unsafe:
                safe = True
        with self._lock:
            for nid in list(self._unsafe_nodes):
                # prune: node gone, or the view says the advisory cleared
                if nid in live_ids and nid not in fresh:
                    self._unsafe_nodes.pop(nid)
                elif nid not in live_ids:
                    self._unsafe_nodes.pop(nid)
            self._unsafe_nodes.update(fresh)
            self._safe_node_exists = safe or not views
        # resolve node placement for replicas that don't know theirs yet
        pending = []
        with self._lock:
            for app in self._apps.values():
                for ds in app["deployments"].values():
                    for r in ds.replicas.values():
                        if r.node_id is None and r.state == RUNNING:
                            pending.append(r)
        for r in pending:
            try:
                view = core.control.call(
                    "get_actor", {"actor_id": r.handle._actor_id},
                    timeout=5.0)
                nid = (view or {}).get("node_id")
            except Exception:
                nid = None
            if nid:
                with self._lock:
                    r.node_id = nid

    @staticmethod
    def _actor_dead(handle) -> bool:
        """Best-effort liveness read from the control plane; False on any
        doubt — a dead-looking replica still gets the kill, it just also
        gets a useless prepare_shutdown first."""
        try:
            from ray_tpu._private.api import current_core

            view = current_core().control.call(
                "get_actor", {"actor_id": handle._actor_id}, timeout=2.0)
            return (view or {}).get("state") == "DEAD"
        except Exception:
            return False

    # -- app deploy/delete --------------------------------------------------

    def deploy_app(self, name: str, route_prefix: Optional[str],
                   deployment_specs: List[Dict[str, Any]],
                   ingress_name: str) -> bool:
        with self._lock:
            old = self._apps.get(name)
            deployments: Dict[str, _DeploymentState] = {}
            for spec in deployment_specs:
                ds = _DeploymentState(name, spec)
                if old and spec["name"] in old["deployments"]:
                    prev = old["deployments"][spec["name"]]
                    if (prev.spec["callable_blob"] == spec["callable_blob"]
                            and prev.spec["init_args_blob"]
                            == spec["init_args_blob"]):
                        # same code: keep live replicas, adopt new target
                        ds.replicas = prev.replicas
                        ds.next_replica_no = prev.next_replica_no
                        if spec.get("user_config") is not None and \
                                spec.get("user_config") != prev.spec.get(
                                    "user_config"):
                            for r in ds.replicas.values():
                                try:
                                    r.handle.reconfigure.remote(
                                        spec["user_config"])
                                except Exception:
                                    pass
                    else:
                        self._stop_replicas(prev)
                deployments[spec["name"]] = ds
            if old:
                for dname, prev in old["deployments"].items():
                    if dname not in deployments:
                        self._stop_replicas(prev)
            self._apps[name] = {
                "deployments": deployments,
                "route_prefix": route_prefix,
                "ingress": ingress_name,
                "status": DEPLOYING,
                "message": "",
            }
            self._routing_version += 1
            self._replica_version += 1
        return True

    def delete_app(self, name: str, drain_s: float = 2.0) -> bool:
        with self._lock:
            app = self._apps.pop(name, None)
            if app is None:
                return False
            states = list(app["deployments"].values())
            self._routing_version += 1
            self._replica_version += 1
        # drain + kill SYNCHRONOUSLY: delete/shutdown must not return while
        # replica actors are still alive (a killed controller would leak
        # them — its drain threads die with it)
        victims = []
        for ds in states:
            with self._lock:
                vs = list(ds.replicas.values())
                ds.replicas.clear()
            victims.extend(vs)
        # skip the drain wait for replicas the control plane already knows
        # are dead — otherwise deleting an app whose replicas were killed
        # burns the full drain timeout per call for actors that can never
        # answer prepare_shutdown
        refs = []
        for r in victims:
            if self._actor_dead(r.handle):
                continue
            try:
                refs.append(r.handle.prepare_shutdown.remote(drain_s))
            except Exception:
                pass
        if refs:
            try:
                ray_tpu.wait(refs, num_returns=len(refs),
                             timeout=drain_s + 2.0)
            except Exception:
                pass
        for r in victims:
            try:
                ray_tpu.kill(r.handle)
            except Exception:
                pass
        return True

    def shutdown(self) -> bool:
        with self._lock:
            self._shutdown = True  # stop reconcile from respawning
            names = list(self._apps)
        for name in names:
            self.delete_app(name, drain_s=0.5)
        try:
            # final publish with the (now empty) app set — otherwise the
            # dashboard renders the last pre-shutdown snapshot's apps as
            # HEALTHY forever
            self._publish_status()
        except Exception:
            pass
        return True

    # -- read API (proxies / handles / status) ------------------------------

    def get_routing_table(self) -> Dict[str, Any]:
        with self._lock:
            routes = {}
            for app_name, app in self._apps.items():
                if app["route_prefix"]:
                    ingress = app["deployments"].get(app["ingress"])
                    spec = ingress.spec if ingress else {}
                    routes[app["route_prefix"]] = {
                        "app": app_name, "deployment": app["ingress"],
                        # the proxy streams chunked responses for
                        # generator/ASGI ingress callables
                        "streaming": bool(spec.get("streaming")),
                        "asgi": bool(spec.get("asgi"))}
            return {"version": self._routing_version, "routes": routes}

    def get_app_table(self) -> Dict[str, Any]:
        """All apps keyed by name — the RPC ingress serves apps without an
        HTTP route_prefix too (the reference's gRPC proxy does likewise)."""
        with self._lock:
            apps = {name: {"app": name, "deployment": app["ingress"]}
                    for name, app in self._apps.items()}
            return {"version": self._routing_version, "apps": apps}

    def get_replica_table(self, app_name: str,
                          deployment_name: str) -> Dict[str, Any]:
        with self._lock:
            app = self._apps.get(app_name)
            if app is None:
                return {"version": self._replica_version, "replicas": [],
                        "max_ongoing_requests": 100}
            ds = app["deployments"].get(deployment_name)
            if ds is None:
                return {"version": self._replica_version, "replicas": [],
                        "max_ongoing_requests": 100}
            return {
                "version": self._replica_version,
                "replicas": [
                    {"replica_id": r.replica_id, "handle": r.handle,
                     "model_ids": list(r.model_ids),
                     "draining": r.draining,
                     "engine": dict(r.engine) if r.engine else None}
                    for r in ds.replicas.values() if r.state == RUNNING],
                "max_ongoing_requests": ds.spec.get(
                    "max_ongoing_requests", 100),
            }

    def get_replica_version(self) -> int:
        return self._replica_version

    def status(self) -> Dict[str, Any]:
        with self._lock:
            out = {}
            for app_name, app in self._apps.items():
                deps = {}
                for dname, ds in app["deployments"].items():
                    deps[dname] = DeploymentStatus(
                        name=dname,
                        status="HEALTHY" if all(
                            r.state == RUNNING
                            for r in ds.replicas.values())
                        and len(ds.replicas) >= ds.target_num_replicas
                        else "UPDATING",
                        target_num_replicas=ds.target_num_replicas,
                        replicas=[ReplicaStatus(r.replica_id, r.state,
                                                r.ongoing)
                                  for r in ds.replicas.values()],
                        message=ds.message)
                out[app_name] = ApplicationStatus(
                    name=app_name, status=app["status"],
                    route_prefix=app["route_prefix"], deployments=deps,
                    message=app["message"], ingress=app["ingress"])
            return out

    def get_http_config(self):
        return {"host": self._http_host, "port": self._http_port}

    def ensure_proxy(self) -> Any:
        """Start the HTTP proxy actor on demand; returns (host, port)."""
        with self._lock:
            if self._proxy is None:
                from ._proxy import HTTPProxy

                self._proxy = ray_tpu.remote(HTTPProxy).options(
                    name="SERVE_PROXY", max_concurrency=8,
                    num_cpus=0).remote(self._http_host, self._http_port)
            proxy = self._proxy
        return ray_tpu.get(proxy.ready.remote(), timeout=30.0)

    def ensure_rpc_proxy(self) -> Any:
        """Start the RPC ingress actor on demand (the reference's gRPC
        proxy analog); returns (host, port)."""
        with self._lock:
            if self._rpc_proxy is None:
                from ._proxy import RpcProxy

                self._rpc_proxy = ray_tpu.remote(RpcProxy).options(
                    name="SERVE_RPC_PROXY", max_concurrency=8,
                    num_cpus=0).remote(self._http_host, 0)
            proxy = self._rpc_proxy
        return ray_tpu.get(proxy.ready.remote(), timeout=30.0)

    def ensure_grpc_proxy(self, servicer_blob: bytes,
                          host: Optional[str] = None) -> Any:
        """Start the REAL gRPC ingress actor on demand (reference:
        proxy.py:558 gRPCProxy); returns (host, port).  The user's
        add_*Servicer_to_server functions arrive pickled (they are
        driver-side code) and pass through to the proxy unopened."""
        import hashlib

        digest = hashlib.sha256(servicer_blob).hexdigest()
        with self._lock:
            if self._grpc_proxy is None:
                from ._grpc import GrpcProxy

                self._grpc_blob_digest = digest
                self._grpc_proxy = ray_tpu.remote(GrpcProxy).options(
                    name="SERVE_GRPC_PROXY", max_concurrency=8,
                    num_cpus=0).remote(host or self._http_host, 0,
                                       servicer_blob=servicer_blob)
            elif digest != self._grpc_blob_digest:
                # a second start_grpc with DIFFERENT services would
                # silently serve only the first set — refuse loudly
                raise ValueError(
                    "the gRPC proxy is already running with a different "
                    "set of servicer functions; serve.shutdown() first "
                    "to change the registered services")
            proxy = self._grpc_proxy
        try:
            return ray_tpu.get(proxy.ready.remote(), timeout=30.0)
        except Exception:
            # failed/dead proxy must not brick every future start_grpc
            # behind the digest guard — forget it so a retry re-creates
            with self._lock:
                if self._grpc_proxy is proxy:
                    self._grpc_proxy = None
                    self._grpc_blob_digest = None
            try:
                ray_tpu.kill(proxy, no_restart=True)
            except Exception:
                pass
            raise

    # -- reconcile loop -----------------------------------------------------

    def _reconcile_loop(self):
        while not self._shutdown:
            try:
                self._sync_node_state()
            except Exception:
                logger.debug("node sync failed", exc_info=True)
            try:
                self._reconcile_once()
            except Exception:
                logger.error("serve reconcile error:\n%s",
                             traceback.format_exc())
            try:
                self._publish_status()
            except Exception:
                logger.debug("serve status publish failed", exc_info=True)
            time.sleep(RECONCILE_PERIOD_S)

    def _publish_status(self):
        """Push a plain-dict snapshot to the control-plane KV (ns
        'serve') so the dashboard — which holds only a control client,
        not a driver — can render serve state without calling into this
        actor (reference shape: the controller checkpoints state the
        serve dashboard module reads)."""
        import json as _json

        from ray_tpu._private.api import current_core

        snap = {"ts": time.time(), "apps": [], "serve_load": {}}
        with self._lock:
            for app_name, app in self._apps.items():
                deps = []
                for dname, ds in app["deployments"].items():
                    running = sum(1 for r in ds.replicas.values()
                                  if r.state == RUNNING)
                    deps.append({
                        "deployment": dname,
                        "status": "HEALTHY"
                        if running >= ds.target_num_replicas
                        else "UPDATING",
                        "replicas": f"{running}/{ds.target_num_replicas}",
                        "ongoing": sum(r.ongoing
                                       for r in ds.replicas.values()),
                        "message": ds.message or "",
                    })
                    engines = [r.engine for r in ds.replicas.values()
                               if r.state == RUNNING and r.engine]
                    if engines:
                        # per-deployment decode-engine load: the
                        # queue-depth / p99-TTFT signals autoscaler v2's
                        # ServeSLOPolicy consumes from LoadMetrics
                        snap["serve_load"][f"{app_name}:{dname}"] = {
                            "replicas": running,
                            "queue_depth": sum(e.get("queue_depth", 0)
                                               for e in engines),
                            "active": sum(e.get("active", 0)
                                          for e in engines),
                            "free_pages": sum(e.get("free_pages", 0)
                                              for e in engines),
                            "accepting": sum(
                                1 for e in engines
                                if e.get("accepting", True)),
                            "ttft_p99_s": max(e.get("ttft_p99_s", 0.0)
                                              for e in engines),
                            "tokens_per_s": sum(
                                e.get("tokens_per_s", 0.0)
                                for e in engines),
                        }
                snap["apps"].append({
                    "app": app_name, "status": app["status"],
                    "route_prefix": app["route_prefix"],
                    "message": app["message"] or "",
                    "deployments": deps,
                })
        # single kv_put (the internal_kv wrapper's overwrite path pays an
        # extra kv_exists round-trip per publish for a return value
        # nobody reads)
        current_core().control.call("kv_put", {
            "ns": "serve", "key": "status",
            "val": _json.dumps(snap).encode()})

    def _reconcile_once(self):
        with self._lock:
            apps = list(self._apps.items())
        for app_name, app in apps:
            all_ready = True
            failed_msg = None
            for ds in list(app["deployments"].values()):
                with self._lock:
                    # a concurrent redeploy may have replaced this
                    # _DeploymentState — reconciling the orphan would leak
                    # replicas running stale code
                    live = self._apps.get(app_name, {}).get(
                        "deployments", {}).get(ds.name)
                    if live is not ds:
                        all_ready = False
                        continue
                    try:
                        self._reconcile_deployment(ds)
                    except _DeployFailed as e:
                        failed_msg = str(e)
                        all_ready = False
                        continue
                    running = sum(1 for r in ds.replicas.values()
                                  if r.state == RUNNING)
                    if running < ds.target_num_replicas:
                        all_ready = False
            with self._lock:
                if app_name in self._apps:
                    if failed_msg:
                        self._apps[app_name]["status"] = DEPLOY_FAILED
                        self._apps[app_name]["message"] = failed_msg
                    elif all_ready:
                        self._apps[app_name]["status"] = APP_RUNNING

    def _reconcile_deployment(self, ds: _DeploymentState):  # holds: _lock
        # caller holds self._lock (RLock): replica-map mutations are never
        # concurrent with get_replica_table/status readers
        self._poll_replica_futures(ds)
        self._autoscale(ds)
        self._mark_draining(ds)
        running_or_starting = [r for r in ds.replicas.values()
                               if r.state in (STARTING, RUNNING)]
        # a draining replica stops counting toward target — its
        # replacement pre-starts NOW, before the node's deadline — but
        # only when somewhere safe exists to put it (otherwise a
        # single-node drain would spawn-loop replicas that are instantly
        # re-marked draining)
        if self._safe_node_exists:
            effective = [r for r in running_or_starting if not r.draining]
        else:
            effective = running_or_starting
        # scale up
        while len(effective) < ds.target_num_replicas:
            r = self._start_replica(ds)
            effective.append(r)
        # scale down (prefer draining STARTING last-in first; node-drain
        # replicas retire through _retire_draining, never as generic
        # excess — killing them early would drop their in-flight work)
        excess = len(effective) - ds.target_num_replicas
        if excess > 0:
            victims = sorted(effective,
                             key=lambda r: (r.state == RUNNING, -r.ongoing))
            self._stop_replica_set(ds, victims[:excess])
        self._retire_draining(ds)

    def _mark_draining(self, ds: _DeploymentState):  # holds: _lock
        """Flag replicas whose node has a preemption/quarantine advisory.
        Marked replicas keep serving (the router deprioritizes but does
        not refuse them — zero-drop when no safe node exists) while their
        replacements start."""
        if not self._unsafe_nodes:
            return
        changed = False
        for r in ds.replicas.values():
            if r.draining or r.node_id is None:
                continue
            deadline = self._unsafe_nodes.get(r.node_id)
            if deadline is not None:
                r.draining = True
                r.drain_deadline = deadline
                changed = True
                logger.warning(
                    "replica %s marked draining (node %s preempted, "
                    "%.1fs left)", r.replica_id, r.node_id,
                    max(0.0, deadline - time.time()))
        if changed:
            self._replica_version += 1

    def _retire_draining(self, ds: _DeploymentState):  # holds: _lock
        """Retire draining replicas once their replacements are RUNNING
        (or the node deadline passed — at that point the node takes the
        replica with it either way, so a last drain attempt is free)."""
        draining = [r for r in ds.replicas.values()
                    if r.draining and r.state in (STARTING, RUNNING)]
        if not draining:
            return
        if not self._safe_node_exists:
            return  # nowhere to retire TO: keep serving on the doomed node
        ready = sum(1 for r in ds.replicas.values()
                    if r.state == RUNNING and not r.draining)
        now = time.time()
        for r in draining:
            if ready >= ds.target_num_replicas or now >= r.drain_deadline:
                drain_s = max(0.5, min(5.0, r.drain_deadline - now))
                logger.info("retiring draining replica %s (%.1fs drain)",
                            r.replica_id, drain_s)
                self._stop_replica_set(ds, [r], drain_s=drain_s)

    def _poll_replica_futures(self, ds: _DeploymentState):
        changed = False
        for r in list(ds.replicas.values()):
            if r.state == STARTING and r.ready_ref is not None:
                done, _ = ray_tpu.wait([r.ready_ref], num_returns=1,
                                       timeout=0)
                if done:
                    try:
                        ray_tpu.get(done[0])
                        r.state = RUNNING
                        r.ready_ref = None
                        changed = True
                    except Exception as e:
                        ds.message = f"replica failed to start: {e}"
                        del ds.replicas[r.replica_id]
                        changed = True
                        raise _DeployFailed(ds.message)
            elif r.state == RUNNING:
                # harvest metrics probe
                if r.metrics_ref is not None:
                    done, _ = ray_tpu.wait([r.metrics_ref], num_returns=1,
                                           timeout=0)
                    if done:
                        try:
                            m = ray_tpu.get(done[0])
                            r.ongoing = m.get("ongoing", 0)
                            r.engine = m.get("engine")
                            new_models = m.get("model_ids", [])
                            if new_models != r.model_ids:
                                r.model_ids = new_models
                                changed = True
                            # NOTE: metrics success does NOT refresh
                            # last_health_ts — a wedged engine answers
                            # metrics fine; only check_health (which
                            # probes the engine's scheduler thread and
                            # step counter) counts as proof of life
                        except Exception:
                            # replica died: drop + let scale-up replace it
                            logger.warning("replica %s died; replacing",
                                           r.replica_id)
                            del ds.replicas[r.replica_id]
                            changed = True
                            continue
                        r.metrics_ref = None
                if r.metrics_ref is None:
                    r.metrics_ref = r.handle.get_metrics.remote()
                # liveness probe: engine-level check_health on a period;
                # a failed OR timed-out probe restarts the replica
                now = time.time()
                if r.health_ref is not None:
                    done, _ = ray_tpu.wait([r.health_ref], num_returns=1,
                                           timeout=0)
                    if done:
                        try:
                            ray_tpu.get(done[0])
                            r.last_health_ts = now
                        except Exception as e:
                            self._restart_replica(
                                ds, r, f"health check failed: {e}")
                            changed = True
                            continue
                        r.health_ref = None
                    elif now - r.health_fired_ts > self._health_timeout:
                        # probe never answered: replica event loop (or the
                        # whole worker) is wedged even though the actor
                        # is nominally alive
                        self._restart_replica(
                            ds, r, "health check timed out "
                            f"({self._health_timeout:.0f}s): wedged")
                        changed = True
                        continue
                if (r.health_ref is None
                        and now - r.last_health_ts >= self._health_period):
                    try:
                        r.health_ref = r.handle.check_health.remote()
                        r.health_fired_ts = now
                    except Exception:
                        pass  # submit fails only mid-shutdown
        if changed:
            with self._lock:
                self._replica_version += 1

    def _restart_replica(self, ds: _DeploymentState, r: _ReplicaState,
                         reason: str):  # holds: _lock
        """Drop a wedged/unhealthy replica; the scale-up pass replaces it
        on the next tick.  The kill runs on a daemon thread — killing a
        wedged worker can block, and this runs under the reconcile lock."""
        logger.warning("restarting replica %s: %s", r.replica_id, reason)
        ds.replicas.pop(r.replica_id, None)
        ds.message = f"replica {r.replica_id} restarted: {reason}"
        handle = r.handle

        def _kill():
            try:
                ray_tpu.kill(handle)
            except Exception:
                pass

        threading.Thread(target=_kill, daemon=True).start()

    def _start_replica(self, ds: _DeploymentState) -> _ReplicaState:
        rid = f"{ds.app_name}#{ds.name}#{ds.next_replica_no}"
        ds.next_replica_no += 1
        opts = dict(ds.spec.get("ray_actor_options") or {})
        opts.setdefault("num_cpus", 0)
        opts["max_concurrency"] = max(
            2, min(8, ds.spec.get("max_ongoing_requests", 100)))
        actor = ray_tpu.remote(Replica).options(**opts).remote(
            ds.app_name, ds.name, rid,
            ds.spec["callable_blob"], ds.spec["init_args_blob"],
            ds.spec.get("user_config"), ds.spec.get("is_function", False))
        r = _ReplicaState(rid, actor)
        r.ready_ref = actor.check_health.remote()
        ds.replicas[rid] = r
        return r

    def _stop_replica_set(self, ds: _DeploymentState,
                          victims: List[_ReplicaState],
                          drain_s: float = 5.0):
        if not victims:
            return
        handles = []
        for r in victims:
            ds.replicas.pop(r.replica_id, None)
            handles.append(r.handle)
        with self._lock:
            self._replica_version += 1

        def _drain_then_kill():
            # drain off-thread so neither reconcile nor deploy_app blocks;
            # prepare_shutdown submission happens here too — submitting to
            # a dead replica can block on connection setup, and the caller
            # may hold the reconcile lock
            refs = []
            for h in handles:
                if self._actor_dead(h):
                    continue  # no drain to wait for: straight to the kill
                try:
                    refs.append(h.prepare_shutdown.remote(drain_s))
                except Exception:
                    pass
            if refs:
                try:
                    ray_tpu.wait(refs, num_returns=len(refs),
                                 timeout=drain_s + 2.0)
                except Exception:
                    pass
            for h in handles:
                try:
                    ray_tpu.kill(h)
                except Exception:
                    pass

        threading.Thread(target=_drain_then_kill, daemon=True).start()

    def _stop_replicas(self, ds: _DeploymentState):
        self._stop_replica_set(ds, list(ds.replicas.values()))

    # -- autoscaling --------------------------------------------------------

    def _autoscale(self, ds: _DeploymentState):
        cfg = ds.autoscaling
        if cfg is None:
            return
        running = [r for r in ds.replicas.values() if r.state == RUNNING]
        if not running:
            return
        total_ongoing = sum(r.ongoing for r in running)
        desired = math.ceil(total_ongoing
                            / max(cfg.target_ongoing_requests, 1e-9))
        # serve-SLO signals from the decode engines: sustained waiting
        # queues or p99 TTFT past the SLO mean the replicas are saturated
        # even if ongoing-request counts look tame (one engine request is
        # "one ongoing" no matter how many are queued behind its slots)
        engines = [r.engine for r in running if r.engine]
        if engines:
            if cfg.target_queue_depth > 0:
                queued = sum(e.get("queue_depth", 0) for e in engines)
                if queued:
                    desired = max(desired, math.ceil(
                        queued / cfg.target_queue_depth))
                if queued / len(running) > cfg.target_queue_depth:
                    desired = max(desired, len(running) + 1)
            if cfg.ttft_slo_s > 0:
                worst = max(e.get("ttft_p99_s", 0.0) for e in engines)
                if worst > cfg.ttft_slo_s:
                    desired = max(desired, len(running) + 1)
        desired = max(cfg.min_replicas, min(cfg.max_replicas, desired))
        now = time.time()
        if desired > ds.target_num_replicas:
            if now - ds.last_scale_up >= cfg.upscale_delay_s:
                logger.info("autoscale %s: %d -> %d (ongoing=%d)", ds.name,
                            ds.target_num_replicas, desired, total_ongoing)
                ds.target_num_replicas = desired
                ds.last_scale_up = now
        elif desired < ds.target_num_replicas:
            if now - ds.last_scale_down >= cfg.downscale_delay_s:
                logger.info("autoscale %s: %d -> %d (ongoing=%d)", ds.name,
                            ds.target_num_replicas, desired, total_ongoing)
                ds.target_num_replicas = desired
                ds.last_scale_down = now
        else:
            ds.last_scale_up = now
            ds.last_scale_down = now


class _DeployFailed(RuntimeError):
    pass
