"""Router: picks a replica for each request.

Reference: python/ray/serve/_private/router.py:313 Router +
replica_scheduler/pow_2_scheduler.py:52 PowerOfTwoChoicesReplicaScheduler —
pick two random candidates, route to the one with the shorter queue.  Queue
lengths come from the controller's metrics probes (cached replica table)
plus a local in-flight count per replica, so the hot path makes NO extra
RPCs.  Multiplexed requests prefer replicas that already hold the model.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, Optional

import ray_tpu

from ._common import CONTROLLER_NAME, NoCapacityError

_TABLE_TTL_S = 1.0


class Router:
    def __init__(self, app_name: str, deployment_name: str, controller=None):
        self.app_name = app_name
        self.deployment_name = deployment_name
        self._controller = controller
        self._lock = threading.Lock()
        # signaled whenever _refresh lands a new replica table, so _pick
        # waiters wake immediately instead of polling on a sleep
        self._table_cv = threading.Condition(self._lock)
        self._replicas: Dict[str, Dict[str, Any]] = {}
        self._max_ongoing = 100
        self._inflight: Dict[str, int] = {}
        self._last_refresh = 0.0

    def _get_controller(self):
        if self._controller is None:
            self._controller = ray_tpu.get_actor(CONTROLLER_NAME)
        return self._controller

    def _refresh(self, force: bool = False):
        now = time.monotonic()
        if not force and now - self._last_refresh < _TABLE_TTL_S:
            return
        table = ray_tpu.get(
            self._get_controller().get_replica_table.remote(
                self.app_name, self.deployment_name), timeout=30.0)
        with self._lock:
            self._replicas = {r["replica_id"]: r
                              for r in table["replicas"]}
            self._max_ongoing = table.get("max_ongoing_requests", 100)
            for rid in list(self._inflight):
                if rid not in self._replicas:
                    del self._inflight[rid]
            self._last_refresh = now
            self._table_cv.notify_all()

    def _pick(self, model_id: Optional[str] = None) -> Dict[str, Any]:
        deadline = time.monotonic() + 30.0
        while True:
            self._refresh()
            with self._lock:
                cands = list(self._replicas.values())
                if cands:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise RuntimeError(
                        f"no RUNNING replicas of "
                        f"{self.app_name}:{self.deployment_name}")
                self._last_refresh = 0.0  # force re-pull next loop
                # wake as soon as any thread's _refresh lands replicas
                # (the timeout only bounds the controller re-poll cadence)
                self._table_cv.wait(timeout=min(0.25, remaining))
        if model_id is not None:
            warm = [c for c in cands if model_id in c.get("model_ids", ())]
            if warm:
                cands = warm
        # admission control on engine headroom: replicas whose decode
        # engine reports accepting=False (queue past the shed watermark)
        # are skipped; with NOBODY accepting, shed the request here —
        # the proxy turns NoCapacityError into 503 + Retry-After
        accepting = [c for c in cands
                     if not isinstance(c.get("engine"), dict)
                     or c["engine"].get("accepting", True)]
        if not accepting:
            retry = max(c["engine"].get("retry_after_s", 1.0)
                        for c in cands)
            raise NoCapacityError(
                f"all {len(cands)} replicas of "
                f"{self.app_name}:{self.deployment_name} are shedding "
                f"(engine queues past watermark)", retry_after_s=retry)
        cands = accepting
        if len(cands) == 1:
            return cands[0]
        a, b = random.sample(cands, 2)
        qa = self._inflight.get(a["replica_id"], 0)
        qb = self._inflight.get(b["replica_id"], 0)
        return a if qa <= qb else b

    def _assign_to(self, method: str, method_name: Optional[str], args,
                   kwargs, metadata, streaming: bool):
        model_id = (metadata or {}).get("multiplexed_model_id")
        replica = self._pick(model_id)
        rid = replica["replica_id"]
        with self._lock:
            self._inflight[rid] = self._inflight.get(rid, 0) + 1

        def done():
            with self._lock:
                n = self._inflight.get(rid, 1)
                self._inflight[rid] = max(0, n - 1)

        try:
            m = getattr(replica["handle"], method)
            if streaming:
                m = m.options(num_returns="streaming")
            ref = m.remote(method_name, args, kwargs, metadata or {})
        except BaseException:
            # a submission that never produced a ref must not count
            # against the replica forever (it would skew power-of-two
            # choice until the replica left the table)
            done()
            raise
        return ref, done

    def assign(self, method_name: Optional[str], args, kwargs,
               metadata: Optional[Dict[str, Any]] = None):
        """Submit to a chosen replica; returns (ObjectRef, done_cb)."""
        return self._assign_to("handle_request", method_name, args, kwargs,
                               metadata, streaming=False)

    def assign_streaming(self, method_name: Optional[str], args, kwargs,
                         metadata: Optional[Dict[str, Any]] = None):
        """Streaming submit; returns (ObjectRefGenerator, done_cb) — one
        ref per item the deployment yields."""
        return self._assign_to("handle_request_streaming", method_name,
                               args, kwargs, metadata, streaming=True)


_routers: Dict[Any, Router] = {}
_routers_lock = threading.Lock()


def get_router(app_name: str, deployment_name: str) -> Router:
    key = (app_name, deployment_name)
    with _routers_lock:
        r = _routers.get(key)
        if r is None:
            r = Router(app_name, deployment_name)
            _routers[key] = r
        return r


def reset_routers():
    with _routers_lock:
        _routers.clear()
