"""Router: picks a replica for each request and replays on failure.

Reference: python/ray/serve/_private/router.py:313 Router +
replica_scheduler/pow_2_scheduler.py:52 PowerOfTwoChoicesReplicaScheduler —
pick two random candidates, route to the one with the shorter queue.  Queue
lengths come from the controller's metrics probes (cached replica table)
plus a local in-flight count per replica, so the hot path makes NO extra
RPCs.  Multiplexed requests prefer replicas that already hold the model.

Fault tolerance lives here too: every request is a `_Submission` that
remembers how to re-issue itself.  When the assigned replica dies
mid-call (actor-died / worker-crashed / object-lost, or an optional
per-attempt deadline expires), the router ejects the replica from its
table and replays the request on a survivor, bounded by
``serve_replay_budget``; exhausting the budget surfaces the ORIGINAL
error.  Streaming requests resume by continuation: a deployment that
registers a continuation (``metadata["resume"]``) gets its remaining
call rewritten from the items already yielded — the built-in
``llm_tokens`` continuation replays ``prompt + tokens_so_far`` with the
sampling-key schedule offset, so greedy AND sampled decode continue
bitwise-identically and the client stream never restarts from token 0.
"""

from __future__ import annotations

import logging
import random
import threading
import time
import uuid
from typing import Any, Callable, Dict, Optional, Set

import ray_tpu

from .._private.config import cfg as _config
from ._common import CONTROLLER_NAME, NoCapacityError

logger = logging.getLogger(__name__)

_TABLE_TTL_S = 1.0
# a replay must not wait the full cold-start pick deadline: if no
# survivor appears quickly the caller wants the original error back
_REPLAY_PICK_TIMEOUT_S = 5.0

_FAILURE_TYPES = (ray_tpu.ActorDiedError, ray_tpu.WorkerCrashedError,
                  ray_tpu.ObjectLostError)
_FAILURE_NAMES = ("ActorDiedError", "WorkerCrashedError", "ObjectLostError")


def replica_failure(e: BaseException) -> bool:
    """True when `e` means the REPLICA is gone/unreachable (replayable),
    as opposed to the request itself failing (app exception, shed).
    Replica-side deaths can cross the task boundary wrapped, so the text
    match backstops the isinstance check."""
    if isinstance(e, _FAILURE_TYPES):
        return True
    if isinstance(e, (NoCapacityError, ValueError, TypeError)):
        return False
    txt = str(e)
    return any(name in txt for name in _FAILURE_NAMES)


# -- continuations -----------------------------------------------------------
# resume functions for streaming requests: (args, kwargs, yielded_items)
# -> (new_args, new_kwargs) for the remainder of the stream, or None when
# the yielded items already complete it.  Keyed by metadata["resume"].

_CONTINUATIONS: Dict[str, Callable] = {}


def register_continuation(name: str, fn: Callable) -> None:
    _CONTINUATIONS[name] = fn


def _resume_llm_tokens(args, kwargs, yielded):
    """Continuation for llm.stream_tokens(tokens, max_new_tokens,
    temperature, seed, top_k, eos_id, key_offset): fold the tokens the
    client already received into the prompt and offset the sampling-key
    schedule so the resumed decode draws the SAME keys the interrupted
    one would have — bitwise-identical continuation, greedy or sampled."""
    names = ("tokens", "max_new_tokens", "temperature", "seed", "top_k",
             "eos_id", "key_offset")
    bound = dict(zip(names, args))
    bound.update(kwargs)
    done = [int(t) for t in yielded]
    eos = bound.get("eos_id")
    if eos is not None and done and done[-1] == int(eos):
        return None                      # stream had already finished
    remaining = int(bound.get("max_new_tokens", 16)) - len(done)
    if remaining < 1:
        return None
    bound["tokens"] = list(bound.get("tokens") or ()) + done
    bound["max_new_tokens"] = remaining
    bound["key_offset"] = int(bound.get("key_offset") or 0) + len(done)
    return (), bound


register_continuation("llm_tokens", _resume_llm_tokens)


class _Submission:
    """One logical request: everything needed to re-issue it after the
    assigned replica dies.  `ref`/`rid`/`_done` describe the CURRENT
    attempt; `yielded` holds streamed items not yet folded into the args
    by a continuation."""

    __slots__ = ("method", "method_name", "args", "kwargs", "metadata",
                 "streaming", "request_id", "rid", "ref", "_done",
                 "attempts", "first_error", "failed_rids",
                 "yielded_count", "yielded")

    def __init__(self, method: str, method_name: Optional[str], args,
                 kwargs, metadata: Optional[Dict[str, Any]],
                 streaming: bool):
        self.method = method
        self.method_name = method_name
        self.args = tuple(args)
        self.kwargs = dict(kwargs or {})
        self.metadata = dict(metadata or {})
        self.metadata.setdefault("request_id", uuid.uuid4().hex[:16])
        self.streaming = streaming
        self.request_id: str = self.metadata["request_id"]
        self.rid: Optional[str] = None
        self.ref = None
        self._done: Optional[Callable] = None
        self.attempts = 0
        self.first_error: Optional[BaseException] = None
        self.failed_rids: Set[str] = set()
        self.yielded_count = 0
        self.yielded: list = []

    def fire_done(self):
        """Release the current attempt's in-flight slot (idempotent)."""
        cb, self._done = self._done, None
        if cb is not None:
            cb()


class Router:
    def __init__(self, app_name: str, deployment_name: str, controller=None):
        self.app_name = app_name
        self.deployment_name = deployment_name
        self._controller = controller
        self._lock = threading.Lock()
        # signaled whenever _refresh lands a new replica table, so _pick
        # waiters wake immediately instead of polling on a sleep
        self._table_cv = threading.Condition(self._lock)
        self._replicas: Dict[str, Dict[str, Any]] = {}  # guarded-by: _lock
        self._max_ongoing = 100
        self._inflight: Dict[str, int] = {}             # guarded-by: _lock
        self._last_refresh = 0.0

    def _get_controller(self):
        if self._controller is None:
            self._controller = ray_tpu.get_actor(CONTROLLER_NAME)
        return self._controller

    def _refresh(self, force: bool = False):
        now = time.monotonic()
        if not force and now - self._last_refresh < _TABLE_TTL_S:
            return
        table = ray_tpu.get(
            self._get_controller().get_replica_table.remote(
                self.app_name, self.deployment_name), timeout=30.0)
        with self._lock:
            self._replicas = {r["replica_id"]: r
                              for r in table["replicas"]}
            self._max_ongoing = table.get("max_ongoing_requests", 100)
            for rid in list(self._inflight):
                if rid not in self._replicas:
                    del self._inflight[rid]
            self._last_refresh = now
            self._table_cv.notify_all()

    def eject(self, rid: str, request_id: str = "", reason: str = ""):
        """Drop a replica the caller observed failing: it leaves the
        local table immediately (don't route more requests into a dead
        actor while the controller converges) and the next pick re-pulls
        the authoritative table."""
        with self._lock:
            existed = self._replicas.pop(rid, None) is not None
            self._inflight.pop(rid, None)
            self._last_refresh = 0.0
        if existed:
            logger.warning("serve replay: ejected replica %s (%s) "
                           "request=%s", rid, reason or "failure",
                           request_id)

    def _pick(self, model_id: Optional[str] = None,
              timeout_s: float = 30.0,
              exclude: Optional[Set[str]] = None) -> Dict[str, Any]:
        deadline = time.monotonic() + timeout_s
        exclude = exclude or set()
        while True:
            self._refresh()
            with self._lock:
                cands = [c for c in self._replicas.values()
                         if c["replica_id"] not in exclude]
                if cands:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise RuntimeError(
                        f"no RUNNING replicas of "
                        f"{self.app_name}:{self.deployment_name}")
                self._last_refresh = 0.0  # force re-pull next loop
                # wake as soon as any thread's _refresh lands replicas
                # (the timeout only bounds the controller re-poll cadence)
                self._table_cv.wait(timeout=min(0.25, remaining))
        if model_id is not None:
            warm = [c for c in cands if model_id in c.get("model_ids", ())]
            if warm:
                cands = warm
        # admission control on engine headroom: replicas whose decode
        # engine reports accepting=False (queue past the shed watermark)
        # are skipped; with NOBODY accepting, shed the request here —
        # the proxy turns NoCapacityError into 503 + Retry-After
        accepting = [c for c in cands
                     if not isinstance(c.get("engine"), dict)
                     or c["engine"].get("accepting", True)]
        if not accepting:
            retry = max((c["engine"].get("retry_after_s", 1.0)
                         for c in cands
                         if isinstance(c.get("engine"), dict)),
                        default=1.0)
            raise NoCapacityError(
                f"all {len(cands)} replicas of "
                f"{self.app_name}:{self.deployment_name} are shedding "
                f"(engine queues past watermark)", retry_after_s=retry)
        cands = accepting
        # drain preference, NOT refusal: a replica on a draining node
        # keeps serving as the fallback (zero-drop guarantee on a
        # single-node cluster) but loses traffic whenever a healthy
        # replica exists
        fresh = [c for c in cands if not c.get("draining")]
        if fresh:
            cands = fresh
        if len(cands) == 1:
            return cands[0]
        a, b = random.sample(cands, 2)
        with self._lock:
            qa = self._inflight.get(a["replica_id"], 0)
            qb = self._inflight.get(b["replica_id"], 0)
        return a if qa <= qb else b

    # -- submission / replay core -------------------------------------------

    def submit(self, method_name: Optional[str], args, kwargs,
               metadata: Optional[Dict[str, Any]] = None,
               streaming: bool = False) -> _Submission:
        """Pick a replica and submit; returns the `_Submission` that
        `call()` / `iter_stream()` consume (and replay on failure)."""
        sub = _Submission(
            "handle_request_streaming" if streaming else "handle_request",
            method_name, args, kwargs, metadata, streaming)
        return self._submit_attempt(sub)

    def _submit_attempt(self, sub: _Submission,
                        timeout_s: float = 30.0) -> _Submission:
        model_id = sub.metadata.get("multiplexed_model_id")
        replica = self._pick(model_id, timeout_s=timeout_s,
                             exclude=sub.failed_rids)
        rid = replica["replica_id"]
        with self._lock:
            self._inflight[rid] = self._inflight.get(rid, 0) + 1

        def done():
            with self._lock:
                n = self._inflight.get(rid, 1)
                self._inflight[rid] = max(0, n - 1)

        try:
            m = getattr(replica["handle"], sub.method)
            if sub.streaming:
                m = m.options(num_returns="streaming")
            ref = m.remote(sub.method_name, sub.args, sub.kwargs,
                           sub.metadata)
        except BaseException:
            # a submission that never produced a ref must not count
            # against the replica forever (it would skew power-of-two
            # choice until the replica left the table)
            done()
            raise
        sub.rid = rid
        sub.ref = ref
        sub.attempts += 1
        sub._done = done
        return sub

    def _replay(self, sub: _Submission, err: BaseException) -> None:
        """Account one failed attempt and resubmit to a survivor.
        Raises the ORIGINAL error when the replay budget is exhausted or
        no surviving replica takes the request."""
        if sub.first_error is None:
            sub.first_error = err
        sub.fire_done()
        if sub.rid is not None:
            sub.failed_rids.add(sub.rid)
            self.eject(sub.rid, request_id=sub.request_id,
                       reason=type(err).__name__)
        budget = _config().serve_replay_budget
        if sub.attempts > budget:
            logger.error(
                "serve replay: request %s exhausted replay budget "
                "(%d attempts); raising original error", sub.request_id,
                sub.attempts)
            raise sub.first_error
        logger.warning(
            "serve replay: request %s replaying (attempt %d) after %s "
            "on replica %s", sub.request_id, sub.attempts + 1,
            type(err).__name__, sub.rid)
        try:
            self._submit_attempt(sub, timeout_s=_REPLAY_PICK_TIMEOUT_S)
        except (RuntimeError, NoCapacityError) as e2:
            # nobody left to replay on: the replica failure is the story,
            # not the empty table it caused
            raise sub.first_error from e2

    def call(self, sub: _Submission,
             timeout_s: Optional[float] = 300.0) -> Any:
        """Resolve a unary submission, replaying across replica deaths.
        With ``serve_call_deadline_s`` set, an attempt that produces no
        answer within the deadline is treated as a dead replica too."""
        deadline = (None if timeout_s is None
                    else time.monotonic() + timeout_s)
        while True:
            per_call = _config().serve_call_deadline_s
            t = None
            if deadline is not None:
                t = max(0.0, deadline - time.monotonic())
            if per_call > 0:
                t = per_call if t is None else min(t, per_call)
            try:
                out = ray_tpu.get(sub.ref, timeout=t)
                sub.fire_done()
                return out
            except ray_tpu.GetTimeoutError:
                left = (None if deadline is None
                        else deadline - time.monotonic())
                if per_call > 0 and (left is None or left > 0):
                    err = ray_tpu.GetTimeoutError(
                        f"replica {sub.rid} unresponsive after "
                        f"{per_call:g}s (request {sub.request_id})")
                    self._replay(sub, err)
                    continue
                sub.fire_done()
                raise
            except Exception as e:
                if not replica_failure(e):
                    sub.fire_done()
                    raise
                self._replay(sub, e)

    def iter_stream(self, sub: _Submission,
                    item_timeout_s: float = 300.0):
        """Iterate a streaming submission's items, replaying/resuming
        across replica deaths.  Closing the generator early (client
        abandoned the stream) still releases the in-flight slot."""
        resume_key = sub.metadata.get("resume")
        cont = _CONTINUATIONS.get(resume_key) if resume_key else None
        try:
            while True:
                per = _config().serve_call_deadline_s
                t = min(item_timeout_s, per) if per > 0 else item_timeout_s
                try:
                    for ref in sub.ref:
                        item = ray_tpu.get(ref, timeout=t)
                        sub.yielded_count += 1
                        if cont is not None:
                            sub.yielded.append(item)
                        yield item
                    return
                except Exception as e:
                    timed_out = (per > 0
                                 and isinstance(e, ray_tpu.GetTimeoutError))
                    if not (replica_failure(e) or timed_out):
                        raise
                    if sub.yielded_count and cont is None:
                        # items already reached the client and nothing
                        # knows how to resume: replaying from scratch
                        # would re-send them
                        raise
                    if cont is not None and sub.yielded:
                        rewritten = cont(sub.args, sub.kwargs, sub.yielded)
                        if rewritten is None:
                            return       # stream was already complete
                        sub.args, sub.kwargs = rewritten
                        sub.yielded = []   # folded into args now
                    self._replay(sub, e)
                    logger.info(
                        "serve replay: request %s stream resumed at "
                        "item %d on replica %s", sub.request_id,
                        sub.yielded_count, sub.rid)
        finally:
            sub.fire_done()

    # -- legacy one-shot API (no replay) ------------------------------------

    def assign(self, method_name: Optional[str], args, kwargs,
               metadata: Optional[Dict[str, Any]] = None):
        """Submit to a chosen replica; returns (ObjectRef, done_cb)."""
        sub = self.submit(method_name, args, kwargs, metadata,
                          streaming=False)
        return sub.ref, sub.fire_done

    def assign_streaming(self, method_name: Optional[str], args, kwargs,
                         metadata: Optional[Dict[str, Any]] = None):
        """Streaming submit; returns (ObjectRefGenerator, done_cb) — one
        ref per item the deployment yields."""
        sub = self.submit(method_name, args, kwargs, metadata,
                          streaming=True)
        return sub.ref, sub.fire_done


_routers: Dict[Any, Router] = {}
_routers_lock = threading.Lock()


def get_router(app_name: str, deployment_name: str) -> Router:
    key = (app_name, deployment_name)
    with _routers_lock:
        r = _routers.get(key)
        if r is None:
            r = Router(app_name, deployment_name)
            _routers[key] = r
        return r


def reset_routers():
    with _routers_lock:
        _routers.clear()
