"""LLM serving: a continuous-batching inference engine behind Serve.

The reference serves LLMs by delegating to an external engine (vLLM) and
wiring it into Serve; here decoding is the framework's own jit program
(models/gpt.py), and by default each replica hosts a **continuous-
batching engine** over a **paged KV cache** (serve/_engine.py): one
fixed-shape compiled step program over a slot batch, sequences joining
at prefill and leaving at EOS/max-tokens at every decode step, pages
refcounted with live prompt-prefix sharing and copy-on-write.  Both the
request/response route and token streaming ride the same engine, so a
short request never waits behind a long one.

Engine selection (``RAY_TPU_SERVE_ENGINE`` or ``engine=`` at bind time):

  * ``paged`` (default) — continuous batching, paged KV arena;
  * ``contiguous`` — continuous batching over per-slot contiguous
    caches (the bitwise-parity baseline for the paged path);
  * ``static`` — the legacy ``serve.batch`` micro-batching path:
    requests grouped by (prompt_len, max_new, sampling params, seed),
    each group one stacked ``generate()`` call, streaming via a
    dedicated per-request prefill + fused sample/decode step loop.

All engine sizing knobs (slots, page size, arena pages, admission
watermarks) are the ``RAY_TPU_SERVE_*`` flags in _private/config.py.

Prompts and completions are token-id lists: tokenizers are deliberately
out of scope (bring your own; nothing here depends on one).
"""

from __future__ import annotations

import functools
from collections import OrderedDict
from typing import Any, Dict, List, Optional

from .._private.config import cfg as _config
from ._deployment import deployment
from .api import run
from .batching import batch

__all__ = ["LLMServer", "build_llm_app"]


def _bucket(n: int, step: int = 128) -> int:
    return ((n + step - 1) // step) * step


class _LLMServerImpl:
    """Deployment body.  cfg_kwargs are GPTConfig fields (or pass
    `preset="gpt2_small"`); params_loader() runs ON THE REPLICA (the
    driver never materializes the weights) and may return either a
    params tree, or a (GPTConfig, params) pair — which is exactly what
    models/hf.from_hf_gpt2 returns, so serving an HF checkpoint is
    `LLMServer().bind(params_loader=lambda: from_hf_gpt2("gpt2"))`."""

    def __init__(self, preset: str = "nano", cfg_kwargs: Optional[dict] = None,
                 params_loader=None, max_seq: int = 512,
                 engine: Optional[str] = None,
                 engine_kwargs: Optional[dict] = None):
        import jax

        from ray_tpu.models import gpt

        self._gpt = gpt
        user_cfg_kwargs = dict(cfg_kwargs or {})
        cfg_kwargs = dict(user_cfg_kwargs)
        cfg_kwargs.setdefault("max_seq", max_seq)
        self._cfg = getattr(gpt.GPTConfig, preset)(**cfg_kwargs)
        loaded = params_loader() if params_loader is not None else None
        if params_loader is not None and loaded is None:
            raise ValueError("params_loader returned None (missing "
                             "return?) — refusing to serve random "
                             "weights in its place")
        if isinstance(loaded, tuple):
            self._cfg, self._params = loaded
            if user_cfg_kwargs:
                # user overrides still apply on top of the loaded config
                import dataclasses

                self._cfg = dataclasses.replace(self._cfg,
                                                **user_cfg_kwargs)
        elif loaded is not None:
            self._params = loaded
        else:
            self._params = gpt.init(jax.random.PRNGKey(0), self._cfg)
        self._jax = jax
        # per-instance (NOT lru_cache on the method: a class-level cache
        # keyed by self would pin replaced replicas' full weights), and
        # bounded: a long-lived replica facing varied (max_new, temp,
        # top_k) tuples must not grow compile-cache memory without limit
        self._gen_cache: "OrderedDict[tuple, Any]" = OrderedDict()
        self._gen_cache_cap = _config().serve_gen_cache_cap
        self._engine_mode = engine or _config().serve_engine
        if self._engine_mode not in ("paged", "contiguous", "static"):
            raise ValueError(f"unknown engine {self._engine_mode!r}")
        self._engine_kwargs = dict(engine_kwargs or {})
        self._engine = None   # built lazily: direct construction (tests,
        #                       tooling) must not allocate the device arena

    def _get_engine(self):
        if self._engine is None:
            from ._engine import ContinuousEngine

            c = _config()
            kw = dict(cache=self._engine_mode,
                      max_slots=c.serve_max_slots,
                      page_size=c.serve_page_size,
                      num_pages=c.serve_num_pages,
                      max_total=c.serve_max_total,
                      queue_cap=c.serve_queue_cap,
                      shed_queue_depth=c.serve_shed_queue_depth,
                      retry_after_s=c.serve_retry_after_s,
                      prefill_bucket=c.serve_prefill_bucket,
                      stall_s=c.serve_engine_stall_s)
            kw.update(self._engine_kwargs)
            self._engine = ContinuousEngine(self._gpt, self._cfg,
                                            self._params, **kw)
        return self._engine

    def engine_stats(self) -> Optional[Dict[str, Any]]:
        """Scheduler snapshot for the replica metrics poll (None until
        the engine has processed its first request, or in static mode)."""
        if self._engine is None:
            return None
        return self._engine.engine_stats()

    def check_health(self):
        """Engine-level liveness probe (controller health loop): a hung
        jit step or dead scheduler thread raises here, which gets this
        replica restarted instead of timing out every request forever."""
        if self._engine is not None:
            self._engine.check_health()

    def prepare_shutdown(self, drain_s: float = 5.0) -> bool:
        """Graceful drain: stop admitting and let active decode slots
        finish before the controller kills the actor."""
        if self._engine is not None:
            return self._engine.drain(drain_s)
        return True

    @staticmethod
    def _request_id() -> Optional[str]:
        """Per-request id from the replica request context (proxy/handle
        propagate it via metadata) — threads it into engine stats so a
        replayed request is traceable across replicas."""
        from ._replica import _request_context

        ctx = _request_context.get()
        return (ctx or {}).get("request_id") if isinstance(ctx, dict) \
            else None

    def _cached(self, key, build):
        """LRU-bounded compiled-program cache (every jitted variant a
        replica ever builds goes through here, so the cap holds no
        matter which routes a client exercises)."""
        fn = self._gen_cache.get(key)
        if fn is None:
            fn = self._gen_cache[key] = build()
            while len(self._gen_cache) > self._gen_cache_cap:
                self._gen_cache.popitem(last=False)
        else:
            self._gen_cache.move_to_end(key)
        return fn

    def _gen_fn(self, max_new: int, temperature: float,
                top_k: Optional[int], max_seq: int):
        return self._cached(
            (max_new, temperature, top_k, max_seq),
            lambda: self._jax.jit(functools.partial(
                self._gpt.generate, cfg=self._cfg, max_new_tokens=max_new,
                temperature=temperature, top_k=top_k, max_seq=max_seq)))

    def _check_capacity(self, plen: int, max_new: int):
        if self._cfg.pos == "learned" and plen + max_new > self._cfg.max_seq:
            raise ValueError(
                f"prompt ({plen}) + max_new_tokens ({max_new}) exceeds "
                f"the model's learned-position capacity "
                f"({self._cfg.max_seq})")

    async def generate_batch(self, requests: List[Dict[str, Any]]
                             ) -> List[Dict[str, Any]]:
        """Group by (prompt_len, max_new, temperature, top_k): each group
        is one stacked generate() call."""
        import numpy as np

        groups: Dict[tuple, List[int]] = {}
        for i, r in enumerate(requests):
            key = (len(r["tokens"]), int(r.get("max_new_tokens", 16)),
                   float(r.get("temperature", 0.0)),
                   r.get("top_k"), int(r.get("seed", 0)))
            groups.setdefault(key, []).append(i)
        out: List[Optional[Dict[str, Any]]] = [None] * len(requests)
        for (plen, max_new, temp, top_k, seed), idxs in groups.items():
            self._check_capacity(plen, max_new)
            prompts = np.asarray([requests[i]["tokens"] for i in idxs],
                                 np.int32)
            fn = self._gen_fn(max_new, temp, top_k,
                              _bucket(plen + max_new))
            toks = np.asarray(fn(self._params, prompt=prompts,
                                 rng=self._jax.random.PRNGKey(seed)))
            for row, i in enumerate(idxs):
                out[i] = {"tokens": toks[row].tolist(),
                          "completion": toks[row, plen:].tolist(),
                          "batch_size": len(idxs)}
        return out

    def _prefill_fn(self, total: int):
        """One jit program for the whole prompt (a per-token Python
        prefill loop costs one dispatch + host sync per position —
        measured 75 ms/step through a remote-TPU tunnel vs one program
        for the lot).  Hidden-only through the stack; the D x V vocab
        projection (the fattest matmul in a small-model decode step)
        runs once, on the final position."""
        jax, gpt, cfg = self._jax, self._gpt, self._cfg

        def build():
            def prefill(params, cache, toks):        # toks [S] int32
                def body(c, t):
                    x, c = gpt._decode_hidden(params, c, t[None], cfg)
                    return c, x

                cache, xs = jax.lax.scan(body, cache, toks)
                logits = jax.numpy.einsum(
                    "bd,dv->bv", xs[-1].astype(cfg.dtype),
                    gpt._unembed_table(params, cfg))
                return logits, cache

            return jax.jit(prefill)

        return self._cached(("prefill", total), build)

    def _sample_body(self, logits, rkey, temperature, top_k):
        # gpt.sample_logits is the one sampling recipe — sharing it is
        # what makes stream/batched seed parity structural, not luck
        return self._gpt.sample_logits(logits, rkey, temperature, top_k)

    def _stream_step_fn(self, temperature: float, top_k: Optional[int],
                        total: int):
        """Fused sample+decode step: returns (token [1], next logits,
        cache).  Sampling runs ON DEVICE so the stream loop transfers a
        4-byte token id per step, not [1, V] logits; the sample recipe
        mirrors gpt.generate's exactly (same key schedule => identical
        completions for the same seed)."""
        jax, gpt, cfg = self._jax, self._gpt, self._cfg

        def build():
            def step(params, cache, logits, rkey):
                tok = self._sample_body(logits, rkey, temperature, top_k)
                new_logits, cache = gpt.decode_step(params, cache, tok,
                                                    cfg)
                return tok, new_logits, cache

            return jax.jit(step)

        return self._cached(("stream_step", temperature, top_k, total),
                            build)

    def _sample_fn(self, temperature: float, top_k: Optional[int]):
        """Sample-only program for the LAST token of a stream — it
        needs no further forward pass or cache write."""
        return self._cached(
            ("sample", temperature, top_k),
            lambda: self._jax.jit(functools.partial(
                self._sample_body, temperature=temperature,
                top_k=top_k)))

    def stream_tokens(self, tokens: List[int], max_new_tokens: int = 16,
                      temperature: float = 0.0, seed: int = 0,
                      top_k: Optional[int] = None,
                      eos_id: Optional[int] = None,
                      key_offset: int = 0):
        """Yield one sampled token id at a time (generator => Serve
        streams it as SSE/chunked over HTTP, itemwise over handles).
        Under the continuous engine the stream is fed by the shared
        slot-batch step loop (tokens appear as the scheduler emits
        them); in static mode it is a dedicated per-request decode
        loop.  Sampling shares gpt.sample_logits and the batched
        route's key schedule either way (token-exact in f32; at bf16,
        fusion-order rounding can flip near-tie logits)."""
        import numpy as np

        if self._engine_mode != "static":
            eng = self._get_engine()
            seq = eng.submit(tokens, max_new_tokens, temperature, seed,
                             top_k, eos_id=eos_id, stream=True,
                             request_id=self._request_id(),
                             key_offset=key_offset)
            yield from eng.stream(seq)
            return
        jax, gpt, cfg = self._jax, self._gpt, self._cfg
        if not tokens:
            raise ValueError("empty prompt: stream_tokens needs at "
                             "least one prompt token")
        self._check_capacity(len(tokens), max_new_tokens)
        total = _bucket(len(tokens) + max_new_tokens)
        cache = gpt.init_cache(cfg, 1, total)
        logits, cache = self._prefill_fn(total)(
            self._params, cache, np.asarray(tokens, np.int32))
        # same key schedule as the batched route (gpt.generate splits
        # rng into max_new_tokens keys up front): seed parity holds for
        # sampled decodes, not just greedy.  key_offset (router resume
        # continuation) re-derives the original request's schedule and
        # skips the keys its delivered tokens consumed.
        keys = jax.random.split(jax.random.PRNGKey(seed),
                                key_offset + max_new_tokens)[key_offset:]
        step = self._stream_step_fn(temperature, top_k, total)
        for i in range(max_new_tokens - 1):
            tok, logits, cache = step(self._params, cache, logits,
                                      keys[i])
            yield int(tok[0])
        if max_new_tokens > 0:   # the last sample needs no further
            tok = self._sample_fn(temperature, top_k)(  # forward pass
                logits, keys[max_new_tokens - 1])
            yield int(tok[0])

    async def _engine_generate(self, body: Dict[str, Any]
                               ) -> Dict[str, Any]:
        """Request/response through the continuous engine: submit is a
        queue append; the result future resolves on the engine thread
        when the sequence leaves its slot."""
        import asyncio

        seq = self._get_engine().submit(
            body["tokens"], int(body.get("max_new_tokens", 16)),
            float(body.get("temperature", 0.0)),
            int(body.get("seed", 0)), body.get("top_k"),
            eos_id=body.get("eos_id"), request_id=self._request_id())
        return await asyncio.wrap_future(seq.result)

    async def __call__(self, request):
        # handle calls pass the body dict directly; HTTP passes a Request
        is_http = not isinstance(request, dict)
        body = await request.json() if is_http else request
        if body.get("stream"):
            if is_http:
                # the HTTP proxy streams only ingresses whose __call__
                # is itself a generator function — that is the dedicated
                # stream app build_llm_app deploys next door
                raise ValueError(
                    "token streaming over HTTP lives on the companion "
                    "'<route>-stream' endpoint; this route is the "
                    "micro-batched JSON API")
            return self.stream_tokens(
                body["tokens"], int(body.get("max_new_tokens", 16)),
                float(body.get("temperature", 0.0)),
                int(body.get("seed", 0)), body.get("top_k"),
                body.get("eos_id"))
        if self._engine_mode != "static":
            return await self._engine_generate(body)
        return await self.generate_batch(body)


def LLMServer(**deployment_kwargs):
    """`LLMServer().bind(preset=..., ...)`-style factory: returns the
    deployment (decorate-once so serve.batch wraps generate_batch)."""
    cls = type("LLMServer", (_LLMServerImpl,), {})
    cls.generate_batch = batch(
        _LLMServerImpl.generate_batch,
        max_batch_size=deployment_kwargs.pop("max_batch_size", 8),
        batch_wait_timeout_s=deployment_kwargs.pop(
            "batch_wait_timeout_s", 0.02))
    return deployment(cls, **deployment_kwargs) \
        if deployment_kwargs else deployment(cls)


class _LLMStreamIngress:
    """HTTP token-streaming ingress: an async-GENERATOR __call__ (the
    proxy streams chunked/SSE only for generator ingresses), relaying
    the shared engine's stream_tokens through a streaming handle —
    weights live once, in the engine deployment."""

    def __init__(self, engine_app: str):
        self._engine_app = engine_app
        self._h = None

    async def __call__(self, request):
        import json as _json

        from .api import get_app_handle

        body = request if isinstance(request, dict) else \
            await request.json()
        if self._h is None:
            self._h = get_app_handle(self._engine_app)
        # resume="llm_tokens": if the engine replica dies mid-stream the
        # router replays prompt+tokens_so_far on a survivor, so the
        # client stream continues instead of restarting from token 0
        gen = self._h.options(
            stream=True, resume="llm_tokens").stream_tokens.remote(
            body["tokens"], int(body.get("max_new_tokens", 16)),
            float(body.get("temperature", 0.0)),
            int(body.get("seed", 0)), body.get("top_k"),
            body.get("eos_id"))
        async for tok in gen:
            yield _json.dumps({"token": int(tok)}) + "\n"


def build_llm_app(preset: str = "nano", *, route_prefix: str = "/llm",
                  name: str = "llm", stream: bool = True, **init_kwargs):
    """Deploy a generation endpoint: POST {tokens, max_new_tokens, ...}
    -> {tokens, completion} at `route_prefix` (micro-batched), plus a
    token-streaming endpoint at `route_prefix`-stream."""
    dep = LLMServer()
    h = run(dep.bind(preset=preset, **init_kwargs), name=name,
            route_prefix=route_prefix)
    if stream:
        run(deployment(_LLMStreamIngress).bind(name),
            name=f"{name}-stream", route_prefix=f"{route_prefix}-stream")
    return h
