"""Real gRPC ingress for Serve (reference: serve/_private/proxy.py:558
gRPCProxy + grpc_util.py gRPCServer/DummyServicer).

Users register their OWN generated proto services: each entry of
`servicer_functions` is a standard `add_<Service>Servicer_to_server`
callable.  It is invoked against a pass-through dummy servicer, and the
server subclass rewrites every registered method handler to route into
Serve instead — the request still travels as the user's proto message,
the reply as raw serialized bytes — so ANY grpc client (any language)
that speaks the user's proto can call a deployment.

Routing: the target application comes from the `application` request
metadata (falling back to the single deployed app); the deployment
method is the RPC method name (falling back to __call__).  Requires
grpcio; `serve.start_grpc` raises ImportError without it.
"""

from __future__ import annotations

import asyncio
import logging
import threading
from typing import Any, Dict, List, Optional

import ray_tpu

from ._proxy import _ControllerTableCache
from ._router import get_router

logger = logging.getLogger("ray_tpu.serve.grpc")


class _DummyServicer:
    """Accepts any method lookup (reference: grpc_util.py:73) — user
    add_*_to_server functions read handler callables off the servicer,
    which the server subclass discards and replaces with the router."""

    def __getattr__(self, attr):
        return None


async def _unimplemented_unary(request_iter, context):
    import grpc

    context.set_code(grpc.StatusCode.UNIMPLEMENTED)
    context.set_details("client-streaming RPCs are not supported by the "
                        "serve gRPC ingress")


async def _unimplemented_stream(request_iter, context):
    import grpc

    context.set_code(grpc.StatusCode.UNIMPLEMENTED)
    context.set_details("client-streaming RPCs are not supported by the "
                        "serve gRPC ingress")
    return
    yield  # pragma: no cover - makes this an async generator


def _make_server(handler_factory):
    """grpc.aio server whose add_generic_rpc_handlers rewrites every
    user method handler onto the Serve router (reference:
    grpc_util.py:9 gRPCServer)."""
    from grpc.aio._server import Server

    class _ServeGrpcServer(Server):
        def add_generic_rpc_handlers(self, generic_rpc_handlers):
            for gh in generic_rpc_handlers:
                handlers = getattr(gh, "_method_handlers", None)
                if not handlers:
                    continue
                replaced = {}
                for service_method, mh in handlers.items():
                    replaced[service_method] = mh._replace(
                        # reply bytes pass through un-reserialized: the
                        # deployment returns the user's proto (or bytes)
                        response_serializer=None,
                        unary_unary=handler_factory(service_method,
                                                    stream=False),
                        unary_stream=handler_factory(service_method,
                                                     stream=True),
                        # client-streaming RPCs are not routed (yet):
                        # answer UNIMPLEMENTED instead of invoking the
                        # dummy servicer's None
                        stream_unary=_unimplemented_unary,
                        stream_stream=_unimplemented_stream,
                    )
                gh._method_handlers = replaced
            super().add_generic_rpc_handlers(generic_rpc_handlers)

    return _ServeGrpcServer(None, (), (), (), None, None)


def _to_wire(out: Any) -> bytes:
    if isinstance(out, (bytes, bytearray)):
        return bytes(out)
    ser = getattr(out, "SerializeToString", None)
    if ser is not None:
        return ser()
    raise TypeError(
        f"gRPC deployment replies must be proto messages or bytes, "
        f"got {type(out).__name__}")


class GrpcProxy:
    """Serve's gRPC ingress actor; `ready()` returns (host, port)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 servicer_functions: Optional[List[Any]] = None,
                 servicer_blob: Optional[bytes] = None):
        import grpc

        if servicer_blob is not None:
            # pickled in the driver, opened HERE (the controller passes
            # the blob through untouched — no double deserialization)
            import cloudpickle

            servicer_functions = cloudpickle.loads(servicer_blob)

        self._table = _ControllerTableCache(
            "get_app_table", lambda t: dict(t["apps"]))
        self._loop = asyncio.new_event_loop()
        self._host = host
        self._bound_port: Optional[int] = None
        self._started = threading.Event()
        self._grpc = grpc
        self._server = None
        self._init_error: Optional[BaseException] = None

        def run():
            # grpc.aio server construction needs the thread's event loop
            # in place — build everything on the serving thread
            asyncio.set_event_loop(self._loop)
            try:
                server = _make_server(self._handler_factory)
                for fn in servicer_functions or []:
                    fn(_DummyServicer(), server)
                self._server = server
                self._bound_port = self._loop.run_until_complete(
                    self._start(port))
            except BaseException as e:
                self._init_error = e
                self._started.set()
                return
            self._started.set()
            self._loop.run_forever()

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="serve-grpc")
        self._thread.start()

    async def _start(self, port: int) -> int:
        bound = self._server.add_insecure_port(f"{self._host}:{port}")
        await self._server.start()
        return bound

    def ready(self):
        if not self._started.wait(timeout=30.0):
            raise RuntimeError("grpc proxy did not start within 30s")
        if self._init_error is not None:
            raise RuntimeError(
                f"grpc proxy failed to start: {self._init_error}")
        return (self._host, self._bound_port)

    # -- routing --------------------------------------------------------

    def _resolve(self, metadata) -> Optional[Dict[str, Any]]:
        apps = self._table.get()
        app = dict(metadata or {}).get("application")
        if app:
            return apps.get(app)
        if len(apps) == 1:
            return next(iter(apps.values()))
        return apps.get("default")

    def _call_blocking(self, service_method: str, request: Any, metadata):
        target = self._resolve(metadata)
        if target is None:
            raise KeyError(
                "no serve application matched; set the 'application' "
                "request metadata")
        method = service_method.rsplit("/", 1)[-1]
        router = get_router(target["app"], target["deployment"])
        ref, done = router.assign(method, (request,), {}, {})
        try:
            return ray_tpu.get(ref, timeout=300.0)
        finally:
            done()

    def _stream_blocking_iter(self, service_method: str, request: Any,
                              metadata):
        target = self._resolve(metadata)
        if target is None:
            raise KeyError(
                "no serve application matched; set the 'application' "
                "request metadata")
        router = get_router(target["app"], target["deployment"])
        gen, done = router.assign_streaming(
            service_method.rsplit("/", 1)[-1], (request,), {}, {})
        try:
            for ref in gen:
                yield ray_tpu.get(ref, timeout=300.0)
        finally:
            done()

    def _handler_factory(self, service_method: str, stream: bool):
        grpc = self._grpc

        async def unary_unary(request, context):
            loop = asyncio.get_event_loop()
            try:
                out = await loop.run_in_executor(
                    None, self._call_blocking, service_method, request,
                    dict(context.invocation_metadata()))
                return _to_wire(out)
            except KeyError as e:
                context.set_code(grpc.StatusCode.NOT_FOUND)
                context.set_details(str(e))
            except Exception as e:
                logger.exception("grpc call %s failed", service_method)
                context.set_code(grpc.StatusCode.INTERNAL)
                context.set_details(f"{type(e).__name__}: {e}")

        async def unary_stream(request, context):
            loop = asyncio.get_event_loop()
            meta = dict(context.invocation_metadata())
            it = iter(self._stream_blocking_iter(service_method, request,
                                                 meta))
            sentinel = object()

            def nxt():
                try:
                    return next(it)
                except StopIteration:
                    return sentinel

            try:
                while True:
                    item = await loop.run_in_executor(None, nxt)
                    if item is sentinel:
                        break
                    yield _to_wire(item)
            except KeyError as e:
                context.set_code(grpc.StatusCode.NOT_FOUND)
                context.set_details(str(e))
            except Exception as e:
                logger.exception("grpc stream %s failed", service_method)
                context.set_code(grpc.StatusCode.INTERNAL)
                context.set_details(f"{type(e).__name__}: {e}")

        return unary_stream if stream else unary_unary
