"""Real gRPC ingress for Serve (reference: serve/_private/proxy.py:558
gRPCProxy + grpc_util.py gRPCServer/DummyServicer).

Users register their OWN generated proto services: each entry of
`servicer_functions` is a standard `add_<Service>Servicer_to_server`
callable.  It is invoked against a pass-through dummy servicer, and the
server subclass rewrites every registered method handler to route into
Serve instead — the request still travels as the user's proto message,
the reply as raw serialized bytes — so ANY grpc client (any language)
that speaks the user's proto can call a deployment.

Routing: the target application comes from the `application` request
metadata (falling back to the single deployed app); the deployment
method is the RPC method name (falling back to __call__).  Requires
grpcio; `serve.start_grpc` raises ImportError without it.
"""

from __future__ import annotations

import asyncio
import logging
import threading
from typing import Any, Dict, List, Optional

import ray_tpu

from ._proxy import _ControllerTableCache
from ._router import get_router

logger = logging.getLogger("ray_tpu.serve.grpc")


class _DummyServicer:
    """Accepts any method lookup (reference: grpc_util.py:73) — user
    add_*_to_server functions read handler callables off the servicer,
    which the server subclass discards and replaces with the router."""

    def __getattr__(self, attr):
        return None


def _set_unimplemented(context):
    import grpc

    context.set_code(grpc.StatusCode.UNIMPLEMENTED)
    context.set_details("client-streaming RPCs are not supported by the "
                        "serve gRPC ingress")


async def _unimplemented_unary(request_iter, context):
    _set_unimplemented(context)


async def _unimplemented_stream(request_iter, context):
    _set_unimplemented(context)
    return
    yield  # pragma: no cover - makes this an async generator


def _make_server(handler_factory):
    """grpc.aio server whose add_generic_rpc_handlers rewrites every
    user method handler onto the Serve router (reference:
    grpc_util.py:9 gRPCServer)."""
    from grpc.aio._server import Server

    class _ServeGrpcServer(Server):
        def add_generic_rpc_handlers(self, generic_rpc_handlers):
            for gh in generic_rpc_handlers:
                handlers = getattr(gh, "_method_handlers", None)
                if not handlers:
                    continue
                replaced = {}
                for service_method, mh in handlers.items():
                    replaced[service_method] = mh._replace(
                        # reply bytes pass through un-reserialized: the
                        # deployment returns the user's proto (or bytes)
                        response_serializer=None,
                        unary_unary=handler_factory(service_method,
                                                    stream=False),
                        unary_stream=handler_factory(service_method,
                                                     stream=True),
                        # client-streaming RPCs are not routed (yet):
                        # answer UNIMPLEMENTED instead of invoking the
                        # dummy servicer's None
                        stream_unary=_unimplemented_unary,
                        stream_stream=_unimplemented_stream,
                    )
                gh._method_handlers = replaced
            super().add_generic_rpc_handlers(generic_rpc_handlers)

    return _ServeGrpcServer(None, (), (), (), None, None)


def _to_wire(out: Any) -> bytes:
    if isinstance(out, (bytes, bytearray)):
        return bytes(out)
    ser = getattr(out, "SerializeToString", None)
    if ser is not None:
        return ser()
    raise TypeError(
        f"gRPC deployment replies must be proto messages or bytes, "
        f"got {type(out).__name__}")


class GrpcProxy:
    """Serve's gRPC ingress actor; `ready()` returns (host, port)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 servicer_functions: Optional[List[Any]] = None,
                 servicer_blob: Optional[bytes] = None):
        import grpc

        if servicer_blob is not None:
            # pickled in the driver, opened HERE (the controller passes
            # the blob through untouched — no double deserialization)
            import cloudpickle

            servicer_functions = cloudpickle.loads(servicer_blob)

        self._table = _ControllerTableCache(
            "get_app_table", lambda t: dict(t["apps"]))
        self._loop = asyncio.new_event_loop()
        self._host = host
        self._bound_port: Optional[int] = None
        self._started = threading.Event()
        self._grpc = grpc
        self._server = None
        self._init_error: Optional[BaseException] = None

        def run():
            # grpc.aio server construction needs the thread's event loop
            # in place — build everything on the serving thread
            asyncio.set_event_loop(self._loop)
            try:
                server = _make_server(self._handler_factory)
                for fn in servicer_functions or []:
                    fn(_DummyServicer(), server)
                self._server = server
                self._bound_port = self._loop.run_until_complete(
                    self._start(port))
            except BaseException as e:
                self._init_error = e
                self._started.set()
                return
            self._started.set()
            self._loop.run_forever()

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="serve-grpc")
        self._thread.start()

    async def _start(self, port: int) -> int:
        bound = self._server.add_insecure_port(f"{self._host}:{port}")
        await self._server.start()
        return bound

    def ready(self):
        if not self._started.wait(timeout=30.0):
            raise RuntimeError("grpc proxy did not start within 30s")
        if self._init_error is not None:
            raise RuntimeError(
                f"grpc proxy failed to start: {self._init_error}")
        return (self._host, self._bound_port)

    # -- routing --------------------------------------------------------

    # the gRPC ingress routes RPC METHOD names: deployments exposing
    # only __call__ still serve them (opt-in resolution fallback flag;
    # handle callers keep strict AttributeError semantics)
    _CALL_META = {"_method_fallback": True}

    def _executor(self):
        # a DEDICATED pool: cancelled calls can pin a thread for up to
        # one ray_tpu.get timeout; on the loop's default executor that
        # would starve every other handler in the process
        pool = getattr(self, "_pool", None)
        if pool is None:
            from concurrent.futures import ThreadPoolExecutor

            pool = self._pool = ThreadPoolExecutor(
                max_workers=32, thread_name_prefix="serve-grpc")
        return pool

    def _router_for(self, service_method: str, metadata):
        """(router, method) or KeyError with the user-facing message."""
        app = dict(metadata or {}).get("application")

        def pick(apps):
            return (apps.get(app) if app
                    else (next(iter(apps.values())) if len(apps) == 1
                          else apps.get("default")))

        target = pick(self._table.get())
        if target is None:
            # a just-deployed app may postdate the cached table: refetch
            # once before answering NOT_FOUND (deploys are rare; the
            # refetch is one controller call)
            self._table.invalidate()
            target = pick(self._table.get())
        if target is None:
            raise KeyError(
                "no serve application matched; set the 'application' "
                "request metadata")
        return (get_router(target["app"], target["deployment"]),
                service_method.rsplit("/", 1)[-1])

    def _call_blocking(self, service_method: str, request: Any, metadata):
        router, method = self._router_for(service_method, metadata)
        ref, done = router.assign(method, (request,), {},
                                  dict(self._CALL_META))
        try:
            return ray_tpu.get(ref, timeout=300.0)
        finally:
            done()

    def _stream_blocking_iter(self, service_method: str, request: Any,
                              metadata):
        router, method = self._router_for(service_method, metadata)
        gen, done = router.assign_streaming(method, (request,), {},
                                            dict(self._CALL_META))
        try:
            for ref in gen:
                yield ray_tpu.get(ref, timeout=300.0)
        finally:
            done()

    def _set_error(self, context, e, service_method):
        grpc = self._grpc

        if isinstance(e, KeyError):
            context.set_code(grpc.StatusCode.NOT_FOUND)
            context.set_details(str(e))
        else:
            logger.exception("grpc %s failed", service_method)
            context.set_code(grpc.StatusCode.INTERNAL)
            context.set_details(f"{type(e).__name__}: {e}")

    def _handler_factory(self, service_method: str, stream: bool):
        async def unary_unary(request, context):
            loop = asyncio.get_event_loop()
            try:
                out = await loop.run_in_executor(
                    self._executor(), self._call_blocking, service_method,
                    request, dict(context.invocation_metadata()))
                return _to_wire(out)
            except Exception as e:
                self._set_error(context, e, service_method)

        async def unary_stream(request, context):
            loop = asyncio.get_event_loop()
            meta = dict(context.invocation_metadata())
            it = iter(self._stream_blocking_iter(service_method, request,
                                                 meta))
            sentinel = object()

            def nxt():
                try:
                    return next(it)
                except StopIteration:
                    return sentinel

            def safe_close(_f=None):
                try:
                    it.close()
                except Exception:
                    pass

            cf = None
            finished = False
            try:
                while True:
                    # submit() + wrap_future, NOT run_in_executor: the
                    # cleanup callback must attach to the CONCURRENT
                    # future, which completes only when nxt() really
                    # returns — a cancelled asyncio wrapper is "done"
                    # immediately, and closing then raises ValueError
                    # (generator still executing) and leaks the slot
                    cf = self._executor().submit(nxt)
                    item = await asyncio.wrap_future(cf)
                    if item is sentinel:
                        finished = True
                        break
                    yield _to_wire(item)
            except Exception as e:
                # nxt returned (by raising): the generator is idle, the
                # inline close runs its finally -> router done() fires
                self._set_error(context, e, service_method)
                finished = True
                safe_close()
            finally:
                if not finished and cf is not None:
                    # client cancellation abandoned the await mid-nxt:
                    # close the generator the moment the blocked next()
                    # returns — no polling thread, no extra pool task on
                    # the happy path
                    cf.add_done_callback(safe_close)

        return unary_stream if stream else unary_unary
