"""Model multiplexing: many models time-share one replica pool.

Reference: python/ray/serve/multiplex.py (_ModelMultiplexWrapper) +
serve/api.py get_multiplexed_model_id.  A replica lazily loads models via
the decorated loader and keeps an LRU of at most
``max_num_models_per_replica``; the router prefers replicas that already
hold the requested model (model ids travel in the controller's metrics
probes — see _controller._poll_replica_futures / _router._pick).
"""

from __future__ import annotations

import asyncio
import collections
import functools
import inspect
from typing import Any, Callable, List, Optional

from ._replica import _request_context


def get_multiplexed_model_id() -> str:
    """Inside a replica: the model id the current request asked for
    (reference: serve/api.py get_multiplexed_model_id)."""
    ctx = _request_context.get() or {}
    return ctx.get("multiplexed_model_id", "")


class _MultiplexCache:
    def __init__(self, loader: Callable, max_models: int):
        self.loader = loader
        self.max_models = max_models
        self.cache: "collections.OrderedDict[str, Any]" = \
            collections.OrderedDict()
        self.locks: dict = {}

    async def get(self, owner, model_id: str) -> Any:
        if model_id in self.cache:
            self.cache.move_to_end(model_id)
            return self.cache[model_id]
        lock = self.locks.setdefault(model_id, asyncio.Lock())
        async with lock:
            if model_id in self.cache:
                return self.cache[model_id]
            out = self.loader(owner, model_id) if owner is not None \
                else self.loader(model_id)
            if inspect.iscoroutine(out):
                out = await out
            while len(self.cache) >= self.max_models:
                old_id, old = self.cache.popitem(last=False)
                del_fn = getattr(old, "__del__", None)
                if del_fn is not None:
                    try:
                        del_fn()
                    except Exception:
                        pass
            self.cache[model_id] = out
            return out


def multiplexed(_func: Optional[Callable] = None, *,
                max_num_models_per_replica: int = 3):
    """Decorator on the replica's model-loader method."""

    def wrap(fn):
        params = list(inspect.signature(fn).parameters)
        is_method = bool(params) and params[0] == "self"
        attr = f"__serve_multiplex_{fn.__name__}"

        if is_method:
            @functools.wraps(fn)
            async def method_wrapper(self, model_id: str):
                cache = getattr(self, attr, None)
                if cache is None:
                    cache = _MultiplexCache(fn, max_num_models_per_replica)
                    setattr(self, attr, cache)
                return await cache.get(self, model_id)

            method_wrapper._serve_multiplex_attr = attr
            return method_wrapper

        holder: List[Optional[_MultiplexCache]] = [None]

        @functools.wraps(fn)
        async def func_wrapper(model_id: str):
            if holder[0] is None:
                holder[0] = _MultiplexCache(fn, max_num_models_per_replica)
            return await holder[0].get(None, model_id)

        func_wrapper._serve_multiplex_holder = holder
        return func_wrapper

    if _func is not None:
        return wrap(_func)
    return wrap


def loaded_model_ids(callable_obj: Any) -> List[str]:
    """Model ids currently cached on a replica's callable (for the
    controller's metrics probe -> router affinity)."""
    ids: List[str] = []
    for name in dir(type(callable_obj)):
        try:
            m = getattr(type(callable_obj), name)
        except AttributeError:
            continue
        attr = getattr(m, "_serve_multiplex_attr", None)
        if attr:
            cache = getattr(callable_obj, attr, None)
            if cache is not None:
                ids.extend(cache.cache.keys())
    return ids
