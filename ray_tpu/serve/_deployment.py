"""@serve.deployment decorator, Deployment, and application binding.

Reference: python/ray/serve/api.py:240 @serve.deployment,
serve/deployment.py Deployment.bind building the deployment DAG.  An
`Application` is the bound DAG; `serve.run` topologically instantiates it,
replacing nested bound nodes in init args with `DeploymentHandle`s.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ._common import AutoscalingConfig


@dataclass
class Deployment:
    func_or_class: Any
    name: str
    num_replicas: int = 1
    user_config: Optional[Any] = None
    max_ongoing_requests: int = 100
    autoscaling_config: Optional[AutoscalingConfig] = None
    ray_actor_options: Dict[str, Any] = field(default_factory=dict)
    health_check_period_s: float = 10.0
    init_args: Tuple = ()
    init_kwargs: Dict[str, Any] = field(default_factory=dict)

    def options(self, **kwargs) -> "Deployment":
        d = copy.copy(self)
        for k, v in kwargs.items():
            if not hasattr(d, k):
                raise ValueError(f"unknown deployment option {k!r}")
            setattr(d, k, v)
        if isinstance(d.autoscaling_config, dict):
            d.autoscaling_config = AutoscalingConfig(**d.autoscaling_config)
        return d

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)

    @property
    def is_function(self) -> bool:
        return not isinstance(self.func_or_class, type)


class Application:
    """A bound deployment node; init args may contain other Applications
    (composition — reference: serve model composition docs)."""

    def __init__(self, deployment: Deployment, args: Tuple,
                 kwargs: Dict[str, Any]):
        self._deployment = deployment
        self._args = args
        self._kwargs = kwargs

    @property
    def name(self) -> str:
        return self._deployment.name

    def _flatten(self) -> List["Application"]:
        """All nodes, dependencies first, deduped by deployment name."""
        seen: Dict[int, "Application"] = {}
        order: List["Application"] = []

        def walk(node: "Application"):
            if id(node) in seen:
                return
            seen[id(node)] = node
            for a in list(node._args) + list(node._kwargs.values()):
                if isinstance(a, Application):
                    walk(a)
            order.append(node)

        walk(self)
        names = set()
        for n in order:
            if n.name in names:
                raise ValueError(
                    f"duplicate deployment name {n.name!r} in application")
            names.add(n.name)
        return order


def deployment(_func_or_class: Optional[Callable] = None, *,
               name: Optional[str] = None, num_replicas: int = 1,
               user_config: Optional[Any] = None,
               max_ongoing_requests: int = 100,
               autoscaling_config: Optional[Any] = None,
               ray_actor_options: Optional[Dict[str, Any]] = None,
               health_check_period_s: float = 10.0):
    """Decorator converting a class or function into a Deployment
    (reference: serve/api.py:240)."""
    if isinstance(autoscaling_config, dict):
        autoscaling_config = AutoscalingConfig(**autoscaling_config)
    if autoscaling_config is not None and num_replicas == 1:
        num_replicas = autoscaling_config.min_replicas

    def wrap(obj):
        return Deployment(
            func_or_class=obj,
            name=name or getattr(obj, "__name__", "deployment"),
            num_replicas=num_replicas,
            user_config=user_config,
            max_ongoing_requests=max_ongoing_requests,
            autoscaling_config=autoscaling_config,
            ray_actor_options=dict(ray_actor_options or {}),
            health_check_period_s=health_check_period_s,
        )

    if _func_or_class is not None:
        return wrap(_func_or_class)
    return wrap
