"""@serve.batch: transparent request batching inside a replica.

Reference: python/ray/serve/batching.py (692 LoC) — callers invoke the
wrapped method with a single item and get a single result; the wrapper
pools concurrent calls into a list, invokes the underlying function once
per batch, and scatters results.  On TPU replicas this is the mechanism
that turns concurrent single requests into one MXU-efficient batched
forward pass of the compiled program.
"""

from __future__ import annotations

import asyncio
import functools
import inspect
from typing import Any, Callable, List, Optional


class _BatchQueue:
    def __init__(self, fn: Callable, max_batch_size: int,
                 batch_wait_timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.batch_wait_timeout_s = batch_wait_timeout_s
        self.queue: Optional[asyncio.Queue] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._flusher: Optional[asyncio.Task] = None

    def _ensure(self):
        # bound to the loop actually running the request; a dead flusher
        # (raised, or cancelled when a previous loop was torn down — the
        # between-tests case) or a loop change re-arms instead of
        # silently queueing onto a task nobody is draining
        loop = asyncio.get_running_loop()
        if (self.queue is not None and self._loop is loop
                and self._flusher is not None
                and not self._flusher.done()):
            return
        if self.queue is not None:
            err: BaseException
            if (self._flusher is not None and self._flusher.done()
                    and not self._flusher.cancelled()
                    and self._flusher.exception() is not None):
                err = self._flusher.exception()
            else:
                err = RuntimeError(
                    "serve.batch flusher died (event loop torn down?)")
            self._fail_pending(err)
        self._loop = loop
        self.queue = asyncio.Queue()
        self._flusher = loop.create_task(self._flush_loop())

    def _fail_pending(self, err: BaseException):
        """Propagate a flusher death to everything still queued — their
        futures may belong to an already-closed loop, so failures to
        set are swallowed (the awaiter is gone with its loop)."""
        while self.queue is not None and not self.queue.empty():
            _, f = self.queue.get_nowait()
            try:
                if not f.done():
                    f.set_exception(err)
            except Exception:
                pass

    async def submit(self, item: Any) -> Any:
        self._ensure()
        fut = asyncio.get_running_loop().create_future()
        self.queue.put_nowait((item, fut))
        return await fut

    async def _flush_loop(self):
        while True:
            item, fut = await self.queue.get()
            batch = [(item, fut)]
            try:
                deadline = asyncio.get_event_loop().time() \
                    + self.batch_wait_timeout_s
                while len(batch) < self.max_batch_size:
                    remaining = deadline - asyncio.get_event_loop().time()
                    if remaining <= 0:
                        break
                    try:
                        batch.append(await asyncio.wait_for(
                            self.queue.get(), timeout=remaining))
                    except asyncio.TimeoutError:
                        break
                out = self.fn([b[0] for b in batch])
                if inspect.iscoroutine(out):
                    out = await out
                if not isinstance(out, (list, tuple)) \
                        or len(out) != len(batch):
                    raise TypeError(
                        f"@serve.batch function must return a list of "
                        f"{len(batch)} results, got {type(out).__name__}")
                for (_, f), r in zip(batch, out):
                    if not f.done():
                        f.set_result(r)
            except BaseException as e:
                # fn errors scatter to the batch and the flusher lives
                # on; cancellation (loop teardown) also fails the batch
                # it was holding — hung futures were the old failure
                # mode — then propagates so _ensure can re-arm later
                for _, f in batch:
                    try:
                        if not f.done():
                            f.set_exception(e)
                    except Exception:
                        pass
                if isinstance(e, (asyncio.CancelledError, GeneratorExit)):
                    raise


def batch(_func: Optional[Callable] = None, *, max_batch_size: int = 10,
          batch_wait_timeout_s: float = 0.01):
    """Decorator for (async) methods taking a List of items and returning a
    List of results; callers pass single items."""

    def wrap(fn):
        attr = f"__serve_batch_queue_{fn.__name__}"

        if _is_method(fn):
            @functools.wraps(fn)
            async def method_wrapper(self, item):
                q = getattr(self, attr, None)
                if q is None:
                    q = _BatchQueue(
                        lambda items: fn(self, items),
                        max_batch_size, batch_wait_timeout_s)
                    setattr(self, attr, q)
                return await q.submit(item)

            method_wrapper._is_serve_batch = True
            return method_wrapper

        q_holder: List[Optional[_BatchQueue]] = [None]

        @functools.wraps(fn)
        async def func_wrapper(item):
            if q_holder[0] is None:
                q_holder[0] = _BatchQueue(fn, max_batch_size,
                                          batch_wait_timeout_s)
            return await q_holder[0].submit(item)

        func_wrapper._is_serve_batch = True
        return func_wrapper

    if _func is not None:
        return wrap(_func)
    return wrap


def _is_method(fn: Callable) -> bool:
    params = list(inspect.signature(fn).parameters)
    return bool(params) and params[0] == "self"
