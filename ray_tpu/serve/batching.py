"""@serve.batch: transparent request batching inside a replica.

Reference: python/ray/serve/batching.py (692 LoC) — callers invoke the
wrapped method with a single item and get a single result; the wrapper
pools concurrent calls into a list, invokes the underlying function once
per batch, and scatters results.  On TPU replicas this is the mechanism
that turns concurrent single requests into one MXU-efficient batched
forward pass of the compiled program.
"""

from __future__ import annotations

import asyncio
import functools
import inspect
from typing import Any, Callable, List, Optional


class _BatchQueue:
    def __init__(self, fn: Callable, max_batch_size: int,
                 batch_wait_timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.batch_wait_timeout_s = batch_wait_timeout_s
        self.queue: Optional[asyncio.Queue] = None
        self._flusher: Optional[asyncio.Task] = None

    def _ensure(self):
        # bound to whichever loop first executes a request
        if self.queue is None:
            self.queue = asyncio.Queue()
            self._flusher = asyncio.get_event_loop().create_task(
                self._flush_loop())

    async def submit(self, item: Any) -> Any:
        self._ensure()
        fut = asyncio.get_event_loop().create_future()
        self.queue.put_nowait((item, fut))
        return await fut

    async def _flush_loop(self):
        while True:
            item, fut = await self.queue.get()
            batch = [(item, fut)]
            deadline = asyncio.get_event_loop().time() \
                + self.batch_wait_timeout_s
            while len(batch) < self.max_batch_size:
                remaining = deadline - asyncio.get_event_loop().time()
                if remaining <= 0:
                    break
                try:
                    batch.append(await asyncio.wait_for(
                        self.queue.get(), timeout=remaining))
                except asyncio.TimeoutError:
                    break
            items = [b[0] for b in batch]
            futs = [b[1] for b in batch]
            try:
                out = self.fn(items)
                if inspect.iscoroutine(out):
                    out = await out
                if not isinstance(out, (list, tuple)) \
                        or len(out) != len(items):
                    raise TypeError(
                        f"@serve.batch function must return a list of "
                        f"{len(items)} results, got {type(out).__name__}")
                for f, r in zip(futs, out):
                    if not f.done():
                        f.set_result(r)
            except BaseException as e:
                for f in futs:
                    if not f.done():
                        f.set_exception(e)


def batch(_func: Optional[Callable] = None, *, max_batch_size: int = 10,
          batch_wait_timeout_s: float = 0.01):
    """Decorator for (async) methods taking a List of items and returning a
    List of results; callers pass single items."""

    def wrap(fn):
        attr = f"__serve_batch_queue_{fn.__name__}"

        if _is_method(fn):
            @functools.wraps(fn)
            async def method_wrapper(self, item):
                q = getattr(self, attr, None)
                if q is None:
                    q = _BatchQueue(
                        lambda items: fn(self, items),
                        max_batch_size, batch_wait_timeout_s)
                    setattr(self, attr, q)
                return await q.submit(item)

            method_wrapper._is_serve_batch = True
            return method_wrapper

        q_holder: List[Optional[_BatchQueue]] = [None]

        @functools.wraps(fn)
        async def func_wrapper(item):
            if q_holder[0] is None:
                q_holder[0] = _BatchQueue(fn, max_batch_size,
                                          batch_wait_timeout_s)
            return await q_holder[0].submit(item)

        func_wrapper._is_serve_batch = True
        return func_wrapper

    if _func is not None:
        return wrap(_func)
    return wrap


def _is_method(fn: Callable) -> bool:
    params = list(inspect.signature(fn).parameters)
    return bool(params) and params[0] == "self"
