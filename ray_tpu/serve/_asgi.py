"""ASGI ingress: serve a FastAPI/Starlette/any-ASGI app as a deployment.

Reference: python/ray/serve/api.py:164 @serve.ingress + the proxy's ASGI
plumbing (serve/_private/proxy.py:864 receive_asgi_messages).  The ASGI
app executes INSIDE the replica; its response events stream back to the
HTTP proxy through the framework's streaming-generator plane, so chunked
and SSE responses reach the client as the app produces them.

Protocol between replica and proxy: the wrapped deployment's __call__
yields ("__asgi_meta__", status, headers) first, then raw body chunks.
"""

from __future__ import annotations

import asyncio
import queue as _queue
import threading
import time
import urllib.parse
from typing import Any

ASGI_META = "__asgi_meta__"


# max seconds between ASGI events before the stream is declared wedged;
# per-event (an active SSE stream with regular frames never trips it)
IDLE_TIMEOUT_S = 300.0
# bounded send queue: backpressure for apps producing faster than the
# consumer drains (send() blocks the app until the wire catches up)
QUEUE_DEPTH = 64


def run_asgi(app, request):
    """Generator driving one ASGI request; yields meta then body chunks.

    The app runs on a private event loop in a side thread; `send` events
    flow through a bounded queue so a chunk yielded by the app is emitted
    here (and on the wire) before the app finishes.  ASGI semantics
    honored: `receive` delivers the body once then blocks until
    disconnect (so `request.is_disconnected()` loops work); abandoning
    this generator (client gone) signals http.disconnect and unblocks a
    full send queue, so the app thread exits instead of leaking."""
    events: "_queue.Queue" = _queue.Queue(maxsize=QUEUE_DEPTH)
    stop_evt = threading.Event()
    body = request._body or b""
    body_sent = [False]

    async def receive():
        if not body_sent[0]:
            body_sent[0] = True
            return {"type": "http.request", "body": body,
                    "more_body": False}
        while not stop_evt.is_set():
            await asyncio.sleep(0.1)
        return {"type": "http.disconnect"}

    async def send(message):
        while True:
            if stop_evt.is_set():
                raise ConnectionError("client disconnected")
            try:
                events.put(message, timeout=0.25)
                return
            except _queue.Full:
                continue

    scope = {
        "type": "http",
        "asgi": {"version": "3.0", "spec_version": "2.3"},
        "http_version": "1.1",
        "method": request.method,
        "path": request.route_path,
        "raw_path": request.route_path.encode(),
        "root_path": "",
        "scheme": "http",
        "query_string": (getattr(request, "query_string", b"")
                         or urllib.parse.urlencode(
                             request.query_params).encode()),
        "headers": [(k.lower().encode(), str(v).encode())
                    for k, v in getattr(request, "header_pairs", None)
                    or request.headers.items()],
        "client": None,
        "server": None,
    }

    def run():
        loop = asyncio.new_event_loop()
        try:
            loop.run_until_complete(app(scope, receive, send))
            events.put({"type": "__done__"}, timeout=IDLE_TIMEOUT_S)
        except BaseException as e:
            try:
                events.put({"type": "__error__",
                            "error": f"{type(e).__name__}: {e}"},
                           timeout=1.0)
            except _queue.Full:
                pass
        finally:
            loop.close()

    t = threading.Thread(target=run, daemon=True, name="serve-asgi")
    t.start()
    started = False
    try:
        while True:
            try:
                ev = events.get(timeout=IDLE_TIMEOUT_S)
            except _queue.Empty:
                raise TimeoutError(
                    "ASGI app produced no event within the idle "
                    "timeout") from None
            typ = ev.get("type")
            if typ == "http.response.start":
                headers = [
                    (k.decode() if isinstance(k, bytes) else str(k),
                     v.decode() if isinstance(v, bytes) else str(v))
                    for k, v in ev.get("headers", [])]
                started = True
                yield (ASGI_META, int(ev.get("status", 200)), headers)
            elif typ == "http.response.body":
                b = ev.get("body", b"")
                if b:
                    yield bytes(b)
                if not ev.get("more_body"):
                    break
            elif typ == "__done__":
                break
            elif typ == "__error__":
                if not started:
                    yield (ASGI_META, 500,
                           [("content-type", "text/plain")])
                yield f"ASGI app failed: {ev['error']}".encode()
                break
    finally:
        # normal end, error, OR abandoned generator (GeneratorExit when
        # the client disconnects): tell the app, unblock its sends
        stop_evt.set()
        t.join(timeout=5)


def ingress(asgi_app):
    """Class decorator: the deployment serves `asgi_app` over HTTP
    (reference: serve/api.py:164 @serve.ingress(app)).

    The replica's instance is published as ``asgi_app.state.serve_deployment``
    (when the app has a ``state``, as FastAPI/Starlette do) so route
    functions can reach warm per-replica state.
    """

    def decorator(cls):
        if not isinstance(cls, type):
            raise TypeError("@serve.ingress decorates a class; for a bare "
                            "ASGI app use serve.ingress(app)(object)")

        class _ASGIIngress(cls):
            __serve_asgi__ = True

            def __init__(self, *args: Any, **kwargs: Any):
                super().__init__(*args, **kwargs)
                state = getattr(asgi_app, "state", None)
                if state is not None:
                    state.serve_deployment = self

            def __call__(self, request):
                return run_asgi(asgi_app, request)

        _ASGIIngress.__name__ = cls.__name__
        _ASGIIngress.__qualname__ = getattr(cls, "__qualname__",
                                            cls.__name__)
        # the wrapper is defined HERE, so its __module__ would be this
        # framework module — keep the user's module so the by-value
        # pickling registration in serve.run sees driver-only code; the
        # app object itself may live in yet another driver-only module,
        # register its class too (FastAPI etc. are installed libs and
        # skipped by the helper)
        _ASGIIngress.__module__ = getattr(cls, "__module__",
                                          _ASGIIngress.__module__)
        from ray_tpu._private.common import _ensure_picklable_by_value

        # the app itself, not type(app): instances resolve __module__
        # through their class, and function-style ASGI apps carry their
        # defining module directly (type() would say builtins.function)
        _ensure_picklable_by_value(asgi_app)
        return _ASGIIngress

    return decorator
