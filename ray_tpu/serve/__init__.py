"""ray_tpu.serve: online model serving.

Reference: python/ray/serve/ (73k LoC) — controller + proxy + replica
actors, p2c routing, autoscaling, batching, multiplexing, composition via
DeploymentHandle.  TPU-native angle: replicas hold *compiled* jax programs;
@serve.batch turns concurrent requests into MXU-shaped batches; multiplexed
replicas LRU-swap model weights in HBM.
"""

from ._common import AutoscalingConfig
from ._deployment import Application, Deployment, deployment
from .schema import (ServeApplicationSchema, ServeDeploySchema,
                     deploy_config, deploy_config_file)
from ._asgi import ingress
from ._handle import (DeploymentHandle, DeploymentResponse,
                      DeploymentResponseGenerator)
from ._proxy import Request, Response, RpcClient
from .api import (delete, get_app_handle, get_deployment_handle, run,
                  shutdown, start, start_grpc, start_rpc_proxy, status)
from .batching import batch
from . import llm  # noqa: F401  (serve.llm.LLMServer / build_llm_app)
from .multiplex import get_multiplexed_model_id, multiplexed

__all__ = [
    "Application", "AutoscalingConfig", "Deployment", "DeploymentHandle",
    "DeploymentResponse", "DeploymentResponseGenerator", "ingress",
    "Request", "Response", "RpcClient", "batch",
    "delete", "deployment", "get_app_handle", "get_deployment_handle",
    "get_multiplexed_model_id", "multiplexed", "run", "shutdown", "start",
    "start_grpc", "start_rpc_proxy", "status",
    "ServeApplicationSchema", "ServeDeploySchema", "deploy_config",
    "deploy_config_file",
]
