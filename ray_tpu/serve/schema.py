"""Declarative serve config: schema + YAML/dict deploy.

Reference parity: python/ray/serve/schema.py (ServeDeploySchema /
ServeApplicationSchema / DeploymentSchema pydantic models) and the
``serve deploy config.yaml`` CLI flow — a config file describes
applications by import path with per-deployment overrides; deploying
reconciles the cluster to the declared state.

Config shape (same field names as the reference)::

    applications:
      - name: app1
        import_path: mypkg.module:app      # an Application or Deployment
        route_prefix: /app1
        args: {}                           # passed to an app *builder*
        deployments:                       # per-deployment overrides
          - name: Model
            num_replicas: 3
            max_ongoing_requests: 16
"""

from __future__ import annotations

import importlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ._deployment import Application, Deployment

_ALLOWED_OVERRIDES = ("num_replicas", "user_config",
                      "max_ongoing_requests", "autoscaling_config",
                      "ray_actor_options", "health_check_period_s")


@dataclass
class DeploymentSchema:
    name: str
    overrides: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def parse(cls, d: Dict[str, Any]) -> "DeploymentSchema":
        d = dict(d)
        name = d.pop("name", None)
        if not name:
            raise ValueError("deployment override needs a 'name'")
        unknown = set(d) - set(_ALLOWED_OVERRIDES)
        if unknown:
            raise ValueError(
                f"unknown deployment fields for {name!r}: {sorted(unknown)}")
        return cls(name=name, overrides=d)


@dataclass
class ServeApplicationSchema:
    name: str
    import_path: str
    route_prefix: Optional[str] = None
    args: Dict[str, Any] = field(default_factory=dict)
    deployments: List[DeploymentSchema] = field(default_factory=list)

    @classmethod
    def parse(cls, d: Dict[str, Any]) -> "ServeApplicationSchema":
        if "import_path" not in d:
            raise ValueError(
                f"application {d.get('name', '?')!r} needs 'import_path'")
        return cls(
            name=d.get("name", "default"),
            import_path=d["import_path"],
            route_prefix=d.get("route_prefix"),
            args=dict(d.get("args") or {}),
            deployments=[DeploymentSchema.parse(x)
                         for x in d.get("deployments") or []],
        )


@dataclass
class ServeDeploySchema:
    applications: List[ServeApplicationSchema]

    @classmethod
    def parse(cls, d: Dict[str, Any]) -> "ServeDeploySchema":
        apps = d.get("applications")
        if not apps:
            raise ValueError("config needs a non-empty 'applications' list")
        parsed = [ServeApplicationSchema.parse(a) for a in apps]
        names = [a.name for a in parsed]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate application names: {names}")
        return cls(applications=parsed)


def import_attr(import_path: str):
    """'pkg.module:attr' (or dotted fallback) -> the attribute."""
    if ":" in import_path:
        mod_name, attr = import_path.split(":", 1)
    else:
        mod_name, _, attr = import_path.rpartition(".")
    mod = importlib.import_module(mod_name)
    obj = mod
    for part in attr.split("."):
        obj = getattr(obj, part)
    return obj


def build_app(schema: ServeApplicationSchema) -> Application:
    """Materialize an Application from its import path + overrides."""
    target = import_attr(schema.import_path)
    if callable(target) and not isinstance(target,
                                           (Application, Deployment)):
        target = target(**schema.args)  # app builder function
    if isinstance(target, Deployment):
        target = target.bind()
    if not isinstance(target, Application):
        raise TypeError(
            f"{schema.import_path} resolved to {type(target).__name__}, "
            "expected an Application, Deployment, or builder returning one")
    if schema.deployments:
        by_name = {d.name: d.overrides for d in schema.deployments}
        for node in target._flatten():
            ov = by_name.pop(node.name, None)
            if ov:
                node._deployment = node._deployment.options(**ov)
        if by_name:
            raise ValueError(
                f"overrides for unknown deployments: {sorted(by_name)}")
    return target


def deploy_config(config: Dict[str, Any]) -> List[str]:
    """Deploy every application in a config dict; returns app names
    (reference: `serve deploy` -> controller deploy_apps)."""
    from . import api

    schema = ServeDeploySchema.parse(config)
    deployed = []
    for app in schema.applications:
        application = build_app(app)
        kwargs = ({} if app.route_prefix is None
                  else {"route_prefix": app.route_prefix})
        api.run(application, name=app.name, **kwargs)
        deployed.append(app.name)
    return deployed


def deploy_config_file(path: str) -> List[str]:
    import yaml

    with open(path) as f:
        return deploy_config(yaml.safe_load(f))
