"""Continuous-batching decode engine with a paged KV cache.

The static `serve.batch` path admits requests only at batch boundaries:
one long sequence stalls every short one, and the device idles between
batches.  This module is the iteration-level scheduler that replaces it
(the vLLM/Orca recipe, per the Gemma-on-TPU serving comparison in
PAPERS.md): a fixed-shape compiled step program runs over a batch of
**slots**; sequences join at prefill and leave at EOS/max-tokens, at
*every* decode step, so the step program never recompiles as traffic
comes and goes.

Memory is a **paged arena** (models/gpt.py init_paged_cache): fixed-size
pages in one preallocated device array, per-slot page tables gathered
inside the decode step.  Pages are refcounted through a free list;
full prompt pages register in a prefix table so live sequences with a
common prompt prefix share pages, with copy-on-write when a new
sequence must write into a shared page (the exact-duplicate-prompt
case: everything is shared but the last prompt position must be
recomputed to produce logits).  Page 0 is the reserved null page —
inactive slots write there and their sampled tokens are discarded
host-side, which is what lets the step program keep one static shape.

A contiguous slot-cache mode (`cache="contiguous"`) runs the same
scheduler over models/gpt.init_slot_cache; the paged path gathers its
pages into the identical [B, H, S, dh] attention view, so greedy decode
is bitwise-identical between the two — the parity tests in
tests/test_serve_continuous.py pin that.

Everything device-facing runs on one daemon thread (the engine loop);
`submit` is thread-safe and hands back a `_Sequence` whose results are
consumed either as a blocking token iterator (streaming) or a
concurrent Future (request/response).
"""

from __future__ import annotations

import functools
import queue
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["AdmissionRejected", "ContinuousEngine", "PageAllocator"]


class AdmissionRejected(Exception):
    """Raised by submit() when the waiting queue is at capacity — the
    proxy maps this to HTTP 503 + Retry-After instead of letting the
    queue collapse under load."""

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


# ---------------------------------------------------------------------------
# metrics (lazy, module-cached: strong refs keep them alive across the
# weakref registry's flush epochs — same pattern as telemetry/recorder)

_metric_lock = threading.Lock()
_metric_cache: Dict[str, Any] = {}

_PHASE_BOUNDARIES = [0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                     0.1, 0.25, 0.5, 1, 2.5]
_TTFT_BOUNDARIES = [0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5,
                    5, 10, 30]


def _metric(key: str, factory):
    with _metric_lock:
        m = _metric_cache.get(key)
        if m is None:
            try:
                m = _metric_cache[key] = factory()
            except Exception:
                return None
        return m


def _m_phase():
    from ..util import metrics as mm
    return _metric("phase", lambda: mm.Histogram(
        "ray_tpu_serve_step_phase_seconds",
        description="Engine loop phase durations (swap/prefill/decode)",
        boundaries=_PHASE_BOUNDARIES, tag_keys=("phase",)))


def _m_ttft():
    from ..util import metrics as mm
    return _metric("ttft", lambda: mm.Histogram(
        "ray_tpu_serve_ttft_seconds",
        description="Time from submit to first streamed token",
        boundaries=_TTFT_BOUNDARIES))


def _m_tokens():
    from ..util import metrics as mm
    return _metric("tokens", lambda: mm.Counter(
        "ray_tpu_serve_tokens_total",
        description="Generated tokens"))


def _m_requests():
    from ..util import metrics as mm
    return _metric("requests", lambda: mm.Counter(
        "ray_tpu_serve_requests_total",
        description="Engine request outcomes", tag_keys=("outcome",)))


def _m_gauge(which: str):
    from ..util import metrics as mm
    names = {
        "active": ("ray_tpu_serve_active_slots", "Occupied decode slots"),
        "queue": ("ray_tpu_serve_queue_depth", "Waiting (unadmitted) requests"),
        "free_pages": ("ray_tpu_serve_free_pages", "Free KV-cache pages"),
    }
    name, desc = names[which]
    return _metric(which, lambda: mm.Gauge(name, description=desc))


# ---------------------------------------------------------------------------
# paged allocator (host-side bookkeeping; the arena itself is on device)


class PageAllocator:
    """Free-list page allocator with refcounts and a prompt-prefix
    registry.

    The registry maps *full, page-aligned token prefixes* — the tuple of
    a prompt's first (i+1)*page_size token ids — to the page holding
    those positions' K/V.  Sharing is live-sequence only: when a page's
    refcount drops to zero it returns to the free list and its registry
    keys are purged, so a registered page always holds exactly the K/V
    its key promises.
    """

    def __init__(self, num_pages: int, page_size: int):
        if num_pages < 2:
            raise ValueError("need >= 2 pages (page 0 is the null page)")
        self.num_pages = num_pages
        self.page_size = page_size
        self._free: deque = deque(range(1, num_pages))
        self._refs: Dict[int, int] = {}
        self._prefix: Dict[Tuple[int, ...], int] = {}
        self._page_keys: Dict[int, List[Tuple[int, ...]]] = {}

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def alloc(self) -> int:
        if not self._free:
            raise RuntimeError("page arena exhausted")
        p = self._free.popleft()
        self._refs[p] = 1
        return p

    def ref(self, page: int) -> None:
        self._refs[page] += 1

    def unref(self, page: int) -> None:
        if page == 0:
            return
        n = self._refs[page] - 1
        if n > 0:
            self._refs[page] = n
            return
        del self._refs[page]
        for key in self._page_keys.pop(page, ()):
            if self._prefix.get(key) == page:
                del self._prefix[key]
        self._free.append(page)

    def refcount(self, page: int) -> int:
        return self._refs.get(page, 0)

    def register_prefix(self, tokens: Tuple[int, ...], page: int) -> None:
        """Publish `page` as holding the K/V of this full-page prefix
        (first writer wins; a concurrent identical prefix is already
        byte-identical, so keeping the incumbent is free)."""
        if tokens in self._prefix:
            return
        self._prefix[tokens] = page
        self._page_keys.setdefault(page, []).append(tokens)

    def lookup_prefix(self, tokens: Tuple[int, ...]) -> Optional[int]:
        return self._prefix.get(tokens)

    def plan(self, tokens: List[int], n_pages_needed: int
             ) -> Optional[Dict[str, Any]]:
        """Plan the page set for a prompt: walk the registry for fully
        shared leading pages (clamped so the LAST prompt position is
        always recomputed — it must produce logits), then check the free
        list covers the rest.  Returns None when the arena can't fit
        the request right now (caller keeps it queued); on success
        returns {pages, shared_len, copies} with all refcounts taken —
        `copies` lists (src, dst) device page copies the caller must
        apply before prefill (copy-on-write out of a shared page).
        """
        ps = self.page_size
        plen = len(tokens)
        shared: List[int] = []
        i = 0
        while (i + 1) * ps <= plen:
            page = self._prefix.get(tuple(tokens[:(i + 1) * ps]))
            if page is None:
                break
            shared.append(page)
            i += 1
        full_shared = len(shared) * ps
        shared_len = min(full_shared, plen - 1)
        cow = shared_len < full_shared   # exact full-page match: the last
        if cow:                          # shared page must be re-written
            cow_src = shared.pop()
        n_fresh = n_pages_needed - len(shared)
        if n_fresh > len(self._free):
            return None
        for p in shared:
            self.ref(p)
        pages = list(shared)
        copies: List[Tuple[int, int]] = []
        if cow:
            dst = self.alloc()
            copies.append((cow_src, dst))
            pages.append(dst)
        while len(pages) < n_pages_needed:
            pages.append(self.alloc())
        return {"pages": pages, "shared_len": shared_len,
                "copies": copies, "n_shared": len(shared)}

    def release(self, pages: List[int]) -> None:
        for p in pages:
            self.unref(p)

    def occupancy(self) -> Dict[str, int]:
        """Arena occupancy for the device-memory census (page 0, the
        reserved null page, is in neither free nor used):
        ``live_shared`` counts pages currently referenced by more than
        one sequence (live prefix sharing, distinct from the engine's
        cumulative ``shared_pages`` total)."""
        return {
            "num_pages": self.num_pages,
            "page_size": self.page_size,
            "free": len(self._free),
            "used": len(self._refs),
            "live_shared": sum(1 for n in self._refs.values() if n > 1),
            "prefix_keys": len(self._prefix),
        }


# ---------------------------------------------------------------------------


class _Sequence:
    """Host-side state of one in-flight request."""

    __slots__ = ("rid", "tokens", "max_new", "temperature", "top_k",
                 "seed", "eos_id", "out_q", "result", "slot", "pages",
                 "pos", "generated", "keys", "t_submit", "t_first",
                 "peak", "stream", "request_id", "key_offset")

    def __init__(self, rid, tokens, max_new, temperature, top_k, seed,
                 eos_id, stream, request_id=None, key_offset=0):
        import concurrent.futures

        self.rid = rid
        self.request_id = request_id
        self.key_offset = int(key_offset)
        self.tokens = list(tokens)
        self.max_new = int(max_new)
        self.temperature = float(temperature)
        self.top_k = top_k
        self.seed = int(seed)
        self.eos_id = eos_id
        self.stream = bool(stream)
        self.out_q: "queue.Queue" = queue.Queue()
        self.result = concurrent.futures.Future()
        self.slot = -1
        self.pages: List[int] = []
        self.pos = 0
        self.generated: List[int] = []
        self.keys = None            # np [max_new, 2] uint32, set at admit
        self.t_submit = time.perf_counter()
        self.t_first: Optional[float] = None
        self.peak = 0               # max co-resident active slots seen


class ContinuousEngine:
    """Per-replica continuous-batching scheduler (one per model)."""

    _END = object()

    def __init__(self, gpt_mod, cfg, params, *, cache: str = "paged",
                 max_slots: int = 8, page_size: int = 16,
                 num_pages: int = 0, max_total: int = 0,
                 queue_cap: int = 32, shed_queue_depth: int = 16,
                 retry_after_s: float = 1.0, prefill_bucket: int = 32,
                 ring_size: int = 256, stall_s: float = 10.0):
        import jax
        import numpy as np

        if cache not in ("paged", "contiguous"):
            raise ValueError(f"unknown cache mode {cache!r}")
        self._jax, self._np, self._gpt = jax, np, gpt_mod
        self._cfg, self._params = cfg, params
        self.cache_mode = cache
        self.max_slots = int(max_slots)
        self.page_size = int(page_size)
        self.max_total = int(max_total) or cfg.max_seq
        self.max_pages_per_seq = -(-self.max_total // self.page_size)
        self.max_total = self.max_pages_per_seq * self.page_size
        self.num_pages = (int(num_pages)
                          or 1 + self.max_slots * self.max_pages_per_seq)
        self.queue_cap = int(queue_cap)
        self.shed_queue_depth = int(shed_queue_depth)
        self.retry_after_s = float(retry_after_s)
        self.prefill_bucket = int(prefill_bucket)

        self._lock = threading.Lock()
        self._waiting: "deque[_Sequence]" = deque()   # guarded-by: _lock
        self._slots: List[Optional[_Sequence]] = [None] * self.max_slots
        self._alloc = (PageAllocator(self.num_pages, self.page_size)
                       if cache == "paged" else None)
        self._fns: Dict[Any, Any] = {}   # bounded by construction: one
        # step program + one prefill per padded-length bucket + setrow +
        # copy_page — not the LRU _gen_cache (evicting the step program
        # mid-traffic would recompile the hot loop)
        self._thread: Optional[threading.Thread] = None
        self._wake = threading.Event()
        self._stopped = False
        self._draining = False        # guarded-by: _lock
        self._rid = 0
        self.stall_s = float(stall_s)
        self._health_snap: Optional[Tuple[int, float]] = None

        # device state (built lazily on the engine thread)
        self._cache = None
        self._logits = None          # [B, V] carried across steps

        # host mirrors of the per-slot step operands
        B, maxp = self.max_slots, self.max_pages_per_seq
        self._pos = np.zeros(B, np.int32)
        self._ptab = np.zeros((B, maxp), np.int32)
        self._toks_keys = np.zeros((B, 2), np.uint32)
        self._temps = np.zeros(B, np.float32)
        self._topks = np.zeros(B, np.int32)

        # telemetry: per-iteration phase ring + running totals
        self._ring: "deque[Dict[str, float]]" = deque(maxlen=ring_size)  # guarded-by: _lock
        self._ttfts: "deque[float]" = deque(maxlen=256)        # guarded-by: _lock
        self._t_window: "deque[Tuple[float, int]]" = deque(maxlen=512)  # guarded-by: _lock
        self._totals = {"requests": 0, "rejected": 0, "tokens": 0,
                        "steps": 0, "prefills": 0, "cow_copies": 0,
                        "shared_pages": 0}

        # device-memory census: report this engine's page-arena
        # occupancy under a per-instance tag (unregistered in stop())
        self._census_tag = f"serve.engine.{id(self):x}"
        try:
            from ..telemetry import device as _devtel

            _devtel.get_census().register_owner(self._census_tag,
                                                self._census_report)
        except Exception:
            pass

    # -- public api ---------------------------------------------------------

    def submit(self, tokens: List[int], max_new_tokens: int = 16,
               temperature: float = 0.0, seed: int = 0,
               top_k: Optional[int] = None, eos_id: Optional[int] = None,
               stream: bool = False, request_id: Optional[str] = None,
               key_offset: int = 0) -> _Sequence:
        """Thread-safe request entry: validates capacity, sheds when the
        waiting queue is full, wakes the engine loop."""
        if not tokens:
            raise ValueError("empty prompt")
        plen, max_new = len(tokens), int(max_new_tokens)
        if max_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if plen + max_new > self.max_total:
            raise ValueError(
                f"prompt ({plen}) + max_new_tokens ({max_new}) exceeds "
                f"engine capacity ({self.max_total})")
        need = -(-min(plen + max_new, self.max_total) // self.page_size)
        if self._alloc is not None and need > self.num_pages - 1:
            # can never fit even with the arena idle — reject now rather
            # than park it at the head of the queue forever
            raise ValueError(
                f"request needs {need} pages but the arena only has "
                f"{self.num_pages - 1}")
        if (self._cfg.pos == "learned"
                and plen + max_new > self._cfg.max_seq):
            raise ValueError(
                f"prompt ({plen}) + max_new_tokens ({max_new}) exceeds "
                f"the model's learned-position capacity "
                f"({self._cfg.max_seq})")
        with self._lock:
            if self._stopped:
                raise RuntimeError("engine stopped")
            if self._draining:
                self._totals["rejected"] += 1
                m = _m_requests()
                if m:
                    m.inc(tags={"outcome": "rejected"})
                raise AdmissionRejected(
                    "engine draining (replica shutting down)",
                    retry_after_s=self.retry_after_s)
            if len(self._waiting) >= self.queue_cap:
                self._totals["rejected"] += 1
                m = _m_requests()
                if m:
                    m.inc(tags={"outcome": "rejected"})
                raise AdmissionRejected(
                    f"waiting queue at capacity ({self.queue_cap})",
                    retry_after_s=self.retry_after_s)
            self._rid += 1
            seq = _Sequence(self._rid, tokens, max_new, temperature,
                            top_k, seed, eos_id, stream,
                            request_id=request_id, key_offset=key_offset)
            self._waiting.append(seq)
            self._totals["requests"] += 1
            self._ensure_thread()
        self._wake.set()
        return seq

    def stream(self, seq: _Sequence):
        """Blocking token iterator over one sequence's output queue
        (call from a worker thread, not the event loop)."""
        while True:
            item = seq.out_q.get()
            if item is self._END:
                # surface a terminal error (if any) to the consumer
                exc = seq.result.exception()
                if exc is not None:
                    raise exc
                return
            yield item

    def collect(self, seq: _Sequence, timeout: Optional[float] = None
                ) -> Dict[str, Any]:
        return seq.result.result(timeout=timeout)

    def engine_stats(self) -> Dict[str, Any]:
        """Scheduler snapshot for admission control and autoscaling."""
        now = time.perf_counter()
        with self._lock:
            active = sum(1 for s in self._slots if s is not None)
            qd = len(self._waiting)
            ttfts = sorted(self._ttfts)
            window = [(t, n) for t, n in self._t_window if now - t <= 10.0]
            draining = self._draining
            req_ids = [s.request_id
                       for s in list(self._slots) + list(self._waiting)
                       if s is not None and s.request_id]
        toks = sum(n for _, n in window)
        span = (now - window[0][0]) if window else 0.0
        free_pages = self._alloc.free_pages if self._alloc else \
            (self.max_slots - active) * self.max_pages_per_seq

        def pct(p):
            return ttfts[min(len(ttfts) - 1, int(p * len(ttfts)))] \
                if ttfts else 0.0

        return {
            "cache": self.cache_mode,
            "active": active,
            "free_slots": self.max_slots - active,
            "queue_depth": qd,
            "free_pages": free_pages,
            "num_pages": self.num_pages,
            "accepting": (not draining) and qd < self.shed_queue_depth,
            "draining": draining,
            "active_request_ids": req_ids,
            "retry_after_s": self.retry_after_s,
            "ttft_p50_s": pct(0.50),
            "ttft_p99_s": pct(0.99),
            "tokens_per_s": (toks / span) if span > 0 else 0.0,
            **self._totals,
        }

    def _census_report(self) -> Dict[str, Any]:
        """Owner callback for telemetry/device.DeviceMemoryCensus: the
        ``pages`` sub-dict feeds ``ray_tpu_kv_pages{state=…}`` — free /
        used are live arena occupancy, shared / cow are the engine's
        cumulative prefix-sharing totals (the serve bench row's
        ``shared_pages`` / ``cow_copies``)."""
        with self._lock:
            totals = dict(self._totals)
        rep: Dict[str, Any] = {"cache": self.cache_mode,
                               "num_pages": self.num_pages,
                               "max_slots": self.max_slots}
        if self._alloc is not None:
            occ = self._alloc.occupancy()
            rep["pages"] = {
                "free": occ["free"],
                "used": occ["used"],
                "shared": totals["shared_pages"],
                "cow": totals["cow_copies"],
                "live_shared": occ["live_shared"],
            }
            rep["prefix_keys"] = occ["prefix_keys"]
        return rep

    def phase_ring(self) -> List[Dict[str, float]]:
        with self._lock:
            return list(self._ring)

    def drain(self, timeout_s: float = 5.0) -> bool:
        """Graceful shutdown, phase 1: stop admitting (submit sheds,
        engine_stats advertises accepting=False so the router steers
        around this replica) and give in-flight sequences a
        deadline-bounded chance to finish.  Returns True when everything
        drained; leftovers are failed by the eventual stop()/kill and
        the router replays them elsewhere."""
        with self._lock:
            self._draining = True
        self._wake.set()
        deadline = time.monotonic() + max(0.0, timeout_s)
        while True:
            with self._lock:
                busy = bool(self._waiting) or any(
                    s is not None for s in self._slots)
            if not busy:
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.02)

    def check_health(self) -> bool:
        """Engine liveness probe (controller health loop): raises when
        the scheduler thread died with work pending, the step counter
        stalls while slots are active (hung jit step), or the page
        free-list went inconsistent — any of which means every future
        request would hang, so the replica must be restarted."""
        with self._lock:
            if self._stopped:
                raise RuntimeError("engine stopped")
            active = sum(1 for s in self._slots if s is not None)
            queued = len(self._waiting)
            steps = self._totals["steps"]
        thread = self._thread
        if thread is not None and not thread.is_alive() \
                and (active or queued):
            raise RuntimeError(
                f"engine scheduler thread died with work pending "
                f"({active} active, {queued} queued)")
        now = time.monotonic()
        snap = self._health_snap
        if active == 0 or snap is None or snap[0] != steps:
            self._health_snap = (steps, now)
        elif now - snap[1] > self.stall_s:
            raise RuntimeError(
                f"engine stalled: {active} active slots but no decode "
                f"step for {now - snap[1]:.1f}s (> {self.stall_s:g}s)")
        if self._alloc is not None:
            a = self._alloc
            in_use = len(a._refs)
            if len(a._free) + in_use != a.num_pages - 1:
                raise RuntimeError(
                    f"page free-list inconsistent: {len(a._free)} free "
                    f"+ {in_use} referenced != {a.num_pages - 1}")
            if any(n <= 0 for n in a._refs.values()):
                raise RuntimeError("page refcount <= 0 in allocator")
        return True

    def stop(self):
        try:
            from ..telemetry import device as _devtel

            _devtel.get_census().unregister_owner(self._census_tag)
        except Exception:
            pass
        with self._lock:
            self._stopped = True
            waiting = list(self._waiting)
            self._waiting.clear()
        self._wake.set()
        # let the loop finish its current iteration before touching the
        # slots — clearing them mid-_step would double-release pages
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        with self._lock:
            active = [s for s in self._slots if s is not None]
            self._slots = [None] * self.max_slots
        self._pos[:] = 0
        self._ptab[:] = 0
        if self._alloc is not None:
            for s in active:
                self._alloc.release(s.pages)
        err = RuntimeError("engine stopped")
        # in-slot sequences must resolve too: a stream consumer blocked
        # on out_q and a request/response caller blocked on the future
        # would otherwise hang forever
        for s in waiting + active:
            self._finish(s, error=err)

    # -- engine loop --------------------------------------------------------

    def _ensure_thread(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="serve-engine", daemon=True)
            self._thread.start()

    def _loop(self):
        while True:
            with self._lock:
                if self._stopped:
                    return
                busy = bool(self._waiting) or any(
                    s is not None for s in self._slots)
            if not busy:
                self._wake.wait(timeout=0.2)
                self._wake.clear()
                continue
            try:
                self._iteration()
            except Exception as e:          # fail every in-flight request
                with self._lock:            # rather than wedge the loop
                    seqs = [s for s in self._slots if s is not None]
                    seqs += list(self._waiting)
                    self._waiting.clear()
                    self._slots = [None] * self.max_slots
                    self._pos[:] = 0
                    self._ptab[:] = 0
                if self._alloc is not None:
                    for s in seqs:
                        self._alloc.release(s.pages)
                for s in seqs:
                    self._finish(s, error=e)

    def _iteration(self):
        t0 = time.perf_counter()
        admitted = self._admit()
        t1 = time.perf_counter()
        stepped = 0
        if any(s is not None for s in self._slots):
            stepped = self._step()
        t2 = time.perf_counter()
        rec = {"swap_s": (t1 - t0) if admitted else 0.0,
               "prefill_s": self._last_prefill_s if admitted else 0.0,
               "decode_s": (t2 - t1) if stepped else 0.0,
               "active": stepped, "admitted": admitted, "ts": t2}
        with self._lock:
            self._ring.append(rec)
            qd = len(self._waiting)
        m = _m_phase()
        if m:
            if admitted:
                m.observe(max(0.0, rec["swap_s"] - rec["prefill_s"]),
                          tags={"phase": "swap"})
                m.observe(rec["prefill_s"], tags={"phase": "prefill"})
            if stepped:
                m.observe(rec["decode_s"], tags={"phase": "decode"})
        for which, val in (("active", stepped),
                           ("queue", qd),
                           ("free_pages",
                            self._alloc.free_pages if self._alloc else 0)):
            g = _m_gauge(which)
            if g:
                g.set(val)

    # -- admission ----------------------------------------------------------

    def _pages_needed(self, seq: _Sequence) -> int:
        total = min(len(seq.tokens) + seq.max_new, self.max_total)
        return -(-total // self.page_size)

    def _admit(self) -> int:
        """Admit waiting sequences into free slots while pages last —
        FIFO (a too-big head request waits for evictions rather than
        being overtaken; admission-order fairness beats packing here).
        """
        self._last_prefill_s = 0.0
        admitted = 0
        while True:
            with self._lock:
                if not self._waiting:
                    break
                try:
                    slot = self._slots.index(None)
                except ValueError:
                    break
                seq = self._waiting[0]
                plan = None
                if self._alloc is not None:
                    plan = self._alloc.plan(seq.tokens,
                                            self._pages_needed(seq))
                    if plan is None:
                        break               # page-starved: wait for evicts
                self._waiting.popleft()
                self._slots[slot] = seq
            self._admit_one(seq, slot, plan)
            admitted += 1
        if admitted:
            n = sum(1 for s in self._slots if s is not None)
            for s in self._slots:
                if s is not None:
                    s.peak = max(s.peak, n)
        return admitted

    def _admit_one(self, seq: _Sequence, slot: int, plan):
        jax, np = self._jax, self._np
        self._ensure_device_state()
        plen = len(seq.tokens)
        if plan is not None:
            seq.pages = plan["pages"]
            shared_len = plan["shared_len"]
            row = np.zeros(self.max_pages_per_seq, np.int32)
            row[:len(seq.pages)] = seq.pages
            self._ptab[slot] = row
            self._totals["cow_copies"] += len(plan["copies"])
            self._totals["shared_pages"] += plan["n_shared"]
            for src, dst in plan["copies"]:
                self._cache = self._fn("copy_page")(self._cache,
                                                    np.int32(dst),
                                                    np.int32(src))
        else:
            shared_len = 0
        seq.slot = slot
        seq.pos = plen
        # key_offset: a resumed continuation (router replay) re-derives
        # the ORIGINAL request's key schedule and skips the keys its
        # already-delivered tokens consumed — sampled decode stays
        # bitwise-identical across the resume, same as greedy
        seq.keys = np.asarray(jax.random.split(
            jax.random.PRNGKey(seq.seed),
            seq.key_offset + seq.max_new))[seq.key_offset:]
        self._pos[slot] = plen                  # first decode write pos
        self._temps[slot] = seq.temperature
        self._topks[slot] = int(seq.top_k or 0)

        # prefill the non-shared prompt suffix as one padded program
        count = plen - shared_len
        T = -(-count // self.prefill_bucket) * self.prefill_bucket
        chunk = np.zeros(T, np.int32)
        chunk[:count] = seq.tokens[shared_len:]
        tp = time.perf_counter()
        if self._alloc is not None:
            logits, self._cache = self._fn(("prefill", T))(
                self._params, self._cache, chunk, self._ptab[slot],
                np.int32(shared_len), np.int32(count - 1))
        else:
            logits, self._cache = self._fn(("prefill", T))(
                self._params, self._cache, chunk, np.int32(shared_len),
                np.int32(count - 1), np.int32(slot))
        self._logits = self._fn("setrow")(self._logits, logits,
                                          np.int32(slot))
        jax.block_until_ready(self._logits)
        self._last_prefill_s += time.perf_counter() - tp
        self._totals["prefills"] += 1

        # register this prompt's full pages for live prefix sharing
        if self._alloc is not None:
            for i in range(plen // self.page_size):
                self._alloc.register_prefix(
                    tuple(seq.tokens[:(i + 1) * self.page_size]),
                    seq.pages[i])

    # -- decode -------------------------------------------------------------

    def _step(self) -> int:
        """One fused sample+decode step over every slot.  Inactive slots
        ride along at pos 0 against the null page; their tokens are
        discarded here on the host."""
        np = self._np
        active = [(i, s) for i, s in enumerate(self._slots)
                  if s is not None]
        for i, s in active:
            self._toks_keys[i] = s.keys[len(s.generated)]
        toks, self._logits, self._cache = self._fn("step")(
            self._params, self._cache, self._logits, self._toks_keys,
            self._temps, self._topks, self._ptab, self._pos)
        toks = np.asarray(toks)
        self._totals["steps"] += 1
        now = time.perf_counter()
        emitted = 0
        finished = []
        for i, s in active:
            tok = int(toks[i])
            s.generated.append(tok)
            emitted += 1
            if s.t_first is None:
                s.t_first = now
                ttft = now - s.t_submit
                with self._lock:
                    self._ttfts.append(ttft)
                m = _m_ttft()
                if m:
                    m.observe(ttft)
            s.out_q.put(tok)
            self._pos[i] += 1
            if (len(s.generated) >= s.max_new
                    or (s.eos_id is not None and tok == s.eos_id)):
                finished.append((i, s))
        self._totals["tokens"] += emitted
        with self._lock:
            self._t_window.append((now, emitted))
        m = _m_tokens()
        if m and emitted:
            m.inc(emitted)
        for i, s in finished:
            self._evict(i, s)
        return len(active)

    def _evict(self, slot: int, seq: _Sequence):
        with self._lock:
            self._slots[slot] = None
        self._pos[slot] = 0
        self._ptab[slot] = 0
        self._temps[slot] = 0.0
        self._topks[slot] = 0
        if self._alloc is not None:
            self._alloc.release(seq.pages)
        self._finish(seq)
        self._wake.set()          # page/slot freed: retry page-starved head

    def _finish(self, seq: _Sequence, error: Optional[Exception] = None):
        if error is not None:
            if not seq.result.done():
                seq.result.set_exception(error)
            m = _m_requests()
            if m:
                m.inc(tags={"outcome": "error"})
        elif not seq.result.done():
            seq.result.set_result({
                "tokens": seq.tokens + seq.generated,
                "completion": list(seq.generated),
                "batch_size": seq.peak,
                "ttft_s": (seq.t_first - seq.t_submit)
                if seq.t_first else None,
            })
            m = _m_requests()
            if m:
                m.inc(tags={"outcome": "ok"})
        seq.out_q.put(self._END)

    # -- compiled programs --------------------------------------------------

    def _ensure_device_state(self):
        if self._cache is not None:
            return
        jnp = self._jax.numpy
        if self.cache_mode == "paged":
            self._cache = self._gpt.init_paged_cache(
                self._cfg, self.num_pages, self.page_size)
        else:
            self._cache = self._gpt.init_slot_cache(
                self._cfg, self.max_slots, self.max_total)
        self._logits = jnp.zeros(
            (self.max_slots, self._cfg.vocab_size), jnp.float32)

    def _fn(self, key):
        fn = self._fns.get(key)
        if fn is not None:
            return fn
        jax, gpt, cfg = self._jax, self._gpt, self._cfg
        jnp = jax.numpy
        paged = self.cache_mode == "paged"
        # every engine program routes through the compilation ledger:
        # "the step program never recompiles" (module docstring) is now
        # a measured claim — bench gates steady-state recompiles at 0
        from ..telemetry import device as devtel

        if key == "step":
            def sample(logits, keys, temps, topks):
                V = logits.shape[-1]
                # mirrors gpt.sample_logits exactly, vectorized per
                # slot: scale FIRST, then top-k truncate at -1e30 (0 =
                # top-k off; greedy rows take the argmax branch).  The
                # whole recipe runs in cfg.dtype even though the engine
                # carries logits as f32: categorical draws its gumbel
                # noise in the logits dtype, so sampling in f32 would
                # draw different noise than generate()'s bf16 path and
                # break seed parity
                lg = logits.astype(cfg.dtype)
                t = jnp.where(temps > 0, temps, 1.0).astype(cfg.dtype)
                scaled = lg / t[:, None]
                k_eff = jnp.where(topks > 0, topks, V)
                srt = jnp.sort(scaled, axis=-1)
                kth = jnp.take_along_axis(srt, (V - k_eff)[:, None],
                                          axis=-1)
                filt = jnp.where(scaled < kth, -1e30, scaled)
                sampled = jax.vmap(jax.random.categorical)(keys, filt)
                greedy = jnp.argmax(lg, axis=-1)
                return jnp.where(temps > 0, sampled,
                                 greedy).astype(jnp.int32)

            if paged:
                def step(params, cache, logits, keys, temps, topks,
                         ptab, pos):
                    toks = sample(logits, keys, temps, topks)
                    new_logits, cache = gpt.paged_decode_step(
                        params, cache, toks, ptab, pos, cfg)
                    return toks, new_logits.astype(jnp.float32), cache
            else:
                def step(params, cache, logits, keys, temps, topks,
                         ptab, pos):
                    toks = sample(logits, keys, temps, topks)
                    new_logits, cache = gpt.slot_decode_step(
                        params, cache, toks, pos, cfg)
                    return toks, new_logits.astype(jnp.float32), cache

            fn = self._fns[key] = devtel.instrument(
                jax.jit(step), name="serve.step")
        elif key == "setrow":
            fn = self._fns[key] = devtel.instrument(jax.jit(
                lambda L, row, slot: L.at[slot].set(
                    row.astype(jnp.float32))), name="serve.setrow")
        elif key == "copy_page":
            fn = self._fns[key] = devtel.instrument(
                jax.jit(gpt.copy_page), name="serve.copy_page")
        elif isinstance(key, tuple) and key[0] == "prefill":
            # per-bucket program name: a healthy engine compiles each
            # padded-length bucket once; the SAME bucket recompiling is
            # the storm signal, a new bucket is not
            if paged:
                fn = self._fns[key] = devtel.instrument(
                    jax.jit(functools.partial(
                        gpt.paged_prefill, cfg=cfg)),
                    name=f"serve.prefill:{key[1]}")
            else:
                fn = self._fns[key] = devtel.instrument(
                    jax.jit(functools.partial(
                        gpt.slot_prefill, cfg=cfg)),
                    name=f"serve.prefill:{key[1]}")
        else:
            raise KeyError(key)
        return fn
