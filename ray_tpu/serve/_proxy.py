"""HTTP proxy actor: the cluster's HTTP ingress.

Reference: python/ray/serve/_private/proxy.py (HTTPProxy :779, proxy_request
:446) — accepts HTTP, matches the longest route prefix from the
controller's routing table, wraps the request, routes it through the p2c
router to a replica, and converts the return value to an HTTP response
(dict/list -> JSON, str -> text, bytes -> raw, Response for full control).

Built on aiohttp (in-image) running inside the actor on a dedicated event
loop thread; replica calls resolve via an executor so the accept loop never
blocks.
"""

from __future__ import annotations

import asyncio
import json
import logging
import threading
import time
from typing import Any, Dict, Optional

import ray_tpu

from ._common import CONTROLLER_NAME
from ._router import get_router

logger = logging.getLogger(__name__)

_ROUTES_TTL_S = 1.0


class Response:
    """Explicit HTTP response (reference: starlette Response usage)."""

    def __init__(self, body: Any = b"", status: int = 200,
                 content_type: str = "application/octet-stream",
                 headers: Optional[Dict[str, str]] = None):
        self.body = body
        self.status = status
        self.content_type = content_type
        self.headers = dict(headers or {})


class Request:
    """Minimal request facade handed to HTTP ingress callables."""

    def __init__(self, method: str, path: str, route_path: str,
                 query_params: Dict[str, str], headers: Dict[str, str],
                 body: bytes):
        self.method = method
        self.path = path            # full path
        self.route_path = route_path  # path with route prefix stripped
        self.query_params = query_params
        self.headers = headers
        self._body = body

    async def body(self) -> bytes:
        return self._body

    async def json(self) -> Any:
        return json.loads(self._body or b"null")

    def __repr__(self):
        return f"Request({self.method} {self.path})"


class HTTPProxy:
    def __init__(self, host: str = "127.0.0.1", port: int = 8000):
        self._host = host
        self._port = port
        self._actual_port = None
        self._routes: Dict[str, Dict[str, str]] = {}
        self._routes_ts = 0.0
        self._controller = None
        self._started = threading.Event()
        self._start_err: Optional[str] = None
        self._thread = threading.Thread(target=self._serve_thread,
                                        name="serve-http", daemon=True)
        self._thread.start()

    # -- actor API ----------------------------------------------------------

    def ready(self):
        if not self._started.wait(timeout=15.0):
            raise RuntimeError("http proxy failed to start (timeout)")
        if self._start_err:
            raise RuntimeError(f"http proxy failed: {self._start_err}")
        return (self._host, self._actual_port)

    # -- server -------------------------------------------------------------

    def _serve_thread(self):
        try:
            from aiohttp import web

            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            app = web.Application(client_max_size=256 * 1024 * 1024)
            app.router.add_route("*", "/{tail:.*}", self._handle)
            runner = web.AppRunner(app, access_log=None)
            loop.run_until_complete(runner.setup())
            site = web.TCPSite(runner, self._host, self._port)
            loop.run_until_complete(site.start())
            self._actual_port = site._server.sockets[0].getsockname()[1]
            self._started.set()
            loop.run_forever()
        except BaseException as e:
            self._start_err = f"{type(e).__name__}: {e}"
            self._started.set()

    def _refresh_routes(self):
        """Blocking controller RPC — only ever called via run_in_executor
        so the aiohttp accept loop never stalls on it."""
        try:
            if self._controller is None:
                self._controller = ray_tpu.get_actor(CONTROLLER_NAME)
            table = ray_tpu.get(
                self._controller.get_routing_table.remote(),
                timeout=10.0)
            self._routes = table["routes"]
            self._routes_ts = time.monotonic()
        except Exception:
            logger.exception("route table refresh failed")

    async def _route_for(self, path: str) -> Optional[Dict[str, str]]:
        if time.monotonic() - self._routes_ts > _ROUTES_TTL_S:
            await asyncio.get_event_loop().run_in_executor(
                None, self._refresh_routes)
        best = None
        for prefix, target in self._routes.items():
            if path == prefix or path.startswith(
                    prefix if prefix.endswith("/") else prefix + "/") \
                    or prefix == "/":
                if best is None or len(prefix) > len(best[0]):
                    best = (prefix, target)
        return best and {"prefix": best[0], **best[1]}

    async def _handle(self, request):
        from aiohttp import web

        path = request.path
        if path == "/-/healthz":
            return web.Response(text="ok")
        if path == "/-/routes":
            self._routes_ts = 0.0
            await self._route_for(path)
            return web.json_response(self._routes)
        target = await self._route_for(path)
        if target is None:
            return web.Response(status=404,
                                text=f"no serve app matches {path!r}")
        prefix = target["prefix"]
        route_path = path[len(prefix):] if prefix != "/" else path
        body = await request.read()
        req = Request(request.method, path, route_path or "/",
                      dict(request.query), dict(request.headers), body)
        router = get_router(target["app"], target["deployment"])
        loop = asyncio.get_event_loop()

        def call():
            ref, done = router.assign(None, (req,), {}, {})
            try:
                return ray_tpu.get(ref, timeout=300.0)
            finally:
                done()

        try:
            out = await loop.run_in_executor(None, call)
        except Exception as e:
            logger.exception("request to %s failed", path)
            return web.Response(status=500,
                               text=f"{type(e).__name__}: {e}")
        return self._to_http(out)

    def _to_http(self, out: Any):
        from aiohttp import web

        if isinstance(out, Response):
            body = out.body
            if isinstance(body, str):
                body = body.encode()
            elif not isinstance(body, (bytes, bytearray)):
                body = json.dumps(body, default=str).encode()
            return web.Response(body=body, status=out.status,
                                content_type=out.content_type,
                                headers=out.headers)
        if isinstance(out, (bytes, bytearray)):
            return web.Response(body=bytes(out))
        if isinstance(out, str):
            return web.Response(text=out)
        return web.json_response(out, dumps=lambda o: json.dumps(
            o, default=str))
