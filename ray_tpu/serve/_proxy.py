"""HTTP proxy actor: the cluster's HTTP ingress.

Reference: python/ray/serve/_private/proxy.py (HTTPProxy :779, proxy_request
:446) — accepts HTTP, matches the longest route prefix from the
controller's routing table, wraps the request, routes it through the p2c
router to a replica, and converts the return value to an HTTP response
(dict/list -> JSON, str -> text, bytes -> raw, Response for full control).

Built on aiohttp (in-image) running inside the actor on a dedicated event
loop thread; replica calls resolve via an executor so the accept loop never
blocks.
"""

from __future__ import annotations

import asyncio
import json
import logging
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

import ray_tpu

from ._common import CONTROLLER_NAME, NoCapacityError
from ._router import get_router

logger = logging.getLogger(__name__)

_ROUTES_TTL_S = 1.0


def _shed_retry_after(e: BaseException) -> Optional[float]:
    """Retry-After seconds when `e` is a load-shed signal, else None.
    Local router sheds arrive typed (NoCapacityError); replica-side
    engine rejections cross the task boundary as a wrapped error whose
    text carries the exception name."""
    if isinstance(e, NoCapacityError):
        return e.retry_after_s
    txt = str(e)
    if "AdmissionRejected" in txt or "NoCapacityError" in txt:
        try:
            from .._private.config import cfg as _cfg

            return _cfg().serve_retry_after_s
        except Exception:
            return 1.0
    return None


class Response:
    """Explicit HTTP response (reference: starlette Response usage)."""

    def __init__(self, body: Any = b"", status: int = 200,
                 content_type: str = "application/octet-stream",
                 headers: Optional[Dict[str, str]] = None):
        self.body = body
        self.status = status
        self.content_type = content_type
        self.headers = dict(headers or {})


class Request:
    """Minimal request facade handed to HTTP ingress callables."""

    def __init__(self, method: str, path: str, route_path: str,
                 query_params: Dict[str, str], headers: Dict[str, str],
                 body: bytes, query_string: bytes = b"",
                 header_pairs: Optional[List[Tuple[str, str]]] = None):
        self.method = method
        self.path = path            # full path
        self.route_path = route_path  # path with route prefix stripped
        self.query_params = query_params
        # raw percent-encoded query string verbatim: repeated keys
        # (?tag=a&tag=b) and escapes (%C3%A9, 1+2) survive here even
        # though the query_params dict keeps only decoded last values
        self.query_string = query_string
        # ordered (name, value) pairs: repeated headers (Set-Cookie,
        # X-Forwarded-For) survive here; the dict keeps only the last
        self.header_pairs = (header_pairs if header_pairs is not None
                             else list(headers.items()))
        self.headers = headers
        self._body = body

    async def body(self) -> bytes:
        return self._body

    async def json(self) -> Any:
        return json.loads(self._body or b"null")

    def __repr__(self):
        return f"Request({self.method} {self.path})"


class _ControllerTableCache:
    """TTL-cached controller table fetch shared by the ingress proxies.

    Resets the cached actor handle on failure so a restarted controller
    (new actor, same name) is re-resolved instead of bricking refreshes.
    """

    def __init__(self, method: str, extract):
        self._method = method
        self._extract = extract
        self._controller = None
        self._value: Dict[str, Any] = {}
        self._ts = 0.0

    def invalidate(self):
        self._ts = 0.0

    def fresh(self) -> bool:
        return time.monotonic() - self._ts <= _ROUTES_TTL_S

    def get(self) -> Dict[str, Any]:
        """Blocking controller RPC on miss — callers on an event loop must
        run this in an executor."""
        if time.monotonic() - self._ts > _ROUTES_TTL_S:
            try:
                if self._controller is None:
                    self._controller = ray_tpu.get_actor(CONTROLLER_NAME)
                table = ray_tpu.get(
                    getattr(self._controller, self._method).remote(),
                    timeout=10.0)
                self._value = self._extract(table)
                self._ts = time.monotonic()
            except Exception:
                self._controller = None  # re-resolve after restarts
                logger.exception("%s refresh failed", self._method)
        return self._value


def _request_id(headers: Dict[str, str]) -> str:
    """Honor a caller-supplied x-request-id (so one id threads client ->
    proxy -> handle -> replica -> engine stats and the router's replay
    log lines); mint one otherwise."""
    for k, v in headers.items():
        if k.lower() == "x-request-id" and v:
            return str(v)[:128]
    return uuid.uuid4().hex[:16]


def _chunk_bytes(item: Any) -> bytes:
    """Wire form of one streamed item: bytes pass through, strings encode
    (SSE framing is the deployment's own `yield "data: ...\\n\\n"`),
    anything else is one JSON line."""
    if isinstance(item, (bytes, bytearray)):
        return bytes(item)
    if isinstance(item, str):
        return item.encode()
    return (json.dumps(item, default=str) + "\n").encode()


class HTTPProxy:
    def __init__(self, host: str = "127.0.0.1", port: int = 8000):
        self._host = host
        self._port = port
        self._actual_port = None
        self._table = _ControllerTableCache(
            "get_routing_table", lambda t: t["routes"])
        self._started = threading.Event()
        self._start_err: Optional[str] = None
        self._thread = threading.Thread(target=self._serve_thread,
                                        name="serve-http", daemon=True)
        self._thread.start()

    # -- actor API ----------------------------------------------------------

    def ready(self):
        if not self._started.wait(timeout=15.0):
            raise RuntimeError("http proxy failed to start (timeout)")
        if self._start_err:
            raise RuntimeError(f"http proxy failed: {self._start_err}")
        return (self._host, self._actual_port)

    # -- server -------------------------------------------------------------

    def _serve_thread(self):
        try:
            from aiohttp import web

            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            app = web.Application(client_max_size=256 * 1024 * 1024)
            app.router.add_route("*", "/{tail:.*}", self._handle)
            runner = web.AppRunner(app, access_log=None)
            loop.run_until_complete(runner.setup())
            site = web.TCPSite(runner, self._host, self._port)
            loop.run_until_complete(site.start())
            self._actual_port = site._server.sockets[0].getsockname()[1]
            self._started.set()
            loop.run_forever()
        except BaseException as e:
            self._start_err = f"{type(e).__name__}: {e}"
            self._started.set()

    async def _route_for(self, path: str) -> Optional[Dict[str, str]]:
        if self._table.fresh():
            routes = self._table._value  # hot path: no executor hop
        else:
            routes = await asyncio.get_event_loop().run_in_executor(
                None, self._table.get)
        best = None
        for prefix, target in routes.items():
            if path == prefix or path.startswith(
                    prefix if prefix.endswith("/") else prefix + "/") \
                    or prefix == "/":
                if best is None or len(prefix) > len(best[0]):
                    best = (prefix, target)
        return best and {"prefix": best[0], **best[1]}

    async def _handle(self, request):
        from aiohttp import web

        path = request.path
        if path == "/-/healthz":
            return web.Response(text="ok")
        if path == "/-/routes":
            self._table.invalidate()
            await self._route_for(path)
            return web.json_response(self._table._value)
        target = await self._route_for(path)
        if target is None:
            return web.Response(status=404,
                                text=f"no serve app matches {path!r}")
        prefix = target["prefix"]
        route_path = path[len(prefix):] if prefix != "/" else path
        body = await request.read()
        req = Request(request.method, path, route_path or "/",
                      dict(request.query), dict(request.headers), body,
                      query_string=(
                          request.rel_url.raw_query_string.encode()),
                      header_pairs=list(request.headers.items()))
        router = get_router(target["app"], target["deployment"])
        loop = asyncio.get_event_loop()
        rid = _request_id(req.headers)

        if target.get("streaming") or target.get("asgi"):
            return await self._handle_streaming(request, req, target,
                                                router, rid)

        def call():
            sub = router.submit(None, (req,), {}, {"request_id": rid})
            return router.call(sub, timeout_s=300.0)

        try:
            out = await loop.run_in_executor(None, call)
        except Exception as e:
            retry = _shed_retry_after(e)
            if retry is not None:
                # overload is not an error: tell the client when to come
                # back instead of letting queues collapse into timeouts
                return web.Response(
                    status=503, text=f"overloaded: {e}",
                    headers={"Retry-After": f"{max(0.0, retry):g}",
                             "x-request-id": rid})
            logger.exception("request %s to %s failed", rid, path)
            return web.Response(status=500,
                                text=f"{type(e).__name__}: {e}",
                                headers={"x-request-id": rid})
        resp = self._to_http(out)
        resp.headers.setdefault("x-request-id", rid)
        return resp

    # long-lived streams pin a thread per in-flight item wait; a
    # dedicated pool keeps ~32 SSE clients from starving the loop's
    # default executor (which the non-streaming path also rides)
    _stream_pool = None
    _stream_pool_lock = threading.Lock()

    @classmethod
    def _stream_executor(cls):
        with cls._stream_pool_lock:
            if cls._stream_pool is None:
                from concurrent.futures import ThreadPoolExecutor

                cls._stream_pool = ThreadPoolExecutor(
                    max_workers=64, thread_name_prefix="proxy-stream")
            return cls._stream_pool

    async def _handle_streaming(self, aio_req, req, target, router,
                                rid: str):
        """Chunked-transfer path for generator/ASGI ingress (reference:
        proxy.py:864 streaming plumbing): each item the deployment yields
        goes onto the wire as soon as its ref resolves — first-token
        latency is one item's production time, not the whole response's.

        A plain-generator deployment may yield a serve.Response FIRST to
        set status/headers (e.g. content_type="text/event-stream" for
        EventSource clients); its body (if any) becomes the first chunk.
        """
        from aiohttp import web

        from ._asgi import ASGI_META

        loop = asyncio.get_event_loop()
        pool = self._stream_executor()
        try:
            sub = await loop.run_in_executor(
                pool, lambda: router.submit(
                    None, (req,), {}, {"request_id": rid},
                    streaming=True))
        except Exception as e:
            retry = _shed_retry_after(e)
            if retry is not None:
                return web.Response(
                    status=503, text=f"overloaded: {e}",
                    headers={"Retry-After": f"{max(0.0, retry):g}",
                             "x-request-id": rid})
            logger.exception("streaming submit %s to %s failed", rid,
                             req.path)
            return web.Response(status=500,
                                text=f"{type(e).__name__}: {e}",
                                headers={"x-request-id": rid})
        # iter_stream resolves items AND replays on replica death; its
        # finally releases the in-flight slot even on client disconnect
        it = router.iter_stream(sub)
        sentinel = object()

        def nxt():
            return next(it, sentinel)

        resp = None
        try:
            first = await loop.run_in_executor(pool, nxt)
            pending = None
            if (target.get("asgi") and isinstance(first, tuple)
                    and first and first[0] == ASGI_META):
                from multidict import CIMultiDict

                # multidict: duplicate names (several Set-Cookie) survive
                headers = CIMultiDict(
                    (k, v) for k, v in first[2]
                    if k.lower() != "content-length")  # chunked
                resp = web.StreamResponse(status=first[1], headers=headers)
            elif isinstance(first, Response):
                from multidict import CIMultiDict

                headers = CIMultiDict(
                    (k, v) for k, v in first.headers.items()
                    if k.lower() != "content-length")  # chunked
                headers["Content-Type"] = first.content_type
                resp = web.StreamResponse(status=first.status,
                                          headers=headers)
                if first.body:
                    pending = first.body
            else:
                resp = web.StreamResponse(
                    status=200,
                    headers={"Content-Type": "text/plain; charset=utf-8"})
                pending = first
            resp.headers.setdefault("x-request-id", rid)
            await resp.prepare(aio_req)
            if pending is not None and pending is not sentinel:
                await resp.write(_chunk_bytes(pending))
            if first is not sentinel:
                while True:
                    item = await loop.run_in_executor(pool, nxt)
                    if item is sentinel:
                        break
                    await resp.write(_chunk_bytes(item))
            await resp.write_eof()
            return resp
        except Exception as e:
            logger.exception("streaming request %s to %s failed", rid,
                             req.path)
            if resp is None or not resp.prepared:
                # nothing hit the wire yet (including prepare() itself
                # failing): a plain 500/503 is still deliverable
                retry = _shed_retry_after(e)
                if retry is not None:
                    return web.Response(
                        status=503, text=f"overloaded: {e}",
                        headers={"Retry-After": f"{max(0.0, retry):g}",
                                 "x-request-id": rid})
                return web.Response(status=500,
                                    text=f"{type(e).__name__}: {e}",
                                    headers={"x-request-id": rid})
            # headers already sent: abort the connection rather than
            # emitting the normal chunked terminator — a clean write_eof
            # would make the truncated body indistinguishable from a
            # complete response for SSE/chunked consumers
            try:
                if aio_req.transport is not None:
                    aio_req.transport.close()
            except Exception:
                pass
            resp.force_close()
            return resp
        finally:
            # closing the iterator runs iter_stream's finally (releases
            # the router's in-flight slot) — including on client abandon
            try:
                it.close()
            except Exception:
                pass

    def _to_http(self, out: Any):
        from aiohttp import web

        if isinstance(out, Response):
            body = out.body
            if isinstance(body, str):
                body = body.encode()
            elif not isinstance(body, (bytes, bytearray)):
                body = json.dumps(body, default=str).encode()
            return web.Response(body=body, status=out.status,
                                content_type=out.content_type,
                                headers=out.headers)
        if isinstance(out, (bytes, bytearray)):
            return web.Response(body=bytes(out))
        if isinstance(out, str):
            return web.Response(text=out)
        return web.json_response(out, dumps=lambda o: json.dumps(
            o, default=str))


class RpcProxy:
    """Binary RPC ingress: the reference's gRPC proxy analog
    (reference: serve/_private/proxy.py gRPCProxy :558) on the framework's
    native frame protocol instead of grpc — one `serve_call` method routes
    {app, method, payload} through the same p2c router as HTTP.  Serves
    every app, including ones without an HTTP route_prefix.

    Clients use serve.RpcClient (or any protocol.Client):
        RpcClient(addr).call("my_app", payload, method="predict")
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        from ray_tpu._private.protocol import DaemonPool, Server

        self._table = _ControllerTableCache(
            "get_app_table", lambda t: dict(t["apps"]))
        self._pool = DaemonPool(max_workers=16, name="serve-rpc")
        self._server = Server(host, port, name="serve-rpc")
        self._server.handle("serve_call", self._handle_call, deferred=True)
        # table fetch blocks on the controller: never on the loop thread
        self._server.handle("serve_routes", self._handle_routes,
                            deferred=True)
        self._server.handle("ping", lambda c, p: "pong")
        self._server.start()

    def ready(self):
        return self._server.addr

    def _handle_routes(self, conn, p, d):
        self._pool.submit(lambda: d.resolve(self._table.get()))

    def _handle_call(self, conn, p, d):
        def run():
            try:
                app = p.get("app") or "default"
                target = self._table.get().get(app)
                if target is None:
                    d.reject(f"no serve app named {app!r}")
                    return
                router = get_router(target["app"], target["deployment"])
                args = p.get("args", ())
                kwargs = p.get("kwargs", {})
                # named-method ingress routes RPC method names: keep the
                # __call__ fallback (same contract as the gRPC ingress);
                # handle callers stay strict
                meta = {"_method_fallback": True}
                if p.get("request_id"):
                    meta["request_id"] = str(p["request_id"])[:128]
                sub = router.submit(p.get("method"), tuple(args),
                                    dict(kwargs), meta)
                d.resolve(router.call(sub, timeout_s=300.0))
            except BaseException as e:
                d.reject(f"{type(e).__name__}: {e}")

        self._pool.submit(run)


class RpcClient:
    """Client for the serve RPC ingress.

    `method` and `timeout` are client-side options; a deployment method
    whose own kwargs collide with those names receives them via
    `call_kwargs`.
    """

    def __init__(self, addr, connect_timeout: float = 30.0):
        from ray_tpu._private.protocol import Client

        self._client = Client(tuple(addr), name="serve-rpc-client",
                              connect_timeout=connect_timeout)

    def call(self, app: str, *args, method: Optional[str] = None,
             timeout: float = 300.0, request_id: Optional[str] = None,
             call_kwargs: Optional[Dict[str, Any]] = None, **kwargs):
        merged = {**(call_kwargs or {}), **kwargs}
        payload = {"app": app, "method": method,
                   "args": args, "kwargs": merged}
        if request_id:
            payload["request_id"] = request_id
        return self._client.call("serve_call", payload, timeout=timeout)

    def routes(self) -> Dict[str, Any]:
        return self._client.call("serve_routes", {}, timeout=30.0)

    def close(self):
        self._client.close()
