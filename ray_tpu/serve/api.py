"""serve public API: run/start/shutdown/delete/status + handles.

Reference: python/ray/serve/api.py (serve.run :463, @serve.deployment :240,
serve.start, serve.status, serve.delete, serve.get_app_handle,
serve.get_deployment_handle).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Optional

import cloudpickle

import ray_tpu

from ._common import (APP_RUNNING, CONTROLLER_NAME, DEPLOY_FAILED,
                      DEFAULT_ROUTE_PREFIX)
from ._controller import ServeController
from ._deployment import Application, Deployment
from ._handle import DeploymentHandle
from ._router import reset_routers

logger = logging.getLogger(__name__)

_controller_handle = None


def start(http_host: str = "127.0.0.1", http_port: int = 0,
          proxy: bool = False):
    """Start (or connect to) the serve controller; optionally the HTTP
    proxy.  Idempotent (reference: serve/api.py serve.start)."""
    global _controller_handle
    from ray_tpu._private.usage_stats import record_library_usage

    record_library_usage("serve")
    if _controller_handle is None:
        try:
            _controller_handle = ray_tpu.get_actor(CONTROLLER_NAME)
        except ValueError:
            _controller_handle = ray_tpu.remote(ServeController).options(
                name=CONTROLLER_NAME, lifetime="detached",
                max_concurrency=8, num_cpus=0).remote(http_host, http_port)
            # wait for it to be live
            ray_tpu.get(_controller_handle.get_replica_version.remote(),
                        timeout=30.0)
    if proxy:
        return ray_tpu.get(_controller_handle.ensure_proxy.remote(),
                           timeout=60.0)
    return _controller_handle


def start_rpc_proxy():
    """Start the binary RPC ingress (the native-protocol fast path);
    returns its (host, port)."""
    return ray_tpu.get(_get_controller().ensure_rpc_proxy.remote(),
                       timeout=60.0)


def start_grpc(servicer_functions, *, host: Optional[str] = None):
    """Start the REAL gRPC ingress (reference: serve/_private/proxy.py:558
    gRPCProxy): `servicer_functions` are standard generated
    `add_<Service>Servicer_to_server` callables; any grpc client that
    speaks the user's proto can then call deployments (app selected via
    the `application` request metadata). Returns (host, port). Requires
    grpcio."""
    try:
        import grpc  # noqa: F401
    except ImportError:
        raise ImportError(
            "serve.start_grpc requires grpcio, which is not installed in "
            "this environment") from None
    import cloudpickle

    from ray_tpu._private import common as _common

    for fn in servicer_functions:
        _common._ensure_picklable_by_value(fn)
    blob = cloudpickle.dumps(list(servicer_functions))
    return ray_tpu.get(
        _get_controller().ensure_grpc_proxy.remote(blob, host),
        timeout=60.0)


def _get_controller():
    global _controller_handle
    if _controller_handle is None:
        start()
    return _controller_handle


def _spec_of(node: Application, handle_env: Dict[str, DeploymentHandle],
             app_name: str) -> Dict[str, Any]:
    from ray_tpu._private import common as _common

    d: Deployment = node._deployment
    _common._ensure_picklable_by_value(d.func_or_class)

    def sub(a):
        if isinstance(a, Application):
            return handle_env[a.name]
        return a

    args = tuple(sub(a) for a in node._args)
    kwargs = {k: sub(v) for k, v in node._kwargs.items()}
    import inspect as _inspect

    # the HTTP proxy streams (chunked transfer) when the ingress __call__
    # is a generator, and speaks ASGI when @serve.ingress wrapped it
    target = d.func_or_class if d.is_function else \
        getattr(d.func_or_class, "__call__", None)
    streaming = bool(target is not None and
                     (_inspect.isgeneratorfunction(target)
                      or _inspect.isasyncgenfunction(target)))
    return {
        "streaming": streaming,
        "asgi": bool(getattr(d.func_or_class, "__serve_asgi__", False)),
        "name": d.name,
        "num_replicas": d.num_replicas,
        "user_config": d.user_config,
        "max_ongoing_requests": d.max_ongoing_requests,
        "autoscaling_config": (d.autoscaling_config.to_dict()
                               if d.autoscaling_config else None),
        "ray_actor_options": d.ray_actor_options,
        "is_function": d.is_function,
        "callable_blob": cloudpickle.dumps(d.func_or_class),
        "init_args_blob": cloudpickle.dumps((args, kwargs)),
    }


def run(app: Application, *, name: str = "default",
        route_prefix: Optional[str] = DEFAULT_ROUTE_PREFIX,
        blocking_timeout_s: float = 60.0) -> DeploymentHandle:
    """Deploy an application and wait for it to be RUNNING; returns the
    ingress deployment's handle (reference: serve/api.py:463)."""
    import time

    controller = _get_controller()
    nodes = app._flatten()
    handle_env = {n.name: DeploymentHandle(n.name, name) for n in nodes}
    specs = [_spec_of(n, handle_env, name) for n in nodes]
    ray_tpu.get(controller.deploy_app.remote(
        name, route_prefix, specs, app.name), timeout=30.0)
    deadline = time.time() + blocking_timeout_s
    while time.time() < deadline:
        st = ray_tpu.get(controller.status.remote(), timeout=30.0)
        app_st = st.get(name)
        if app_st is not None and app_st.status == APP_RUNNING:
            return handle_env[app.name]
        if app_st is not None and app_st.status == DEPLOY_FAILED:
            raise RuntimeError(
                f"deploying app {name!r} failed: {app_st.message}")
        time.sleep(0.1)
    raise TimeoutError(f"app {name!r} did not become RUNNING "
                       f"in {blocking_timeout_s}s")


def status() -> Dict[str, Any]:
    return ray_tpu.get(_get_controller().status.remote(), timeout=30.0)


def delete(name: str):
    ray_tpu.get(_get_controller().delete_app.remote(name), timeout=30.0)
    reset_routers()


def get_app_handle(name: str = "default") -> DeploymentHandle:
    st = status()
    if name not in st:
        raise ValueError(f"no serve app named {name!r}")
    return DeploymentHandle(st[name].ingress, name)


def get_deployment_handle(deployment_name: str,
                          app_name: str = "default") -> DeploymentHandle:
    return DeploymentHandle(deployment_name, app_name)


def shutdown():
    """Tear down all apps, the proxy, and the controller."""
    global _controller_handle
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
    except (ValueError, Exception):
        _controller_handle = None
        reset_routers()
        return
    try:
        ray_tpu.get(controller.shutdown.remote(), timeout=30.0)
    except Exception:
        pass
    for proxy_name in ("SERVE_PROXY", "SERVE_RPC_PROXY"):
        try:
            ray_tpu.kill(ray_tpu.get_actor(proxy_name))
        except Exception:
            pass
    try:
        ray_tpu.kill(controller)
    except Exception:
        pass
    _controller_handle = None
    reset_routers()
