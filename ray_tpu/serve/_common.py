"""Shared Serve dataclasses (reference: python/ray/serve/schema.py,
serve/config.py AutoscalingConfig/DeploymentConfig)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

CONTROLLER_NAME = "SERVE_CONTROLLER"
DEFAULT_ROUTE_PREFIX = "/"

# replica states (reference: serve/_private/common.py ReplicaState)
STARTING = "STARTING"
RUNNING = "RUNNING"
STOPPING = "STOPPING"

# app states (reference: ApplicationStatus)
DEPLOYING = "DEPLOYING"
APP_RUNNING = "RUNNING"
DEPLOY_FAILED = "DEPLOY_FAILED"
DELETING = "DELETING"


@dataclass
class AutoscalingConfig:
    """reference: serve/config.py AutoscalingConfig."""

    min_replicas: int = 1
    max_replicas: int = 1
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 3.0
    downscale_delay_s: float = 30.0

    def to_dict(self) -> Dict[str, Any]:
        return {"min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas,
                "target_ongoing_requests": self.target_ongoing_requests,
                "upscale_delay_s": self.upscale_delay_s,
                "downscale_delay_s": self.downscale_delay_s}

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "AutoscalingConfig":
        return AutoscalingConfig(**d)


@dataclass
class ReplicaStatus:
    replica_id: str
    state: str
    ongoing: int = 0


@dataclass
class DeploymentStatus:
    name: str
    status: str
    target_num_replicas: int
    replicas: List[ReplicaStatus] = field(default_factory=list)
    message: str = ""


@dataclass
class ApplicationStatus:
    name: str
    status: str
    route_prefix: Optional[str]
    deployments: Dict[str, DeploymentStatus] = field(default_factory=dict)
    message: str = ""
    ingress: str = ""
