"""Shared Serve dataclasses (reference: python/ray/serve/schema.py,
serve/config.py AutoscalingConfig/DeploymentConfig)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

CONTROLLER_NAME = "SERVE_CONTROLLER"
DEFAULT_ROUTE_PREFIX = "/"


class NoCapacityError(Exception):
    """Every candidate replica is shedding (engine accepting=False):
    the router refuses the request up front so the proxy can answer
    503 + Retry-After instead of letting replica queues collapse."""

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = retry_after_s

# replica states (reference: serve/_private/common.py ReplicaState)
STARTING = "STARTING"
RUNNING = "RUNNING"
STOPPING = "STOPPING"

# app states (reference: ApplicationStatus)
DEPLOYING = "DEPLOYING"
APP_RUNNING = "RUNNING"
DEPLOY_FAILED = "DEPLOY_FAILED"
DELETING = "DELETING"


@dataclass
class AutoscalingConfig:
    """reference: serve/config.py AutoscalingConfig."""

    min_replicas: int = 1
    max_replicas: int = 1
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 3.0
    downscale_delay_s: float = 30.0
    # serve-SLO signals (0 = disabled): consumed by the controller's
    # _autoscale from decode-engine stats — average engine waiting-queue
    # depth per replica to hold, and the p99 time-to-first-token SLO
    target_queue_depth: float = 0.0
    ttft_slo_s: float = 0.0

    def to_dict(self) -> Dict[str, Any]:
        return {"min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas,
                "target_ongoing_requests": self.target_ongoing_requests,
                "upscale_delay_s": self.upscale_delay_s,
                "downscale_delay_s": self.downscale_delay_s,
                "target_queue_depth": self.target_queue_depth,
                "ttft_slo_s": self.ttft_slo_s}

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "AutoscalingConfig":
        return AutoscalingConfig(**d)


@dataclass
class ReplicaStatus:
    replica_id: str
    state: str
    ongoing: int = 0


@dataclass
class DeploymentStatus:
    name: str
    status: str
    target_num_replicas: int
    replicas: List[ReplicaStatus] = field(default_factory=list)
    message: str = ""


@dataclass
class ApplicationStatus:
    name: str
    status: str
    route_prefix: Optional[str]
    deployments: Dict[str, DeploymentStatus] = field(default_factory=dict)
    message: str = ""
    ingress: str = ""
