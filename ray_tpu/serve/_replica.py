"""Replica actor: hosts one copy of a deployment's callable.

Reference: python/ray/serve/_private/replica.py — the replica wraps the
user callable, tracks ongoing-request counts (the router's routing signal
and the controller's autoscaling signal), runs health checks, and applies
``reconfigure(user_config)`` without a restart.

Requests run as *async actor tasks*: ``handle_request`` is a coroutine, so
one replica interleaves up to max_ongoing_requests concurrent calls on its
event loop — the TPU-relevant case being a replica that holds a compiled
jax program and batches requests into it (see batching.py).
"""

from __future__ import annotations

import contextvars
import inspect
import time
from typing import Any, Dict, Optional

import cloudpickle

# set during request execution; read by serve.get_multiplexed_model_id()
_request_context: contextvars.ContextVar = contextvars.ContextVar(
    "serve_request_context", default=None)


class _FunctionWrapper:
    """Adapts a function deployment to the callable-object protocol."""

    def __init__(self, fn):
        self._fn = fn

    async def __call__(self, *args, **kwargs):
        out = self._fn(*args, **kwargs)
        if inspect.iscoroutine(out):
            out = await out
        return out


class Replica:
    def __init__(self, app_name: str, deployment_name: str, replica_id: str,
                 callable_blob: bytes, init_args_blob: bytes,
                 user_config: Optional[Any], is_function: bool):
        self.app_name = app_name
        self.deployment_name = deployment_name
        self.replica_id = replica_id
        self._ongoing = 0
        self._total = 0
        func_or_class = cloudpickle.loads(callable_blob)
        args, kwargs = cloudpickle.loads(init_args_blob)
        if is_function:
            self._callable = _FunctionWrapper(func_or_class)
        else:
            self._callable = func_or_class(*args, **kwargs)
        if user_config is not None:
            self._apply_reconfigure(user_config)

    # -- request path -------------------------------------------------------

    def _resolve_target(self, method_name: Optional[str],
                        allow_fallback: bool = False):
        if method_name in (None, "__call__") and callable(self._callable):
            return self._callable
        if allow_fallback:
            # opt-in (gRPC ingress routes RPC method names and declares
            # the fallback): a deployment that only defines __call__
            # still serves named RPCs.  NOT the default — handle callers
            # typo-ing a method name must keep getting AttributeError,
            # not a silently-wrong __call__ result.
            target = getattr(self._callable, method_name or "__call__",
                             None)
            if target is None and callable(self._callable):
                return self._callable
            if target is not None:
                return target
        return getattr(self._callable, method_name or "__call__")

    async def handle_request(self, method_name: Optional[str], args, kwargs,
                             metadata: Optional[Dict[str, Any]] = None):
        self._ongoing += 1
        self._total += 1
        token = _request_context.set(metadata or {})
        try:
            out = self._resolve_target(
                method_name,
                allow_fallback=bool((metadata or {}).get(
                    "_method_fallback")))(*args, **kwargs)
            if inspect.iscoroutine(out):
                out = await out
            return out
        finally:
            _request_context.reset(token)
            self._ongoing -= 1

    def handle_request_streaming(self, method_name: Optional[str], args,
                                 kwargs, metadata: Optional[Dict] = None):
        """Streaming request path (reference: proxy.py:864
        receive_asgi_messages / generator deployments): the user target's
        yields flow out as a streaming generator — the first token
        reaches the client while the rest is still being produced.

        Sync generator method: on this async-actor replica it drains in
        an executor thread (see worker_proc), so blocking iteration is
        fine; async generators pump on a private event loop."""
        import asyncio

        self._ongoing += 1
        self._total += 1
        token = _request_context.set(metadata or {})
        loop = None
        try:
            out = self._resolve_target(
                method_name,
                allow_fallback=bool((metadata or {}).get(
                    "_method_fallback")))(*args, **kwargs)
            if inspect.iscoroutine(out):
                # e.g. _FunctionWrapper: the coroutine may resolve to the
                # generator itself
                loop = asyncio.new_event_loop()
                out = loop.run_until_complete(out)
            if inspect.isasyncgen(out):
                loop = loop or asyncio.new_event_loop()
                while True:
                    try:
                        yield loop.run_until_complete(out.__anext__())
                    except StopAsyncIteration:
                        break
            elif inspect.isgenerator(out):
                yield from out
            else:
                yield out
        finally:
            if loop is not None:
                loop.close()
            _request_context.reset(token)
            self._ongoing -= 1

    # -- control path ---------------------------------------------------------

    def get_metrics(self) -> Dict[str, Any]:
        """Queue-length probe (router p2c) + autoscaling stats + loaded
        multiplexed models (router affinity) + decode-engine scheduler
        stats when the callable hosts one (queue depth / TTFT / page
        headroom — the serve-SLO autoscaling signals)."""
        from .multiplex import loaded_model_ids

        out = {"ongoing": self._ongoing, "total": self._total,
               "model_ids": loaded_model_ids(self._callable),
               "ts": time.time()}
        stats_fn = getattr(self._callable, "engine_stats", None)
        if stats_fn is not None:
            try:
                eng = stats_fn()
                if eng:
                    out["engine"] = eng
            except Exception:
                pass  # a metrics probe must never take the replica down
        return out

    def check_health(self) -> bool:
        fn = getattr(self._callable, "check_health", None)
        if fn is not None:
            fn()
        return True

    def _apply_reconfigure(self, user_config):
        fn = getattr(self._callable, "reconfigure", None)
        if fn is not None:
            fn(user_config)

    def reconfigure(self, user_config) -> bool:
        self._apply_reconfigure(user_config)
        return True

    async def prepare_shutdown(self, drain_s: float = 5.0) -> bool:
        """Drain: wait (cooperatively — this replica is an async actor, so
        in-flight requests keep running) until ongoing hits 0.  A callable
        that owns a decode engine drains it first (stop admitting, let
        active slots finish) instead of dropping the in-flight decodes
        when the actor is killed."""
        import asyncio

        deadline = time.time() + drain_s
        fn = getattr(self._callable, "prepare_shutdown", None)
        if fn is not None:
            # engine drain blocks: run it off the actor event loop so
            # concurrent metric probes / streaming reads keep flowing
            try:
                await asyncio.get_event_loop().run_in_executor(
                    None, lambda: fn(drain_s))
            except Exception:
                pass  # shutdown best-effort: the kill follows regardless
        while self._ongoing > 0 and time.time() < deadline:
            await asyncio.sleep(0.02)
        return True
