"""Cross-process collective API (reference:
python/ray/util/collective/collective.py — init_collective_group :120,
allreduce :258, barrier :298, broadcast :373, allgather :423,
reducescatter :472, send :531, recv :594, GroupManager :40).

Two backends:

  * "xla" — the declared intent for the data plane: the group's members are
    expected to be inside one SPMD program (jax.distributed multi-host or a
    local mesh); this module then only provides rendezvous/barrier, and the
    collectives themselves are the compiled helpers in xla_group.py.
  * "kv" — the Gloo-equivalent control-plane backend: CPU tensors move
    through the control-plane KV store with a rendezvous protocol (the
    reference's Gloo group bootstraps exactly this way through the Ray
    internal KV, reference: gloo_util.py:271 RayInternalKvStore).  Built for
    correctness of small control-plane syncs (init barriers, metric merges),
    not bandwidth.

The KV protocol is epoch-numbered: every op on a group bumps a local op
counter, keys are f"{group}/{op_idx}/{rank}"; readers poll-and-delete.
"""

from __future__ import annotations

import dataclasses
import functools
import pickle
import time
from typing import Any, Dict, List, Optional, Union

import numpy as np

from ray_tpu._private.protocol import Backoff
from ray_tpu.collective.compression import (CompressionConfig, compress_array,
                                            decompress_array,
                                            resolve_compression,
                                            result_block_size, wire_ratio)
from ray_tpu.util import tracing

_NS = "collective"


def _record_op(op: str, t0: float, x: Optional[np.ndarray] = None,
               cc: Optional[CompressionConfig] = None,
               breakdown: Optional[Dict[str, float]] = None,
               elapsed: Optional[float] = None):
    """Feed the flight recorder (telemetry.recorder): op latency into the
    current step's "collective" phase + Prometheus series, logical vs
    wire bytes so compression savings are visible in production.
    `breakdown` carries measured quantize/transfer/dequantize sub-phase
    seconds when the caller timed its stages; `elapsed` overrides the
    t0-derived duration (async ops report issue+finish time, not the
    caller's overlap window)."""
    try:
        from ray_tpu.telemetry import recorder as _rec

        payload = float(x.nbytes) if x is not None else 0.0
        wire = None
        if x is not None and cc is not None:
            wire = payload * wire_ratio(x.size, cc,
                                        baseline_itemsize=x.itemsize)
        dur = elapsed if elapsed is not None else time.perf_counter() - t0
        _rec.record_collective(op, dur, payload, wire, breakdown=breakdown)
    except Exception:
        pass


def _kv():
    from ray_tpu._private.core import current_core

    return current_core().control


def _kv_put(key: str, val: bytes):
    _kv().call("kv_put", {"ns": _NS, "key": key, "val": val})


def _kv_get(key: str, timeout: float = 120.0) -> bytes:
    deadline = time.monotonic() + timeout
    # jittered backoff (not a fixed-period busy-poll): groups of pollers
    # de-synchronize instead of hammering the control plane in lockstep
    bo = Backoff(base=0.002, cap=0.05)
    while True:
        v = _kv().call("kv_get", {"ns": _NS, "key": key})
        if v is not None:
            return v
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise TimeoutError(f"collective rendezvous timed out on {key}")
        bo.sleep(max_s=remaining)


def _kv_del(key: str):
    _kv().call("kv_del", {"ns": _NS, "key": key})


class GroupHandle:
    def __init__(self, name: str, world_size: int, rank: int, backend: str):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self.backend = backend
        self.op_idx = 0
        self._xla_jit_cache: Dict[tuple, Any] = {}

    def _key(self, op: str, rank: int) -> str:
        return f"{self.name}/{self.op_idx}/{op}/{rank}"


_groups: Dict[str, GroupHandle] = {}


def init_collective_group(world_size: int, rank: int, backend: str = "kv",
                          group_name: str = "default") -> GroupHandle:
    """Register this process as `rank` of `group_name` and barrier until all
    members arrive (reference: collective.py:120)."""
    if backend not in ("kv", "xla"):
        raise ValueError(f"unknown backend {backend!r}")
    with tracing.span("collective.init", group=group_name,
                      world_size=world_size, rank=rank, backend=backend):
        g = GroupHandle(group_name, world_size, rank, backend)
        _groups[group_name] = g
        _kv_put(f"{group_name}/init/{rank}", b"1")
        deadline = time.monotonic() + 120.0
        bo = Backoff(base=0.005, cap=0.1)
        while True:
            n = sum(1 for r in range(world_size)
                    if _kv().call(
                        "kv_exists",
                        {"ns": _NS, "key": f"{group_name}/init/{r}"}))
            if n == world_size:
                return g
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"collective group {group_name} init: only "
                    f"{n}/{world_size} arrived")
            bo.sleep(max_s=remaining)


def get_group_handle(group_name: str = "default") -> GroupHandle:
    if group_name not in _groups:
        raise ValueError(f"collective group {group_name!r} not initialized "
                         f"in this process")
    return _groups[group_name]


class CollectiveTeardownTimeout(RuntimeError):
    """destroy_collective_group(timeout=...) expired before every member
    posted its fin marker; the message names the absent ranks."""


def destroy_collective_group(group_name: str = "default",
                             timeout: Optional[float] = None):
    """Deregister and sweep the group's KV namespace.  Members that died
    mid-op leave `{name}/{op_idx}/{op}/{rank}` mailbox entries behind;
    without the sweep those leak in the control plane forever.

    Only the LAST member to arrive here sweeps: ranks leave a collective
    at different times (rank 0 posts the reduced result and returns
    before slower ranks have read it), so an early leaver deleting the
    shared `/-1` result key would strand a reader mid-poll for the full
    rendezvous timeout.  Members that died before destroy never post
    their fin marker, so their debris is swept when a later same-named
    group completes its own destroy over the shared prefix.

    With ``timeout=None`` (the default) an early leaver returns
    immediately without sweeping.  With a timeout, this member waits up
    to that many seconds for every fin marker and raises
    :class:`CollectiveTeardownTimeout` naming the ranks that never
    posted one — turning a silent KV leak into an actionable error."""
    g = _groups.pop(group_name, None)
    if g is None:
        return
    with tracing.span("collective.destroy", group=group_name,
                      world_size=g.world_size, rank=g.rank):
        _kv_put(f"{g.name}/fin/{g.rank}", b"1")

        def _missing() -> List[int]:
            return [r for r in range(g.world_size)
                    if not _kv().call(
                        "kv_exists",
                        {"ns": _NS, "key": f"{g.name}/fin/{r}"})]

        missing = _missing()
        if missing and timeout is not None:
            deadline = time.monotonic() + timeout
            bo = Backoff(base=0.005, cap=0.1)
            while missing:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise CollectiveTeardownTimeout(
                        f"destroy_collective_group({group_name!r}): timed "
                        f"out after {timeout}s waiting for fin markers "
                        f"from ranks {missing} of world {g.world_size} — "
                        f"those members likely died mid-run or never "
                        f"called destroy; their KV debris will be swept "
                        f"by the next same-named group's destroy")
                bo.sleep(max_s=remaining)
                missing = _missing()
        if missing:
            return
        prefix = f"{g.name}/"
        try:
            residual = _kv().call("kv_keys",
                                  {"ns": _NS, "prefix": prefix}) or []
        except Exception:
            residual = []
        for k in set(residual) | {f"{g.name}/init/{g.rank}"}:
            _kv_del(k)


def _as_numpy(t) -> np.ndarray:
    return np.asarray(t)


def barrier(group_name: str = "default"):
    """All members rendezvous (reference: collective.py:298)."""
    g = get_group_handle(group_name)
    g.op_idx += 1
    t0 = time.perf_counter()
    try:
        _kv_put(g._key("bar", g.rank), b"1")
        for r in range(g.world_size):
            _kv_get(g._key("bar", r))
    finally:
        _record_op("barrier", t0)


def _xla_stacked(g: GroupHandle, x: np.ndarray):
    """Global [world, *shape] jax.Array whose rank-r shard is rank r's
    tensor, over a mesh of one device per member process.  Requires the
    members to be processes of ONE jax.distributed runtime (the Train
    spmd backend sets that up); the compiled ops below are then real
    XLA collectives over that runtime — the NCCL-group analog, not the
    KV mailbox."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    # The compiled backend identifies group member r with process r of
    # the jax.distributed runtime: an xla group must be EXACTLY processes
    # 0..world_size-1, with this process participating as its own
    # process_index.  Anything else — runtime smaller than the group,
    # this process outside the group's process range, or a renumbered /
    # subset group — can't be expressed as a device mesh here and needs
    # backend='kv'.
    if jax.process_count() < g.world_size:
        raise RuntimeError(
            f"xla collective groups must be exactly processes "
            f"0..world_size-1 of one jax.distributed runtime, but this "
            f"group has world_size={g.world_size} while the runtime "
            f"spans only {jax.process_count()} process(es) — initialize "
            f"a large enough jax.distributed runtime first (Train's "
            f"JaxConfig(mode='spmd') does this), or use backend='kv'")
    if jax.process_index() >= g.world_size:
        raise RuntimeError(
            f"xla collective groups must be exactly processes "
            f"0..world_size-1 of the jax.distributed runtime, but this "
            f"process is process_index={jax.process_index()}, outside "
            f"the group's range 0..{g.world_size - 1} — a subset group "
            f"over other processes needs backend='kv'")
    if g.rank != jax.process_index():
        # the mesh maps member r to process r's first device; data for
        # another process's device is not addressable from here
        raise RuntimeError(
            f"xla collective groups must be exactly processes "
            f"0..world_size-1 in process order: this process's rank "
            f"({g.rank}) must equal its jax.process_index() "
            f"({jax.process_index()}); renumbered groups need "
            f"backend='kv'")
    first = {}
    for d in jax.devices():
        first.setdefault(d.process_index, d)
    devs = [first[i] for i in range(g.world_size)]
    mesh = Mesh(np.array(devs), ("cc",))
    arr = jax.make_array_from_single_device_arrays(
        (g.world_size,) + x.shape, NamedSharding(mesh, P("cc")),
        [jax.device_put(x[None], devs[g.rank])])
    return arr, mesh


def _xla_run(g: GroupHandle, x: np.ndarray, op_key: str, fn):
    """jit fn over the stacked global array with a replicated output,
    fetched back to host — every member executes the same program (SPMD:
    all members must call in the same order, like NCCL).  The jitted
    program is cached per (op, shape, dtype) on the handle; without
    that, per-call lambdas would re-trace+compile every invocation and
    the 'compiled' backend would lose to the KV mailbox it replaces."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    arr, mesh = _xla_stacked(g, x)
    cache_key = (op_key, x.shape, str(x.dtype))
    jitted = g._xla_jit_cache.get(cache_key)
    if jitted is None:
        jitted = g._xla_jit_cache[cache_key] = jax.jit(
            fn, out_shardings=NamedSharding(mesh, P()))
    return np.asarray(jitted(arr))


def _xla_sum(a):
    return a.sum(0)


def _xla_mean(a):
    return a.mean(0)


def _xla_max(a):
    return a.max(0)


def _xla_min(a):
    return a.min(0)


def _xla_identity(a):
    return a


def _xla_take_row(a, src: int):
    return a[src]


_XLA_REDUCE = {"sum": _xla_sum, "mean": _xla_mean, "max": _xla_max,
               "min": _xla_min}


def _resolve_op_compression(x: np.ndarray, op: str,
                            compression) -> Optional[CompressionConfig]:
    """Per-call compression decision.  Explicit arg wins; otherwise the
    group default / RAY_TPU_COLLECTIVE_COMPRESSION flag applies, but a
    defaulted config silently steps aside for ops it can't express
    (max/min) and payloads not worth compressing (small or non-float) —
    only an explicitly requested incompatible combination errors."""
    explicit = compression is not None
    cc = resolve_compression(compression)
    if cc is None:
        return None
    if op not in ("sum", "mean"):
        if explicit:
            raise ValueError(f"compressed allreduce supports op in "
                             f"('sum', 'mean'), got {op!r}")
        return None
    if x.size < cc.min_size or not np.issubdtype(x.dtype, np.floating):
        return None
    return cc


def _rng_for(g: GroupHandle, cc: CompressionConfig, rank: int):
    if not cc.stochastic:
        return None
    return np.random.default_rng((g.op_idx * (g.world_size + 1)) + rank + 1)


def _key_at(g: GroupHandle, idx: int, op: str, rank: int) -> str:
    """Mailbox key pinned to a captured op epoch — async ops finish after
    later ops have bumped g.op_idx, so they must not read it live."""
    return f"{g.name}/{idx}/{op}/{rank}"


def _new_breakdown() -> Dict[str, float]:
    return {"quantize": 0.0, "transfer": 0.0, "dequantize": 0.0}


def _kv_q_allreduce_issue(g: GroupHandle, idx: int, x: np.ndarray,
                          cc: CompressionConfig,
                          bd: Dict[str, float]) -> None:
    """Publish this rank's quantized contribution (the non-blocking half)."""
    t = time.perf_counter()
    payload = compress_array(x, cc, _rng_for(g, cc, g.rank))
    bd["quantize"] += time.perf_counter() - t
    t = time.perf_counter()
    _kv_put(_key_at(g, idx, "qar", g.rank), pickle.dumps(payload, protocol=5))
    bd["transfer"] += time.perf_counter() - t


def _kv_q_allreduce_finish(g: GroupHandle, idx: int, x: np.ndarray, op: str,
                           cc: CompressionConfig,
                           bd: Dict[str, float]) -> np.ndarray:
    """Reduce/fetch half: rank 0 dequantizes all contributions, reduces
    in f32, and republishes a requantized result so every rank lands on
    the SAME (quantized) value — same two-quantization structure as the
    compiled EQuARX path in xla_group.py."""
    if g.rank == 0:
        acc = np.zeros(x.shape, np.float32)
        for r in range(g.world_size):
            t = time.perf_counter()
            raw = _kv_get(_key_at(g, idx, "qar", r))
            bd["transfer"] += time.perf_counter() - t
            t = time.perf_counter()
            acc += decompress_array(pickle.loads(raw)).astype(np.float32)
            bd["dequantize"] += time.perf_counter() - t
        if op == "mean":
            acc /= g.world_size
        # finer result block: the republished value is the only
        # quantization the group sees from here (compression.result_block_size)
        rcc = dataclasses.replace(cc, block_size=result_block_size(
            cc.block_size))
        t = time.perf_counter()
        result = compress_array(acc, rcc, _rng_for(g, cc, g.world_size))
        bd["quantize"] += time.perf_counter() - t
        t = time.perf_counter()
        _kv_put(_key_at(g, idx, "qar", -1),
                pickle.dumps(result, protocol=5))
        bd["transfer"] += time.perf_counter() - t
    else:
        t = time.perf_counter()
        result = pickle.loads(_kv_get(_key_at(g, idx, "qar", -1)))
        bd["transfer"] += time.perf_counter() - t
    t = time.perf_counter()
    out = decompress_array(result).astype(x.dtype)
    bd["dequantize"] += time.perf_counter() - t
    return out


def _kv_compressed_allreduce(g: GroupHandle, x: np.ndarray, op: str,
                             cc: CompressionConfig,
                             bd: Optional[Dict[str, float]] = None
                             ) -> np.ndarray:
    """KV allreduce shipping int8 blocks + scales (~0.25x the wire bytes
    at block=256); issue + finish back-to-back."""
    if bd is None:
        bd = _new_breakdown()
    idx = g.op_idx
    _kv_q_allreduce_issue(g, idx, x, cc, bd)
    return _kv_q_allreduce_finish(g, idx, x, op, cc, bd)


def _xla_compressed_allreduce_issue(g: GroupHandle, x: np.ndarray, op: str,
                                    cc: CompressionConfig):
    """Dispatch the compiled EQuARX path over the group's device mesh and
    return the (asynchronously executing) device array: the two-phase
    quantized allreduce from xla_group.py (same caching contract as
    _xla_run)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu.collective import xla_group

    arr, mesh = _xla_stacked(g, x)
    chunks = xla_group._resolve_chunks(cc, x.size, x.dtype.itemsize)
    cache_key = (f"q-allreduce-{op}-{cc.block_size}-{int(cc.stochastic)}"
                 f"-c{chunks}", x.shape, str(x.dtype))
    jitted = g._xla_jit_cache.get(cache_key)
    if jitted is None:
        def fn(a, seed):
            red = xla_group._q_allreduce_impl(a, seed, mesh, "cc", op,
                                              cc.block_size, cc.stochastic,
                                              chunks)
            return red[0]

        jitted = g._xla_jit_cache[cache_key] = jax.jit(
            fn, out_shardings=NamedSharding(mesh, P()))
    return jitted(arr, jnp.int32(g.op_idx))


def _xla_compressed_allreduce(g: GroupHandle, x: np.ndarray, op: str,
                              cc: CompressionConfig) -> np.ndarray:
    return np.asarray(_xla_compressed_allreduce_issue(g, x, op, cc))


def allreduce(tensor, group_name: str = "default", op: str = "sum",
              compression: Union[None, str, "CompressionConfig"] = None):
    """Allreduce; returns the reduced array (reference: collective.py:258).
    kv backend: rank 0 reduces through the KV plane, others fetch.
    xla backend: one compiled XLA all-reduce over the members' devices.

    compression: "int8" (or a CompressionConfig / spec string like
    "int8:block=512,stochastic=1") moves the payload as block-wise int8
    + per-block scales on either backend — ~4x fewer wire bytes for a
    bounded quantization error (sum/mean only).  Defaults to the group's
    installed config or the RAY_TPU_COLLECTIVE_COMPRESSION flag."""
    g = get_group_handle(group_name)
    g.op_idx += 1
    x = _as_numpy(tensor)
    cc = _resolve_op_compression(x, op, compression)
    t0 = time.perf_counter()
    bd = None
    try:
        if g.backend == "xla":
            if op not in _XLA_REDUCE:
                raise ValueError(f"unknown op {op}")
            if cc is not None:
                return _xla_compressed_allreduce(g, x, op, cc)
            return _xla_run(g, x, f"allreduce-{op}", _XLA_REDUCE[op])
        if cc is not None:
            bd = _new_breakdown()
            return _kv_compressed_allreduce(g, x, op, cc, bd)
        _kv_put(g._key("ar", g.rank), pickle.dumps(x, protocol=5))
        if g.rank == 0:
            acc = x.copy()
            for r in range(1, g.world_size):
                other = pickle.loads(_kv_get(g._key("ar", r)))
                if op == "sum" or op == "mean":
                    acc = acc + other
                elif op == "max":
                    acc = np.maximum(acc, other)
                elif op == "min":
                    acc = np.minimum(acc, other)
                else:
                    raise ValueError(f"unknown op {op}")
            if op == "mean":
                acc = acc / g.world_size
            _kv_put(g._key("ar", -1), pickle.dumps(acc, protocol=5))
            return acc
        return pickle.loads(_kv_get(g._key("ar", -1)))
    finally:
        _record_op("allreduce", t0, x, cc, breakdown=bd)


class AllreduceHandle:
    """In-flight allreduce from :func:`allreduce_async`; ``result()``
    blocks for (and caches) the reduced array.  Issue order IS the op
    order — every member must issue the same sequence of collectives,
    matching the SPMD discipline of the synchronous API — but results
    may be awaited late, so callers can keep producing bucket k+1 while
    bucket k's reduce is in flight (the GradientSynchronizer pipeline)."""

    def __init__(self, finish):
        self._finish = finish
        self._value = None

    def result(self) -> np.ndarray:
        if self._finish is not None:
            self._value = self._finish()
            self._finish = None
        return self._value


def allreduce_async(tensor, group_name: str = "default", op: str = "sum",
                    compression: Union[None, str, "CompressionConfig"] = None
                    ) -> AllreduceHandle:
    """Issue an allreduce and return an :class:`AllreduceHandle` without
    blocking for the result.

    kv backend: this rank's (possibly quantized) contribution is
    published immediately; the reduce/fetch half runs at ``result()``,
    so quantize+publish of the next bucket overlaps peers' posting of
    this one.  xla backend: the compiled program is dispatched
    asynchronously (XLA's async execution IS the overlap) and
    ``result()`` fetches the host copy.  Telemetry records issue+finish
    time — not the caller's overlap window — under the same sub-phase
    breakdown as the blocking path."""
    g = get_group_handle(group_name)
    g.op_idx += 1
    x = _as_numpy(tensor)
    cc = _resolve_op_compression(x, op, compression)
    t0 = time.perf_counter()
    if g.backend == "xla":
        if op not in _XLA_REDUCE:
            raise ValueError(f"unknown op {op}")
        if cc is not None:
            fut = _xla_compressed_allreduce_issue(g, x, op, cc)
        else:
            arr, mesh = _xla_stacked(g, x)
            cache_key = (f"allreduce-{op}", x.shape, str(x.dtype))
            jitted = g._xla_jit_cache.get(cache_key)
            if jitted is None:
                import jax
                from jax.sharding import NamedSharding, PartitionSpec as P

                jitted = g._xla_jit_cache[cache_key] = jax.jit(
                    _XLA_REDUCE[op],
                    out_shardings=NamedSharding(mesh, P()))
            fut = jitted(arr)
        issued = time.perf_counter() - t0

        def finish_xla():
            t1 = time.perf_counter()
            out = np.asarray(fut)
            _record_op("allreduce", t0, x, cc,
                       elapsed=issued + time.perf_counter() - t1)
            return out

        return AllreduceHandle(finish_xla)
    idx = g.op_idx
    if cc is not None:
        bd = _new_breakdown()
        _kv_q_allreduce_issue(g, idx, x, cc, bd)
        issued = time.perf_counter() - t0

        def finish_q():
            t1 = time.perf_counter()
            out = _kv_q_allreduce_finish(g, idx, x, op, cc, bd)
            _record_op("allreduce", t0, x, cc, breakdown=bd,
                       elapsed=issued + time.perf_counter() - t1)
            return out

        return AllreduceHandle(finish_q)
    if op not in ("sum", "mean", "max", "min"):
        raise ValueError(f"unknown op {op}")
    _kv_put(_key_at(g, idx, "ar", g.rank), pickle.dumps(x, protocol=5))
    issued = time.perf_counter() - t0

    def finish_kv():
        t1 = time.perf_counter()
        if g.rank == 0:
            acc = x.copy()
            for r in range(1, g.world_size):
                other = pickle.loads(_kv_get(_key_at(g, idx, "ar", r)))
                if op in ("sum", "mean"):
                    acc = acc + other
                elif op == "max":
                    acc = np.maximum(acc, other)
                else:
                    acc = np.minimum(acc, other)
            if op == "mean":
                acc = acc / g.world_size
            _kv_put(_key_at(g, idx, "ar", -1), pickle.dumps(acc, protocol=5))
            out = acc
        else:
            out = pickle.loads(_kv_get(_key_at(g, idx, "ar", -1)))
        _record_op("allreduce", t0, x, cc,
                   elapsed=issued + time.perf_counter() - t1)
        return out

    return AllreduceHandle(finish_kv)


def allgather(tensor, group_name: str = "default",
              compression: Union[None, str, "CompressionConfig"] = None
              ) -> List[np.ndarray]:
    """Every member receives every member's tensor, rank-ordered
    (reference: collective.py:423).  With `compression`, each tensor
    travels as int8 blocks + scales (lossy, kv backend only — the xla
    backend stays full precision for gather since its payload is already
    on-device)."""
    g = get_group_handle(group_name)
    g.op_idx += 1
    x = _as_numpy(tensor)
    cc = _resolve_op_compression(x, "sum", compression) \
        if compression is not None else None
    t0 = time.perf_counter()
    try:
        if g.backend == "xla":
            stacked = _xla_run(g, x, "allgather", _xla_identity)
            return [stacked[r] for r in range(g.world_size)]
        if cc is not None:
            payload = compress_array(x, cc, _rng_for(g, cc, g.rank))
            _kv_put(g._key("qag", g.rank), pickle.dumps(payload, protocol=5))
            return [decompress_array(pickle.loads(_kv_get(g._key("qag", r))))
                    .astype(x.dtype) for r in range(g.world_size)]
        _kv_put(g._key("ag", g.rank), pickle.dumps(x, protocol=5))
        return [pickle.loads(_kv_get(g._key("ag", r)))
                for r in range(g.world_size)]
    finally:
        _record_op("allgather", t0, x,
                   cc if g.backend != "xla" else None)


def reducescatter(tensor, group_name: str = "default", op: str = "sum",
                  compression: Union[None, str, "CompressionConfig"] = None):
    """Reduce then scatter equal chunks; returns this rank's chunk
    (reference: collective.py:472).  `compression` applies to the
    underlying allreduce (sum/mean only)."""
    g = get_group_handle(group_name)
    full = allreduce(tensor, group_name, op=op, compression=compression)
    chunks = np.array_split(full, g.world_size, axis=0)
    return chunks[g.rank]


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    """Root's tensor to everyone (reference: collective.py:373).

    xla backend: SPMD — EVERY rank must pass a tensor of the same shape
    and dtype (non-root values are ignored but shape the program); the
    kv backend only reads the root's tensor."""
    g = get_group_handle(group_name)
    g.op_idx += 1
    t0 = time.perf_counter()
    x = None
    try:
        if g.backend == "xla":
            if tensor is None:
                raise TypeError(
                    "broadcast on the xla backend is an SPMD op: every rank "
                    "must pass a same-shape/dtype tensor (non-root values "
                    "are ignored); got None — pass e.g. np.zeros_like(root)")
            x = _as_numpy(tensor)
            return _xla_run(g, x, f"broadcast-{src_rank}",
                            functools.partial(_xla_take_row, src=src_rank))
        if g.rank == src_rank:
            x = _as_numpy(tensor)
            _kv_put(g._key("bc", src_rank), pickle.dumps(x, protocol=5))
            return x
        out = pickle.loads(_kv_get(g._key("bc", src_rank)))
        x = out
        return out
    finally:
        _record_op("broadcast", t0, x)


def send(tensor, dst_rank: int, group_name: str = "default"):
    """P2P send via KV mailbox (reference: collective.py:531)."""
    g = get_group_handle(group_name)
    if g.backend == "xla":
        raise NotImplementedError(
            "send/recv are not SPMD ops (only two members participate); "
            "use backend='kv' for p2p, or ppermute inside a shard_map "
            "program for the compiled path")
    g.op_idx += 1
    _kv_put(g._key(f"p2p-{g.rank}-{dst_rank}", g.rank),
            pickle.dumps(_as_numpy(tensor), protocol=5))


def recv(src_rank: int, group_name: str = "default"):
    """P2P recv (reference: collective.py:594).  The sender and receiver
    must issue matching op sequences (same as NCCL send/recv pairing)."""
    g = get_group_handle(group_name)
    if g.backend == "xla":
        raise NotImplementedError(
            "send/recv are not SPMD ops (only two members participate); "
            "use backend='kv' for p2p, or ppermute inside a shard_map "
            "program for the compiled path")
    g.op_idx += 1
    return pickle.loads(_kv_get(g._key(f"p2p-{src_rank}-{g.rank}", src_rank)))
