from .collective import (allgather, allreduce, barrier, broadcast,
                         destroy_collective_group, get_group_handle,
                         init_collective_group, recv, reducescatter, send)
from .xla_group import (mesh_allgather, mesh_allreduce, mesh_all_to_all,
                        mesh_broadcast, mesh_ppermute, mesh_reducescatter)

__all__ = [
    "init_collective_group", "destroy_collective_group", "get_group_handle",
    "allreduce", "allgather", "reducescatter", "broadcast", "barrier",
    "send", "recv",
    "mesh_allreduce", "mesh_allgather", "mesh_reducescatter",
    "mesh_broadcast", "mesh_ppermute", "mesh_all_to_all",
]
