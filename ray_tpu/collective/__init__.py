from .collective import (AllreduceHandle, allgather, allreduce,
                         allreduce_async, barrier, broadcast,
                         destroy_collective_group, get_group_handle,
                         init_collective_group, recv, reducescatter, send)
from .compression import (CompressionConfig, compress_array, decompress_array,
                          parse_compression, resolve_compression,
                          set_group_compression)
from .xla_group import (mesh_allgather, mesh_allreduce, mesh_all_to_all,
                        mesh_broadcast, mesh_ppermute, mesh_reducescatter)

__all__ = [
    "init_collective_group", "destroy_collective_group", "get_group_handle",
    "allreduce", "allreduce_async", "AllreduceHandle",
    "allgather", "reducescatter", "broadcast", "barrier",
    "send", "recv",
    "CompressionConfig", "parse_compression", "resolve_compression",
    "set_group_compression", "compress_array", "decompress_array",
    "mesh_allreduce", "mesh_allgather", "mesh_reducescatter",
    "mesh_broadcast", "mesh_ppermute", "mesh_all_to_all",
]
