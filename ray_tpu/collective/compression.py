"""Compression config + host wire codec for compressed collectives.

Two consumers share this module:

  * The kv backend in `collective/collective.py` ships gradients through
    the control plane as pickled payloads; the codec here turns an f32
    ndarray into (int8 values, f32 per-block scales) — ~0.25x the wire
    bytes at block=256 — using ONLY numpy, so importing it never drags
    jax into the control-plane path.
  * The xla backend and `parallel/sharding.py` consume `CompressionConfig`
    (and its spec-string round-trip) to parameterize the in-graph
    quantized collectives in `xla_group.py` / `ops/quantize.py`.

The numerics here mirror `ops/quantize.py` bit-for-bit for deterministic
rounding (same absmax/127 scale, numpy's round half-to-even matches
jnp.round), which is what lets error feedback recompute the compression
residual on the host without a second wire round trip.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple, Union

import numpy as np

INT8_MAX = 127.0

_TRUE = ("1", "true", "yes", "on")


@dataclasses.dataclass(frozen=True)
class CompressionConfig:
    """How a collective compresses payloads.

    dtype: quantized wire dtype — only "int8" today.
    block_size: elements per scale block; smaller = lower error, more
        scale overhead (wire ratio ~= 1/4 + 1/block_size at int8).
    stochastic: unbiased stochastic rounding instead of round-to-even.
        Useful without error feedback; with EF, deterministic rounding
        lets the residual be recomputed exactly.
    error_feedback: accumulate the per-parameter compression residual
        and re-inject it next step (keeps compressed SGD convergent).
        Consumed by `parallel/sharding.GradientSynchronizer`, not by the
        one-shot collective calls.
    min_size: arrays with fewer elements ship uncompressed (scale
        overhead would beat the savings).
    """

    dtype: str = "int8"
    block_size: int = 256
    stochastic: bool = False
    error_feedback: bool = True
    min_size: int = 1024

    def __post_init__(self):
        if self.dtype != "int8":
            raise ValueError(
                f"unsupported compression dtype {self.dtype!r}; only 'int8'")
        if self.block_size <= 0:
            raise ValueError(
                f"block_size must be positive, got {self.block_size}")

    def to_spec(self) -> str:
        """Inverse of parse_compression — env-var/CLI-safe string."""
        return (f"{self.dtype}:block={self.block_size}"
                f",stochastic={int(self.stochastic)}"
                f",ef={int(self.error_feedback)}"
                f",min={self.min_size}")


def result_block_size(block_size: int) -> int:
    """Block size for the second (result) quantization of a two-phase
    allreduce.  The reduced value is quantized exactly once on its way
    back, so finer per-block scales there buy error margin almost for
    free: at block=256 contributions, a block/8 result stage moves
    ~0.27x the baseline wire bytes total while cutting the result-stage
    error by ~35% (two equal int8 stages sit right AT the 1e-2 line;
    this keeps the end-to-end error near 0.009 with margin)."""
    return max(16, block_size // 8)


def parse_compression(
    spec: Union[None, str, CompressionConfig]) -> Optional[CompressionConfig]:
    """Parse "int8" / "int8:block=512,stochastic=1,ef=0,min=0" (or pass
    through a config / None).  Empty string means off."""
    if spec is None or isinstance(spec, CompressionConfig):
        return spec
    spec = spec.strip()
    if not spec or spec.lower() in ("none", "off", "0", "false"):
        return None
    dtype, _, rest = spec.partition(":")
    kw: Dict[str, object] = {"dtype": dtype.strip()}
    if rest:
        for item in rest.split(","):
            k, sep, v = item.partition("=")
            if not sep:
                raise ValueError(f"bad compression spec item {item!r} "
                                 f"in {spec!r} (want key=value)")
            k, v = k.strip(), v.strip()
            if k == "block":
                kw["block_size"] = int(v)
            elif k == "stochastic":
                kw["stochastic"] = v.lower() in _TRUE
            elif k == "ef":
                kw["error_feedback"] = v.lower() in _TRUE
            elif k == "min":
                kw["min_size"] = int(v)
            else:
                raise ValueError(f"unknown compression spec key {k!r} in "
                                 f"{spec!r} (known: block, stochastic, ef, "
                                 f"min)")
    return CompressionConfig(**kw)  # type: ignore[arg-type]


# Per-process group default, installed by the Train backend so workers
# compress without threading a config through every allreduce call.
_group_default: Optional[CompressionConfig] = None


def set_group_compression(
        spec: Union[None, str, CompressionConfig]) -> Optional[CompressionConfig]:
    global _group_default
    _group_default = parse_compression(spec)
    return _group_default


def resolve_compression(
    spec: Union[None, str, CompressionConfig] = None,
    *, use_default: bool = True) -> Optional[CompressionConfig]:
    """Precedence: explicit arg > group default > RAY_TPU_COLLECTIVE_COMPRESSION
    flag.  Explicit "off"/"" disables even when a default is installed."""
    if spec is not None:
        return parse_compression(spec)
    if not use_default:
        return None
    if _group_default is not None:
        return _group_default
    from ray_tpu._private.config import cfg
    return parse_compression(cfg().collective_compression)


# ---------------------------------------------------------------------------
# Host wire codec (numpy; kv backend + error-feedback residuals)
# ---------------------------------------------------------------------------


def _host_blocks(x: np.ndarray, block_size: int) -> np.ndarray:
    flat = np.asarray(x, dtype=np.float32).reshape(-1)
    pad = (-flat.size) % block_size
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, np.float32)])
    return flat.reshape(-1, block_size)


def compress_array(x: np.ndarray, config: CompressionConfig,
                   rng: Optional[np.random.Generator] = None) -> dict:
    """ndarray -> wire payload dict (pickles to ~0.25x the f32 bytes).

    Payload keys: v (int8 [npad]), s (f32 [nblocks]), shape, dtype (str),
    block.  `rng` drives stochastic rounding when config.stochastic.
    """
    x = np.asarray(x)
    blocks = _host_blocks(x, config.block_size)
    absmax = np.max(np.abs(blocks), axis=-1, keepdims=True)
    scales = np.where(absmax > 0, absmax / INT8_MAX, 1.0).astype(np.float32)
    y = blocks / scales
    if config.stochastic:
        rng = rng or np.random.default_rng(0)
        y = np.floor(y + rng.random(y.shape, dtype=np.float32))
    else:
        y = np.round(y)  # numpy rounds half-to-even, matching jnp.round
    q = np.clip(y, -INT8_MAX, INT8_MAX).astype(np.int8)
    return {"v": q.reshape(-1), "s": scales[:, 0], "shape": x.shape,
            "dtype": str(x.dtype), "block": config.block_size}


def decompress_array(payload: dict) -> np.ndarray:
    q = payload["v"].reshape(-1, payload["block"]).astype(np.float32)
    out = q * payload["s"][:, None]
    n = int(np.prod(payload["shape"])) if payload["shape"] else 1
    return out.reshape(-1)[:n].reshape(payload["shape"]).astype(
        payload["dtype"])


def compression_residual(x: np.ndarray, config: CompressionConfig) -> np.ndarray:
    """x - decompress(compress(x)) with deterministic rounding — the error
    that error feedback carries to the next step."""
    det = dataclasses.replace(config, stochastic=False)
    return np.asarray(x, np.float32) - decompress_array(
        compress_array(x, det)).astype(np.float32)


def wire_bytes(payload: dict) -> int:
    return payload["v"].nbytes + payload["s"].nbytes


def wire_ratio(n_elements: int, config: CompressionConfig,
               baseline_itemsize: int = 4) -> float:
    """Compressed wire bytes / uncompressed, for n f32 elements."""
    block = config.block_size
    npad = n_elements + (-n_elements) % block
    compressed = npad * 1 + (npad // block) * 4
    return compressed / float(n_elements * baseline_itemsize)
