"""Compression config + host wire codec for compressed collectives.

Two consumers share this module:

  * The kv backend in `collective/collective.py` ships gradients through
    the control plane as pickled payloads; the codec here turns an f32
    ndarray into (int8 values, f32 per-block scales) — ~0.25x the wire
    bytes at block=256 — using ONLY numpy, so importing it never drags
    jax into the control-plane path.
  * The xla backend and `parallel/sharding.py` consume `CompressionConfig`
    (and its spec-string round-trip) to parameterize the in-graph
    quantized collectives in `xla_group.py` / `ops/quantize.py`.

The numerics here mirror `ops/quantize.py` bit-for-bit for deterministic
rounding (same absmax/127 scale, numpy's round half-to-even matches
jnp.round), which is what lets error feedback recompute the compression
residual on the host without a second wire round trip.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple, Union

import numpy as np

INT8_MAX = 127.0

_TRUE = ("1", "true", "yes", "on")


@dataclasses.dataclass(frozen=True)
class CompressionConfig:
    """How a collective compresses payloads.

    dtype: quantized wire dtype — only "int8" today.
    block_size: elements per scale block; smaller = lower error, more
        scale overhead (wire ratio ~= 1/4 + 1/block_size at int8).
    stochastic: unbiased stochastic rounding instead of round-to-even.
        Useful without error feedback; with EF, deterministic rounding
        lets the residual be recomputed exactly.
    error_feedback: accumulate the per-parameter compression residual
        and re-inject it next step (keeps compressed SGD convergent).
        Consumed by `parallel/sharding.GradientSynchronizer`, not by the
        one-shot collective calls.
    min_size: arrays with fewer elements ship uncompressed (scale
        overhead would beat the savings).
    pipeline_chunks: split large tensors into this many block-aligned
        chunks inside the quantized allreduce and double-buffer them —
        quantize of chunk k+1 overlaps transfer of chunk k.  0 = auto
        (tuned from tensor size and backend; see auto_pipeline_chunks).
        1 = monolithic.  Chunked and monolithic results are bit-identical
        for deterministic rounding (chunk boundaries are block-aligned so
        every per-block scale is unchanged).
    bucket_bytes: GradientSynchronizer coalesces per-parameter gradients
        into flat buckets of about this many (f32) bytes, so many small
        leaves ride one pipelined collective instead of one blocking
        call each.
    """

    dtype: str = "int8"
    block_size: int = 256
    stochastic: bool = False
    error_feedback: bool = True
    min_size: int = 1024
    pipeline_chunks: int = 0
    bucket_bytes: int = 4 << 20

    def __post_init__(self):
        if self.dtype != "int8":
            raise ValueError(
                f"unsupported compression dtype {self.dtype!r}; only 'int8'")
        if self.block_size <= 0:
            raise ValueError(
                f"block_size must be positive, got {self.block_size}")
        if self.pipeline_chunks < 0:
            raise ValueError(
                f"pipeline_chunks must be >= 0 (0 = auto), got "
                f"{self.pipeline_chunks}")
        if self.bucket_bytes <= 0:
            raise ValueError(
                f"bucket_bytes must be positive, got {self.bucket_bytes}")

    def to_spec(self) -> str:
        """Inverse of parse_compression — env-var/CLI-safe string."""
        return (f"{self.dtype}:block={self.block_size}"
                f",stochastic={int(self.stochastic)}"
                f",ef={int(self.error_feedback)}"
                f",min={self.min_size}"
                f",chunks={self.pipeline_chunks}"
                f",bucket={self.bucket_bytes}")


def result_block_size(block_size: int) -> int:
    """Block size for the second (result) quantization of a two-phase
    allreduce.  The reduced value is quantized exactly once on its way
    back, so finer per-block scales there buy error margin almost for
    free: at block=256 contributions, a block/8 result stage moves
    ~0.27x the baseline wire bytes total while cutting the result-stage
    error by ~35% (two equal int8 stages sit right AT the 1e-2 line;
    this keeps the end-to-end error near 0.009 with margin)."""
    return max(16, block_size // 8)


# Per-chunk payload the auto-tuner aims for.  Small enough that a few
# chunks fit in flight (quantize of k+1 behind transfer of k), large
# enough that per-chunk collective launch overhead stays negligible.
CHUNK_TARGET_BYTES = 4 << 20
MAX_PIPELINE_CHUNKS = 8


def auto_pipeline_chunks(n_elements: int, itemsize: int = 4,
                         backend: str = "") -> int:
    """Pick a pipeline chunk count for an n-element tensor.

    Pure math (no jax import); callers pass the device backend string.
    On hosts where the "interconnect" is shared memory (the cpu backend —
    incl. XLA_FLAGS-forced multi-device CPU meshes) transfer is a memcpy
    that cannot be hidden behind compute, and every extra chunk adds a
    collective rendezvous, so auto always picks 1 there.  On real
    accelerator fabrics, chunk so each piece is ~CHUNK_TARGET_BYTES."""
    if backend not in ("tpu", "gpu"):
        return 1
    total = int(n_elements) * int(itemsize)
    if total < 2 * CHUNK_TARGET_BYTES:
        return 1
    return min(MAX_PIPELINE_CHUNKS, total // CHUNK_TARGET_BYTES)


def chunk_layout(n_blocks: int, chunks: int) -> Tuple[int, ...]:
    """Split n_blocks quantization blocks into `chunks` contiguous runs.

    Chunk boundaries land ON block boundaries by construction — that is
    what keeps the chunked allreduce bit-identical to the monolithic one
    (every per-block absmax/scale sees exactly the same elements).  The
    remainder is spread over the leading chunks, so uneven splits (say 7
    blocks into 2 chunks -> (4, 3)) stay valid.  Requesting more chunks
    than blocks clamps; empty chunks are never returned.
    """
    if chunks <= 0:
        raise ValueError(
            f"pipeline chunk count must be >= 1, got {chunks} — use "
            f"pipeline_chunks=0 on CompressionConfig for auto-tuning or 1 "
            f"to disable chunking")
    if n_blocks <= 0:
        raise ValueError(f"n_blocks must be >= 1, got {n_blocks}")
    chunks = min(chunks, n_blocks)
    base, extra = divmod(n_blocks, chunks)
    return tuple(base + (1 if c < extra else 0) for c in range(chunks))


def validate_chunk_elems(chunk_elems: int, block_size: int) -> None:
    """Guard for callers that slice their own chunks (rather than going
    through chunk_layout, which can only produce aligned chunks): a chunk
    whose size is not a block multiple would shift every later block
    boundary, silently changing per-block scales and breaking both the
    chunked==monolithic guarantee and host-codec residual recomputation."""
    if chunk_elems % block_size:
        raise ValueError(
            f"pipeline chunk of {chunk_elems} elements is not a multiple "
            f"of block_size={block_size}: chunk boundaries must land on "
            f"quantization-block boundaries or the per-block scales (and "
            f"the bit-exact host-codec contract) change.  Pick a chunk "
            f"count that divides the tensor into block-aligned pieces "
            f"(compression.chunk_layout does this), or pad the tensor to "
            f"a multiple of block_size first")


def parse_compression(
    spec: Union[None, str, CompressionConfig]) -> Optional[CompressionConfig]:
    """Parse "int8" / "int8:block=512,stochastic=1,ef=0,min=0,chunks=4,
    bucket=4194304" (or pass through a config / None).  Empty string
    means off."""
    if spec is None or isinstance(spec, CompressionConfig):
        return spec
    spec = spec.strip()
    if not spec or spec.lower() in ("none", "off", "0", "false"):
        return None
    dtype, _, rest = spec.partition(":")
    kw: Dict[str, object] = {"dtype": dtype.strip()}
    if rest:
        for item in rest.split(","):
            k, sep, v = item.partition("=")
            if not sep:
                raise ValueError(f"bad compression spec item {item!r} "
                                 f"in {spec!r} (want key=value)")
            k, v = k.strip(), v.strip()
            if k == "block":
                kw["block_size"] = int(v)
            elif k == "stochastic":
                kw["stochastic"] = v.lower() in _TRUE
            elif k == "ef":
                kw["error_feedback"] = v.lower() in _TRUE
            elif k == "min":
                kw["min_size"] = int(v)
            elif k == "chunks":
                kw["pipeline_chunks"] = int(v)
            elif k == "bucket":
                kw["bucket_bytes"] = int(v)
            else:
                raise ValueError(f"unknown compression spec key {k!r} in "
                                 f"{spec!r} (known: block, stochastic, ef, "
                                 f"min, chunks, bucket)")
    return CompressionConfig(**kw)  # type: ignore[arg-type]


# Per-process group default, installed by the Train backend so workers
# compress without threading a config through every allreduce call.
_group_default: Optional[CompressionConfig] = None


def set_group_compression(
        spec: Union[None, str, CompressionConfig]) -> Optional[CompressionConfig]:
    global _group_default
    _group_default = parse_compression(spec)
    return _group_default


def resolve_compression(
    spec: Union[None, str, CompressionConfig] = None,
    *, use_default: bool = True) -> Optional[CompressionConfig]:
    """Precedence: explicit arg > group default > RAY_TPU_COLLECTIVE_COMPRESSION
    flag.  Explicit "off"/"" disables even when a default is installed."""
    if spec is not None:
        return parse_compression(spec)
    if not use_default:
        return None
    if _group_default is not None:
        return _group_default
    from ray_tpu._private.config import cfg
    return parse_compression(cfg().collective_compression)


# ---------------------------------------------------------------------------
# Host wire codec (numpy; kv backend + error-feedback residuals)
# ---------------------------------------------------------------------------


def _host_blocks(x: np.ndarray, block_size: int) -> np.ndarray:
    flat = np.asarray(x, dtype=np.float32).reshape(-1)
    pad = (-flat.size) % block_size
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, np.float32)])
    return flat.reshape(-1, block_size)


def compress_array(x: np.ndarray, config: CompressionConfig,
                   rng: Optional[np.random.Generator] = None) -> dict:
    """ndarray -> wire payload dict (pickles to ~0.25x the f32 bytes).

    Payload keys: v (int8 [npad]), s (f32 [nblocks]), shape, dtype (str),
    block.  `rng` drives stochastic rounding when config.stochastic.
    """
    x = np.asarray(x)
    blocks = _host_blocks(x, config.block_size)
    absmax = np.max(np.abs(blocks), axis=-1, keepdims=True)
    scales = np.where(absmax > 0, absmax / INT8_MAX, 1.0).astype(np.float32)
    # multiply by the rounded reciprocal instead of dividing: divides are
    # the slowest VPU/host op in the codec, and 1/scale is IEEE-identical
    # between numpy and XLA, so the jit path (ops/quantize.py) makes the
    # same substitution and the bit-exactness contract holds
    y = blocks * (np.float32(1.0) / scales)
    if config.stochastic:
        rng = rng or np.random.default_rng(0)
        y = np.floor(y + rng.random(y.shape, dtype=np.float32))
    else:
        y = np.round(y)  # numpy rounds half-to-even, matching jnp.round
    q = np.clip(y, -INT8_MAX, INT8_MAX).astype(np.int8)
    return {"v": q.reshape(-1), "s": scales[:, 0], "shape": x.shape,
            "dtype": str(x.dtype), "block": config.block_size}


def decompress_array(payload: dict) -> np.ndarray:
    q = payload["v"].reshape(-1, payload["block"]).astype(np.float32)
    out = q * payload["s"][:, None]
    n = int(np.prod(payload["shape"])) if payload["shape"] else 1
    return out.reshape(-1)[:n].reshape(payload["shape"]).astype(
        payload["dtype"])


def compression_residual(x: np.ndarray, config: CompressionConfig) -> np.ndarray:
    """x - decompress(compress(x)) with deterministic rounding — the error
    that error feedback carries to the next step."""
    det = dataclasses.replace(config, stochastic=False)
    return np.asarray(x, np.float32) - decompress_array(
        compress_array(x, det)).astype(np.float32)


def wire_bytes(payload: dict) -> int:
    return payload["v"].nbytes + payload["s"].nbytes


def wire_ratio(n_elements: int, config: CompressionConfig,
               baseline_itemsize: int = 4) -> float:
    """Compressed wire bytes / uncompressed, for n f32 elements."""
    block = config.block_size
    npad = n_elements + (-n_elements) % block
    compressed = npad * 1 + (npad // block) * 4
    return compressed / float(n_elements * baseline_itemsize)
