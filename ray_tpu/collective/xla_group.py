"""Compiled (XLA) collectives over a device mesh — the NCCL-group analog.

The reference's NCCL collective group issues runtime library calls per
operation (reference: nccl_collective_group.py:830 LoC of stream/comm
management).  On TPU the idiomatic equivalent is *compiled* collectives:
`shard_map` over a `jax.sharding.Mesh` lowers `lax.psum`/`all_gather`/
`psum_scatter`/`ppermute`/`all_to_all` to ICI/DCN programs fused into the
surrounding computation.  These helpers give that capability the shape of a
collective API for code that isn't already inside a pjit program; inside
one, use `jax.lax` primitives directly.

All helpers are single-controller: they operate on (possibly sharded) global
arrays over the local mesh.  The multi-process story is the Train backend
(jax.distributed + the same compiled collectives across hosts).
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ray_tpu._private.jax_compat import shard_map

from ray_tpu.collective.compression import (CompressionConfig,
                                            parse_compression,
                                            result_block_size, wire_ratio)
from ray_tpu.ops.quantize import (dequantize_blockwise, padded_len,
                                  quantize_blockwise)
from ray_tpu.util import tracing

import time


def _record_mesh_op(op: str, t0: float, x,
                    cc: Optional[CompressionConfig]) -> None:
    """Report dispatch time + byte counters to the flight recorder.
    Dispatch-side only — no forced fence here: blocking the hot path to
    measure it would serialize the very overlap XLA buys us.  Device
    time lands in the step's fenced total instead."""
    try:
        from ray_tpu.telemetry import recorder as _rec

        nbytes = float(getattr(x, "nbytes", 0) or 0)
        wire = None
        if nbytes and cc is not None:
            itemsize = getattr(getattr(x, "dtype", None), "itemsize", 4)
            wire = nbytes * wire_ratio(x.size, cc,
                                       baseline_itemsize=itemsize)
        _rec.record_collective(op, time.perf_counter() - t0, nbytes, wire)
    except Exception:
        pass


def _axis(mesh: Mesh, axis_name: Optional[str]) -> str:
    if axis_name is None:
        if len(mesh.axis_names) != 1:
            raise ValueError(f"specify axis_name for multi-axis mesh "
                             f"{mesh.axis_names}")
        return mesh.axis_names[0]
    return axis_name


@functools.partial(jax.jit, static_argnames=("mesh", "axis", "op"))
def _allreduce_impl(x, mesh: Mesh, axis: str, op: str):
    spec = P(axis)

    def f(shard):
        if op == "sum":
            return jax.lax.psum(shard, axis)
        if op == "max":
            return jax.lax.pmax(shard, axis)
        if op == "min":
            return jax.lax.pmin(shard, axis)
        if op == "mean":
            return jax.lax.pmean(shard, axis)
        raise ValueError(f"unknown reduce op {op}")

    return shard_map(f, check_vma=False, mesh=mesh, in_specs=spec, out_specs=spec)(x)


def mesh_allreduce(x, mesh: Mesh, axis_name: Optional[str] = None,
                   op: str = "sum",
                   compression: Union[None, str, CompressionConfig] = None,
                   seed: int = 0):
    """Allreduce a leading-axis-sharded array across a mesh axis.

    x has a per-device leading chunk layout [n_dev * k, ...]; each device's
    chunk is reduced with its peers' — the allreduce of the NCCL API, but
    compiled (reference API: collective.py:258 allreduce).

    compression: a CompressionConfig / spec string ("int8", "int8:block=512")
    switches to the EQuARX-style two-phase quantized path: blockwise int8
    quantize → all_to_all (the reduce-scatter phase) → dequantize+reduce →
    requantize → all_gather → dequantize once per block.  Wire traffic
    drops ~4x; result carries quantization error (sum/mean only).  `seed`
    feeds stochastic rounding when the config asks for it."""
    axis = _axis(mesh, axis_name)
    cc = parse_compression(compression)
    t0 = time.perf_counter()
    with tracing.span("collective.mesh_allreduce", axis=axis, op=op,
                      compressed=cc is not None):
        if cc is None:
            out = _allreduce_impl(x, mesh, axis, op)
        else:
            if op not in ("sum", "mean"):
                raise ValueError(f"compressed allreduce supports op in "
                                 f"('sum', 'mean'), got {op!r}")
            out = _q_allreduce_impl(x, jnp.int32(seed), mesh, axis, op,
                                    cc.block_size, cc.stochastic)
    _record_mesh_op("mesh_allreduce", t0, x, cc)
    return out


# ---------------------------------------------------------------------------
# Quantized (EQuARX-style) variants.  Same shard_map in/out contracts as the
# full-precision impls above; inside the body the payload moves between
# devices as int8 blocks + f32 per-block scales (ops/quantize.py layout).
# ---------------------------------------------------------------------------


def _fold_key(seed, axis: str, stochastic: bool):
    if not stochastic:
        return None
    return jax.random.fold_in(jax.random.PRNGKey(seed),
                              jax.lax.axis_index(axis))


def _dequant_rows(q, s, world: int, block: int):
    # q [world, nblk*block] int8, s [world, nblk] -> f32 [world, nblk, block]
    return q.reshape(world, -1, block).astype(jnp.float32) * s[:, :, None]


@functools.partial(jax.jit, static_argnames=("mesh", "axis", "op", "block",
                                             "stochastic"))
def _q_allreduce_impl(x, seed, mesh: Mesh, axis: str, op: str, block: int,
                      stochastic: bool):
    world = mesh.shape[axis]
    spec = P(axis)

    def f(shard, seed_):
        shape, dtype = shard.shape, shard.dtype
        flat = shard.reshape(-1).astype(jnp.float32)
        n = flat.shape[0]
        total = padded_len(n, world * block)
        if total != n:
            flat = jnp.pad(flat, (0, total - n))
        sub = total // world
        nblk = sub // block
        idx = jax.lax.axis_index(axis)
        key = _fold_key(seed_, axis, stochastic)
        q, s = quantize_blockwise(flat.reshape(world, sub), block,
                                  stochastic=stochastic, key=key,
                                  seed=seed_ * world + idx)
        # phase 1 (reduce-scatter): all_to_all hands device i every peer's
        # sub-chunk i, still in int8
        qx = jax.lax.all_to_all(q.reshape(world, sub), axis, split_axis=0,
                                concat_axis=0, tiled=True)
        sx = jax.lax.all_to_all(s.reshape(world, nblk), axis, split_axis=0,
                                concat_axis=0, tiled=True)
        red = _dequant_rows(qx, sx, world, block).sum(axis=0).reshape(sub)
        if op == "mean":
            red = red / world
        # phase 2 (allgather): requantize the reduced chunk this device
        # owns — with a finer result block, the only quantization the
        # receivers see (see compression.result_block_size)
        rblock = result_block_size(block)
        key2 = jax.random.fold_in(key, world) if stochastic else None
        q2, s2 = quantize_blockwise(red, rblock, stochastic=stochastic,
                                    key=key2, seed=seed_ * world + idx + 1)
        qg = jax.lax.all_gather(q2, axis, tiled=True)
        sg = jax.lax.all_gather(s2, axis, tiled=True)
        # per-device chunks may carry rblock padding; dequantize row-wise
        # and strip it before restitching the flat stream
        out = _dequant_rows(qg.reshape(world, -1), sg.reshape(world, -1),
                            world, rblock)
        out = out.reshape(world, -1)[:, :sub]
        return out.reshape(-1)[:n].reshape(shape).astype(dtype)

    return shard_map(f, check_vma=False, mesh=mesh, in_specs=(spec, P()),
                     out_specs=spec)(x, seed)


@functools.partial(jax.jit, static_argnames=("mesh", "axis", "block",
                                             "stochastic"))
def _q_reducescatter_impl(x, seed, mesh: Mesh, axis: str, block: int,
                          stochastic: bool):
    world = mesh.shape[axis]

    def f(shard, seed_):
        row = shard[0].astype(jnp.float32)      # this device's [N] contribution
        sub = row.shape[0] // world
        sub_pad = padded_len(sub, block)
        chunks = row.reshape(world, sub)
        if sub_pad != sub:
            chunks = jnp.pad(chunks, ((0, 0), (0, sub_pad - sub)))
        idx = jax.lax.axis_index(axis)
        key = _fold_key(seed_, axis, stochastic)
        q, s = quantize_blockwise(chunks, block, stochastic=stochastic,
                                  key=key, seed=seed_ * world + idx)
        qx = jax.lax.all_to_all(q.reshape(world, sub_pad), axis, split_axis=0,
                                concat_axis=0, tiled=True)
        sx = jax.lax.all_to_all(s.reshape(world, sub_pad // block), axis,
                                split_axis=0, concat_axis=0, tiled=True)
        red = _dequant_rows(qx, sx, world, block).sum(axis=0).reshape(sub_pad)
        return red[:sub][None].astype(shard.dtype)

    return shard_map(f, check_vma=False, mesh=mesh, in_specs=(P(axis), P()),
                     out_specs=P(axis))(x, seed)


@functools.partial(jax.jit, static_argnames=("mesh", "axis", "block",
                                             "stochastic"))
def _q_allgather_impl(x, seed, mesh: Mesh, axis: str, block: int,
                      stochastic: bool):
    world = mesh.shape[axis]

    def f(shard, seed_):
        flat = shard.reshape(-1).astype(jnp.float32)
        n = flat.shape[0]
        npad = padded_len(n, block)
        idx = jax.lax.axis_index(axis)
        key = _fold_key(seed_, axis, stochastic)
        q, s = quantize_blockwise(flat, block, stochastic=stochastic,
                                  key=key, seed=seed_ * world + idx)
        qg = jax.lax.all_gather(q, axis, tiled=True).reshape(world, npad)
        sg = jax.lax.all_gather(s, axis, tiled=True).reshape(world, -1)
        out = _dequant_rows(qg, sg, world, block).reshape(world, npad)[:, :n]
        return out.reshape((world * shard.shape[0],)
                           + shard.shape[1:]).astype(shard.dtype)

    return shard_map(f, check_vma=False, mesh=mesh, in_specs=(P(axis), P()),
                     out_specs=P())(x, seed)


@functools.partial(jax.jit, static_argnames=("mesh", "axis", "tiled"))
def _allgather_impl(x, mesh: Mesh, axis: str, tiled: bool):
    def f(shard):
        return jax.lax.all_gather(shard, axis, tiled=tiled)

    return shard_map(f, check_vma=False, mesh=mesh, in_specs=P(axis), out_specs=P())(x)


def mesh_allgather(x, mesh: Mesh, axis_name: Optional[str] = None,
                   compression: Union[None, str, CompressionConfig] = None,
                   seed: int = 0):
    """Each device contributes its shard; all get the concatenation
    (reference API: collective.py:423 allgather).  With `compression`,
    shards travel as int8 blocks + scales and are dequantized on arrival
    (lossy; see compression.py)."""
    axis = _axis(mesh, axis_name)
    cc = parse_compression(compression)
    t0 = time.perf_counter()
    with tracing.span("collective.mesh_allgather", axis=axis,
                      compressed=cc is not None):
        if cc is None:
            out = _allgather_impl(x, mesh, axis, True)
        else:
            out = _q_allgather_impl(x, jnp.int32(seed), mesh, axis,
                                    cc.block_size, cc.stochastic)
    _record_mesh_op("mesh_allgather", t0, x, cc)
    return out


@functools.partial(jax.jit, static_argnames=("mesh", "axis"))
def _reducescatter_impl(x, mesh: Mesh, axis: str):
    def f(shard):
        # shard is [1, N] (this device's contribution row); NCCL semantics:
        # reduce all rows, each device keeps its N/world chunk
        y = jax.lax.psum_scatter(shard[0], axis, scatter_dimension=0,
                                 tiled=True)
        return y[None]

    return shard_map(f, check_vma=False, mesh=mesh, in_specs=P(axis),
                     out_specs=P(axis))(x)


def mesh_reducescatter(x, mesh: Mesh, axis_name: Optional[str] = None,
                       compression: Union[None, str, CompressionConfig] = None,
                       seed: int = 0):
    """Reduce across the axis, leave each device its scattered chunk
    (reference API: collective.py:472 reducescatter).  Input is the stacked
    per-device contributions [world, N]; output [world, N/world] where row r
    is the reduced chunk owned by device r.  With `compression`,
    contributions travel as int8 blocks + scales (sum semantics, lossy)."""
    axis = _axis(mesh, axis_name)
    cc = parse_compression(compression)
    t0 = time.perf_counter()
    with tracing.span("collective.mesh_reducescatter", axis=axis,
                      compressed=cc is not None):
        if cc is None:
            out = _reducescatter_impl(x, mesh, axis)
        else:
            world = mesh.shape[axis]
            if x.shape[-1] % world:
                raise ValueError(
                    f"compressed reducescatter needs the payload dim "
                    f"({x.shape[-1]}) divisible by the axis size "
                    f"({world})")
            out = _q_reducescatter_impl(x, jnp.int32(seed), mesh, axis,
                                        cc.block_size, cc.stochastic)
    _record_mesh_op("mesh_reducescatter", t0, x, cc)
    return out


def mesh_broadcast(x, mesh: Mesh, axis_name: Optional[str] = None,
                   root: int = 0):
    """Every device receives root's shard (reference API: collective.py:373)."""
    axis = _axis(mesh, axis_name)
    n = mesh.shape[axis]

    @functools.partial(jax.jit, static_argnames=())
    def run(v):
        def f(shard):
            # rotate root's shard to everyone: gather then index is simplest
            # and XLA turns the gather+slice into a broadcast from root
            full = jax.lax.all_gather(shard, axis)
            return full[root]

        return shard_map(f, check_vma=False, mesh=mesh, in_specs=P(axis), out_specs=P(axis))(v)

    return run(x)


def mesh_ppermute(x, mesh: Mesh, perm: Sequence[tuple],
                  axis_name: Optional[str] = None):
    """Point-to-point shard rotation — the send/recv of the compiled world
    (reference API: collective.py:531/:594 send/recv); the building block of
    ring attention and pipeline microbatching."""
    axis = _axis(mesh, axis_name)
    perm = tuple((int(a), int(b)) for a, b in perm)

    @functools.partial(jax.jit)
    def run(v):
        def f(shard):
            return jax.lax.ppermute(shard, axis, perm)

        return shard_map(f, check_vma=False, mesh=mesh, in_specs=P(axis), out_specs=P(axis))(v)

    return run(x)


def mesh_all_to_all(x, mesh: Mesh, axis_name: Optional[str] = None,
                    split_axis: int = 1, concat_axis: int = 0):
    """All-to-all reshard — the Ulysses/MoE-dispatch primitive.

    With the array sharded on dim 0 over the mesh axis, each device splits
    its shard along `split_axis` and exchanges pieces, concatenating along
    `concat_axis` (maps to lax.all_to_all; EP token dispatch and
    sequence<->head resharding are this one op)."""
    axis = _axis(mesh, axis_name)

    @functools.partial(jax.jit)
    def run(v):
        def f(shard):
            return jax.lax.all_to_all(shard, axis, split_axis, concat_axis,
                                      tiled=True)

        return shard_map(f, check_vma=False, mesh=mesh, in_specs=P(axis), out_specs=P(axis))(v)

    return run(x)
