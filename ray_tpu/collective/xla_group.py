"""Compiled (XLA) collectives over a device mesh — the NCCL-group analog.

The reference's NCCL collective group issues runtime library calls per
operation (reference: nccl_collective_group.py:830 LoC of stream/comm
management).  On TPU the idiomatic equivalent is *compiled* collectives:
`shard_map` over a `jax.sharding.Mesh` lowers `lax.psum`/`all_gather`/
`psum_scatter`/`ppermute`/`all_to_all` to ICI/DCN programs fused into the
surrounding computation.  These helpers give that capability the shape of a
collective API for code that isn't already inside a pjit program; inside
one, use `jax.lax` primitives directly.

All helpers are single-controller: they operate on (possibly sharded) global
arrays over the local mesh.  The multi-process story is the Train backend
(jax.distributed + the same compiled collectives across hosts).
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ray_tpu._private.jax_compat import shard_map


def _axis(mesh: Mesh, axis_name: Optional[str]) -> str:
    if axis_name is None:
        if len(mesh.axis_names) != 1:
            raise ValueError(f"specify axis_name for multi-axis mesh "
                             f"{mesh.axis_names}")
        return mesh.axis_names[0]
    return axis_name


@functools.partial(jax.jit, static_argnames=("mesh", "axis", "op"))
def _allreduce_impl(x, mesh: Mesh, axis: str, op: str):
    spec = P(axis)

    def f(shard):
        if op == "sum":
            return jax.lax.psum(shard, axis)
        if op == "max":
            return jax.lax.pmax(shard, axis)
        if op == "min":
            return jax.lax.pmin(shard, axis)
        if op == "mean":
            return jax.lax.pmean(shard, axis)
        raise ValueError(f"unknown reduce op {op}")

    return shard_map(f, check_vma=False, mesh=mesh, in_specs=spec, out_specs=spec)(x)


def mesh_allreduce(x, mesh: Mesh, axis_name: Optional[str] = None,
                   op: str = "sum"):
    """Allreduce a leading-axis-sharded array across a mesh axis.

    x has a per-device leading chunk layout [n_dev * k, ...]; each device's
    chunk is reduced with its peers' — the allreduce of the NCCL API, but
    compiled (reference API: collective.py:258 allreduce)."""
    axis = _axis(mesh, axis_name)
    return _allreduce_impl(x, mesh, axis, op)


@functools.partial(jax.jit, static_argnames=("mesh", "axis", "tiled"))
def _allgather_impl(x, mesh: Mesh, axis: str, tiled: bool):
    def f(shard):
        return jax.lax.all_gather(shard, axis, tiled=tiled)

    return shard_map(f, check_vma=False, mesh=mesh, in_specs=P(axis), out_specs=P())(x)


def mesh_allgather(x, mesh: Mesh, axis_name: Optional[str] = None):
    """Each device contributes its shard; all get the concatenation
    (reference API: collective.py:423 allgather)."""
    axis = _axis(mesh, axis_name)
    return _allgather_impl(x, mesh, axis, True)


@functools.partial(jax.jit, static_argnames=("mesh", "axis"))
def _reducescatter_impl(x, mesh: Mesh, axis: str):
    def f(shard):
        # shard is [1, N] (this device's contribution row); NCCL semantics:
        # reduce all rows, each device keeps its N/world chunk
        y = jax.lax.psum_scatter(shard[0], axis, scatter_dimension=0,
                                 tiled=True)
        return y[None]

    return shard_map(f, check_vma=False, mesh=mesh, in_specs=P(axis),
                     out_specs=P(axis))(x)


def mesh_reducescatter(x, mesh: Mesh, axis_name: Optional[str] = None):
    """Reduce across the axis, leave each device its scattered chunk
    (reference API: collective.py:472 reducescatter).  Input is the stacked
    per-device contributions [world, N]; output [world, N/world] where row r
    is the reduced chunk owned by device r."""
    axis = _axis(mesh, axis_name)
    return _reducescatter_impl(x, mesh, axis)


def mesh_broadcast(x, mesh: Mesh, axis_name: Optional[str] = None,
                   root: int = 0):
    """Every device receives root's shard (reference API: collective.py:373)."""
    axis = _axis(mesh, axis_name)
    n = mesh.shape[axis]

    @functools.partial(jax.jit, static_argnames=())
    def run(v):
        def f(shard):
            # rotate root's shard to everyone: gather then index is simplest
            # and XLA turns the gather+slice into a broadcast from root
            full = jax.lax.all_gather(shard, axis)
            return full[root]

        return shard_map(f, check_vma=False, mesh=mesh, in_specs=P(axis), out_specs=P(axis))(v)

    return run(x)


def mesh_ppermute(x, mesh: Mesh, perm: Sequence[tuple],
                  axis_name: Optional[str] = None):
    """Point-to-point shard rotation — the send/recv of the compiled world
    (reference API: collective.py:531/:594 send/recv); the building block of
    ring attention and pipeline microbatching."""
    axis = _axis(mesh, axis_name)
    perm = tuple((int(a), int(b)) for a, b in perm)

    @functools.partial(jax.jit)
    def run(v):
        def f(shard):
            return jax.lax.ppermute(shard, axis, perm)

        return shard_map(f, check_vma=False, mesh=mesh, in_specs=P(axis), out_specs=P(axis))(v)

    return run(x)


def mesh_all_to_all(x, mesh: Mesh, axis_name: Optional[str] = None,
                    split_axis: int = 1, concat_axis: int = 0):
    """All-to-all reshard — the Ulysses/MoE-dispatch primitive.

    With the array sharded on dim 0 over the mesh axis, each device splits
    its shard along `split_axis` and exchanges pieces, concatenating along
    `concat_axis` (maps to lax.all_to_all; EP token dispatch and
    sequence<->head resharding are this one op)."""
    axis = _axis(mesh, axis_name)

    @functools.partial(jax.jit)
    def run(v):
        def f(shard):
            return jax.lax.all_to_all(shard, axis, split_axis, concat_axis,
                                      tiled=True)

        return shard_map(f, check_vma=False, mesh=mesh, in_specs=P(axis), out_specs=P(axis))(v)

    return run(x)
