"""Compiled (XLA) collectives over a device mesh — the NCCL-group analog.

The reference's NCCL collective group issues runtime library calls per
operation (reference: nccl_collective_group.py:830 LoC of stream/comm
management).  On TPU the idiomatic equivalent is *compiled* collectives:
`shard_map` over a `jax.sharding.Mesh` lowers `lax.psum`/`all_gather`/
`psum_scatter`/`ppermute`/`all_to_all` to ICI/DCN programs fused into the
surrounding computation.  These helpers give that capability the shape of a
collective API for code that isn't already inside a pjit program; inside
one, use `jax.lax` primitives directly.

All helpers are single-controller: they operate on (possibly sharded) global
arrays over the local mesh.  The multi-process story is the Train backend
(jax.distributed + the same compiled collectives across hosts).
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ray_tpu._private.jax_compat import shard_map

from ray_tpu.collective.compression import (CompressionConfig,
                                            auto_pipeline_chunks,
                                            chunk_layout, parse_compression,
                                            result_block_size, wire_ratio)
from ray_tpu.ops.quantize import (dequantize_accumulate, dequantize_blockwise,
                                  fused_reduce_scatter, fused_rs_vmem_bytes,
                                  padded_len, quantize_blockwise)
from ray_tpu.util import tracing

import os
import time


def _record_mesh_op(op: str, t0: float, x, cc: Optional[CompressionConfig],
                    breakdown: Optional[dict] = None) -> None:
    """Report dispatch time + byte counters to the flight recorder.
    Dispatch-side only — no forced fence here: blocking the hot path to
    measure it would serialize the very overlap XLA buys us.  Device
    time lands in the step's fenced total instead.  `breakdown` carries
    measured quantize/transfer/dequantize sub-phase seconds when the
    caller ran the staged (fenced) profiling path."""
    try:
        from ray_tpu.telemetry import recorder as _rec

        nbytes = float(getattr(x, "nbytes", 0) or 0)
        wire = None
        if nbytes and cc is not None:
            itemsize = getattr(getattr(x, "dtype", None), "itemsize", 4)
            wire = nbytes * wire_ratio(x.size, cc,
                                       baseline_itemsize=itemsize)
        _rec.record_collective(op, time.perf_counter() - t0, nbytes, wire,
                               breakdown=breakdown)
    except Exception:
        pass


def _axis(mesh: Mesh, axis_name: Optional[str]) -> str:
    if axis_name is None:
        if len(mesh.axis_names) != 1:
            raise ValueError(f"specify axis_name for multi-axis mesh "
                             f"{mesh.axis_names}")
        return mesh.axis_names[0]
    return axis_name


@functools.partial(jax.jit, static_argnames=("mesh", "axis", "op"))
def _allreduce_impl(x, mesh: Mesh, axis: str, op: str):
    spec = P(axis)

    def f(shard):
        if op == "sum":
            return jax.lax.psum(shard, axis)
        if op == "max":
            return jax.lax.pmax(shard, axis)
        if op == "min":
            return jax.lax.pmin(shard, axis)
        if op == "mean":
            return jax.lax.pmean(shard, axis)
        raise ValueError(f"unknown reduce op {op}")

    return shard_map(f, check_vma=False, mesh=mesh, in_specs=spec, out_specs=spec)(x)


def _resolve_chunks(cc: CompressionConfig, n_elements: int,
                    itemsize: int) -> int:
    if cc.pipeline_chunks:
        return cc.pipeline_chunks
    return auto_pipeline_chunks(n_elements, itemsize, jax.default_backend())


# Largest per-chunk VMEM footprint the fused single-kernel reduce-scatter
# will accept before falling back to the staged kernels (quantize kernel
# -> all_to_all -> dequant-accumulate kernel).
_FUSED_RS_VMEM_CAP = 8 << 20


def _resolve_rs_impl(impl: str, world: int, block: int, stochastic: bool,
                     max_chunk_elems: int) -> str:
    """Pick how the reduce-scatter phase runs.  "fused" = the one-kernel
    quantize->remote-DMA-exchange->accumulate path (TPU only,
    deterministic rounding only, chunk must fit VMEM);
    "fused_interpret" forces the same kernel through the pallas
    interpreter (CPU tests); anything else takes the XLA-lowered
    fallback with identical numerics."""
    if impl != "auto":
        return impl
    if os.environ.get("RAY_TPU_FUSED_RS", "1") in ("0", "false", "off"):
        return "xla"
    if (jax.default_backend() == "tpu" and not stochastic
            and block % 128 == 0 and world > 1
            and fused_rs_vmem_bytes(world, max_chunk_elems)
            <= _FUSED_RS_VMEM_CAP):
        return "fused"
    return "xla"


def mesh_allreduce(x, mesh: Mesh, axis_name: Optional[str] = None,
                   op: str = "sum",
                   compression: Union[None, str, CompressionConfig] = None,
                   seed: int = 0, impl: str = "auto",
                   profile: bool = False):
    """Allreduce a leading-axis-sharded array across a mesh axis.

    x has a per-device leading chunk layout [n_dev * k, ...]; each device's
    chunk is reduced with its peers' — the allreduce of the NCCL API, but
    compiled (reference API: collective.py:258 allreduce).

    compression: a CompressionConfig / spec string ("int8", "int8:block=512")
    switches to the EQuARX-style two-phase quantized path: blockwise int8
    quantize → all_to_all (the reduce-scatter phase) → fused
    dequantize+accumulate → requantize → all_gather → dequantize.  Wire
    traffic drops ~4x; result carries quantization error (sum/mean only).
    `seed` feeds stochastic rounding when the config asks for it.

    The quantized path is chunked and pipelined per
    `CompressionConfig.pipeline_chunks` (0 = auto): the tensor is split
    into block-aligned chunks emitted so quantization of chunk k+1
    overlaps the exchange of chunk k and the accumulate of chunk k-1
    (XLA's latency-hiding scheduler does the overlap; chunk results are
    bit-identical to the monolithic path for deterministic rounding).
    On TPU, each chunk's reduce-scatter hop runs as ONE pallas kernel
    (quantize -> remote DMA exchange -> accumulate, never leaving VMEM);
    `impl` overrides the choice ("fused", "fused_interpret", "xla").

    profile=True runs the same numerics as separate fenced stage
    programs and reports measured quantize/transfer/dequantize sub-phase
    seconds to the flight recorder — attribution mode for bench/debug;
    the fused path stays the production default because the fences
    serialize the very overlap the pipeline buys."""
    axis = _axis(mesh, axis_name)
    cc = parse_compression(compression)
    t0 = time.perf_counter()
    breakdown = None
    with tracing.span("collective.mesh_allreduce", axis=axis, op=op,
                      compressed=cc is not None):
        if cc is None:
            out = _allreduce_impl(x, mesh, axis, op)
        else:
            if op not in ("sum", "mean"):
                raise ValueError(f"compressed allreduce supports op in "
                                 f"('sum', 'mean'), got {op!r}")
            if profile:
                out, breakdown = _q_allreduce_profiled(
                    x, jnp.int32(seed), mesh, axis, op, cc, impl)
            else:
                chunks = _resolve_chunks(cc, x.size, x.dtype.itemsize)
                out = _q_allreduce_impl(x, jnp.int32(seed), mesh, axis, op,
                                        cc.block_size, cc.stochastic,
                                        chunks, impl)
    _record_mesh_op("mesh_allreduce", t0, x, cc, breakdown)
    return out


# ---------------------------------------------------------------------------
# Quantized (EQuARX-style) variants.  Same shard_map in/out contracts as the
# full-precision impls above; inside the body the payload moves between
# devices as int8 blocks + f32 per-block scales (ops/quantize.py layout).
# ---------------------------------------------------------------------------


def _fold_key(seed, axis: str, stochastic: bool):
    if not stochastic:
        return None
    return jax.random.fold_in(jax.random.PRNGKey(seed),
                              jax.lax.axis_index(axis))


def _dequant_rows(q, s, world: int, block: int):
    # q [world, nblk*block] int8, s [world, nblk] -> f32 [world, nblk, block]
    return q.reshape(world, -1, block).astype(jnp.float32) * s[:, :, None]


@functools.partial(jax.jit, static_argnames=("mesh", "axis", "op", "block",
                                             "stochastic", "chunks", "impl"))
def _q_allreduce_impl(x, seed, mesh: Mesh, axis: str, op: str, block: int,
                      stochastic: bool, chunks: int = 1, impl: str = "auto"):
    """Chunked, software-pipelined two-phase quantized allreduce.

    The flat payload is padded to a world*block multiple, viewed as
    [world, sub], and split column-wise into `chunks` block-aligned
    pieces (compression.chunk_layout).  Per chunk the EQuARX structure
    runs: quantize -> all_to_all (the reduce-scatter hop, still int8) ->
    fused dequantize-accumulate -> requantize at the finer result block
    -> all_gather -> dequantize.  Emission order is software-pipelined —
    chunk k+1's quantize is emitted before chunk k's exchange is
    consumed — so XLA's latency-hiding scheduler overlaps codec compute
    with transfer; there is no barrier between chunks.

    Because chunk boundaries land on (result-)block boundaries, every
    per-block scale sees exactly the elements it would monolithically,
    and the f32 accumulation order over the world axis is unchanged:
    chunked and monolithic results are BIT-IDENTICAL for deterministic
    rounding (stochastic draws differ per chunk layout and are exempt).

    On TPU (impl="fused"/auto) each chunk's whole reduce-scatter hop is
    ONE pallas kernel doing quantize -> remote-DMA exchange ->
    accumulate in VMEM (ops/quantize.fused_reduce_scatter);
    "fused_interpret" drives the same kernel through the pallas
    interpreter on CPU meshes, and the default CPU path is the
    XLA-lowered stage sequence with identical numerics."""
    world = mesh.shape[axis]
    spec = P(axis)

    def f(shard, seed_):
        shape, dtype = shard.shape, shard.dtype
        flat = shard.reshape(-1).astype(jnp.float32)
        n = flat.shape[0]
        total = padded_len(n, world * block)
        if total != n:
            flat = jnp.pad(flat, (0, total - n))
        sub = total // world
        nblk = sub // block
        layout = chunk_layout(nblk, chunks)
        csizes = [nb * block for nb in layout]
        offs = [0]
        for csz in csizes[:-1]:
            offs.append(offs[-1] + csz)
        C = len(csizes)
        x2d = flat.reshape(world, sub)
        idx = jax.lax.axis_index(axis)
        key = _fold_key(seed_, axis, stochastic)
        rs_impl = _resolve_rs_impl(impl, world, block, stochastic,
                                   max(csizes))
        rblock = result_block_size(block)
        # phase 2 pipelines per chunk only when chunk boundaries are also
        # result-block boundaries (true whenever rblock divides block);
        # otherwise the reduced chunks are restitched and phase 2 runs
        # monolithically — either way bit-identical to chunks=1
        p2_chunked = C > 1 and block % rblock == 0

        def quantize_chunk(c):
            xc = x2d[:, offs[c]:offs[c] + csizes[c]]
            # C == 1 keeps the exact pre-chunking key/seed derivation so
            # stochastic draws reproduce across versions
            kc = None
            if stochastic:
                kc = key if C == 1 else jax.random.fold_in(key, c)
            return quantize_blockwise(xc, block, stochastic=stochastic,
                                      key=kc, seed=seed_ * world + idx + c)

        def requant_chunk(c, red_c):
            kc = (jax.random.fold_in(key, world + c)
                  if stochastic else None)
            return quantize_blockwise(red_c, rblock, stochastic=stochastic,
                                      key=kc,
                                      seed=seed_ * world + idx + c + 1)

        reds = [None] * C
        if rs_impl in ("fused", "fused_interpret"):
            for c in range(C):
                xc = x2d[:, offs[c]:offs[c] + csizes[c]]
                reds[c] = fused_reduce_scatter(
                    xc, axis, block,
                    interpret=(rs_impl == "fused_interpret"))
        else:
            qs = [None] * C
            ss = [None] * C
            qs[0], ss[0] = quantize_chunk(0)
            for c in range(C):
                # exchange chunk c ...
                qx = jax.lax.all_to_all(qs[c].reshape(world, csizes[c]),
                                        axis, split_axis=0, concat_axis=0,
                                        tiled=True)
                sx = jax.lax.all_to_all(ss[c].reshape(world, layout[c]),
                                        axis, split_axis=0, concat_axis=0,
                                        tiled=True)
                # ... while quantizing chunk c+1 (emitted before the
                # exchange is consumed: the scheduler may overlap them)
                if c + 1 < C:
                    qs[c + 1], ss[c + 1] = quantize_chunk(c + 1)
                reds[c] = dequantize_accumulate(qx.reshape(-1),
                                                sx.reshape(-1), world,
                                                block)
        if op == "mean":
            reds = [r / world for r in reds]

        def gather_chunk(q2, s2, csz):
            qg = jax.lax.all_gather(q2, axis, tiled=True)
            sg = jax.lax.all_gather(s2, axis, tiled=True)
            # per-device pieces may carry rblock padding; dequantize
            # row-wise and strip before restitching
            out = _dequant_rows(qg.reshape(world, -1),
                                sg.reshape(world, -1), world, rblock)
            return out.reshape(world, -1)[:, :csz]

        if p2_chunked:
            q2s = [None] * C
            s2s = [None] * C
            q2s[0], s2s[0] = requant_chunk(0, reds[0])
            pieces = [None] * C
            for c in range(C):
                if c + 1 < C:
                    q2s[c + 1], s2s[c + 1] = requant_chunk(c + 1,
                                                           reds[c + 1])
                pieces[c] = gather_chunk(q2s[c], s2s[c], csizes[c])
            out2d = jnp.concatenate(pieces, axis=1)
        else:
            red = reds[0] if C == 1 else jnp.concatenate(reds)
            q2, s2 = requant_chunk(0, red)
            out2d = gather_chunk(q2, s2, sub)
        return out2d.reshape(-1)[:n].reshape(shape).astype(dtype)

    return shard_map(f, check_vma=False, mesh=mesh, in_specs=(spec, P()),
                     out_specs=spec)(x, seed)


# --- staged profiling path -------------------------------------------------
# Same numerics as _q_allreduce_impl with chunks=1, but split into six
# separately-jitted, fenced stage programs so wall time is attributable to
# quantize / transfer / dequantize sub-phases.  The fences serialize the
# overlap the pipelined path exists to create, so this is a measurement
# mode (bench --emit-telemetry, debugging), never the production default.


@functools.partial(jax.jit, static_argnames=("mesh", "axis", "block",
                                             "stochastic"))
def _qprof_quantize(x, seed, mesh: Mesh, axis: str, block: int,
                    stochastic: bool):
    world = mesh.shape[axis]

    def f(shard, seed_):
        flat = shard.reshape(-1).astype(jnp.float32)
        n = flat.shape[0]
        total = padded_len(n, world * block)
        if total != n:
            flat = jnp.pad(flat, (0, total - n))
        sub = total // world
        idx = jax.lax.axis_index(axis)
        key = _fold_key(seed_, axis, stochastic)
        q, s = quantize_blockwise(flat.reshape(world, sub), block,
                                  stochastic=stochastic, key=key,
                                  seed=seed_ * world + idx)
        return q.reshape(world, sub), s.reshape(world, sub // block)

    return shard_map(f, check_vma=False, mesh=mesh, in_specs=(P(axis), P()),
                     out_specs=(P(axis), P(axis)))(x, seed)


@functools.partial(jax.jit, static_argnames=("mesh", "axis"))
def _qprof_exchange(q, s, mesh: Mesh, axis: str):
    def f(qs, ss):
        qx = jax.lax.all_to_all(qs, axis, split_axis=0, concat_axis=0,
                                tiled=True)
        sx = jax.lax.all_to_all(ss, axis, split_axis=0, concat_axis=0,
                                tiled=True)
        return qx, sx

    return shard_map(f, check_vma=False, mesh=mesh,
                     in_specs=(P(axis), P(axis)),
                     out_specs=(P(axis), P(axis)))(q, s)


@functools.partial(jax.jit, static_argnames=("mesh", "axis", "op", "block"))
def _qprof_accumulate(qx, sx, mesh: Mesh, axis: str, op: str, block: int):
    world = mesh.shape[axis]

    def f(q, s):
        red = dequantize_accumulate(q.reshape(-1), s.reshape(-1), world,
                                    block)
        if op == "mean":
            red = red / world
        return red

    return shard_map(f, check_vma=False, mesh=mesh,
                     in_specs=(P(axis), P(axis)), out_specs=P(axis))(qx, sx)


@functools.partial(jax.jit, static_argnames=("mesh", "axis", "block",
                                             "stochastic"))
def _qprof_requant(red, seed, mesh: Mesh, axis: str, block: int,
                   stochastic: bool):
    world = mesh.shape[axis]
    rblock = result_block_size(block)

    def f(r, seed_):
        idx = jax.lax.axis_index(axis)
        key = _fold_key(seed_, axis, stochastic)
        key2 = jax.random.fold_in(key, world) if stochastic else None
        return quantize_blockwise(r.reshape(-1), rblock,
                                  stochastic=stochastic, key=key2,
                                  seed=seed_ * world + idx + 1)

    return shard_map(f, check_vma=False, mesh=mesh, in_specs=(P(axis), P()),
                     out_specs=(P(axis), P(axis)))(red, seed)


@functools.partial(jax.jit, static_argnames=("mesh", "axis"))
def _qprof_gather(q2, s2, mesh: Mesh, axis: str):
    def f(qv, sv):
        qg = jax.lax.all_gather(qv, axis, tiled=True)
        sg = jax.lax.all_gather(sv, axis, tiled=True)
        return qg, sg

    return shard_map(f, check_vma=False, mesh=mesh,
                     in_specs=(P(axis), P(axis)),
                     out_specs=(P(), P()))(q2, s2)


@functools.partial(jax.jit, static_argnames=("mesh", "axis", "rblock", "sub",
                                             "n", "shape", "dtype"))
def _qprof_stitch(qg, sg, mesh: Mesh, axis: str, rblock: int, sub: int,
                  n: int, shape: tuple, dtype: str):
    world = mesh.shape[axis]

    def f(qg_, sg_):
        out = _dequant_rows(qg_.reshape(world, -1), sg_.reshape(world, -1),
                            world, rblock)
        out = out.reshape(world, -1)[:, :sub]
        return out.reshape(-1)[:n].reshape(shape).astype(jnp.dtype(dtype))

    return shard_map(f, check_vma=False, mesh=mesh, in_specs=(P(), P()),
                     out_specs=P(axis))(qg, sg)


def _q_allreduce_profiled(x, seed, mesh: Mesh, axis: str, op: str,
                          cc: CompressionConfig, impl: str):
    """Run the quantized allreduce as six fenced stage programs and
    return (result, {"quantize","transfer","dequantize"} seconds).
    Bit-identical to _q_allreduce_impl(chunks=1) for deterministic
    rounding; `impl` is ignored — attribution always uses the XLA stage
    sequence (a fused kernel cannot be split for timing)."""
    del impl
    block, stochastic = cc.block_size, cc.stochastic
    world = mesh.shape[axis]
    rblock = result_block_size(block)
    pershard = (x.shape[0] // world,) + tuple(x.shape[1:])
    n = 1
    for d in pershard:
        n *= d
    sub = padded_len(n, world * block) // world
    times = {"quantize": 0.0, "transfer": 0.0, "dequantize": 0.0}

    def run(bucket, fn, *a):
        t = time.perf_counter()
        out = fn(*a)
        jax.block_until_ready(out)
        times[bucket] += time.perf_counter() - t
        return out

    x = jax.block_until_ready(x)
    q, s = run("quantize", _qprof_quantize, x, seed, mesh, axis, block,
               stochastic)
    qx, sx = run("transfer", _qprof_exchange, q, s, mesh, axis)
    red = run("dequantize", _qprof_accumulate, qx, sx, mesh, axis, op, block)
    q2, s2 = run("quantize", _qprof_requant, red, seed, mesh, axis, block,
                 stochastic)
    qg, sg = run("transfer", _qprof_gather, q2, s2, mesh, axis)
    out = run("dequantize", _qprof_stitch, qg, sg, mesh, axis, rblock, sub,
              n, pershard, jnp.dtype(x.dtype).name)
    return out, times


@functools.partial(jax.jit, static_argnames=("mesh", "axis", "block",
                                             "stochastic"))
def _q_reducescatter_impl(x, seed, mesh: Mesh, axis: str, block: int,
                          stochastic: bool):
    world = mesh.shape[axis]

    def f(shard, seed_):
        row = shard[0].astype(jnp.float32)      # this device's [N] contribution
        sub = row.shape[0] // world
        sub_pad = padded_len(sub, block)
        chunks = row.reshape(world, sub)
        if sub_pad != sub:
            chunks = jnp.pad(chunks, ((0, 0), (0, sub_pad - sub)))
        idx = jax.lax.axis_index(axis)
        key = _fold_key(seed_, axis, stochastic)
        q, s = quantize_blockwise(chunks, block, stochastic=stochastic,
                                  key=key, seed=seed_ * world + idx)
        qx = jax.lax.all_to_all(q.reshape(world, sub_pad), axis, split_axis=0,
                                concat_axis=0, tiled=True)
        sx = jax.lax.all_to_all(s.reshape(world, sub_pad // block), axis,
                                split_axis=0, concat_axis=0, tiled=True)
        red = dequantize_accumulate(qx.reshape(-1), sx.reshape(-1), world,
                                    block)
        return red[:sub][None].astype(shard.dtype)

    return shard_map(f, check_vma=False, mesh=mesh, in_specs=(P(axis), P()),
                     out_specs=P(axis))(x, seed)


@functools.partial(jax.jit, static_argnames=("mesh", "axis", "block",
                                             "stochastic"))
def _q_allgather_impl(x, seed, mesh: Mesh, axis: str, block: int,
                      stochastic: bool):
    world = mesh.shape[axis]

    def f(shard, seed_):
        flat = shard.reshape(-1).astype(jnp.float32)
        n = flat.shape[0]
        npad = padded_len(n, block)
        idx = jax.lax.axis_index(axis)
        key = _fold_key(seed_, axis, stochastic)
        q, s = quantize_blockwise(flat, block, stochastic=stochastic,
                                  key=key, seed=seed_ * world + idx)
        qg = jax.lax.all_gather(q, axis, tiled=True).reshape(world, npad)
        sg = jax.lax.all_gather(s, axis, tiled=True).reshape(world, -1)
        out = _dequant_rows(qg, sg, world, block).reshape(world, npad)[:, :n]
        return out.reshape((world * shard.shape[0],)
                           + shard.shape[1:]).astype(shard.dtype)

    return shard_map(f, check_vma=False, mesh=mesh, in_specs=(P(axis), P()),
                     out_specs=P())(x, seed)


@functools.partial(jax.jit, static_argnames=("mesh", "axis", "tiled"))
def _allgather_impl(x, mesh: Mesh, axis: str, tiled: bool):
    def f(shard):
        return jax.lax.all_gather(shard, axis, tiled=tiled)

    return shard_map(f, check_vma=False, mesh=mesh, in_specs=P(axis), out_specs=P())(x)


def mesh_allgather(x, mesh: Mesh, axis_name: Optional[str] = None,
                   compression: Union[None, str, CompressionConfig] = None,
                   seed: int = 0):
    """Each device contributes its shard; all get the concatenation
    (reference API: collective.py:423 allgather).  With `compression`,
    shards travel as int8 blocks + scales and are dequantized on arrival
    (lossy; see compression.py)."""
    axis = _axis(mesh, axis_name)
    cc = parse_compression(compression)
    t0 = time.perf_counter()
    with tracing.span("collective.mesh_allgather", axis=axis,
                      compressed=cc is not None):
        if cc is None:
            out = _allgather_impl(x, mesh, axis, True)
        else:
            out = _q_allgather_impl(x, jnp.int32(seed), mesh, axis,
                                    cc.block_size, cc.stochastic)
    _record_mesh_op("mesh_allgather", t0, x, cc)
    return out


@functools.partial(jax.jit, static_argnames=("mesh", "axis"))
def _reducescatter_impl(x, mesh: Mesh, axis: str):
    def f(shard):
        # shard is [1, N] (this device's contribution row); NCCL semantics:
        # reduce all rows, each device keeps its N/world chunk
        y = jax.lax.psum_scatter(shard[0], axis, scatter_dimension=0,
                                 tiled=True)
        return y[None]

    return shard_map(f, check_vma=False, mesh=mesh, in_specs=P(axis),
                     out_specs=P(axis))(x)


def mesh_reducescatter(x, mesh: Mesh, axis_name: Optional[str] = None,
                       compression: Union[None, str, CompressionConfig] = None,
                       seed: int = 0):
    """Reduce across the axis, leave each device its scattered chunk
    (reference API: collective.py:472 reducescatter).  Input is the stacked
    per-device contributions [world, N]; output [world, N/world] where row r
    is the reduced chunk owned by device r.  With `compression`,
    contributions travel as int8 blocks + scales (sum semantics, lossy)."""
    axis = _axis(mesh, axis_name)
    cc = parse_compression(compression)
    t0 = time.perf_counter()
    with tracing.span("collective.mesh_reducescatter", axis=axis,
                      compressed=cc is not None):
        if cc is None:
            out = _reducescatter_impl(x, mesh, axis)
        else:
            world = mesh.shape[axis]
            if x.shape[-1] % world:
                raise ValueError(
                    f"compressed reducescatter needs the payload dim "
                    f"({x.shape[-1]}) divisible by the axis size "
                    f"({world})")
            out = _q_reducescatter_impl(x, jnp.int32(seed), mesh, axis,
                                        cc.block_size, cc.stochastic)
    _record_mesh_op("mesh_reducescatter", t0, x, cc)
    return out


@functools.partial(jax.jit, static_argnames=("mesh", "axis", "root"))
def _broadcast_impl(x, mesh: Mesh, axis: str, root: int):
    def f(shard):
        # rotate root's shard to everyone: gather then index is simplest
        # and XLA turns the gather+slice into a broadcast from root
        full = jax.lax.all_gather(shard, axis)
        return full[root]

    return shard_map(f, check_vma=False, mesh=mesh, in_specs=P(axis),
                     out_specs=P(axis))(x)


def mesh_broadcast(x, mesh: Mesh, axis_name: Optional[str] = None,
                   root: int = 0):
    """Every device receives root's shard (reference API: collective.py:373)."""
    # NOTE: this (and ppermute/all_to_all below) used to jit a closure
    # built per call — a fresh wrapper per invocation discards the trace
    # cache, so EVERY broadcast recompiled.  The compilation ledger made
    # the storm visible and the jit-per-call lint now flags the pattern;
    # the impls are module-level with hashable statics, like
    # _allreduce_impl always was.
    axis = _axis(mesh, axis_name)
    return _broadcast_impl(x, mesh, axis, int(root))


@functools.partial(jax.jit, static_argnames=("mesh", "axis", "perm"))
def _ppermute_impl(x, mesh: Mesh, axis: str, perm):
    def f(shard):
        return jax.lax.ppermute(shard, axis, perm)

    return shard_map(f, check_vma=False, mesh=mesh, in_specs=P(axis),
                     out_specs=P(axis))(x)


def mesh_ppermute(x, mesh: Mesh, perm: Sequence[tuple],
                  axis_name: Optional[str] = None):
    """Point-to-point shard rotation — the send/recv of the compiled world
    (reference API: collective.py:531/:594 send/recv); the building block of
    ring attention and pipeline microbatching."""
    axis = _axis(mesh, axis_name)
    perm = tuple((int(a), int(b)) for a, b in perm)
    return _ppermute_impl(x, mesh, axis, perm)


@functools.partial(jax.jit, static_argnames=("mesh", "axis", "split_axis",
                                             "concat_axis"))
def _all_to_all_impl(x, mesh: Mesh, axis: str, split_axis: int,
                     concat_axis: int):
    def f(shard):
        return jax.lax.all_to_all(shard, axis, split_axis, concat_axis,
                                  tiled=True)

    return shard_map(f, check_vma=False, mesh=mesh, in_specs=P(axis),
                     out_specs=P(axis))(x)


def mesh_all_to_all(x, mesh: Mesh, axis_name: Optional[str] = None,
                    split_axis: int = 1, concat_axis: int = 0):
    """All-to-all reshard — the Ulysses/MoE-dispatch primitive.

    With the array sharded on dim 0 over the mesh axis, each device splits
    its shard along `split_axis` and exchanges pieces, concatenating along
    `concat_axis` (maps to lax.all_to_all; EP token dispatch and
    sequence<->head resharding are this one op)."""
    axis = _axis(mesh, axis_name)
    return _all_to_all_impl(x, mesh, axis, int(split_axis),
                            int(concat_axis))


# -- compilation-ledger hookup (telemetry/device.py) ------------------------
# The decorated defs above stay plain jax.jit so the static analyzer's
# decorator-based traced-function discovery is undisturbed; the module
# then routes the compiled entry points through the process ledger, so a
# mesh collective recompiling in steady state shows up as a recompile
# (with a cause diff) instead of silent step-time jitter.

from ray_tpu.telemetry import device as _devtel  # noqa: E402

_allreduce_impl = _devtel.instrument(
    _allreduce_impl, name="collective.allreduce")
_q_allreduce_impl = _devtel.instrument(
    _q_allreduce_impl, name="collective.q_allreduce")
_q_reducescatter_impl = _devtel.instrument(
    _q_reducescatter_impl, name="collective.q_reducescatter")
_q_allgather_impl = _devtel.instrument(
    _q_allgather_impl, name="collective.q_allgather")
_allgather_impl = _devtel.instrument(
    _allgather_impl, name="collective.allgather")
_reducescatter_impl = _devtel.instrument(
    _reducescatter_impl, name="collective.reducescatter")
_broadcast_impl = _devtel.instrument(
    _broadcast_impl, name="collective.broadcast")
_ppermute_impl = _devtel.instrument(
    _ppermute_impl, name="collective.ppermute")
_all_to_all_impl = _devtel.instrument(
    _all_to_all_impl, name="collective.all_to_all")
