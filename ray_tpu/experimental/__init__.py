"""Experimental utilities (reference: python/ray/experimental/ —
internal_kv.py, tqdm_ray.py)."""

from . import internal_kv, tqdm_ray

__all__ = ["internal_kv", "tqdm_ray"]
