"""Distributed-safe progress bars (reference:
python/ray/experimental/tqdm_ray.py): remote workers report progress
through the driver instead of fighting over the terminal.

Worker side: tqdm(...) returns a bar whose updates publish to the control
pubsub "tqdm" topic.  Driver side: call install_driver_listener() once to
subscribe and render per-bar lines on stderr; without a listener the
updates are dropped by the pubsub hub (and a bar created outside any
cluster renders locally)."""

from __future__ import annotations

import os
import sys
import threading
import time
import uuid
from typing import Any, Dict, Iterable, Optional

_lock = threading.Lock()


class tqdm:
    """API-compatible subset of tqdm.tqdm (total/desc/update/close,
    iterable wrapping)."""

    def __init__(self, iterable: Optional[Iterable] = None, *,
                 desc: str = "", total: Optional[int] = None,
                 position: Optional[int] = None, flush_interval_s: float = 0.5,
                 **_ignored):
        self.iterable = iterable
        self.desc = desc
        if total is None and iterable is not None:
            try:
                total = len(iterable)  # type: ignore[arg-type]
            except TypeError:
                total = None
        self.total = total
        self.n = 0
        self.bar_id = uuid.uuid4().hex[:12]
        self._last_flush = 0.0
        self._flush_interval = flush_interval_s
        self._closed = False

    # -- core --------------------------------------------------------------

    def update(self, n: int = 1):
        self.n += n
        now = time.monotonic()
        if now - self._last_flush >= self._flush_interval:
            self._last_flush = now
            self._publish()

    def close(self):
        if not self._closed:
            self._closed = True
            self._publish(final=True)

    def __iter__(self):
        if self.iterable is None:
            raise TypeError("tqdm() was not given an iterable")
        try:
            for x in self.iterable:
                yield x
                self.update(1)
        finally:
            self.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def set_description(self, desc: str):
        self.desc = desc

    # -- reporting ---------------------------------------------------------

    def _state(self) -> Dict[str, Any]:
        return {"bar_id": self.bar_id, "desc": self.desc, "n": self.n,
                "total": self.total, "pid": os.getpid(),
                "closed": self._closed}

    def _publish(self, final: bool = False):
        state = self._state()
        try:
            from ray_tpu._private.api import current_core

            core = current_core()
            core.control.notify("publish", {"topic": "tqdm",
                                            "payload": state})
        except Exception:
            # no cluster: render locally like plain tqdm would
            with _lock:
                pct = ""
                if self.total:
                    pct = f" {100.0 * self.n / max(1, self.total):5.1f}%"
                sys.stderr.write(
                    f"\r{self.desc}: {self.n}/{self.total or '?'}{pct}")
                if final:
                    sys.stderr.write("\n")
                sys.stderr.flush()


def safe_print(*values, **kwargs):
    """Print that won't interleave with bar rendering."""
    with _lock:
        print(*values, **kwargs)


_listener_installed = False
_bars: Dict[str, Dict[str, Any]] = {}


def _render(state: Dict[str, Any]):
    with _lock:
        _bars[state["bar_id"]] = state
        pct = ""
        if state.get("total"):
            pct = f" {100.0 * state['n'] / max(1, state['total']):5.1f}%"
        end = "\n" if state.get("closed") else ""
        sys.stderr.write(
            f"\r[{state.get('pid')}] {state.get('desc') or 'progress'}: "
            f"{state['n']}/{state.get('total') or '?'}{pct}{end}")
        sys.stderr.flush()
        if state.get("closed"):
            _bars.pop(state["bar_id"], None)


def install_driver_listener() -> bool:
    """Subscribe the driver to remote bars and render them on stderr.
    Returns False when no cluster connection exists."""
    global _listener_installed
    if _listener_installed:
        return True
    try:
        from ray_tpu._private.api import current_core

        core = current_core()
        core.control.call("subscribe", {"topics": ["tqdm"]}, timeout=30.0)
        core.add_push_handler("pub:tqdm", _render)
        _listener_installed = True
        return True
    except Exception:
        return False
