"""Cluster-wide KV store API (reference:
python/ray/experimental/internal_kv.py — thin client over the GCS KV;
ours talks to the control plane's KV manager, control.py h_kv_*)."""

from __future__ import annotations

from typing import List, Optional, Tuple

DEFAULT_NAMESPACE = "default"


def _control():
    from ray_tpu._private.api import current_core

    return current_core().control


def _norm(key, namespace) -> Tuple[str, str]:
    ns = namespace or DEFAULT_NAMESPACE
    if isinstance(ns, bytes):
        ns = ns.decode()
    k = key.decode() if isinstance(key, bytes) else key
    return ns, k


def _internal_kv_initialized() -> bool:
    try:
        _control()
        return True
    except Exception:
        return False


def _internal_kv_put(key, value, overwrite: bool = True,
                     namespace=None) -> bool:
    """Returns True if the key already existed (reference semantics)."""
    ns, k = _norm(key, namespace)
    c = _control()
    if overwrite:
        existed = bool(c.call("kv_exists", {"ns": ns, "key": k},
                              timeout=30.0))
        c.call("kv_put", {"ns": ns, "key": k, "val": value,
                          "overwrite": True}, timeout=30.0)
        return existed
    stored = c.call("kv_put", {"ns": ns, "key": k, "val": value,
                               "overwrite": False}, timeout=30.0)
    return not stored


def _internal_kv_get(key, namespace=None) -> Optional[bytes]:
    ns, k = _norm(key, namespace)
    return _control().call("kv_get", {"ns": ns, "key": k}, timeout=30.0)


def _internal_kv_exists(key, namespace=None) -> bool:
    ns, k = _norm(key, namespace)
    return bool(_control().call("kv_exists", {"ns": ns, "key": k},
                                timeout=30.0))


def _internal_kv_del(key, namespace=None) -> bool:
    ns, k = _norm(key, namespace)
    return bool(_control().call("kv_del", {"ns": ns, "key": k},
                                timeout=30.0))


def _internal_kv_list(prefix, namespace=None) -> List[bytes]:
    ns, p = _norm(prefix, namespace)
    keys = _control().call("kv_keys", {"ns": ns, "prefix": p}, timeout=30.0)
    return [k.encode() for k in keys]


# public aliases (the reference keeps these private but they are widely
# used; we also expose unprefixed names)
kv_put = _internal_kv_put
kv_get = _internal_kv_get
kv_del = _internal_kv_del
kv_exists = _internal_kv_exists
kv_list = _internal_kv_list
