"""GPT-class transformer LM — the flagship model, TPU-first.

Pure-function + pytree design (no module framework): params are nested dicts
of jax arrays with a parallel tree of Logical axis annotations, so any mesh
shape (dp/fsdp/tp/sp/pp) shards the same code.  Layers are *stacked* on a
leading axis and scanned (`lax.scan` + `jax.checkpoint`), which keeps compile
time O(1) in depth and gives PP a natural stage axis.

Capability target: the reference runs GPT-2 via Train integrations
(reference: release/air_tests/air_benchmarks, train/examples/deepspeed/
deepspeed_torch_trainer.py fine-tunes GPT-2-class models); here the model is
in-tree and sharding-native.  BASELINE.md north star: GPT-2-medium
throughput on pods.

Supports both the GPT-2 recipe (learned positions, LayerNorm, GELU) and the
modern recipe (RoPE, RMSNorm, SwiGLU) via config flags.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from ray_tpu.ops import (apply_rope, attention, blockwise_attention,
                         fused_softmax_cross_entropy, gelu_mlp, layer_norm,
                         rms_norm, rope_table, softmax_cross_entropy, swiglu)
from ray_tpu.ops.ring_attention import ring_attention_sharded
from ray_tpu.parallel.sharding import Logical, spec_from_logical


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50304
    n_layers: int = 12
    d_model: int = 768
    n_heads: int = 12
    d_head: int = 64
    d_ff: int = 3072
    max_seq: int = 1024
    norm: str = "ln"          # "ln" | "rms"
    act: str = "gelu"         # "gelu" | "swiglu"
    pos: str = "learned"      # "learned" | "rope"
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True
    # "full" recomputes the whole block in the bwd pass; "dots" saves
    # matmul outputs and recomputes only cheap elementwise ops
    # (jax.checkpoint_policies.dots_with_no_batch_dims_saveable) — most
    # of no-remat's speed at a fraction of its activation memory
    remat_policy: str = "full"
    # CE over sequence chunks of this size, fusing the vocab projection
    # into the loss so [B, S, V] logits are never materialized — an
    # opt-in memory saver (peak [B, chunk, V] instead of [B, S, V]): on
    # v5e GPT-2-small@512 it measured ~1% slower than the dense path
    # (XLA already fuses the CE epilogue well), so dense is the default.
    # Ignored (dense fallback) when S isn't divisible or under sp.
    loss_chunk: Optional[int] = None
    attention_impl: str = "auto"
    # q/k/v/o projection biases (real GPT-2 checkpoints have them; our
    # from-scratch recipes don't need them)
    attn_bias: bool = False
    sp_mode: str = "ring"     # how to handle a >1 sp axis: "ring" | "none"
    z_loss: float = 1e-4
    tie_embeddings: bool = True
    num_microbatches: Optional[int] = None  # pp microbatches; default = pp

    @classmethod
    def gpt2_small(cls, **kw):
        return cls(n_layers=12, d_model=768, n_heads=12, d_head=64,
                   d_ff=3072, **kw)

    @classmethod
    def gpt2_medium(cls, **kw):
        return cls(n_layers=24, d_model=1024, n_heads=16, d_head=64,
                   d_ff=4096, **kw)

    @classmethod
    def gpt2_large(cls, **kw):
        return cls(n_layers=36, d_model=1280, n_heads=20, d_head=64,
                   d_ff=5120, **kw)

    @classmethod
    def gpt2_xl(cls, **kw):
        return cls(n_layers=48, d_model=1600, n_heads=25, d_head=64,
                   d_ff=6400, **kw)

    @classmethod
    def nano(cls, **kw):
        """Tiny config for tests: runs on an 8-device CPU mesh."""
        kw.setdefault("vocab_size", 256)
        kw.setdefault("max_seq", 128)
        return cls(n_layers=4, d_model=64, n_heads=4, d_head=16, d_ff=128,
                   **kw)


def logical_axes(cfg: GPTConfig) -> Dict[str, Any]:
    """Logical sharding annotations mirroring init()'s param tree."""
    lp = {
        "attn_norm": Logical("layers", None),
        "wq": Logical("layers", "embed", "heads", "head_dim"),
        "wk": Logical("layers", "embed", "heads", "head_dim"),
        "wv": Logical("layers", "embed", "heads", "head_dim"),
        "wo": Logical("layers", "heads", "head_dim", "embed"),
        "mlp_norm": Logical("layers", None),
        "mlp_out": Logical("layers", "mlp", "embed"),
    }
    if cfg.act == "swiglu":
        lp["mlp_gate"] = Logical("layers", "embed", "mlp")
        lp["mlp_up"] = Logical("layers", "embed", "mlp")
    else:
        lp["mlp_in"] = Logical("layers", "embed", "mlp")
        lp["mlp_in_b"] = Logical("layers", "mlp")
        lp["mlp_out_b"] = Logical("layers", None)
    if cfg.norm == "ln":
        lp["attn_norm_b"] = Logical("layers", None)
        lp["mlp_norm_b"] = Logical("layers", None)
    if cfg.attn_bias:
        lp["wq_b"] = Logical("layers", "heads", "head_dim")
        lp["wk_b"] = Logical("layers", "heads", "head_dim")
        lp["wv_b"] = Logical("layers", "heads", "head_dim")
        lp["wo_b"] = Logical("layers", None)
    out = {
        # vocab-only sharding: the table's lookup is a gather, and an
        # fsdp-sharded embed dim makes the partitioner emit embed-sharded
        # activations + a full reshard ("involuntary full
        # rematerialization"); vocab(tp) already gives the table a
        # sharded-storage story
        "embed": Logical("vocab", None),
        "layers": lp,
        "final_norm": Logical(None),
    }
    if cfg.norm == "ln":
        out["final_norm_b"] = Logical(None)
    if cfg.pos == "learned":
        out["pos_embed"] = Logical(None, "embed")
    if not cfg.tie_embeddings:
        out["unembed"] = Logical("embed", "vocab")
    return out


def init(key, cfg: GPTConfig) -> Dict[str, Any]:
    """Initialize the (host or sharded — see training.init_sharded) params."""
    L, D, H, dh, F, V = (cfg.n_layers, cfg.d_model, cfg.n_heads, cfg.d_head,
                         cfg.d_ff, cfg.vocab_size)
    pd = cfg.param_dtype
    k = iter(jax.random.split(key, 16))

    def norm_init(shape):
        return jnp.ones(shape, pd)

    def dense(rng, shape, fan_in):
        return (jax.random.normal(rng, shape, pd)
                * (1.0 / math.sqrt(fan_in)))

    lp = {
        "attn_norm": norm_init((L, D)),
        "wq": dense(next(k), (L, D, H, dh), D),
        "wk": dense(next(k), (L, D, H, dh), D),
        "wv": dense(next(k), (L, D, H, dh), D),
        # residual-branch scaling a la GPT-2 (1/sqrt(2L))
        "wo": dense(next(k), (L, H, dh, D), H * dh) / math.sqrt(2 * L),
        "mlp_norm": norm_init((L, D)),
        "mlp_out": dense(next(k), (L, F, D), F) / math.sqrt(2 * L),
    }
    if cfg.act == "swiglu":
        lp["mlp_gate"] = dense(next(k), (L, D, F), D)
        lp["mlp_up"] = dense(next(k), (L, D, F), D)
    else:
        lp["mlp_in"] = dense(next(k), (L, D, F), D)
        lp["mlp_in_b"] = jnp.zeros((L, F), pd)
        lp["mlp_out_b"] = jnp.zeros((L, D), pd)
    if cfg.norm == "ln":
        lp["attn_norm_b"] = jnp.zeros((L, D), pd)
        lp["mlp_norm_b"] = jnp.zeros((L, D), pd)
    if cfg.attn_bias:
        lp["wq_b"] = jnp.zeros((L, H, dh), pd)
        lp["wk_b"] = jnp.zeros((L, H, dh), pd)
        lp["wv_b"] = jnp.zeros((L, H, dh), pd)
        lp["wo_b"] = jnp.zeros((L, D), pd)
    params = {
        "embed": jax.random.normal(next(k), (V, D), pd) * 0.02,
        "layers": lp,
        "final_norm": norm_init((D,)),
    }
    if cfg.norm == "ln":
        params["final_norm_b"] = jnp.zeros((D,), pd)
    if cfg.pos == "learned":
        params["pos_embed"] = jax.random.normal(next(k), (cfg.max_seq, D),
                                                pd) * 0.01
    if not cfg.tie_embeddings:
        params["unembed"] = dense(next(k), (D, V), D)
    return params


def _norm(x, w, b, kind):
    if kind == "rms":
        return rms_norm(x, w)
    return layer_norm(x, w, b)


def _constrain(x, *axes):
    """Activation sharding constraint (ACTIVATION_RULES: fsdp stays on
    the batch dim — params' embed-dim fsdp sharding is gathered on use,
    never propagated onto activations)."""
    from ray_tpu.parallel.sharding import (ACTIVATION_RULES,
                                           spec_from_logical)

    try:
        return jax.lax.with_sharding_constraint(
            x, spec_from_logical(axes, ACTIVATION_RULES))
    except Exception:
        return x  # outside jit / no mesh context


def _attention_op(q, k, v, cfg: GPTConfig, mesh, allow_manual: bool = True):
    """Pick the attention path: ring over sp when the mesh has an sp axis,
    otherwise flash/blockwise on the whole (possibly tp-sharded) arrays.

    The sp region is *partial-manual* shard_map (axis_names={'sp'}): dp/tp
    stay automatic.  Inside the pp pipeline region (allow_manual=False)
    shardy cannot nest another manual region, so attention falls back to
    GSPMD partitioning there (exact, all-gathers KV over sp)."""
    if (allow_manual and mesh is not None and mesh.shape.get("sp", 1) > 1
            and cfg.sp_mode == "ring"):
        from ray_tpu._private.jax_compat import shard_map
        from jax.sharding import PartitionSpec as P

        spec = P(None, None, "sp", None)
        fn = lambda q_, k_, v_: ring_attention_sharded(
            q_, k_, v_, "sp", causal=True)
        # mesh=None -> ambient context mesh, so this nests inside the pp
        # pipeline's manual region (whose context mesh has pp already Manual)
        return shard_map(fn, check_vma=False,
                         in_specs=(spec, spec, spec), out_specs=spec,
                         axis_names=frozenset({"sp"}))(q, k, v)
    return attention(q, k, v, causal=True, impl=cfg.attention_impl)


def _qkv_proj(x, layer, cfg: GPTConfig, rope, positions=None):
    """Pre-norm + QKV projection + rope — the one source of truth shared
    by the training forward and the KV-cache decode path (a recipe tweak
    made in only one of them would silently break decode==forward
    parity, which test_gpt_decode_matches_full_forward enforces)."""
    h = _norm(x, layer["attn_norm"], layer.get("attn_norm_b"), cfg.norm)
    h = h.astype(cfg.dtype)
    q = jnp.einsum("bsd,dhk->bhsk", h, layer["wq"].astype(cfg.dtype))
    k = jnp.einsum("bsd,dhk->bhsk", h, layer["wk"].astype(cfg.dtype))
    v = jnp.einsum("bsd,dhk->bhsk", h, layer["wv"].astype(cfg.dtype))
    if cfg.attn_bias:
        q = q + layer["wq_b"].astype(cfg.dtype)[None, :, None]
        k = k + layer["wk_b"].astype(cfg.dtype)[None, :, None]
        v = v + layer["wv_b"].astype(cfg.dtype)[None, :, None]
    if rope is not None:
        q = apply_rope(q, *rope, positions=positions)
        k = apply_rope(k, *rope, positions=positions)
    return q, k, v


def _attn_out_and_mlp(x, o, layer, cfg: GPTConfig):
    """Output projection + residual + MLP sublayer (shared, see
    _qkv_proj)."""
    att = jnp.einsum("bhsk,hkd->bsd", o, layer["wo"].astype(cfg.dtype))
    if cfg.attn_bias:
        att = att + layer["wo_b"].astype(cfg.dtype)
    x = x + att
    h2 = _norm(x, layer["mlp_norm"], layer.get("mlp_norm_b"), cfg.norm)
    h2 = h2.astype(cfg.dtype)
    if cfg.act == "swiglu":
        m = swiglu(h2, layer["mlp_gate"].astype(cfg.dtype),
                   layer["mlp_up"].astype(cfg.dtype),
                   layer["mlp_out"].astype(cfg.dtype))
    else:
        m = gelu_mlp(h2, layer["mlp_in"].astype(cfg.dtype),
                     layer["mlp_in_b"].astype(cfg.dtype),
                     layer["mlp_out"].astype(cfg.dtype),
                     layer["mlp_out_b"].astype(cfg.dtype))
    return x + m


def _scan_blocks(x, layers, cfg: GPTConfig, rope, mesh=None,
                 allow_manual: bool = True):
    """Scan a (stacked) layer slice over x — the one block recipe shared
    by the full SPMD forward, the SPMD pp stage_fn, and the MPMD
    per-stage programs (parallel/mpmd.py), so every pipelining story
    computes bit-for-bit the same math as the reference stack."""

    def block(x, layer):
        q, k, v = _qkv_proj(x, layer, cfg, rope)
        q = _constrain(q, "batch", "heads", "seq", "head_dim")
        k = _constrain(k, "batch", "heads", "seq", "head_dim")
        v = _constrain(v, "batch", "heads", "seq", "head_dim")
        o = _attention_op(q, k, v, cfg, mesh, allow_manual=allow_manual)
        x = _attn_out_and_mlp(x, o, layer, cfg)
        return _constrain(x, "batch", "seq", "embed")

    def scan_body(x, layer):
        if cfg.remat:
            policy = (jax.checkpoint_policies
                      .dots_with_no_batch_dims_saveable
                      if cfg.remat_policy == "dots" else None)
            x = jax.checkpoint(block, policy=policy)(x, layer)
        else:
            x = block(x, layer)
        return x, None

    x, _ = jax.lax.scan(scan_body, x, layers)
    return x


def apply_hidden(params, tokens, cfg: GPTConfig, mesh=None):
    """Transformer stack up to (and including) the final norm: tokens
    [B, S] int32 -> hidden [B, S, D].  The vocab projection is split out
    so loss_fn can fuse it into a chunked CE that never materializes the
    [B, S, V] logits (see ops/layers.py fused_softmax_cross_entropy)."""
    B, S = tokens.shape
    x = params["embed"][tokens].astype(cfg.dtype)
    if cfg.pos == "learned":
        x = x + params["pos_embed"][:S][None].astype(cfg.dtype)
        rope = None
    else:
        rope = rope_table(S, cfg.d_head, dtype=jnp.float32)
    x = _constrain(x, "batch", "seq", "embed")
    pp = mesh.shape.get("pp", 1) if mesh is not None else 1

    if pp > 1:
        from ray_tpu.parallel.pipeline import (merge_microbatches,
                                               pipeline_apply,
                                               split_microbatches)

        if cfg.n_layers % pp:
            raise ValueError(f"n_layers {cfg.n_layers} not divisible by "
                             f"pp {pp}")
        M = cfg.num_microbatches or pp

        def stage_fn(stage_layers, xm):
            return _scan_blocks(xm, stage_layers, cfg, rope, mesh,
                                allow_manual=False)

        stacked = jax.tree.map(
            lambda p: p.reshape(pp, cfg.n_layers // pp, *p.shape[1:]),
            params["layers"])
        x = merge_microbatches(
            pipeline_apply(stage_fn, stacked, split_microbatches(x, M), mesh))
    else:
        x = _scan_blocks(x, params["layers"], cfg, rope, mesh,
                         allow_manual=True)
    x = _norm(x, params["final_norm"], params.get("final_norm_b"), cfg.norm)
    return x


# ---------------------------------------------------------------------------
# MPMD pipeline partitioning (parallel/mpmd.py).  Unlike the SPMD pp path
# above — ONE compiled program where every rank holds every stage's
# schedule — these helpers slice the model into per-stage param trees and
# per-stage forward programs, each compiled alone on its own worker gang,
# so model depth is no longer capped by what a single program can hold.


def partition_stage_params(params, cfg: GPTConfig, stages: int):
    """Slice init()'s tree into `stages` contiguous per-stage trees.

    Stage 0 owns embed (+ learned positions); the last stage owns the
    final norm and the vocab projection.  With tied embeddings BOTH end
    stages hold the table (stage 0 for lookup, the last for unembed) —
    parallel/mpmd.py keeps the two copies identical by exchanging embed
    grads between them every step."""
    if cfg.n_layers % stages:
        raise ValueError(f"n_layers {cfg.n_layers} not divisible by "
                         f"stages {stages}")
    per = cfg.n_layers // stages
    out = []
    for s in range(stages):
        st = {"layers": jax.tree.map(lambda p: p[s * per:(s + 1) * per],
                                     params["layers"])}
        if s == 0:
            st["embed"] = params["embed"]
            if cfg.pos == "learned":
                st["pos_embed"] = params["pos_embed"]
        if s == stages - 1:
            st["final_norm"] = params["final_norm"]
            if cfg.norm == "ln":
                st["final_norm_b"] = params["final_norm_b"]
            if cfg.tie_embeddings:
                st.setdefault("embed", params["embed"])
            else:
                st["unembed"] = params["unembed"]
        out.append(st)
    return out


def merge_stage_trees(stage_trees, cfg: GPTConfig, grads: bool = False,
                      tie_summed: bool = False):
    """Inverse of partition_stage_params: reassemble the full tree.

    For params (grads=False) the tied embed copies are identical and
    stage 0's is taken; for grads (grads=True) the two ends' partials
    are SUMMED — the chain-rule contributions of the lookup and the
    unembed projection to the one shared table.  When the pipeline has
    already run its tied-embed exchange both copies hold the total
    (tie_summed=True): take one instead of double-counting."""
    stages = len(stage_trees)
    first, last = stage_trees[0], stage_trees[-1]
    out = {"layers": jax.tree.map(lambda *xs: jnp.concatenate(xs, axis=0),
                                  *[t["layers"] for t in stage_trees])}
    out["embed"] = first["embed"]
    if grads and cfg.tie_embeddings and stages > 1 and not tie_summed:
        out["embed"] = out["embed"] + last["embed"]
    if cfg.pos == "learned":
        out["pos_embed"] = first["pos_embed"]
    out["final_norm"] = last["final_norm"]
    if cfg.norm == "ln":
        out["final_norm_b"] = last["final_norm_b"]
    if not cfg.tie_embeddings:
        out["unembed"] = last["unembed"]
    return out


def stage_hidden(stage_params, x, cfg: GPTConfig, stage: int, stages: int):
    """One MPMD stage's forward: tokens [B, S] (stage 0) or hidden
    [B, S, D] -> hidden [B, S, D] (final-normed on the last stage)."""
    if stage == 0:
        S = x.shape[1]
        h = stage_params["embed"][x].astype(cfg.dtype)
        if cfg.pos == "learned":
            h = h + stage_params["pos_embed"][:S][None].astype(cfg.dtype)
    else:
        S = x.shape[1]
        h = x.astype(cfg.dtype)
    rope = (None if cfg.pos == "learned"
            else rope_table(S, cfg.d_head, dtype=jnp.float32))
    h = _scan_blocks(h, stage_params["layers"], cfg, rope, mesh=None)
    if stage == stages - 1:
        h = _norm(h, stage_params["final_norm"],
                  stage_params.get("final_norm_b"), cfg.norm)
    return h


def stage_loss(stage_params, x, targets, cfg: GPTConfig, stage: int,
               stages: int):
    """Last-stage forward + next-token CE (mean over this microbatch;
    with equal microbatch sizes the mean-of-means equals loss_fn's
    global mean, which the MPMD<->SPMD parity tests pin down)."""
    h = stage_hidden(stage_params, x, cfg, stage, stages)
    table = (stage_params["embed"].T if cfg.tie_embeddings
             else stage_params["unembed"]).astype(cfg.dtype)
    logits = jnp.einsum("bsd,dv->bsv", h.astype(cfg.dtype), table)
    return jnp.mean(softmax_cross_entropy(logits, targets,
                                          z_loss=cfg.z_loss))


def _unembed_table(params, cfg: GPTConfig):
    return (params["embed"].T if cfg.tie_embeddings
            else params["unembed"]).astype(cfg.dtype)


def apply(params, tokens, cfg: GPTConfig, mesh=None):
    """Forward pass: tokens [B, S] int32 -> logits [B, S, V]."""
    x = apply_hidden(params, tokens, cfg, mesh)
    logits = jnp.einsum("bsd,dv->bsv", x.astype(cfg.dtype),
                        _unembed_table(params, cfg))
    return _constrain(logits, "batch", "seq", "vocab")


def loss_fn(params, batch, cfg: GPTConfig, mesh=None):
    """Next-token LM loss.  batch: {"tokens": [B, S+1]} or
    {"inputs","targets"}."""
    if "inputs" in batch:
        inputs, targets = batch["inputs"], batch["targets"]
    else:
        toks = batch["tokens"]
        inputs, targets = toks[:, :-1], toks[:, 1:]
    chunk = cfg.loss_chunk
    sp = 1 if mesh is None else mesh.shape.get("sp", 1)
    if chunk and targets.shape[1] % chunk == 0 and sp == 1:
        # fused path: chunk over the (locally whole) sequence axis —
        # with an sp axis the sequence is device-sharded, so slicing it
        # host-side would gather; fall back to dense there
        x = apply_hidden(params, inputs, cfg, mesh)
        loss = fused_softmax_cross_entropy(
            x.astype(cfg.dtype), _unembed_table(params, cfg), targets,
            z_loss=cfg.z_loss, chunk=chunk)
    else:
        logits = apply(params, inputs, cfg, mesh)
        loss = softmax_cross_entropy(logits, targets, z_loss=cfg.z_loss)
    if "mask" in batch:
        mask = batch["mask"].astype(jnp.float32)
        return jnp.sum(loss * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.mean(loss)


# ---------------------------------------------------------------------------
# KV-cache decoding (inference).  The reference delegates generation to
# torch/vLLM; here decode is a first-class jit program: per-layer KV
# buffers carried through a lax.scan over the stacked layer params, one
# dynamic_update_slice per step — static shapes throughout, so the whole
# generate loop compiles once for a given (batch, max_seq).


def init_cache(cfg: GPTConfig, batch: int, max_seq: Optional[int] = None
               ) -> Dict[str, Any]:
    """Empty KV cache: [L, B, H, max_seq, d_head] per side + a scalar
    write position."""
    S = max_seq or cfg.max_seq
    shape = (cfg.n_layers, batch, cfg.n_heads, S, cfg.d_head)
    return {"k": jnp.zeros(shape, cfg.dtype),
            "v": jnp.zeros(shape, cfg.dtype),
            "pos": jnp.zeros((), jnp.int32)}


def _decode_hidden(params, cache, tokens, cfg: GPTConfig, rope=None):
    """One decode position through the stack: tokens [B] at position
    cache['pos'] -> (final-norm hidden [B, D], updated cache).  The
    layer recipe is the shared _qkv_proj/_attn_out_and_mlp (identical to
    the training forward); only the attention inner product runs against
    the cache with a position mask.  `rope` may be precomputed by the
    caller (generate hoists it out of its scans)."""
    S = cache["k"].shape[3]
    pos = cache["pos"]
    x = params["embed"][tokens].astype(cfg.dtype)          # [B, D]
    if cfg.pos == "learned":
        x = x + jnp.take(params["pos_embed"], pos, axis=0)[None].astype(
            cfg.dtype)
        rope = None
    elif rope is None:
        rope = rope_table(S, cfg.d_head, dtype=jnp.float32)
    x = x[:, None]                                         # [B, 1, D]
    mask = (jax.lax.broadcasted_iota(jnp.int32, (1, 1, 1, S), 3)
            <= pos)                                        # causal @ pos

    def block(x, inp):
        layer, kc, vc = inp                                # kc/vc [B,H,S,Dh]
        q, k, v = _qkv_proj(x, layer, cfg, rope, positions=pos[None])
        kc = jax.lax.dynamic_update_slice(kc, k, (0, 0, pos, 0))
        vc = jax.lax.dynamic_update_slice(vc, v, (0, 0, pos, 0))
        s = jnp.einsum("bhqk,bhsk->bhqs", q.astype(jnp.float32),
                       kc.astype(jnp.float32)) * (cfg.d_head ** -0.5)
        s = jnp.where(mask, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhqs,bhsk->bhqk", p.astype(cfg.dtype), vc)
        return _attn_out_and_mlp(x, o, layer, cfg), (kc, vc)

    # full unroll: a rolled scan at decode shapes ([B, D] operands) is
    # dominated by per-op fixed cost and blocks cross-layer fusion —
    # unrolling the 12-layer stack measured +55% decode steps/s on v5e
    # (786 -> 1219 at B=8, gpt2-small)
    x, (k_new, v_new) = jax.lax.scan(
        block, x, (params["layers"], cache["k"], cache["v"]),
        unroll=cfg.n_layers)
    x = _norm(x, params["final_norm"], params.get("final_norm_b"), cfg.norm)
    return x[:, 0], {"k": k_new, "v": v_new, "pos": pos + 1}


def decode_step(params, cache, tokens, cfg: GPTConfig, rope=None):
    """One decode position: tokens [B] int32 at position cache['pos'] ->
    (logits [B, V], updated cache)."""
    x, cache = _decode_hidden(params, cache, tokens, cfg, rope)
    logits = jnp.einsum("bd,dv->bv", x.astype(cfg.dtype),
                        _unembed_table(params, cfg))
    return logits, cache


def _decode_fast_eligible(cfg: GPTConfig) -> bool:
    # the fast path hand-writes the GPT-2-family recipe; other variants
    # (rope/rms/swiglu) take the generic shared-recipe path
    return cfg.norm == "ln" and cfg.act == "gelu" and cfg.pos == "learned"


def _decode_view(params, cfg: GPTConfig):
    """Decode-optimized view of the param tree: compute-dtype weights
    (decode re-reads every weight every step, so storing f32 and
    casting per use would double the HBM traffic that bounds the loop)
    and the q/k/v projections fused into one [D, 3*H*dh] matmul per
    layer.  Built INSIDE the jitted generate call — one pass over the
    weights, amortized across all decode steps."""
    L, D, H, dh = cfg.n_layers, cfg.d_model, cfg.n_heads, cfg.d_head
    lp = params["layers"]
    dt = cfg.dtype

    def f(w):
        return w.astype(dt)

    view = {
        "embed": f(params["embed"]),
        "pos_embed": f(params["pos_embed"]),
        "wqkv": jnp.concatenate([f(lp["wq"]).reshape(L, D, H * dh),
                                 f(lp["wk"]).reshape(L, D, H * dh),
                                 f(lp["wv"]).reshape(L, D, H * dh)], -1),
        "wo": f(lp["wo"]).reshape(L, H * dh, D),
        "attn_norm": lp["attn_norm"], "attn_norm_b": lp["attn_norm_b"],
        "mlp_norm": lp["mlp_norm"], "mlp_norm_b": lp["mlp_norm_b"],
        "mlp_in": f(lp["mlp_in"]), "mlp_in_b": f(lp["mlp_in_b"]),
        "mlp_out": f(lp["mlp_out"]), "mlp_out_b": f(lp["mlp_out_b"]),
        "final_norm": params["final_norm"],
        "final_norm_b": params.get("final_norm_b"),
    }
    if cfg.attn_bias:
        view["bqkv"] = jnp.concatenate(
            [f(lp["wq_b"]).reshape(L, H * dh),
             f(lp["wk_b"]).reshape(L, H * dh),
             f(lp["wv_b"]).reshape(L, H * dh)], -1)
        view["wo_b"] = f(lp["wo_b"])
    view["unembed"] = (view["embed"].T if cfg.tie_embeddings
                       else f(params["unembed"]))
    return view


def _decode_hidden_fast(view, cfg: GPTConfig, kcache, vcache, pos, toks):
    """One decode position on the view: toks [B] -> (final-norm hidden
    [B, D], kcache, vcache).  Python-unrolled layer loop (decode-shape
    ops are fixed-cost-dominated; a rolled scan also blocks cross-layer
    fusion), cache layout [L, B, H, S, dh] (a seq-major layout measured
    ~40% SLOWER on v5e: strided attention reads cost more than the
    scattered single-position writes)."""
    B = toks.shape[0]
    L, H, dh = cfg.n_layers, cfg.n_heads, cfg.d_head
    S = kcache.shape[3]
    x = view["embed"][toks] + view["pos_embed"][pos][None]      # [B, D]
    mask = (jax.lax.broadcasted_iota(jnp.int32, (1, 1, S), 2) <= pos)
    for l in range(L):
        h = layer_norm(x, view["attn_norm"][l],
                       view["attn_norm_b"][l]).astype(cfg.dtype)
        qkv = h @ view["wqkv"][l]                               # [B, 3Hd]
        if cfg.attn_bias:
            qkv = qkv + view["bqkv"][l]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        knew = k.reshape(B, H, dh)[:, :, None].astype(kcache.dtype)
        vnew = v.reshape(B, H, dh)[:, :, None].astype(vcache.dtype)
        kcache = jax.lax.dynamic_update_slice(kcache, knew[None],
                                              (l, 0, 0, pos, 0))
        vcache = jax.lax.dynamic_update_slice(vcache, vnew[None],
                                              (l, 0, 0, pos, 0))
        q = q.reshape(B, H, dh)
        s = jnp.einsum("bhk,bhsk->bhs", q.astype(jnp.float32),
                       kcache[l].astype(jnp.float32)) * (dh ** -0.5)
        s = jnp.where(mask, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        vc = vcache[l]
        if vc.dtype != cfg.dtype:
            vc = vc.astype(cfg.dtype)
        o = jnp.einsum("bhs,bhsk->bhk", p.astype(cfg.dtype), vc)
        att = o.reshape(B, H * dh) @ view["wo"][l]
        if cfg.attn_bias:
            att = att + view["wo_b"][l]
        x = x + att
        h2 = layer_norm(x, view["mlp_norm"][l],
                        view["mlp_norm_b"][l]).astype(cfg.dtype)
        m = jax.nn.gelu(h2 @ view["mlp_in"][l] + view["mlp_in_b"][l])
        x = x + (m @ view["mlp_out"][l] + view["mlp_out_b"][l])
    x = layer_norm(x, view["final_norm"], view["final_norm_b"])
    return x.astype(cfg.dtype), kcache, vcache


# ---------------------------------------------------------------------------
# Slot-batch decoding (continuous batching).  The serving engine keeps a
# fixed-shape batch of B "slots"; sequences join at prefill and leave at
# EOS/max-tokens, so every slot sits at its OWN position.  Two cache
# layouts share the identical attention math:
#
#   * contiguous slot cache [L, B, H, S, dh] — one row per slot (kept for
#     bitwise parity tests against the paged path);
#   * paged cache: a device arena of fixed-size pages [L, P, H, ps, dh]
#     plus per-slot page tables gathered inside the decode step.  Page 0
#     is reserved as the null page: inactive slots write there and their
#     outputs are discarded host-side, so the compiled step program
#     never changes shape as sequences come and go.


def _slot_rope(x, cos, sin, positions):
    """Per-slot rotary embedding: x [B, H, 1, dh], positions [B] (each
    batch row at its own decode position, unlike ops.apply_rope whose
    positions are shared across the batch)."""
    c = cos[positions][:, None, None]           # [B, 1, 1, dh/2]
    sn = sin[positions][:, None, None]
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([x1 * c - x2 * sn, x2 * c + x1 * sn],
                           axis=-1).astype(x.dtype)


def _slot_embed(params, tokens, pos, cfg: GPTConfig):
    x = params["embed"][tokens].astype(cfg.dtype)          # [B, D]
    if cfg.pos == "learned":
        x = x + params["pos_embed"][pos].astype(cfg.dtype)  # per-slot row
    return x[:, None]                                      # [B, 1, D]


def _slot_qkv(x, layer, cfg: GPTConfig, rope, pos):
    q, k, v = _qkv_proj(x, layer, cfg, rope=None)
    if rope is not None:
        q = _slot_rope(q, *rope, positions=pos)
        k = _slot_rope(k, *rope, positions=pos)
    return q, k, v


def _slot_attention(q, kc, vc, pos, cfg: GPTConfig):
    """q [B,H,1,dh] against a per-slot cache view kc/vc [B,H,S,dh] with
    per-slot causal masks (<= pos[b]).  This is the ONE attention recipe
    both cache layouts feed — the paged path gathers its pages into
    exactly this [B,H,S,dh] view, which is what makes paged==contiguous
    a structural identity rather than a numerical accident."""
    S = kc.shape[2]
    mask = (jax.lax.broadcasted_iota(jnp.int32, (1, 1, 1, S), 3)
            <= pos[:, None, None, None])
    s = jnp.einsum("bhqk,bhsk->bhqs", q.astype(jnp.float32),
                   kc.astype(jnp.float32)) * (cfg.d_head ** -0.5)
    s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    vcd = vc if vc.dtype == cfg.dtype else vc.astype(cfg.dtype)
    return jnp.einsum("bhqs,bhsk->bhqk", p.astype(cfg.dtype), vcd)


def init_slot_cache(cfg: GPTConfig, slots: int, max_total: int
                    ) -> Dict[str, Any]:
    """Contiguous slot cache: [L, slots, H, max_total, d_head] per side.
    Positions live with the engine (per-slot, host-driven), not in the
    cache — unlike init_cache's scalar lockstep `pos`."""
    shape = (cfg.n_layers, slots, cfg.n_heads, max_total, cfg.d_head)
    return {"k": jnp.zeros(shape, cfg.dtype),
            "v": jnp.zeros(shape, cfg.dtype)}


def _slot_decode_hidden(params, kcache, vcache, tokens, pos, cfg: GPTConfig,
                        rope=None):
    """One decode position for every slot: tokens [B] at per-slot
    positions pos [B] -> (hidden [B, D], kcache, vcache).  kcache/vcache
    [L, B, H, S, dh]."""
    B = tokens.shape[0]
    S = kcache.shape[3]
    if cfg.pos == "learned":
        rope = None
    elif rope is None:
        rope = rope_table(S, cfg.d_head, dtype=jnp.float32)
    x = _slot_embed(params, tokens, pos, cfg)
    bidx = jnp.arange(B)

    def block(x, inp):
        layer, kc, vc = inp                    # kc/vc [B, H, S, dh]
        q, k, v = _slot_qkv(x, layer, cfg, rope, pos)
        kc = kc.at[bidx, :, pos, :].set(k[:, :, 0, :].astype(kc.dtype))
        vc = vc.at[bidx, :, pos, :].set(v[:, :, 0, :].astype(vc.dtype))
        o = _slot_attention(q, kc, vc, pos, cfg)
        return _attn_out_and_mlp(x, o, layer, cfg), (kc, vc)

    x, (k_new, v_new) = jax.lax.scan(
        block, x, (params["layers"], kcache, vcache), unroll=cfg.n_layers)
    x = _norm(x, params["final_norm"], params.get("final_norm_b"), cfg.norm)
    return x[:, 0], k_new, v_new


def slot_decode_step(params, cache, tokens, pos, cfg: GPTConfig, rope=None):
    """Slot-batch decode on the contiguous cache: tokens [B] at per-slot
    positions pos [B] -> (logits [B, V], cache)."""
    x, k_new, v_new = _slot_decode_hidden(params, cache["k"], cache["v"],
                                          tokens, pos, cfg, rope)
    logits = jnp.einsum("bd,dv->bv", x.astype(cfg.dtype),
                        _unembed_table(params, cfg))
    return logits, {"k": k_new, "v": v_new}


def slot_prefill(params, cache, toks, start, last_idx, slot,
                 cfg: GPTConfig, rope=None):
    """Prefill ONE slot while the rest of the batch is frozen: toks [T]
    (padded; positions are clamped so pad steps never overflow the
    row — pad writes land at positions decode overwrites before it
    attends them), starting at position `start`; logits are taken at
    scanned index `last_idx` (the last REAL prompt token).  Returns
    (logits [V], cache)."""
    S = cache["k"].shape[3]
    kc = jax.lax.dynamic_slice_in_dim(cache["k"], slot, 1, 1)
    vc = jax.lax.dynamic_slice_in_dim(cache["v"], slot, 1, 1)
    T = toks.shape[0]
    positions = jnp.minimum(start + jnp.arange(T, dtype=jnp.int32), S - 1)
    if cfg.pos != "learned" and rope is None:
        rope = rope_table(S, cfg.d_head, dtype=jnp.float32)

    def body(carry, inp):
        kc, vc = carry
        tok, p = inp
        x, kc, vc = _slot_decode_hidden(params, kc, vc, tok[None],
                                        p[None], cfg, rope)
        return (kc, vc), x[0]

    (kc, vc), xs = jax.lax.scan(body, (kc, vc), (toks, positions))
    x = jax.lax.dynamic_index_in_dim(xs, last_idx, 0, keepdims=False)
    logits = jnp.einsum("d,dv->v", x.astype(cfg.dtype),
                        _unembed_table(params, cfg))
    cache = {
        "k": jax.lax.dynamic_update_slice_in_dim(cache["k"], kc, slot, 1),
        "v": jax.lax.dynamic_update_slice_in_dim(cache["v"], vc, slot, 1),
    }
    return logits, cache


# -- paged variant ----------------------------------------------------------


def init_paged_cache(cfg: GPTConfig, num_pages: int, page_size: int
                     ) -> Dict[str, Any]:
    """Paged KV arena: [L, num_pages, H, page_size, d_head] per side.
    Page 0 is the reserved null page (inactive-slot writes land there;
    the allocator never hands it out)."""
    shape = (cfg.n_layers, num_pages, cfg.n_heads, page_size, cfg.d_head)
    return {"k": jnp.zeros(shape, cfg.dtype),
            "v": jnp.zeros(shape, cfg.dtype)}


def _paged_decode_hidden(params, kpages, vpages, tokens, ptab, pos,
                         cfg: GPTConfig, rope=None):
    """One decode position for every slot against the page arena:
    tokens [B], ptab [B, max_pages] (page ids in sequence order; unused
    entries 0), pos [B] -> (hidden [B, D], kpages, vpages).  Writes
    scatter into each slot's current page; attention gathers the slot's
    pages into the contiguous [B, H, S, dh] view and runs the shared
    _slot_attention recipe."""
    B = tokens.shape[0]
    H, dh = cfg.n_heads, cfg.d_head
    ps = kpages.shape[3]
    maxp = ptab.shape[1]
    S = maxp * ps
    pos = jnp.minimum(pos, S - 1)
    if cfg.pos == "learned":
        rope = None
    elif rope is None:
        rope = rope_table(S, cfg.d_head, dtype=jnp.float32)
    x = _slot_embed(params, tokens, pos, cfg)
    pidx = jnp.take_along_axis(ptab, (pos // ps)[:, None], axis=1)[:, 0]
    poff = pos % ps

    def gather(pages):
        g = pages[ptab]                        # [B, maxp, H, ps, dh]
        return jnp.transpose(g, (0, 2, 1, 3, 4)).reshape(B, H, S, dh)

    def block(x, inp):
        layer, kc, vc = inp                    # kc/vc [P, H, ps, dh]
        q, k, v = _slot_qkv(x, layer, cfg, rope, pos)
        kc = kc.at[pidx, :, poff, :].set(k[:, :, 0, :].astype(kc.dtype))
        vc = vc.at[pidx, :, poff, :].set(v[:, :, 0, :].astype(vc.dtype))
        o = _slot_attention(q, gather(kc), gather(vc), pos, cfg)
        return _attn_out_and_mlp(x, o, layer, cfg), (kc, vc)

    x, (k_new, v_new) = jax.lax.scan(
        block, x, (params["layers"], kpages, vpages), unroll=cfg.n_layers)
    x = _norm(x, params["final_norm"], params.get("final_norm_b"), cfg.norm)
    return x[:, 0], k_new, v_new


def paged_decode_step(params, cache, tokens, ptab, pos, cfg: GPTConfig,
                      rope=None):
    """Slot-batch decode on the paged cache: -> (logits [B, V], cache)."""
    x, k_new, v_new = _paged_decode_hidden(params, cache["k"], cache["v"],
                                           tokens, ptab, pos, cfg, rope)
    logits = jnp.einsum("bd,dv->bv", x.astype(cfg.dtype),
                        _unembed_table(params, cfg))
    return logits, {"k": k_new, "v": v_new}


def paged_prefill(params, cache, toks, ptab_row, start, last_idx,
                  cfg: GPTConfig, rope=None):
    """Prefill one slot's pages: toks [T] (padded) starting at position
    `start` (positions before `start` are prefix-shared pages already
    holding valid K/V); logits at scanned index `last_idx`.  Returns
    (logits [V], cache)."""
    kc, vc = cache["k"], cache["v"]
    ps = kc.shape[3]
    S = ptab_row.shape[0] * ps
    T = toks.shape[0]
    positions = jnp.minimum(start + jnp.arange(T, dtype=jnp.int32), S - 1)
    if cfg.pos != "learned" and rope is None:
        rope = rope_table(S, cfg.d_head, dtype=jnp.float32)

    def body(carry, inp):
        kc, vc = carry
        tok, p = inp
        x, kc, vc = _paged_decode_hidden(params, kc, vc, tok[None],
                                         ptab_row[None], p[None], cfg,
                                         rope)
        return (kc, vc), x[0]

    (kc, vc), xs = jax.lax.scan(body, (kc, vc), (toks, positions))
    x = jax.lax.dynamic_index_in_dim(xs, last_idx, 0, keepdims=False)
    logits = jnp.einsum("d,dv->v", x.astype(cfg.dtype),
                        _unembed_table(params, cfg))
    return logits, {"k": kc, "v": vc}


def copy_page(cache, dst, src):
    """Copy-on-write: duplicate page `src` into `dst` across all layers
    (both K and V sides) — used when a new sequence diverges inside a
    prefix-shared page."""
    return {"k": cache["k"].at[:, dst].set(cache["k"][:, src]),
            "v": cache["v"].at[:, dst].set(cache["v"][:, src])}


def sample_logits(logits, key, temperature: float = 0.0,
                  top_k: Optional[int] = None, dtype=jnp.int32):
    """The ONE sampling recipe (greedy argmax at temperature 0, else
    temperature-scaled, optionally top-k-truncated categorical) — shared
    by generate() and the serving stream step so seed parity between
    routes can't drift."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(dtype)
    logits = logits / temperature
    if top_k is not None:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -1e30, logits)
    return jax.random.categorical(key, logits).astype(dtype)


def generate(params, cfg: GPTConfig, prompt, max_new_tokens: int, *,
             temperature: float = 0.0, top_k: Optional[int] = None,
             rng=None, max_seq: Optional[int] = None):
    """Autoregressive generation: prompt [B, S] int32 -> [B, S + new].

    temperature == 0 is greedy argmax; otherwise categorical sampling
    over logits/temperature (optionally top_k-truncated).  The prefill
    and decode loops are both lax.scans of decode_step, so the entire
    call jits to one program with static shapes.  GPT-2-family configs
    take a decode-view fast path (fused QKV, compute-dtype weights,
    unrolled layers) measured ~2x the generic path on v5e; both paths
    share sample_logits and the key schedule (token-exact in f32; at
    bf16, fusion-order rounding can flip near-tie logits).
    """
    B, S = prompt.shape
    total = S + max_new_tokens
    if max_seq is None:
        max_seq = total
    if total > max_seq:
        raise ValueError(f"prompt ({S}) + max_new_tokens "
                         f"({max_new_tokens}) > max_seq ({max_seq})")
    if cfg.pos == "learned" and total > cfg.max_seq:
        raise ValueError(f"learned positions stop at {cfg.max_seq}")
    if rng is None:
        rng = jax.random.PRNGKey(0)

    def sample(logits, key):
        return sample_logits(logits, key, temperature, top_k,
                             dtype=prompt.dtype)

    keys = jax.random.split(rng, max_new_tokens)

    if _decode_fast_eligible(cfg):
        view = _decode_view(params, cfg)
        shape = (cfg.n_layers, B, cfg.n_heads, max_seq, cfg.d_head)
        kc0 = jnp.zeros(shape, cfg.dtype)
        vc0 = jnp.zeros(shape, cfg.dtype)

        def prefill_f(carry, tok):
            kc, vc, pos = carry
            # hidden only — projecting [B, V] logits per prompt
            # position would throw away all but the last
            x, kc, vc = _decode_hidden_fast(view, cfg, kc, vc, pos, tok)
            return (kc, vc, pos + 1), x

        (kc, vc, pos), hidden_all = jax.lax.scan(
            prefill_f, (kc0, vc0, jnp.zeros((), jnp.int32)), prompt.T)
        last_logits = hidden_all[-1] @ view["unembed"]

        def step_f(carry, key):
            kc, vc, pos, logits = carry
            tok = sample(logits, key)
            x, kc, vc = _decode_hidden_fast(view, cfg, kc, vc, pos, tok)
            return (kc, vc, pos + 1, x @ view["unembed"]), tok

        (_, _, _, _), new_tokens = jax.lax.scan(
            step_f, (kc, vc, pos, last_logits), keys)
        return jnp.concatenate([prompt, new_tokens.T], axis=1)

    cache = init_cache(cfg, B, max_seq)
    # hoisted out of both scan bodies: the table is position-invariant
    rope = (rope_table(max_seq, cfg.d_head, dtype=jnp.float32)
            if cfg.pos != "learned" else None)

    def prefill(cache, tok):
        # hidden only (see prefill_f above)
        x, cache = _decode_hidden(params, cache, tok, cfg, rope)
        return cache, x

    cache, hidden_all = jax.lax.scan(prefill, cache, prompt.T)
    last_logits = jnp.einsum("bd,dv->bv",
                             hidden_all[-1].astype(cfg.dtype),
                             _unembed_table(params, cfg))

    def step(carry, key):
        cache, logits = carry
        tok = sample(logits, key)
        new_logits, cache = decode_step(params, cache, tok, cfg, rope)
        return (cache, new_logits), tok

    (_, _), new_tokens = jax.lax.scan(step, (cache, last_logits), keys)
    return jnp.concatenate([prompt, new_tokens.T], axis=1)


def num_params(cfg: GPTConfig) -> int:
    p = init(jax.random.PRNGKey(0), dataclasses.replace(cfg, n_layers=1))
    base = sum(x.size for x in jax.tree.leaves(p))
    per_layer = sum(x.size for x in jax.tree.leaves(p["layers"]))
    return base + per_layer * (cfg.n_layers - 1)
