"""Mixture-of-experts GPT: the EP (expert-parallel) flagship model.

The reference has no in-tree MoE/EP (SURVEY.md §2.3) — this is the native
build: a decoder-only transformer whose MLP is a top-k routed expert bank
(ops/moe.py), expert-sharded over the mesh's `ep` axis with all_to_all
token dispatch inside a partial-manual shard_map region.  Attention, norms,
rope, scan-over-layers and the sharding-constraint idiom are shared with
models/gpt.py.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.ops.layers import rms_norm, rope_table, apply_rope, \
    softmax_cross_entropy
from ray_tpu.ops.moe import expert_capacity, moe_ffn, moe_ffn_sharded
from ray_tpu.parallel.sharding import Logical

from . import gpt as _gpt
from .gpt import GPTConfig, _attention_op, _constrain, _norm


@dataclasses.dataclass(frozen=True)
class MoEConfig(GPTConfig):
    """GPT config + expert bank. d_ff is the per-expert hidden size."""

    n_experts: int = 8
    expert_top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    router_z_weight: float = 0.001

    @classmethod
    def mixtral_nano(cls, **kw):
        kw.setdefault("norm", "rms")
        kw.setdefault("act", "gelu")
        kw.setdefault("pos", "rope")
        return cls(n_layers=2, d_model=64, n_heads=4, d_head=16, d_ff=128,
                   vocab_size=256, max_seq=128, n_experts=4,
                   tie_embeddings=True, **kw)

    @classmethod
    def small(cls, **kw):
        kw.setdefault("norm", "rms")
        kw.setdefault("pos", "rope")
        return cls(n_layers=12, d_model=768, n_heads=12, d_head=64,
                   d_ff=2048, n_experts=8, **kw)


def logical_axes(cfg: MoEConfig) -> Dict[str, Any]:
    lp = {
        "attn_norm": Logical("layers", None),
        "wq": Logical("layers", "embed", "heads", "head_dim"),
        "wk": Logical("layers", "embed", "heads", "head_dim"),
        "wv": Logical("layers", "embed", "heads", "head_dim"),
        "wo": Logical("layers", "heads", "head_dim", "embed"),
        "mlp_norm": Logical("layers", None),
        # router replicated over experts (every token scores every expert)
        "router": Logical("layers", "embed", None),
        "w_in": Logical("layers", "experts", "embed", "mlp"),
        "w_out": Logical("layers", "experts", "mlp", "embed"),
    }
    if cfg.norm == "ln":
        lp["attn_norm_b"] = Logical("layers", None)
        lp["mlp_norm_b"] = Logical("layers", None)
    out = {
        "embed": Logical("vocab", "embed"),
        "layers": lp,
        "final_norm": Logical(None),
    }
    if cfg.norm == "ln":
        out["final_norm_b"] = Logical(None)
    if cfg.pos == "learned":
        out["pos_embed"] = Logical(None, "embed")
    if not cfg.tie_embeddings:
        out["unembed"] = Logical("embed", "vocab")
    return out


def init(key, cfg: MoEConfig) -> Dict[str, Any]:
    L, D, H, dh, F, V, E = (cfg.n_layers, cfg.d_model, cfg.n_heads,
                            cfg.d_head, cfg.d_ff, cfg.vocab_size,
                            cfg.n_experts)
    pd = cfg.param_dtype
    k = iter(jax.random.split(key, 16))

    def dense(rng, shape, fan_in):
        return jax.random.normal(rng, shape, pd) * (1.0 / math.sqrt(fan_in))

    lp = {
        "attn_norm": jnp.ones((L, D), pd),
        "wq": dense(next(k), (L, D, H, dh), D),
        "wk": dense(next(k), (L, D, H, dh), D),
        "wv": dense(next(k), (L, D, H, dh), D),
        "wo": dense(next(k), (L, H, dh, D), H * dh) / math.sqrt(2 * L),
        "mlp_norm": jnp.ones((L, D), pd),
        "router": dense(next(k), (L, D, E), D),
        "w_in": dense(next(k), (L, E, D, F), D),
        "w_out": dense(next(k), (L, E, F, D), F) / math.sqrt(2 * L),
    }
    if cfg.norm == "ln":
        lp["attn_norm_b"] = jnp.zeros((L, D), pd)
        lp["mlp_norm_b"] = jnp.zeros((L, D), pd)
    params = {
        "embed": jax.random.normal(next(k), (V, D), pd) * 0.02,
        "layers": lp,
        "final_norm": jnp.ones((D,), pd),
    }
    if cfg.norm == "ln":
        params["final_norm_b"] = jnp.zeros((D,), pd)
    if cfg.pos == "learned":
        params["pos_embed"] = jax.random.normal(next(k), (cfg.max_seq, D),
                                                pd) * 0.01
    if not cfg.tie_embeddings:
        params["unembed"] = dense(next(k), (D, V), D)
    return params


def _moe_op(h, router_w, w_in, w_out, cfg: MoEConfig, mesh,
            allow_manual: bool = True):
    """Routed MLP on [B, S, D] activations; returns (out, aux, z).

    With an ep axis on the mesh the expert computation runs in a
    partial-manual shard_map over {'ep'}: tokens stay sharded over the data
    axes automatically, experts are split manually, and dispatch is one
    lax.all_to_all each way over ICI.  Inside the pp pipeline's manual
    region (allow_manual=False) shardy cannot open another manual region,
    so expert parallelism falls back to GSPMD auto-partitioning of the
    dense routed-FFN einsums over the expert-sharded weights.
    """
    B, S, D = h.shape
    x2 = h.reshape(B * S, D)
    if allow_manual and mesh is not None and mesh.shape.get("ep", 1) > 1:
        from ray_tpu._private.jax_compat import shard_map
        from jax.sharding import PartitionSpec as P

        n_ep = mesh.shape["ep"]
        # partial-manual over {'ep'} divides the token dim by ep only (the
        # dp/fsdp shards stay inside the body's GSPMD-auto dimension), so
        # the routing group holds B*S/ep tokens
        cap = expert_capacity(x2.shape[0] // n_ep, cfg.n_experts,
                              cfg.expert_top_k, cfg.capacity_factor)
        fn = lambda xt, wr, wi, wo: moe_ffn_sharded(
            xt, wr, wi, wo, axis_name="ep", k=cfg.expert_top_k,
            capacity=cap)
        out, aux, z = shard_map(
            fn, check_vma=False, mesh=mesh,
            in_specs=(P("ep"), P(), P("ep"), P("ep")),
            out_specs=(P("ep"), P(), P()),
            axis_names=frozenset({"ep"}))(x2, router_w, w_in, w_out)
    else:
        out, aux, z = moe_ffn(x2, router_w, w_in, w_out,
                              k=cfg.expert_top_k,
                              capacity_factor=cfg.capacity_factor)
    return out.reshape(B, S, D), aux, z


def apply(params, tokens, cfg: MoEConfig, mesh=None
          ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Forward: tokens [B, S] -> (logits [B, S, V], {"aux","z"} losses)."""
    B, S = tokens.shape
    pp = mesh.shape.get("pp", 1) if mesh is not None else 1
    x = params["embed"][tokens].astype(cfg.dtype)
    if cfg.pos == "learned":
        x = x + params["pos_embed"][:S][None].astype(cfg.dtype)
        rope = None
    else:
        rope = rope_table(S, cfg.d_head, dtype=jnp.float32)
    x = _constrain(x, "batch", "seq", "embed")

    def block(x, layer):
        h = _norm(x, layer["attn_norm"], layer.get("attn_norm_b"), cfg.norm)
        h = h.astype(cfg.dtype)
        q = jnp.einsum("bsd,dhk->bhsk", h, layer["wq"].astype(cfg.dtype))
        k = jnp.einsum("bsd,dhk->bhsk", h, layer["wk"].astype(cfg.dtype))
        v = jnp.einsum("bsd,dhk->bhsk", h, layer["wv"].astype(cfg.dtype))
        if rope is not None:
            q = apply_rope(q, *rope)
            k = apply_rope(k, *rope)
        q = _constrain(q, "batch", "heads", "seq", "head_dim")
        k = _constrain(k, "batch", "heads", "seq", "head_dim")
        v = _constrain(v, "batch", "heads", "seq", "head_dim")
        o = _attention_op(q, k, v, cfg, mesh, allow_manual=(pp == 1))
        att = jnp.einsum("bhsk,hkd->bsd", o, layer["wo"].astype(cfg.dtype))
        x = x + att
        h2 = _norm(x, layer["mlp_norm"], layer.get("mlp_norm_b"), cfg.norm)
        m, aux, z = _moe_op(h2.astype(cfg.dtype),
                            layer["router"].astype(cfg.dtype),
                            layer["w_in"].astype(cfg.dtype),
                            layer["w_out"].astype(cfg.dtype), cfg, mesh,
                            allow_manual=(pp == 1))
        x = x + m
        return _constrain(x, "batch", "seq", "embed"), aux, z

    def scan_body(carry, layer):
        x, aux_sum, z_sum = carry
        if cfg.remat:
            x, aux, z = jax.checkpoint(block)(x, layer)
        else:
            x, aux, z = block(x, layer)
        return (x, aux_sum + aux, z_sum + z), None

    if pp > 1:
        # MoE through the pp pipeline: the (x, aux, z) triple rides the
        # rotation as a pytree carry (parallel/pipeline.py), so router
        # losses from every stage reach the output
        from ray_tpu.parallel.pipeline import (merge_microbatches,
                                               pipeline_apply,
                                               split_microbatches)

        if cfg.n_layers % pp:
            raise ValueError(f"n_layers {cfg.n_layers} not divisible by "
                             f"pp {pp}")
        M = cfg.num_microbatches or pp

        def stage_fn(stage_layers, carry):
            (x, aux, z), _ = jax.lax.scan(scan_body, carry, stage_layers)
            return (x, aux, z)

        stacked = jax.tree.map(
            lambda p: p.reshape(pp, cfg.n_layers // pp, *p.shape[1:]),
            params["layers"])
        zeros_mb = jnp.zeros((M,), jnp.float32)
        x_out, aux_mb, z_mb = pipeline_apply(
            stage_fn, stacked,
            (split_microbatches(x, M), zeros_mb, zeros_mb), mesh)
        x = merge_microbatches(x_out)
        aux_sum = jnp.mean(aux_mb)
        z_sum = jnp.mean(z_mb)
    else:
        zero = jnp.zeros((), jnp.float32)
        (x, aux_sum, z_sum), _ = jax.lax.scan(
            scan_body, (x, zero, zero), params["layers"])
    x = _norm(x, params["final_norm"], params.get("final_norm_b"), cfg.norm)
    unembed = (params["embed"].T if cfg.tie_embeddings
               else params["unembed"]).astype(cfg.dtype)
    logits = jnp.einsum("bsd,dv->bsv", x.astype(cfg.dtype), unembed)
    losses = {"aux": aux_sum / cfg.n_layers, "z": z_sum / cfg.n_layers}
    return _constrain(logits, "batch", "seq", "vocab"), losses


def loss_fn(params, batch, cfg: MoEConfig, mesh=None):
    """LM loss + weighted router aux losses."""
    if "inputs" in batch:
        inputs, targets = batch["inputs"], batch["targets"]
    else:
        toks = batch["tokens"]
        inputs, targets = toks[:, :-1], toks[:, 1:]
    logits, extras = apply(params, inputs, cfg, mesh)
    loss = softmax_cross_entropy(logits, targets, z_loss=cfg.z_loss)
    if "mask" in batch:
        mask = batch["mask"].astype(jnp.float32)
        lm = jnp.sum(loss * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    else:
        lm = jnp.mean(loss)
    return (lm + cfg.aux_loss_weight * extras["aux"]
            + cfg.router_z_weight * extras["z"])


def num_params(cfg: MoEConfig) -> int:
    L, D, H, dh, F, V, E = (cfg.n_layers, cfg.d_model, cfg.n_heads,
                            cfg.d_head, cfg.d_ff, cfg.vocab_size,
                            cfg.n_experts)
    per_layer = (2 * D + 3 * D * H * dh + H * dh * D + D * E
                 + 2 * E * D * F)
    total = V * D + L * per_layer + D
    if not cfg.tie_embeddings:
        total += D * V
    if cfg.pos == "learned":
        total += cfg.max_seq * D
    return total
