"""HuggingFace GPT-2 checkpoint interop.

`from_hf_gpt2` maps a `transformers` GPT-2 model's weights into this
framework's param tree (models/gpt.py layout: layers stacked on a
leading axis for the scan), so HF checkpoints train, decode, and serve
here natively — the reference reaches HF models by running torch inside
its workers (reference: python/ray/train/huggingface/); here the weights
cross once into jax and everything downstream is the TPU-native path.

Layout notes (verified against transformers' GPT2 implementation):
  * HF Conv1D stores weight as [in_features, out_features] (already the
    orientation our einsums want — no transposes);
  * c_attn packs q/k/v along the output axis: split thirds;
  * GPT-2 uses the tanh-approximate GELU, which is jax.nn.gelu's
    default, and layer-norm eps 1e-5, which matches ops/layers.py;
  * lm_head is tied to wte (tie_embeddings=True).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax.numpy as jnp
import numpy as np

from . import gpt

__all__ = ["from_hf_gpt2"]


def from_hf_gpt2(model: Any, *, dtype=jnp.bfloat16, param_dtype=jnp.float32,
                 **cfg_overrides) -> Tuple[gpt.GPTConfig, Dict[str, Any]]:
    """transformers GPT2LMHeadModel (or a name to load) -> (cfg, params).

    Pass a model instance to stay offline; a string name delegates to
    transformers.AutoModelForCausalLM.from_pretrained (needs the weights
    to be locally cached in a zero-egress environment).
    """
    if isinstance(model, str):
        from transformers import AutoModelForCausalLM

        model = AutoModelForCausalLM.from_pretrained(model)
    hc = model.config
    # variants that change the math this converter hardcodes must fail
    # loudly, not produce silently-divergent logits
    act = getattr(hc, "activation_function", "gelu_new")
    if act not in ("gelu_new", "gelu_pytorch_tanh"):
        raise NotImplementedError(
            f"activation_function={act!r} (converter assumes the tanh "
            f"GELU GPT-2 ships with)")
    for flag in ("scale_attn_by_inverse_layer_idx",
                 "reorder_and_upcast_attn"):
        if getattr(hc, flag, False):
            raise NotImplementedError(f"{flag}=True is not supported")
    ln_eps = float(getattr(hc, "layer_norm_epsilon", 1e-5))
    if abs(ln_eps - 1e-5) > 1e-12:
        # ops/layers.py layer_norm hardcodes eps=1e-5; converting such a
        # checkpoint would silently produce divergent logits
        raise NotImplementedError(
            f"layer_norm_epsilon={ln_eps!r} (converter assumes GPT-2's "
            f"default 1e-5, which is what this framework's layer_norm "
            f"uses)")
    sd = {k: np.asarray(v.detach().cpu().numpy())
          for k, v in model.state_dict().items()}
    prefix = "transformer." if any(k.startswith("transformer.")
                                   for k in sd) else ""

    D, H, L = hc.n_embd, hc.n_head, hc.n_layer
    dh = D // H
    F = getattr(hc, "n_inner", None) or 4 * D
    cfg = gpt.GPTConfig(
        n_layers=L, d_model=D, n_heads=H, d_head=dh, d_ff=F,
        vocab_size=hc.vocab_size, max_seq=hc.n_positions,
        norm="ln", act="gelu", pos="learned", tie_embeddings=True,
        attn_bias=True, dtype=dtype, param_dtype=param_dtype,
        **cfg_overrides)

    def g(name):
        return sd[prefix + name].astype(np.float32)

    def stack(fmt, reshape=None):
        arrs = [g(fmt.format(i)) for i in range(L)]
        if reshape is not None:
            arrs = [a.reshape(reshape) for a in arrs]
        return jnp.asarray(np.stack(arrs), param_dtype)

    # one pass over c_attn per layer (not one per q/k/v: gpt2-xl's
    # [1600, 4800] f32 copies are worth not tripling)
    qkv_w = [[], [], []]
    qkv_b = [[], [], []]
    for i in range(L):
        w = g(f"h.{i}.attn.c_attn.weight")            # [D, 3D]
        b = g(f"h.{i}.attn.c_attn.bias")              # [3D]
        for which in range(3):
            qkv_w[which].append(
                w[:, which * D:(which + 1) * D].reshape(D, H, dh))
            qkv_b[which].append(
                b[which * D:(which + 1) * D].reshape(H, dh))
    (wq, wk, wv), (wq_b, wk_b, wv_b) = (
        [jnp.asarray(np.stack(a), param_dtype) for a in qkv_w],
        [jnp.asarray(np.stack(a), param_dtype) for a in qkv_b])
    lp = {
        "attn_norm": stack("h.{}.ln_1.weight"),
        "attn_norm_b": stack("h.{}.ln_1.bias"),
        "wq": wq, "wk": wk, "wv": wv,
        "wq_b": wq_b, "wk_b": wk_b, "wv_b": wv_b,
        "wo": stack("h.{}.attn.c_proj.weight", reshape=(H, dh, D)),
        "wo_b": stack("h.{}.attn.c_proj.bias"),
        "mlp_norm": stack("h.{}.ln_2.weight"),
        "mlp_norm_b": stack("h.{}.ln_2.bias"),
        "mlp_in": stack("h.{}.mlp.c_fc.weight"),
        "mlp_in_b": stack("h.{}.mlp.c_fc.bias"),
        "mlp_out": stack("h.{}.mlp.c_proj.weight"),
        "mlp_out_b": stack("h.{}.mlp.c_proj.bias"),
    }
    params = {
        "embed": jnp.asarray(g("wte.weight"), param_dtype),
        "pos_embed": jnp.asarray(g("wpe.weight"), param_dtype),
        "layers": lp,
        "final_norm": jnp.asarray(g("ln_f.weight"), param_dtype),
        "final_norm_b": jnp.asarray(g("ln_f.bias"), param_dtype),
    }
    return cfg, params
