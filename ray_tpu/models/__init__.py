from . import gpt, moe, resnet, training
from .gpt import GPTConfig
from .moe import MoEConfig
from .resnet import ResNetConfig
from .training import (init_sharded, make_eval_step, make_train_step,
                       shard_batch)

__all__ = [
    "gpt", "moe", "resnet", "training", "GPTConfig", "MoEConfig",
    "ResNetConfig", "make_train_step", "make_eval_step", "init_sharded",
    "shard_batch",
]
