"""ResNet-50 — the vision e2e model (BASELINE.md north star: Ray Train
ResNet-50 images/sec/chip on pods).

Pure-function pytree design like gpt.py.  BatchNorm statistics are computed
over the *global* batch: with the batch sharded over dp/fsdp, `jnp.mean`
reductions become cross-device psums under GSPMD — synchronized BN with no
extra code.  Running statistics live in a separate `state` pytree
(params, state) -> (out, new_state).

Channels-last NHWC layout (TPU-native conv layout).
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.parallel.sharding import Logical


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    num_classes: int = 1000
    stage_sizes: Tuple[int, ...] = (3, 4, 6, 3)   # ResNet-50
    width: int = 64
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    bn_momentum: float = 0.9
    bn_eps: float = 1e-5

    @classmethod
    def resnet18(cls, **kw):
        return cls(stage_sizes=(2, 2, 2, 2), **kw)

    @classmethod
    def resnet50(cls, **kw):
        return cls(stage_sizes=(3, 4, 6, 3), **kw)

    @classmethod
    def tiny(cls, **kw):
        """For tests: 2 stages, narrow."""
        kw.setdefault("num_classes", 10)
        return cls(stage_sizes=(1, 1), width=8, **kw)


def _conv_init(key, kh, kw_, cin, cout, dtype):
    fan_in = kh * kw_ * cin
    return jax.random.normal(key, (kh, kw_, cin, cout), dtype) * math.sqrt(
        2.0 / fan_in)


def _bn_params(c, dtype):
    return {"scale": jnp.ones((c,), dtype), "bias": jnp.zeros((c,), dtype)}


def _bn_state(c):
    return {"mean": jnp.zeros((c,), jnp.float32),
            "var": jnp.ones((c,), jnp.float32)}


def init(key, cfg: ResNetConfig):
    """Returns (params, state)."""
    pd = cfg.param_dtype
    keys = iter(jax.random.split(key, 256))
    params: Dict[str, Any] = {
        "stem_conv": _conv_init(next(keys), 7, 7, 3, cfg.width, pd),
        "stem_bn": _bn_params(cfg.width, pd),
    }
    state: Dict[str, Any] = {"stem_bn": _bn_state(cfg.width)}
    cin = cfg.width
    for si, nblocks in enumerate(cfg.stage_sizes):
        cmid = cfg.width * (2 ** si)
        cout = cmid * 4
        for bi in range(nblocks):
            name = f"s{si}b{bi}"
            stride = 2 if (si > 0 and bi == 0) else 1
            blk = {
                "conv1": _conv_init(next(keys), 1, 1, cin, cmid, pd),
                "bn1": _bn_params(cmid, pd),
                "conv2": _conv_init(next(keys), 3, 3, cmid, cmid, pd),
                "bn2": _bn_params(cmid, pd),
                "conv3": _conv_init(next(keys), 1, 1, cmid, cout, pd),
                "bn3": _bn_params(cout, pd),
            }
            st = {"bn1": _bn_state(cmid), "bn2": _bn_state(cmid),
                  "bn3": _bn_state(cout)}
            if cin != cout or stride != 1:
                blk["proj"] = _conv_init(next(keys), 1, 1, cin, cout, pd)
                blk["proj_bn"] = _bn_params(cout, pd)
                st["proj_bn"] = _bn_state(cout)
            params[name] = blk
            state[name] = st
            cin = cout
    params["head"] = {
        "w": jax.random.normal(next(keys), (cin, cfg.num_classes), pd) * 0.01,
        "b": jnp.zeros((cfg.num_classes,), pd),
    }
    return params, state


def logical_axes(cfg: ResNetConfig, params) -> Any:
    """Conv kernels shard their output channels over fsdp (ZeRO); head over
    tp.  BN/bias replicate."""

    def annotate(path, leaf):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        nd = leaf.ndim
        if nd == 4:
            return Logical(None, None, None, "conv_out")
        if nd == 2:
            return Logical("embed", "vocab")  # head: classes over tp
        return Logical(*([None] * nd))

    return jax.tree_util.tree_map_with_path(annotate, params)


def _batch_norm(x, p, s, training: bool, momentum: float, eps: float):
    x32 = x.astype(jnp.float32)
    if training:
        axes = tuple(range(x.ndim - 1))
        mean = jnp.mean(x32, axis=axes)          # global batch: sync BN
        var = jnp.var(x32, axis=axes)
        new_s = {"mean": momentum * s["mean"] + (1 - momentum) * mean,
                 "var": momentum * s["var"] + (1 - momentum) * var}
    else:
        mean, var = s["mean"], s["var"]
        new_s = s
    y = (x32 - mean) * jax.lax.rsqrt(var + eps)
    y = y * p["scale"].astype(jnp.float32) + p["bias"].astype(jnp.float32)
    return y.astype(x.dtype), new_s


def _conv(x, w, stride: int = 1, padding="SAME"):
    # no preferred_element_type: with bf16 operands the MXU already
    # accumulates in f32, and an explicit f32 output breaks the VJP
    # (conv transpose would see an f32 cotangent against bf16 weights)
    return jax.lax.conv_general_dilated(
        x, w.astype(x.dtype), (stride, stride), padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def apply(params, state, images, cfg: ResNetConfig, training: bool = False):
    """images [B, H, W, 3] -> (logits [B, classes], new_state)."""
    x = images.astype(cfg.dtype)
    new_state: Dict[str, Any] = {}
    x = _conv(x, params["stem_conv"], stride=2)
    x, new_state["stem_bn"] = _batch_norm(
        x, params["stem_bn"], state["stem_bn"], training, cfg.bn_momentum,
        cfg.bn_eps)
    x = jax.nn.relu(x)
    x = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, (1, 3, 3, 1),
                              (1, 2, 2, 1), "SAME")
    cin = cfg.width
    for si, nblocks in enumerate(cfg.stage_sizes):
        for bi in range(nblocks):
            name = f"s{si}b{bi}"
            blk, st = params[name], state[name]
            stride = 2 if (si > 0 and bi == 0) else 1
            ns: Dict[str, Any] = {}
            residual = x
            y = _conv(x, blk["conv1"])
            y, ns["bn1"] = _batch_norm(y, blk["bn1"], st["bn1"], training,
                                       cfg.bn_momentum, cfg.bn_eps)
            y = jax.nn.relu(y)
            y = _conv(y, blk["conv2"], stride=stride)
            y, ns["bn2"] = _batch_norm(y, blk["bn2"], st["bn2"], training,
                                       cfg.bn_momentum, cfg.bn_eps)
            y = jax.nn.relu(y)
            y = _conv(y, blk["conv3"])
            y, ns["bn3"] = _batch_norm(y, blk["bn3"], st["bn3"], training,
                                       cfg.bn_momentum, cfg.bn_eps)
            if "proj" in blk:
                residual = _conv(x, blk["proj"], stride=stride)
                residual, ns["proj_bn"] = _batch_norm(
                    residual, blk["proj_bn"], st["proj_bn"], training,
                    cfg.bn_momentum, cfg.bn_eps)
            x = jax.nn.relu(y + residual)
            new_state[name] = ns
    x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
    head = params["head"]
    logits = x @ head["w"].astype(jnp.float32) + head["b"].astype(jnp.float32)
    return logits, new_state


def loss_fn(params, state, batch, cfg: ResNetConfig, training: bool = True):
    logits, new_state = apply(params, state, batch["image"], cfg, training)
    labels = batch["label"]
    loss = jnp.mean(
        -jax.nn.log_softmax(logits)[jnp.arange(labels.shape[0]), labels])
    acc = jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))
    return loss, (new_state, {"loss": loss, "accuracy": acc})
