"""Sharded train-step builder: DP/FSDP/TP/SP via GSPMD partition specs.

The reference's gradient sync is a runtime NCCL allreduce issued by torch
DDP/FSDP inside Train workers (reference: train/torch/config.py process
groups); here the entire step — forward, backward, gradient reduction,
optimizer update — is ONE compiled XLA program over the mesh: data-parallel
gradient psums, ZeRO-3 parameter all-gathers/reduce-scatters, TP collectives
and SP ring exchanges are all inserted by the partitioner from the sharding
annotations, riding ICI.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.parallel.mesh import batch_sharding
from ray_tpu.parallel.sharding import Logical, spec_from_logical, tree_shardings
from ray_tpu.telemetry import device as devtel

from . import gpt


def _use_mesh(mesh: Mesh):
    # jax>=0.7 context-manager form; lets bare PartitionSpecs flow to
    # with_sharding_constraint inside the jitted step
    return jax.set_mesh(mesh)


def param_shardings(cfg: gpt.GPTConfig, mesh: Mesh):
    return tree_shardings(gpt.logical_axes(cfg), mesh)


def opt_state_shardings(tx, params_shape, p_shardings, mesh: Mesh):
    """Optimizer state mirrors param sharding where shapes match, else
    replicated (adam mu/nu get the ZeRO treatment for free)."""
    state_shape = jax.eval_shape(tx.init, params_shape)
    flat_params = {id_shape(l): s for l, s in zip(
        jax.tree.leaves(params_shape), jax.tree.leaves(p_shardings))}

    def assign(leaf):
        return flat_params.get(id_shape(leaf), NamedSharding(mesh, P()))

    return jax.tree.map(assign, state_shape)


def id_shape(l) -> Tuple:
    return (tuple(l.shape), str(l.dtype)) if hasattr(l, "shape") else ("s",)


def init_sharded(key, cfg: gpt.GPTConfig, mesh: Mesh):
    """Initialize parameters directly sharded on the mesh (no host copy of
    the full model — each device materializes only its shard)."""
    shardings = param_shardings(cfg, mesh)
    with _use_mesh(mesh):
        # once-per-run init: jit only for out_shardings materialization
        init_fn = devtel.jit(functools.partial(gpt.init, cfg=cfg),  # jax-ok
                             name="train.init_sharded",
                             out_shardings=shardings)
        return init_fn(key)


def make_train_step(cfg: gpt.GPTConfig, mesh: Mesh, tx=None,
                    donate: bool = True) -> Tuple[Callable, Callable]:
    """Returns (init_state_fn, step_fn), both jitted over the mesh.

    state = {"params", "opt_state", "step"}
    step_fn(state, batch) -> (state, metrics)
    """
    if tx is None:
        tx = optax.adamw(3e-4, weight_decay=0.1)
    p_shardings = param_shardings(cfg, mesh)
    key_shard = NamedSharding(mesh, P())
    b_shard = NamedSharding(mesh, P(("dp", "fsdp", "ep"), None))

    def init_state(key):
        params = gpt.init(key, cfg)
        opt_state = tx.init(params)
        return {"params": params, "opt_state": opt_state,
                "step": jnp.zeros((), jnp.int32)}

    params_shape = jax.eval_shape(functools.partial(gpt.init, cfg=cfg),
                                  jax.random.PRNGKey(0))
    o_shardings = opt_state_shardings(tx, params_shape, p_shardings, mesh)
    state_shardings = {"params": p_shardings, "opt_state": o_shardings,
                       "step": NamedSharding(mesh, P())}

    with _use_mesh(mesh):
        init_state_fn = devtel.jit(init_state, name="train.init_state",
                                   out_shardings=state_shardings)

    def step(state, batch):
        def loss(p):
            return gpt.loss_fn(p, batch, cfg, mesh)

        loss_val, grads = jax.value_and_grad(loss)(state["params"])
        updates, new_opt = tx.update(grads, state["opt_state"],
                                     state["params"])
        new_params = optax.apply_updates(state["params"], updates)
        gnorm = optax.global_norm(grads)
        return ({"params": new_params, "opt_state": new_opt,
                 "step": state["step"] + 1},
                {"loss": loss_val.astype(jnp.float32),
                 "grad_norm": gnorm.astype(jnp.float32)})

    with _use_mesh(mesh):
        step_fn = devtel.jit(
            step,
            name="train.step",
            in_shardings=(state_shardings, None),
            out_shardings=(state_shardings, None),
            donate_argnums=(0,) if donate else (),
        )

    def wrapped_step(state, batch):
        with _use_mesh(mesh):
            return step_fn(state, batch)

    def wrapped_init(key):
        with _use_mesh(mesh):
            return init_state_fn(key)

    return wrapped_init, wrapped_step


def make_eval_step(cfg: gpt.GPTConfig, mesh: Mesh):
    p_shardings = param_shardings(cfg, mesh)

    def eval_step(params, batch):
        return gpt.loss_fn(params, batch, cfg, mesh)

    with _use_mesh(mesh):
        fn = devtel.jit(eval_step, name="train.eval_step",
                        in_shardings=(p_shardings, None))

    def wrapped(params, batch):
        with _use_mesh(mesh):
            return fn(params, batch)

    return wrapped


def shard_batch(batch: Dict[str, Any], mesh: Mesh):
    """Place a host batch onto the mesh with canonical batch sharding."""
    sh = NamedSharding(mesh, P(("dp", "fsdp", "ep"), None))
    return jax.tree.map(lambda x: jax.device_put(x, sh), batch)
