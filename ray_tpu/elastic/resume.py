"""Shrink-to-fit math for elastic resume.

Pure functions (unit-testable without a cluster) used by the
BackendExecutor's supervised restart loop: pick the largest feasible
width over data-parallel replicas while preserving tp/sp axes, and split
a constant global batch exactly across the new width.
"""

from __future__ import annotations

from typing import List, Optional


class InsufficientWorkersError(RuntimeError):
    """Fewer survivors than ElasticConfig.min_workers allows."""


def shrink_to_fit(alive: int, min_workers: int,
                  max_workers: Optional[int] = None,
                  workers_per_replica: int = 1) -> int:
    """Largest feasible width <= alive: a multiple of the model-replica
    unit (tp*sp hosts), capped by max_workers, floored by min_workers."""
    unit = max(1, workers_per_replica)
    cap = alive if max_workers is None else min(alive, max_workers)
    n = (cap // unit) * unit
    floor = max(min_workers, unit)
    if n < floor:
        raise InsufficientWorkersError(
            f"only {alive} workers survive; the largest width that keeps "
            f"whole model replicas (unit={unit}, cap={cap}) is {n}, below "
            f"min_workers={min_workers}")
    return n


def per_replica_batches(global_batch: int, world: int) -> List[int]:
    """Split a global batch over ``world`` replicas so the sizes sum to
    exactly global_batch (remainder spread over the first ranks): the
    global batch — and thus the gradient — is invariant under width
    changes."""
    if world < 1:
        raise ValueError("world must be >= 1")
    base, rem = divmod(global_batch, world)
    return [base + (1 if i < rem else 0) for i in range(world)]


def batch_offsets(batches: List[int]) -> List[int]:
    """Start offset of each rank's slice within the global batch."""
    offsets, acc = [], 0
    for b in batches:
        offsets.append(acc)
        acc += b
    return offsets
