"""Shrink-to-fit math for elastic resume.

Pure functions (unit-testable without a cluster) used by the
BackendExecutor's supervised restart loop: pick the post-shrink width
over data-parallel replicas while preserving tp/sp axes, and split a
constant global batch exactly across the new width.

Width selection is goodput-*predicted*, not greedy: ``choose_width``
ranks every feasible width by the effective round rate predicted from
``IncarnationHistory`` — the recorded rounds-per-wall-second of every
gang incarnation this run has lived through, recovery churn included.
"Largest feasible" is the MLPerf TPU-pod scaling trap (arXiv:1909.09756):
when the widest gang keeps collapsing (a flaky host, repeat preemption),
its *effective* rate — rounds divided by wall time including the
recoveries it caused — falls below a narrower, stable gang's, and the
history says so.  With no history (or history at a single width, where
extrapolation is monotonic) the choice degrades to the classic largest
feasible width.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional


class InsufficientWorkersError(RuntimeError):
    """Fewer survivors than ElasticConfig.min_workers allows."""


def shrink_to_fit(alive: int, min_workers: int,
                  max_workers: Optional[int] = None,
                  workers_per_replica: int = 1) -> int:
    """Largest feasible width <= alive: a multiple of the model-replica
    unit (tp*sp hosts), capped by max_workers, floored by min_workers."""
    unit = max(1, workers_per_replica)
    cap = alive if max_workers is None else min(alive, max_workers)
    n = (cap // unit) * unit
    floor = max(min_workers, unit)
    if n < floor:
        raise InsufficientWorkersError(
            f"only {alive} workers survive; the largest width that keeps "
            f"whole model replicas (unit={unit}, cap={cap}) is {n}, below "
            f"min_workers={min_workers}")
    return n


class IncarnationHistory:
    """Per-incarnation effective-throughput records.

    The BackendExecutor opens a record at ``start_training`` (width,
    rounds counter, wall clock) and closes it when the incarnation ends
    (recovery entry / run end).  A closed record's ``rate`` is rounds
    per wall second — *wall*, not productive time, so a width that kept
    dying carries its recovery churn in its own score.
    """

    def __init__(self):
        self._records: List[Dict[str, Any]] = []
        self._open: Optional[Dict[str, Any]] = None

    def begin(self, incarnation: int, width: int, rounds: int,
              now: float) -> None:
        self.end(rounds, now)  # an unclosed prior record ends here
        self._open = {"incarnation": incarnation, "width": int(width),
                      "rounds0": int(rounds), "t0": float(now)}

    def end(self, rounds: int, now: float) -> None:
        if self._open is None:
            return
        o, self._open = self._open, None
        wall = max(now - o["t0"], 1e-9)
        done = max(int(rounds) - o["rounds0"], 0)
        self._records.append({
            "incarnation": o["incarnation"], "width": o["width"],
            "rounds": done, "wall_s": round(wall, 6),
            "rate": done / wall,
        })

    def records(self) -> List[Dict[str, Any]]:
        return list(self._records)


def predict_rate(width: int,
                 records: List[Dict[str, Any]]) -> Optional[float]:
    """Predicted effective round rate at ``width`` from history.

    Observed widths use their mean recorded rate.  Unobserved widths
    extrapolate linearly from the nearest observed width: with a
    constant global batch, per-replica work shrinks ~1/width, so round
    rate scales ~linearly in width absent degradation — and degradation
    is exactly what the observed rates encode.  Returns None with no
    usable history.
    """
    by_width: Dict[int, List[float]] = {}
    for rec in records:
        if rec.get("rounds", 0) > 0 and rec.get("width", 0) > 0:
            by_width.setdefault(int(rec["width"]), []).append(
                float(rec["rate"]))
    if not by_width:
        return None
    means = {w: sum(rs) / len(rs) for w, rs in by_width.items()}
    if width in means:
        return means[width]
    # nearest observed width; ties prefer the wider anchor
    w0 = min(means, key=lambda w: (abs(w - width), -w))
    return means[w0] * (width / w0)


def choose_width(alive: int, min_workers: int,
                 max_workers: Optional[int] = None,
                 workers_per_replica: int = 1,
                 history: Optional[IncarnationHistory] = None) -> int:
    """Post-shrink gang width by predicted goodput.

    Candidates are every feasible width (multiples of the replica unit
    between the floor and the shrink-to-fit cap); the winner maximizes
    the history-predicted effective rate, ties going to the wider gang.
    Degrades to ``shrink_to_fit`` (largest feasible) when there is no
    history to predict from.
    """
    top = shrink_to_fit(alive, min_workers, max_workers,
                        workers_per_replica)
    records = history.records() if history is not None else []
    unit = max(1, workers_per_replica)
    floor = max(min_workers, unit)
    candidates = list(range(floor, top + 1, unit))
    if len(candidates) <= 1:
        return top
    best, best_rate = top, None
    for w in candidates:
        rate = predict_rate(w, records)
        if rate is None:
            return top  # no usable history: largest feasible
        if best_rate is None or rate > best_rate or \
                (rate == best_rate and w > best):
            best, best_rate = w, rate
    return best


def per_replica_batches(global_batch: int, world: int) -> List[int]:
    """Split a global batch over ``world`` replicas so the sizes sum to
    exactly global_batch (remainder spread over the first ranks): the
    global batch — and thus the gradient — is invariant under width
    changes."""
    if world < 1:
        raise ValueError("world must be >= 1")
    base, rem = divmod(global_batch, world)
    return [base + (1 if i < rem else 0) for i in range(world)]


def batch_offsets(batches: List[int]) -> List[int]:
    """Start offset of each rank's slice within the global batch."""
    offsets, acc = [], 0
    for b in batches:
        offsets.append(acc)
        acc += b
    return offsets
