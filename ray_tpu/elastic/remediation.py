"""Remediation: close the detect→act loop on failure-detection advisories.

PR-3/PR-4 built the *detection* stack — drain notices, straggler
advisories, goodput accounting — but nothing acted on any of it: a
sustained straggler was a pubsub message and a Prometheus counter while
the gang's lockstep collectives dragged every rank to the slowest
worker's pace (the pod-scale goodput killer in the MLPerf TPU scaling
report, arXiv:1909.09756).  ``RemediationEngine`` turns those advisories
into actions under a policy that can never thrash a healthy cluster:

  hysteresis    — an open straggler episode must persist
                  ``remediation_confirm_rounds`` rounds *beyond* the
                  aggregator's own sustain threshold before any action,
                  so a transient GC pause or one slow input shard never
                  triggers a rebalance;
  rate limits   — at most ``remediation_max_episodes`` actions per run,
                  with ``remediation_cooldown_s`` between them, and one
                  open remediation at a time;
  advisory mode — the default.  The engine logs/publishes exactly what
                  it *would* do (a cause→action record with
                  ``dry_run=True``) and changes nothing; operators flip
                  ``ElasticConfig.remediation_mode="enforce"`` once the
                  recommendations look sane.

Every remediation is a cause→action→effect record: the cause is the
straggler advisory that tripped the policy, the action is the quarantine
+ elastic rebalance (node id, grace, post-shrink width), and the effect
is measured — the engine keeps watching post-action rounds and stamps
whether the gang's median busy time returned to within
``remediation_recover_tolerance`` of the pre-episode baseline.  Records
flow to the "train" pubsub topic, the structured cluster event log, and
control-plane KV (ns ``remediation``) where the flight-recorder timeline
(``chrome_trace``), ``GET /api/train/timeline`` and the
``ray-tpu remediations <job>`` CLI pick them up — the timeline shows
*why* the cluster changed shape, not just that it did.
"""

from __future__ import annotations

import json
import logging
import statistics
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger(__name__)

#: control-plane KV namespace for cause→action→effect logs, keyed by
#: trial name.  Deliberately NOT ns "train": the dashboard's /api/train
#: json-loads every key there as a run state.
REMEDIATION_NS = "remediation"

#: valid ElasticConfig.remediation_mode values
MODES = ("off", "advisory", "enforce")


def _default_publish(payload: Dict[str, Any]) -> None:
    from ray_tpu._private import core as core_mod

    core = core_mod._current_core
    if core is None or getattr(core, "_shutdown", False):
        return
    core.control.call("publish", {"topic": "train", "payload": payload},
                      timeout=5.0)


def _default_control_call(method: str, payload: Dict[str, Any]) -> Any:
    from ray_tpu._private import core as core_mod

    core = core_mod._current_core
    if core is None or getattr(core, "_shutdown", False):
        return None
    return core.control.call(method, payload, timeout=5.0)


def fetch_records(control_client, trial: str) -> List[Dict[str, Any]]:
    """Read a trial's cause→action→effect log back from control KV
    (the CLI / dashboard read side)."""
    try:
        raw = control_client.call(
            "kv_get", {"ns": REMEDIATION_NS, "key": trial}, timeout=10.0)
    except Exception:
        return []
    if not raw:
        return []
    try:
        recs = json.loads(raw)
        return recs if isinstance(recs, list) else []
    except Exception:
        return []


class RemediationEngine:
    """Driver-side policy engine; one per training run.

    The trainer calls ``observe_round(aggregator)`` once per lockstep
    round (after ``StepAggregator.ingest_round``).  The return value is
    an enforcement decision dict when the policy wants an action *this*
    round — the trainer then quarantines the rank's node through the
    executor, reports back via ``note_enforced``/``note_recovered``, and
    raises so the existing elastic-recovery path rebalances the gang.
    In advisory mode ``observe_round`` never returns a decision; it only
    records what it would have done.
    """

    def __init__(self, config, trial: str = "",
                 publish: Optional[Callable[[Dict[str, Any]], None]] = None,
                 control_call: Optional[Callable[[str, Dict], Any]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 wall: Callable[[], float] = time.time):
        self.mode = getattr(config, "remediation_mode", "advisory")
        if self.mode not in MODES:
            raise ValueError(f"remediation_mode must be one of {MODES}, "
                             f"got {self.mode!r}")
        self.trial = trial
        self.confirm_rounds = int(
            getattr(config, "remediation_confirm_rounds", 2))
        self.cooldown_s = float(
            getattr(config, "remediation_cooldown_s", 30.0))
        self.max_episodes = int(
            getattr(config, "remediation_max_episodes", 2))
        self.quarantine_grace_s = float(
            getattr(config, "quarantine_grace_s", 600.0))
        self.effect_window = int(
            getattr(config, "remediation_effect_window", 3))
        self.recover_tolerance = float(
            getattr(config, "remediation_recover_tolerance", 0.15))
        self._sustain = None  # learned from the aggregator's config
        self._publish = publish or _default_publish
        self._control_call = control_call or _default_control_call
        self._clock = clock
        self._wall = wall
        #: completed + in-flight cause→action→effect records
        self.records: List[Dict[str, Any]] = []
        self.episodes = 0          # actions taken (enforce) or recommended
        self.actions_enforced = 0  # actions actually executed
        self._last_action_at: Optional[float] = None
        #: ranks already handled (recommended or enforced) in their
        #: CURRENT open episode — cleared when the episode closes, so a
        #: rank that recovers and degrades again is a fresh episode
        self._handled: set = set()
        #: rolling gang-median busy time over healthy rounds (the
        #: pre-episode baseline the effect measurement compares against)
        self._baseline: deque = deque(maxlen=32)
        #: in-flight effect watch: record + post-action medians
        self._watch: Optional[Dict[str, Any]] = None

    # -- the per-round hook ------------------------------------------------

    def observe_round(self, aggregator) -> Optional[Dict[str, Any]]:
        """Feed one lockstep round; returns an enforcement decision or
        None.  Never raises — remediation must not take down training."""
        try:
            return self._observe(aggregator)
        except Exception:
            logger.exception("remediation observe_round failed")
            return None

    def _observe(self, aggregator) -> Optional[Dict[str, Any]]:
        view = aggregator.last_view()
        if view is None:
            return None
        busy = view.get("busy") or {}
        open_eps = aggregator.open_episodes()
        if self._sustain is None:
            self._sustain = int(getattr(aggregator.config,
                                        "straggler_sustain", 3))
        # episode bookkeeping: a closed episode re-arms its rank
        self._handled &= set(open_eps)
        median = statistics.median(busy.values()) if busy else None
        if median is not None and not open_eps:
            self._baseline.append(median)
        self._feed_effect_watch(median, view.get("step"))
        if not open_eps:
            return None

        # hysteresis: the aggregator advises at `sustain` consecutive
        # over-threshold rounds; the policy acts only once the episode
        # has outlived that by confirm_rounds more.
        need = self._sustain + self.confirm_rounds
        ripe = {r: c for r, c in open_eps.items()
                if c >= need and r not in self._handled}
        if not ripe:
            return None
        # worst offender first; one action per round
        rank = max(ripe, key=lambda r: busy.get(r, 0.0))

        # rate limits apply to enforcement AND recommendations — a
        # dry-run that would have thrashed is exactly what advisory mode
        # exists to expose, so it must follow the same policy.
        now = self._clock()
        if self.episodes >= self.max_episodes:
            self._handled.add(rank)
            logger.warning(
                "remediation suppressed (rank %s, trial %s): episode "
                "budget %d exhausted", rank, self.trial, self.max_episodes)
            return None
        if (self._last_action_at is not None
                and now - self._last_action_at < self.cooldown_s):
            # not handled: re-evaluated next round, acts once cooled down
            return None
        if self._watch is not None:
            return None  # one remediation in flight at a time

        cause = self._cause_for(aggregator, rank)
        record = {
            "id": f"rem-{len(self.records)}",
            "trial": self.trial,
            "mode": self.mode,
            "ts": self._wall(),
            "cause": cause,
            "action": {
                "kind": ("quarantine_rebalance" if self.mode == "enforce"
                         else "recommend_quarantine"),
                "rank": rank,
                "dry_run": self.mode != "enforce",
                "grace_s": self.quarantine_grace_s,
                "confirmed_rounds": open_eps[rank],
                "ts": self._wall(),
            },
            "effect": None,
        }
        self.records.append(record)
        self.episodes += 1
        self._last_action_at = now
        self._handled.add(rank)
        baseline = (statistics.median(self._baseline)
                    if self._baseline else None)

        if self.mode != "enforce":
            logger.warning(
                "remediation (advisory): WOULD quarantine rank %d of trial "
                "%s (busy %.4fs vs gang median %.4fs) — set "
                "ElasticConfig.remediation_mode='enforce' to act",
                rank, self.trial, busy.get(rank, 0.0), median or 0.0)
            self._emit("remediation_recommended", record)
            self._flush()
            return None

        logger.warning(
            "remediation (enforce): quarantining rank %d of trial %s "
            "(busy %.4fs vs gang median %.4fs, episode open %d rounds)",
            rank, self.trial, busy.get(rank, 0.0), median or 0.0,
            open_eps[rank])
        self._watch = {"record": record, "baseline": baseline,
                       "post": [], "armed_at_step": view.get("step")}
        return {"rank": rank, "record": record,
                "reason": (f"sustained straggler: busy "
                           f"{busy.get(rank, 0.0):.4f}s vs gang median "
                           f"{(median or 0.0):.4f}s"),
                "grace_s": self.quarantine_grace_s}

    def observe_advisory(self, advisory: Dict[str, Any]) -> None:
        """Record a device-runtime advisory (``recompile_storm`` /
        ``memory_watermark`` from telemetry/device.py) as a cause-only
        record in advisory mode.  No enforcement action exists for
        these yet — the record puts the storm in the trial's
        cause→action→effect log, so the timeline and ``ray-tpu
        remediations`` answer "why did goodput dip here" when the
        answer is the device runtime, not a straggler.  Never raises."""
        try:
            kind = advisory.get("kind", "device")
            record = {
                "id": f"rem-{len(self.records)}",
                "trial": self.trial,
                "mode": self.mode,
                "ts": advisory.get("ts", self._wall()),
                "cause": dict(advisory),
                "action": {"kind": f"observe_{kind}", "dry_run": True,
                           "ts": self._wall()},
                "effect": None,
            }
            self.records.append(record)
            logger.warning(
                "remediation (advisory): device %s on trial %s recorded "
                "(program=%s)", kind, self.trial,
                advisory.get("program", "n/a"))
            self._emit("remediation_recommended", record)
            self._flush()
        except Exception:
            logger.exception("remediation observe_advisory failed")

    # -- enforcement feedback from the trainer -----------------------------

    def note_enforced(self, decision: Dict[str, Any],
                      node_id: Optional[str]) -> None:
        """The trainer quarantined the node: finalize + publish the
        action half of the record."""
        record = decision["record"]
        record["action"]["node_id"] = node_id
        self.actions_enforced += 1
        self._emit("remediation", record, phase="action")
        self._record_cluster_event(
            "WARNING", "remediation_action",
            f"trial {self.trial}: quarantined rank "
            f"{record['action']['rank']} (node {str(node_id)[:12]}) for "
            f"sustained straggling; rebalancing gang", record)
        self._flush()

    def note_recovered(self, new_world: int, step: int) -> None:
        """Elastic recovery after the quarantine finished: stamp the
        post-rebalance shape on the open action."""
        if self._watch is None:
            return
        record = self._watch["record"]
        record["action"]["new_world"] = new_world
        record["action"]["resume_step"] = step
        self._flush()

    # -- effect measurement ------------------------------------------------

    def _feed_effect_watch(self, median: Optional[float],
                           step: Optional[int]) -> None:
        if self._watch is None or median is None:
            return
        # only post-rebalance rounds count (the action sets new_world
        # when recovery completes; rounds before that are the old gang)
        if "new_world" not in self._watch["record"]["action"]:
            return
        self._watch["post"].append(median)
        if len(self._watch["post"]) < self.effect_window:
            return
        record = self._watch["record"]
        post = statistics.median(self._watch["post"])
        baseline = self._watch["baseline"]
        recovered = (baseline is not None
                     and post <= (1.0 + self.recover_tolerance) * baseline)
        record["effect"] = {
            "baseline_busy_s": (round(baseline, 6)
                                if baseline is not None else None),
            "post_busy_s": round(post, 6),
            "tolerance": self.recover_tolerance,
            "measured_rounds": len(self._watch["post"]),
            "recovered": bool(recovered),
            "ts": self._wall(),
        }
        self._watch = None
        logger.warning(
            "remediation effect (trial %s): gang median busy %.4fs vs "
            "pre-episode baseline %s -> %s", self.trial, post,
            f"{baseline:.4f}s" if baseline is not None else "n/a",
            "recovered" if recovered else "NOT recovered")
        self._emit("remediation", record, phase="effect")
        self._record_cluster_event(
            "INFO" if recovered else "WARNING", "remediation_effect",
            f"trial {self.trial}: post-remediation gang median busy "
            f"{post:.4f}s ({'within' if recovered else 'OUTSIDE'} "
            f"{self.recover_tolerance:.0%} of baseline)", record)
        self._flush()

    # -- plumbing ----------------------------------------------------------

    def _cause_for(self, aggregator, rank: int) -> Dict[str, Any]:
        for adv in reversed(aggregator.advisories):
            if adv.get("rank") == rank:
                return dict(adv)
        return {"event": "straggler_detected", "trial": self.trial,
                "rank": rank}

    def _emit(self, event: str, record: Dict[str, Any],
              phase: Optional[str] = None) -> None:
        payload = {"event": event, "trial": self.trial, **record}
        if phase is not None:
            payload["phase"] = phase
        try:
            self._publish(payload)
        except Exception:
            pass

    def _record_cluster_event(self, severity: str, event_type: str,
                              message: str,
                              record: Dict[str, Any]) -> None:
        try:
            self._control_call("report_event", {
                "severity": severity, "source": "remediation",
                "event_type": event_type, "entity_id": self.trial,
                "message": message, "custom": {"record_id": record["id"]},
            })
        except Exception:
            pass

    def _flush(self) -> None:
        """Persist the full log to control KV so the CLI/timeline can
        read it after the run (advisory, never fails training)."""
        try:
            self._control_call("kv_put", {
                "ns": REMEDIATION_NS, "key": self.trial,
                "val": json.dumps(self.records).encode(),
            })
        except Exception:
            pass

    def summary(self) -> Dict[str, Any]:
        return {
            "mode": self.mode,
            "episodes": self.episodes,
            "enforced": self.actions_enforced,
            "records": list(self.records),
        }
