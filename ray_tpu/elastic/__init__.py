"""ray_tpu.elastic: preemption-aware elastic training.

Three cooperating pieces (see COMPONENTS.md):

  * preemption  — PreemptionWatcher + sources: raylets learn a host is
    going away and report a drain notice to the control plane, which
    broadcasts a ``node_draining`` advisory over pubsub.
  * emergency   — EmergencyCheckpointer: async device->host snapshots of
    each worker's train-state shard, peer-replicated to K ring
    successors through the control-plane KV mailbox; recovery needs no
    persistent-storage round-trip.
  * resume      — shrink-to-fit width selection (goodput-predicted via
    IncarnationHistory) + exact global-batch resplitting, driven by
    BackendExecutor.elastic_recover().
  * remediation — RemediationEngine: turns sustained straggler
    advisories into quarantine+rebalance actions (advisory by default)
    with hysteresis, rate limits, and measured cause→action→effect
    records.

User surface: ``JaxConfig(elastic=ElasticConfig(...))`` plus
``elastic.snapshot(state, step)`` inside the train loop.

Exports resolve lazily (PEP 562): raylets import only the preemption
submodule, and must not drag the train stack (which ``emergency`` needs
for its Checkpoint base class) into every node daemon.
"""

_EXPORTS = {
    "ElasticConfig": "config",
    "EmergencyCheckpoint": "emergency",
    "EmergencyCheckpointer": "emergency",
    "fold_shards": "emergency",
    "get_checkpointer": "emergency",
    "select_quorum": "emergency",
    "snapshot": "emergency",
    "wait_replicated": "emergency",
    "FakePreemptionSource": "preemption",
    "FilePreemptionSource": "preemption",
    "PreemptionNotice": "preemption",
    "PreemptionSource": "preemption",
    "PreemptionWatcher": "preemption",
    "TpuMetadataSource": "preemption",
    "source_from_env": "preemption",
    "IncarnationHistory": "resume",
    "InsufficientWorkersError": "resume",
    "batch_offsets": "resume",
    "choose_width": "resume",
    "per_replica_batches": "resume",
    "predict_rate": "resume",
    "shrink_to_fit": "resume",
    "REMEDIATION_NS": "remediation",
    "RemediationEngine": "remediation",
    "fetch_records": "remediation",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(f".{mod}", __name__), name)


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
