"""Preemption notices: learn a host is going away BEFORE it dies.

TPU VMs get advance warning of maintenance events and spot reclamation
through the GCE metadata server (``instance/maintenance-event`` /
``instance/preempted``); this module polls such a source on every raylet
and turns a positive reading into a ``report_draining`` call to the
control plane, which broadcasts a ``node_draining`` advisory over pubsub.
Consumers (the Train BackendExecutor's drain listener) then checkpoint
and shrink *proactively* — well inside the grace window — instead of
discovering the loss via the heartbeat timeout after the fact.

Sources are injectable so CPU tier-1 tests exercise the whole path:
``FakePreemptionSource`` (in-process trigger), ``FilePreemptionSource``
(a sentinel file, which also works across processes — the raylet side is
driven this way via the ``RAY_TPU_PREEMPTION_FILE`` env var), and
``TpuMetadataSource`` (the real GCE endpoint).
"""

from __future__ import annotations

import json
import logging
import os
import threading
from dataclasses import dataclass
from typing import Callable, Optional

logger = logging.getLogger(__name__)

#: GCE maintenance-event endpoint; value "NONE" means no event pending.
_DEFAULT_METADATA_URL = ("http://metadata.google.internal/computeMetadata"
                         "/v1/instance/maintenance-event")


@dataclass
class PreemptionNotice:
    """One impending-loss advisory from a preemption source."""

    reason: str = "preemption"
    #: seconds until the host is expected to go away (advisory)
    grace_s: Optional[float] = None
    #: opaque per-event identity stamped by the source.  The watcher
    #: never re-fires the identity it last consumed: a source that
    #: re-arms while still holding the SAME event (a file renamed back,
    #: a stale re-read) is a replay, not a new edge.  None opts out
    #: (sources that cannot distinguish events keep pure edge semantics).
    key: Optional[object] = None


class PreemptionSource:
    """Poll interface.  ``poll()`` returns the currently pending notice,
    or None when the host is healthy.  Sources are level-triggered; the
    watcher edge-detects so one pending event fires one callback."""

    def poll(self) -> Optional[PreemptionNotice]:
        raise NotImplementedError


class FakePreemptionSource(PreemptionSource):
    """In-process source for tests: arm with trigger(), clear()."""

    def __init__(self):
        self._lock = threading.Lock()
        self._notice: Optional[PreemptionNotice] = None
        self._seq = 0  # guarded-by: _lock

    def trigger(self, reason: str = "test-preemption",
                grace_s: Optional[float] = None):
        with self._lock:
            self._seq += 1
            self._notice = PreemptionNotice(reason=reason, grace_s=grace_s,
                                            key=("fake", self._seq))

    def clear(self):
        with self._lock:
            self._notice = None

    def poll(self) -> Optional[PreemptionNotice]:
        with self._lock:
            return self._notice


class FilePreemptionSource(PreemptionSource):
    """A sentinel file arms the notice — works across process boundaries
    (tests touch the file; the raylet's watcher sees it).  The file body
    may be empty or a JSON object {"reason": ..., "grace_s": ...}."""

    def __init__(self, path: str):
        self.path = path

    def poll(self) -> Optional[PreemptionNotice]:
        try:
            st = os.stat(self.path)
        except OSError:
            return None
        reason, grace = "preemption", None
        try:
            with open(self.path) as f:
                body = f.read().strip()
            if body:
                spec = json.loads(body)
                reason = str(spec.get("reason", reason))
                if spec.get("grace_s") is not None:
                    grace = float(spec["grace_s"])
        except Exception:
            pass  # an empty/garbled sentinel still means "draining"
        # identity rides the mtime: the same untouched sentinel seen again
        # after a re-arm is the SAME event; a rewritten file is a new one
        return PreemptionNotice(reason=reason, grace_s=grace,
                                key=("file", st.st_mtime_ns))


class TpuMetadataSource(PreemptionSource):
    """The real thing: poll the GCE metadata server's maintenance-event
    key (any value other than NONE means the host is going away)."""

    def __init__(self, url: Optional[str] = None, timeout_s: float = 1.0):
        self.url = url or os.environ.get("RAY_TPU_PREEMPTION_METADATA_URL",
                                         _DEFAULT_METADATA_URL)
        self.timeout_s = timeout_s

    def poll(self) -> Optional[PreemptionNotice]:
        import urllib.request

        req = urllib.request.Request(
            self.url, headers={"Metadata-Flavor": "Google"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                value = resp.read().decode("utf-8", "replace").strip()
        except Exception:
            return None  # unreachable metadata server != preemption
        if not value or value.upper() == "NONE":
            return None
        return PreemptionNotice(reason=f"maintenance-event:{value}")


def source_from_env() -> Optional[PreemptionSource]:
    """The raylet's source, chosen by env: RAY_TPU_PREEMPTION_FILE names
    a sentinel file; RAY_TPU_PREEMPTION_METADATA=1 polls the GCE
    endpoint.  None disables the watcher (the CPU-test default)."""
    path = os.environ.get("RAY_TPU_PREEMPTION_FILE")
    if path:
        return FilePreemptionSource(path)
    if os.environ.get("RAY_TPU_PREEMPTION_METADATA"):
        return TpuMetadataSource()
    return None


class PreemptionWatcher:
    """Polls a source on its own thread; fires ``on_notice`` once per
    event edge (armed after being clear), so a level-held maintenance
    event produces exactly one drain report until it clears.

    ``debounce_s`` suppresses flapping: a notice edge arriving within
    the window after the last fired notice is swallowed instead of
    fired.  A drain→cancel→drain flap inside one window therefore costs
    ONE drain report, not two (and one elastic recovery, not two).  If
    the re-trigger is still pending when the window closes, the watcher
    fires it then — a real second event is delayed, never lost."""

    def __init__(self, source: PreemptionSource,
                 on_notice: Callable[[PreemptionNotice], None],
                 poll_interval_s: float = 1.0,
                 debounce_s: float = 0.0,
                 clock: Callable[[], float] = None):
        import time
        self.source = source
        self.on_notice = on_notice
        self.poll_interval_s = poll_interval_s
        self.debounce_s = debounce_s
        self._clock = clock or time.monotonic
        self._stop = threading.Event()
        self._armed = True  # fire on the first positive poll
        self._last_fired_at: Optional[float] = None
        self._last_fired_key: Optional[object] = None
        self._replay_logged = False
        self._pending_flap = False  # edge swallowed inside the window
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="preemption-watcher")
        self.notices_fired = 0
        self.notices_suppressed = 0

    def start(self):
        self._thread.start()

    def stop(self, timeout: float = 2.0):
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=timeout)

    def poll_once(self) -> bool:
        """One synchronous poll+edge-detect (also used by tests)."""
        try:
            notice = self.source.poll()
        except Exception:
            logger.exception("preemption source poll failed")
            return False
        if notice is None:
            self._armed = True
            self._pending_flap = False  # the flap cleared: nothing owed
            self._replay_logged = False
            return False
        if (notice.key is not None
                and notice.key == self._last_fired_key):
            # the source re-armed but still holds the identity we already
            # consumed (e.g. a sentinel file briefly unreadable, then the
            # same bytes again): a replay, never an edge — do NOT fire it
            # into the fresh incarnation.  Stay armed so a genuinely new
            # identity fires on its next poll.
            if self._armed and not self._replay_logged:
                self._replay_logged = True
                self.notices_suppressed += 1
                logger.info(
                    "preemption notice (%s) is a replay of the already-"
                    "consumed event: suppressed", notice.reason)
            return False
        in_window = (self.debounce_s > 0.0
                     and self._last_fired_at is not None
                     and (self._clock() - self._last_fired_at)
                     < self.debounce_s)
        if not self._armed and not self._pending_flap:
            return False
        if in_window:
            # a fresh edge inside the debounce window: swallow it, but
            # remember it so a still-pending notice fires when the
            # window closes
            if self._armed:
                self._armed = False
                self._pending_flap = True
                self.notices_suppressed += 1
                logger.info(
                    "preemption notice (%s) debounced: within %.1fs of "
                    "the previous notice", notice.reason, self.debounce_s)
            return False
        self._armed = False
        self._pending_flap = False
        self._last_fired_at = self._clock()
        self._last_fired_key = notice.key
        self._replay_logged = False
        self.notices_fired += 1
        try:
            self.on_notice(notice)
        except Exception:
            logger.exception("preemption notice callback failed")
        return True

    def _loop(self):
        while not self._stop.is_set():
            self.poll_once()
            self._stop.wait(self.poll_interval_s)
