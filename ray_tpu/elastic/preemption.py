"""Preemption notices: learn a host is going away BEFORE it dies.

TPU VMs get advance warning of maintenance events and spot reclamation
through the GCE metadata server (``instance/maintenance-event`` /
``instance/preempted``); this module polls such a source on every raylet
and turns a positive reading into a ``report_draining`` call to the
control plane, which broadcasts a ``node_draining`` advisory over pubsub.
Consumers (the Train BackendExecutor's drain listener) then checkpoint
and shrink *proactively* — well inside the grace window — instead of
discovering the loss via the heartbeat timeout after the fact.

Sources are injectable so CPU tier-1 tests exercise the whole path:
``FakePreemptionSource`` (in-process trigger), ``FilePreemptionSource``
(a sentinel file, which also works across processes — the raylet side is
driven this way via the ``RAY_TPU_PREEMPTION_FILE`` env var), and
``TpuMetadataSource`` (the real GCE endpoint).
"""

from __future__ import annotations

import json
import logging
import os
import threading
from dataclasses import dataclass
from typing import Callable, Optional

logger = logging.getLogger(__name__)

#: GCE maintenance-event endpoint; value "NONE" means no event pending.
_DEFAULT_METADATA_URL = ("http://metadata.google.internal/computeMetadata"
                         "/v1/instance/maintenance-event")


@dataclass
class PreemptionNotice:
    """One impending-loss advisory from a preemption source."""

    reason: str = "preemption"
    #: seconds until the host is expected to go away (advisory)
    grace_s: Optional[float] = None


class PreemptionSource:
    """Poll interface.  ``poll()`` returns the currently pending notice,
    or None when the host is healthy.  Sources are level-triggered; the
    watcher edge-detects so one pending event fires one callback."""

    def poll(self) -> Optional[PreemptionNotice]:
        raise NotImplementedError


class FakePreemptionSource(PreemptionSource):
    """In-process source for tests: arm with trigger(), clear()."""

    def __init__(self):
        self._lock = threading.Lock()
        self._notice: Optional[PreemptionNotice] = None

    def trigger(self, reason: str = "test-preemption",
                grace_s: Optional[float] = None):
        with self._lock:
            self._notice = PreemptionNotice(reason=reason, grace_s=grace_s)

    def clear(self):
        with self._lock:
            self._notice = None

    def poll(self) -> Optional[PreemptionNotice]:
        with self._lock:
            return self._notice


class FilePreemptionSource(PreemptionSource):
    """A sentinel file arms the notice — works across process boundaries
    (tests touch the file; the raylet's watcher sees it).  The file body
    may be empty or a JSON object {"reason": ..., "grace_s": ...}."""

    def __init__(self, path: str):
        self.path = path

    def poll(self) -> Optional[PreemptionNotice]:
        if not os.path.exists(self.path):
            return None
        reason, grace = "preemption", None
        try:
            with open(self.path) as f:
                body = f.read().strip()
            if body:
                spec = json.loads(body)
                reason = str(spec.get("reason", reason))
                if spec.get("grace_s") is not None:
                    grace = float(spec["grace_s"])
        except Exception:
            pass  # an empty/garbled sentinel still means "draining"
        return PreemptionNotice(reason=reason, grace_s=grace)


class TpuMetadataSource(PreemptionSource):
    """The real thing: poll the GCE metadata server's maintenance-event
    key (any value other than NONE means the host is going away)."""

    def __init__(self, url: Optional[str] = None, timeout_s: float = 1.0):
        self.url = url or os.environ.get("RAY_TPU_PREEMPTION_METADATA_URL",
                                         _DEFAULT_METADATA_URL)
        self.timeout_s = timeout_s

    def poll(self) -> Optional[PreemptionNotice]:
        import urllib.request

        req = urllib.request.Request(
            self.url, headers={"Metadata-Flavor": "Google"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                value = resp.read().decode("utf-8", "replace").strip()
        except Exception:
            return None  # unreachable metadata server != preemption
        if not value or value.upper() == "NONE":
            return None
        return PreemptionNotice(reason=f"maintenance-event:{value}")


def source_from_env() -> Optional[PreemptionSource]:
    """The raylet's source, chosen by env: RAY_TPU_PREEMPTION_FILE names
    a sentinel file; RAY_TPU_PREEMPTION_METADATA=1 polls the GCE
    endpoint.  None disables the watcher (the CPU-test default)."""
    path = os.environ.get("RAY_TPU_PREEMPTION_FILE")
    if path:
        return FilePreemptionSource(path)
    if os.environ.get("RAY_TPU_PREEMPTION_METADATA"):
        return TpuMetadataSource()
    return None


class PreemptionWatcher:
    """Polls a source on its own thread; fires ``on_notice`` once per
    event edge (armed after being clear), so a level-held maintenance
    event produces exactly one drain report until it clears."""

    def __init__(self, source: PreemptionSource,
                 on_notice: Callable[[PreemptionNotice], None],
                 poll_interval_s: float = 1.0):
        self.source = source
        self.on_notice = on_notice
        self.poll_interval_s = poll_interval_s
        self._stop = threading.Event()
        self._armed = True  # fire on the first positive poll
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="preemption-watcher")
        self.notices_fired = 0

    def start(self):
        self._thread.start()

    def stop(self, timeout: float = 2.0):
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=timeout)

    def poll_once(self) -> bool:
        """One synchronous poll+edge-detect (also used by tests)."""
        try:
            notice = self.source.poll()
        except Exception:
            logger.exception("preemption source poll failed")
            return False
        if notice is None:
            self._armed = True
            return False
        if not self._armed:
            return False
        self._armed = False
        self.notices_fired += 1
        try:
            self.on_notice(notice)
        except Exception:
            logger.exception("preemption notice callback failed")
        return True

    def _loop(self):
        while not self._stop.is_set():
            self.poll_once()
            self._stop.wait(self.poll_interval_s)
