"""ElasticConfig: user-facing knobs for preemption-aware elastic training.

Attached to ``JaxConfig(elastic=ElasticConfig(...))``; consumed by the
BackendExecutor's supervised restart loop (train/backend_executor.py) and
by the per-worker EmergencyCheckpointer (elastic/emergency.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


@dataclass
class ElasticConfig:
    """How a training run shrinks and recovers when hosts are lost.

    min_workers: smallest data-parallel width the run may shrink to;
        below it elastic recovery gives up and the normal
        restart-from-storage path (FailureConfig) takes over.
    max_workers: cap on width (None = the ScalingConfig's num_workers).
        Shrink-to-fit never grows past the current width; the cap exists
        so configs round-trip when re-used after capacity returns.
    replication_factor: K — each worker's emergency shard is replicated
        to its K ring successors, so recovery survives losing any K
        hosts without a persistent-storage round-trip.
    workers_per_replica: workers per model replica (the product of the
        non-data-parallel mesh axes, tp*sp, in hosts).  Shrink-to-fit
        only drops whole model replicas: the new width is always a
        multiple of this unit, preserving tp/sp axes.
    snapshot_every: emergency-snapshot cadence in steps (1 = every
        ``elastic.snapshot()`` call replicates).
    keep_steps: how many distinct snapshot steps each worker's in-memory
        vault retains.
    drain_grace_s: advisory deadline attached to a drain notice that
        carries no explicit grace.
    global_batch_size: when set, the executor publishes an exact
        per-replica batch split (``ctx.extra["per_replica_batch"]`` /
        ``"batch_offset"``) that keeps the global batch constant across
        width changes.
    replicate_timeout_s: per-snapshot bound on the background peer
        exchange (a dead peer must not wedge the replication thread).
    recover_timeout_s: per-RPC bound during recovery (ping / abort /
        inventory / fetch) — recovery must finish well inside one
        heartbeat-death interval, so no call may block on a dead host.
    """

    min_workers: int = 1
    max_workers: Optional[int] = None
    replication_factor: int = 1
    workers_per_replica: int = 1
    snapshot_every: int = 1
    keep_steps: int = 2
    drain_grace_s: float = 30.0
    global_batch_size: Optional[int] = None
    replicate_timeout_s: float = 15.0
    recover_timeout_s: float = 5.0

    def __post_init__(self):
        if self.min_workers < 1:
            raise ValueError("min_workers must be >= 1")
        if self.max_workers is not None and self.max_workers < self.min_workers:
            raise ValueError(
                f"max_workers ({self.max_workers}) < min_workers "
                f"({self.min_workers})")
        if self.replication_factor < 0:
            raise ValueError("replication_factor must be >= 0")
        if self.workers_per_replica < 1:
            raise ValueError("workers_per_replica must be >= 1")
        if self.min_workers % self.workers_per_replica:
            raise ValueError(
                f"min_workers ({self.min_workers}) must be a multiple of "
                f"workers_per_replica ({self.workers_per_replica}): shrink "
                f"drops whole model replicas")
        if self.snapshot_every < 1:
            raise ValueError("snapshot_every must be >= 1")
        if self.keep_steps < 1:
            raise ValueError("keep_steps must be >= 1")

    def validate_for(self, num_workers: int) -> None:
        """Check this config against a worker-group width at start."""
        if num_workers < self.min_workers:
            raise ValueError(
                f"ScalingConfig.num_workers ({num_workers}) < "
                f"ElasticConfig.min_workers ({self.min_workers})")
        if self.max_workers is not None and num_workers > self.max_workers:
            raise ValueError(
                f"ScalingConfig.num_workers ({num_workers}) > "
                f"ElasticConfig.max_workers ({self.max_workers})")
        if num_workers % self.workers_per_replica:
            raise ValueError(
                f"num_workers ({num_workers}) must be a multiple of "
                f"workers_per_replica ({self.workers_per_replica})")
        if self.replication_factor > num_workers - 1:
            raise ValueError(
                f"replication_factor ({self.replication_factor}) must be "
                f"< num_workers ({num_workers}): a shard cannot replicate "
                f"to more peers than exist")
