"""ElasticConfig: user-facing knobs for preemption-aware elastic training.

Attached to ``JaxConfig(elastic=ElasticConfig(...))``; consumed by the
BackendExecutor's supervised restart loop (train/backend_executor.py) and
by the per-worker EmergencyCheckpointer (elastic/emergency.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


@dataclass
class ElasticConfig:
    """How a training run shrinks and recovers when hosts are lost.

    min_workers: smallest data-parallel width the run may shrink to;
        below it elastic recovery gives up and the normal
        restart-from-storage path (FailureConfig) takes over.
    max_workers: cap on width (None = the ScalingConfig's num_workers).
        Shrink-to-fit never grows past the current width; the cap exists
        so configs round-trip when re-used after capacity returns.
    replication_factor: K — each worker's emergency shard is replicated
        to its K ring successors, so recovery survives losing any K
        hosts without a persistent-storage round-trip.
    workers_per_replica: workers per model replica (the product of the
        non-data-parallel mesh axes, tp*sp, in hosts).  Shrink-to-fit
        only drops whole model replicas: the new width is always a
        multiple of this unit, preserving tp/sp axes.
    snapshot_every: emergency-snapshot cadence in steps (1 = every
        ``elastic.snapshot()`` call replicates).
    keep_steps: how many distinct snapshot steps each worker's in-memory
        vault retains.
    drain_grace_s: advisory deadline attached to a drain notice that
        carries no explicit grace.
    global_batch_size: when set, the executor publishes an exact
        per-replica batch split (``ctx.extra["per_replica_batch"]`` /
        ``"batch_offset"``) that keeps the global batch constant across
        width changes.
    replicate_timeout_s: per-snapshot bound on the background peer
        exchange (a dead peer must not wedge the replication thread).
    recover_timeout_s: per-RPC bound during recovery (ping / abort /
        inventory / fetch) — recovery must finish well inside one
        heartbeat-death interval, so no call may block on a dead host.

    Remediation (self-healing — see elastic/remediation.py):

    remediation_mode: what the RemediationEngine does with sustained
        ``straggler_detected`` advisories.
          "off"      — no engine; advisories stay advisories.
          "advisory" — the default.  The engine runs the full policy
                       (hysteresis, rate limits) and records/publishes
                       exactly what it WOULD do (cause→action records
                       with ``dry_run=True``) but changes nothing.
                       Inspect with ``ray-tpu remediations <trial>``.
          "enforce"  — act: quarantine the straggler's node (scheduler
                       avoidance on the control plane) and rebalance the
                       gang off it through elastic recovery.
    remediation_confirm_rounds: hysteresis — rounds an episode must stay
        open BEYOND the aggregator's ``straggler_sustain`` before the
        policy acts; a transient pause never triggers a rebalance.
    remediation_cooldown_s: minimum seconds between two remediation
        episodes (rate limit against thrash).
    remediation_max_episodes: cap on remediation episodes per run.
    remediation_effect_window: post-rebalance rounds measured before the
        cause→action→effect record is stamped recovered-or-not.
    remediation_recover_tolerance: the effect verdict — recovered when
        the post-rebalance gang median busy time is within this fraction
        of the pre-episode baseline (0.15 = within 15%).
    quarantine_grace_s: how long the control plane keeps the quarantined
        node out of scheduling before it may take work again.
    """

    min_workers: int = 1
    max_workers: Optional[int] = None
    replication_factor: int = 1
    workers_per_replica: int = 1
    snapshot_every: int = 1
    keep_steps: int = 2
    drain_grace_s: float = 30.0
    global_batch_size: Optional[int] = None
    replicate_timeout_s: float = 15.0
    recover_timeout_s: float = 5.0
    remediation_mode: str = "advisory"
    remediation_confirm_rounds: int = 2
    remediation_cooldown_s: float = 30.0
    remediation_max_episodes: int = 2
    remediation_effect_window: int = 3
    remediation_recover_tolerance: float = 0.15
    quarantine_grace_s: float = 600.0

    def __post_init__(self):
        if self.min_workers < 1:
            raise ValueError("min_workers must be >= 1")
        if self.max_workers is not None and self.max_workers < self.min_workers:
            raise ValueError(
                f"max_workers ({self.max_workers}) < min_workers "
                f"({self.min_workers})")
        if self.replication_factor < 0:
            raise ValueError("replication_factor must be >= 0")
        if self.workers_per_replica < 1:
            raise ValueError("workers_per_replica must be >= 1")
        if self.min_workers % self.workers_per_replica:
            raise ValueError(
                f"min_workers ({self.min_workers}) must be a multiple of "
                f"workers_per_replica ({self.workers_per_replica}): shrink "
                f"drops whole model replicas")
        if self.snapshot_every < 1:
            raise ValueError("snapshot_every must be >= 1")
        if self.keep_steps < 1:
            raise ValueError("keep_steps must be >= 1")
        if self.remediation_mode not in ("off", "advisory", "enforce"):
            raise ValueError(
                f"remediation_mode must be 'off', 'advisory' or 'enforce', "
                f"got {self.remediation_mode!r}")
        if self.remediation_confirm_rounds < 0:
            raise ValueError("remediation_confirm_rounds must be >= 0")
        if self.remediation_max_episodes < 0:
            raise ValueError("remediation_max_episodes must be >= 0")
        if self.remediation_effect_window < 1:
            raise ValueError("remediation_effect_window must be >= 1")
        if not 0.0 <= self.remediation_recover_tolerance < 1.0:
            raise ValueError(
                "remediation_recover_tolerance must be in [0, 1)")

    def validate_for(self, num_workers: int) -> None:
        """Check this config against a worker-group width at start."""
        if num_workers < self.min_workers:
            raise ValueError(
                f"ScalingConfig.num_workers ({num_workers}) < "
                f"ElasticConfig.min_workers ({self.min_workers})")
        if self.max_workers is not None and num_workers > self.max_workers:
            raise ValueError(
                f"ScalingConfig.num_workers ({num_workers}) > "
                f"ElasticConfig.max_workers ({self.max_workers})")
        if num_workers % self.workers_per_replica:
            raise ValueError(
                f"num_workers ({num_workers}) must be a multiple of "
                f"workers_per_replica ({self.workers_per_replica})")
        if self.replication_factor > num_workers - 1:
            raise ValueError(
                f"replication_factor ({self.replication_factor}) must be "
                f"< num_workers ({num_workers}): a shard cannot replicate "
                f"to more peers than exist")
