"""Emergency checkpoints: async device->host snapshots, peer-replicated.

Each training worker keeps an in-memory *vault* of recent snapshots of
its own train-state shard plus the shards of its K ring predecessors
(``replication_factor``).  Replication rides the existing control-plane
KV mailbox (the same transport the kv collective backend uses): rank r
posts its serialized shard at ``{tag}/{step}/shard/{r}``, pulls the
shards of ranks ``(r-1..r-K) mod n`` into its vault, acks each, and the
owner retires its mailbox key once all K successors acked — so steady
state leaves nothing in the KV store, and the durable copies live in
worker memory where recovery can reach them without a persistent-storage
round-trip.

The step path pays only a device->host copy (the snapshot must be
consistent — the next step may donate/overwrite the buffers); pickling
and the network exchange happen on a background thread.

Recovery (driver side, see BackendExecutor.elastic_recover): collect
``_inventory()`` from every reachable worker, pick the freshest step
whose full shard set {0..n_old-1} is covered by the union of survivor
vaults (``select_quorum``), ``_fetch`` the payloads, and hand each new
rank an :class:`EmergencyCheckpoint` with its folded shards
(``old_shard % n_new == new_rank``).
"""

from __future__ import annotations

import logging
import pickle
import queue
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private.protocol import Backoff
from ray_tpu.train.checkpoint import Checkpoint

logger = logging.getLogger(__name__)

_NS = "elastic"


def _kv():
    from ray_tpu._private.core import current_core

    return current_core().control


def _kv_put(key: str, val: bytes):
    _kv().call("kv_put", {"ns": _NS, "key": key, "val": val})


def _kv_poll(key: str, deadline: float) -> Optional[bytes]:
    """Non-destructive polling read with an absolute deadline (the shard
    key is read by K fetchers; only the owner deletes it)."""
    bo = Backoff(base=0.005, cap=0.1)
    while True:
        v = _kv().call("kv_get", {"ns": _NS, "key": key})
        if v is not None:
            return v
        if time.monotonic() >= deadline:
            return None
        bo.sleep()


def _kv_del(key: str):
    try:
        _kv().call("kv_del", {"ns": _NS, "key": key})
    except Exception:
        pass


def _to_host(state: Any) -> Any:
    """Device->host copy of a pytree (numpy leaves pass through).  This
    is the only work snapshot() does on the step path: the buffers must
    be materialized before the next step can donate/overwrite them."""
    try:
        import jax

        return jax.device_get(state)
    except Exception:
        return state


# -- per-worker-process vault (module-global: survives checkpointer
# re-initialization across elastic incarnations, which is exactly what
# makes the surviving workers a recovery source) ---------------------------

_LOCK = threading.RLock()
_VAULT: Dict[Tuple[int, int], bytes] = {}   # (step, shard_id) -> payload
_VAULT_WORLDS: Dict[int, int] = {}          # step -> world size at snapshot
_CKPT: Optional["EmergencyCheckpointer"] = None


class EmergencyCheckpointer:
    """Owns the background replication thread of one worker."""

    def __init__(self, tag: str, rank: int, world_size: int,
                 replication_factor: int = 1, keep_steps: int = 2,
                 snapshot_every: int = 1, replicate_timeout_s: float = 15.0):
        self.tag = tag
        self.rank = rank
        self.world_size = world_size
        # can't replicate to more peers than exist
        self.k = max(0, min(replication_factor, world_size - 1))
        self.keep_steps = keep_steps
        self.snapshot_every = max(1, snapshot_every)
        self.replicate_timeout_s = replicate_timeout_s
        self._auto_step = 0
        self._queue: "queue.Queue" = queue.Queue()
        self._idle = threading.Event()
        self._idle.set()
        self._stop = False
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"emergency-ckpt-r{rank}")
        self._thread.start()

    # -- step path ---------------------------------------------------------

    def snapshot(self, state: Any, step: Optional[int] = None) -> bool:
        """Enqueue a snapshot of ``state`` for background replication.
        Returns True when the snapshot was accepted (cadence hit)."""
        if step is None:
            step = self._auto_step
        self._auto_step = step + 1
        if step % self.snapshot_every:
            return False
        host_state = _to_host(state)
        # coalesce: if replication lags, drop the oldest queued snapshot
        # rather than stalling the step path (bounded memory; quorum
        # selection skips steps without full coverage)
        while self._queue.qsize() >= 2:
            try:
                self._queue.get_nowait()
            except queue.Empty:
                break
        self._idle.clear()
        self._queue.put((step, host_state))
        return True

    # -- background thread -------------------------------------------------

    def _loop(self):
        while True:
            item = self._queue.get()
            if item is None:
                return
            step, host_state = item
            try:
                self._replicate(step, host_state)
            except Exception:
                logger.warning("emergency replication for step %s failed",
                               step, exc_info=True)
            finally:
                if self._queue.empty():
                    self._idle.set()

    def _key(self, step: int, kind: str, *parts) -> str:
        return "/".join([self.tag, str(step), kind, *map(str, parts)])

    def _replicate(self, step: int, host_state: Any):
        payload = pickle.dumps(host_state, protocol=5)
        n, r, k = self.world_size, self.rank, self.k
        with _LOCK:
            _VAULT[(step, r)] = payload
            _VAULT_WORLDS[step] = n
        if k == 0 or n <= 1:
            self._prune()
            return
        _kv_put(self._key(step, "shard", r), payload)
        deadline = time.monotonic() + self.replicate_timeout_s
        # pull my K ring predecessors' shards into my vault, ack each
        for j in range(1, k + 1):
            src = (r - j) % n
            b = _kv_poll(self._key(step, "shard", src), deadline)
            if b is None:
                logger.warning("rank %d: no shard from peer %d for step %d "
                               "within %.1fs", r, src, step,
                               self.replicate_timeout_s)
                continue
            with _LOCK:
                _VAULT[(step, src)] = b
            _kv_put(self._key(step, "ack", src, r), b"1")
        # wait for my successors' acks, then retire my mailbox key
        acked = True
        for j in range(1, k + 1):
            dst = (r + j) % n
            if _kv_poll(self._key(step, "ack", r, dst), deadline) is None:
                acked = False
            else:
                _kv_del(self._key(step, "ack", r, dst))
        if acked:
            _kv_del(self._key(step, "shard", r))
        self._prune()

    def _prune(self):
        with _LOCK:
            steps = sorted(_VAULT_WORLDS)
            while len(steps) > self.keep_steps:
                s = steps.pop(0)
                _VAULT_WORLDS.pop(s, None)
                for key in [kk for kk in _VAULT if kk[0] == s]:
                    _VAULT.pop(key, None)

    # -- control -----------------------------------------------------------

    def wait_idle(self, timeout: Optional[float] = None) -> bool:
        return self._idle.wait(timeout)

    def stop(self, timeout: float = 2.0):
        if self._stop:
            return
        self._stop = True
        self._queue.put(None)
        if self._thread.is_alive():
            self._thread.join(timeout=timeout)


# -- worker-side module API (run inside the worker via execute()) ----------


def _init_worker_checkpointer(tag: str, rank: int, world_size: int,
                              replication_factor: int, keep_steps: int,
                              snapshot_every: int,
                              replicate_timeout_s: float) -> bool:
    """(Re-)install this process's checkpointer.  The vault is module
    state and deliberately survives re-init: after an elastic shrink the
    new incarnation's checkpointer starts fresh while the old shards
    remain fetchable until pruned by new snapshots."""
    global _CKPT
    if _CKPT is not None:
        _CKPT.stop()
    _CKPT = EmergencyCheckpointer(
        tag, rank, world_size, replication_factor=replication_factor,
        keep_steps=keep_steps, snapshot_every=snapshot_every,
        replicate_timeout_s=replicate_timeout_s)
    return True


def get_checkpointer() -> Optional[EmergencyCheckpointer]:
    return _CKPT


def snapshot(state: Any, step: Optional[int] = None) -> bool:
    """User-facing: snapshot this worker's train-state shard from inside
    the train loop (no-op returning False when elastic is not
    configured, so loops stay portable)."""
    ck = _CKPT
    if ck is None:
        return False
    return ck.snapshot(state, step)


def wait_replicated(timeout: Optional[float] = None) -> bool:
    """Block until queued snapshots finished replicating (tests; drain
    handlers that want a final synchronous flush)."""
    ck = _CKPT
    if ck is None:
        return True
    return ck.wait_idle(timeout)


def _inventory() -> List[Dict[str, Any]]:
    """What this worker's vault holds: [{step, world, shards}, ...]."""
    with _LOCK:
        return [{"step": s, "world": w,
                 "shards": sorted(sid for (st, sid) in _VAULT if st == s)}
                for s, w in sorted(_VAULT_WORLDS.items())]


def _fetch(step: int, shard_id: int) -> Optional[bytes]:
    with _LOCK:
        return _VAULT.get((step, shard_id))


def _clear_vault() -> bool:
    """Test hook: wipe this process's vault."""
    with _LOCK:
        _VAULT.clear()
        _VAULT_WORLDS.clear()
    return True


def vault_footprint() -> Dict[str, Any]:
    """Bytes resident in this worker's emergency vault — the device
    memory census reports this alongside the KV page arena so recovery
    headroom is visible (telemetry/device.py)."""
    with _LOCK:
        return {"entries": len(_VAULT),
                "bytes": sum(len(v) for v in _VAULT.values()),
                "steps": len(_VAULT_WORLDS)}


# -- driver-side recovery helpers ------------------------------------------


def select_quorum(inventories: Dict[int, List[Dict[str, Any]]]
                  ) -> Optional[Tuple[int, int, Dict[int, int]]]:
    """Freshest step whose full shard set is covered by the survivors.

    inventories: worker index -> that worker's ``_inventory()`` output.
    Returns (step, world_size, holders) with holders mapping each
    shard_id to a worker index that can serve it, or None when no step
    has full coverage.
    """
    coverage: Dict[Tuple[int, int], Dict[int, int]] = {}
    for widx, inv in inventories.items():
        for entry in inv or ():
            holders = coverage.setdefault(
                (int(entry["step"]), int(entry["world"])), {})
            for sid in entry["shards"]:
                holders.setdefault(int(sid), widx)
    for (step, world) in sorted(coverage, reverse=True):
        holders = coverage[(step, world)]
        if set(holders) >= set(range(world)):
            return step, world, holders
    return None


def fold_shards(old_world: int, new_rank: int, new_world: int) -> List[int]:
    """Which old shards new rank r owns after shrinking: round-robin
    fold (old_shard % new_world == new_rank), so every old shard has
    exactly one new owner and the load difference is at most one."""
    return [s for s in range(old_world) if s % new_world == new_rank]


class EmergencyCheckpoint(Checkpoint):
    """An in-memory checkpoint handed to resumed workers: the folded
    old-world shards this new rank is responsible for.  Not backed by a
    directory — ``to_directory``/``as_directory`` raise."""

    def __init__(self, step: int, source_world_size: int,
                 shards: Dict[int, bytes]):
        self.step = step
        self.source_world_size = source_world_size
        self.shards = dict(shards)
        self.path = f"emergency://step_{step}"

    def shard_ids(self) -> List[int]:
        return sorted(self.shards)

    def load(self) -> List[Any]:
        """Deserialize this rank's shards, ordered by old rank."""
        return [pickle.loads(self.shards[s]) for s in self.shard_ids()]

    def get_metadata(self) -> Dict[str, Any]:
        return {"tier": "emergency", "step": self.step,
                "source_world_size": self.source_world_size,
                "shards": self.shard_ids()}

    def to_directory(self, path=None, subdir=None):
        raise NotImplementedError(
            "EmergencyCheckpoint is in-memory (peer-replicated shards); "
            "use .load() from the train loop")

    def as_directory(self, subdir=None):
        raise NotImplementedError(
            "EmergencyCheckpoint is in-memory (peer-replicated shards); "
            "use .load() from the train loop")

    def __reduce__(self):
        return (EmergencyCheckpoint,
                (self.step, self.source_world_size, self.shards))

    def __repr__(self):
        return (f"EmergencyCheckpoint(step={self.step}, "
                f"source_world_size={self.source_world_size}, "
                f"shards={self.shard_ids()})")
