"""Single-file dashboard UI served at ``/``.

The reference ships a 21.5k-line React/TS frontend
(reference: python/ray/dashboard/client/); this is the dependency-free
equivalent for the same data: one HTML page that polls the head's JSON
API (/api/cluster_status, /api/nodes, /api/actors, /api/jobs,
/api/placement_groups, /api/tasks) and renders live tables — cluster
overview, nodes, actors, jobs, placement groups, recent task events.
"""

PAGE = """<!doctype html>
<html>
<head>
<meta charset="utf-8">
<title>ray_tpu dashboard</title>
<style>
  body { font-family: -apple-system, "Segoe UI", Roboto, sans-serif;
         margin: 0; background: #f7f7f9; color: #1a1a2e; }
  header { background: #1a1a2e; color: #fff; padding: 10px 24px;
           display: flex; align-items: baseline; gap: 16px; }
  header h1 { font-size: 18px; margin: 0; }
  header .sub { color: #9aa; font-size: 12px; }
  main { padding: 16px 24px; max-width: 1200px; margin: 0 auto; }
  .cards { display: flex; gap: 12px; flex-wrap: wrap; margin: 12px 0; }
  .card { background: #fff; border-radius: 8px; padding: 12px 18px;
          box-shadow: 0 1px 3px rgba(0,0,0,.08); min-width: 130px; }
  .card .v { font-size: 24px; font-weight: 600; }
  .card .k { font-size: 12px; color: #667; }
  h2 { font-size: 14px; margin: 18px 0 6px; color: #334; }
  table { border-collapse: collapse; width: 100%; background: #fff;
          border-radius: 8px; overflow: hidden; font-size: 13px;
          box-shadow: 0 1px 3px rgba(0,0,0,.08); }
  th { text-align: left; background: #eef; padding: 6px 10px;
       font-size: 12px; }
  td { padding: 5px 10px; border-top: 1px solid #f0f0f4;
       font-family: ui-monospace, monospace; font-size: 12px; }
  .ALIVE, .RUNNING, .SUCCEEDED, .FINISHED { color: #0a7d33; }
  .DEAD, .FAILED { color: #c0262d; }
  .PENDING, .RESTARTING { color: #b26a00; }
  #err { color: #c0262d; font-size: 12px; }
</style>
</head>
<body>
<header><h1>ray_tpu</h1><span class="sub" id="addr"></span>
<span class="sub" id="ts"></span><span id="err"></span></header>
<main>
  <div class="cards" id="cards"></div>
  <h2>Nodes</h2><table id="nodes"></table>
  <h2>Actors</h2><table id="actors"></table>
  <h2>Jobs</h2><table id="jobs"></table>
  <h2>Placement groups</h2><table id="pgs"></table>
  <h2>Recent task events</h2><table id="tasks"></table>
</main>
<script>
const fmt = (x) => x === null || x === undefined ? "" :
  (typeof x === "object" ? JSON.stringify(x) : String(x));
const esc = (s) => s.replace(/&/g, "&amp;").replace(/</g, "&lt;")
  .replace(/>/g, "&gt;").replace(/"/g, "&quot;");
function table(el, rows, cols) {
  const t = document.getElementById(el);
  if (!rows || !rows.length) { t.innerHTML = "<tr><td>none</td></tr>"; return; }
  let h = "<tr>" + cols.map(c => `<th>${esc(c)}</th>`).join("") + "</tr>";
  for (const r of rows.slice(0, 50)) {
    h += "<tr>" + cols.map(c => {
      // escape BEFORE interpolation: entrypoints / actor names / error
      // strings are workload-controlled (stored-XSS sink otherwise)
      const v = fmt(r[c]);
      const cls = /^(ALIVE|DEAD|PENDING|RESTARTING|RUNNING|SUCCEEDED|FAILED|FINISHED)$/.test(v) ? ` class="${v}"` : "";
      return `<td${cls}>${esc(v.slice(0, 80))}</td>`;
    }).join("") + "</tr>";
  }
  t.innerHTML = h;
}
async function j(path) { const r = await fetch(path); return r.json(); }
async function tick() {
  try {
    const [cs, nodes, actors, jobs, pgs, tasks, ver] = await Promise.all([
      j("/api/cluster_status"), j("/api/nodes"), j("/api/actors"),
      j("/api/jobs"), j("/api/placement_groups"),
      j("/api/tasks?limit=50"), j("/api/version")]);
    document.getElementById("addr").textContent = ver.control_address;
    const total = cs.total_resources || {}, avail = cs.available_resources || {};
    const card = (k, v) => `<div class="card"><div class="v">${v}</div><div class="k">${k}</div></div>`;
    document.getElementById("cards").innerHTML =
      card("alive nodes", cs.alive_nodes) +
      card("CPU free/total", `${avail.CPU ?? 0}/${total.CPU ?? 0}`) +
      card("TPU free/total", `${avail.TPU ?? 0}/${total.TPU ?? 0}`) +
      card("actors", actors.length) + card("jobs", jobs.length) +
      card("placement groups", pgs.length);
    table("nodes", nodes, ["node_id", "addr", "state", "total", "available", "labels"]);
    table("actors", actors, ["actor_id", "class_name", "name", "state", "node_id", "restarts"]);
    table("jobs", jobs, ["submission_id", "entrypoint", "status", "message"]);
    table("pgs", pgs, ["pg_id", "name", "state", "bundles", "strategy"]);
    table("tasks", tasks.records || [], ["task_id", "name", "state", "actor_id", "error"]);
    document.getElementById("ts").textContent = new Date().toLocaleTimeString();
    document.getElementById("err").textContent = "";
  } catch (e) { document.getElementById("err").textContent = " " + e; }
}
tick(); setInterval(tick, 2000);
</script>
</body>
</html>
"""
