"""Single-file dashboard UI served at ``/``.

The reference ships a 21.5k-line React/TS frontend
(reference: python/ray/dashboard/client/); this is the dependency-free
equivalent for the same data: one HTML page that polls the head's JSON
API (/api/cluster_status, /api/nodes, /api/actors, /api/jobs,
/api/placement_groups, /api/tasks) and renders live tables — cluster
overview, nodes, actors, jobs, placement groups, recent task events —
plus two canvas views the reference renders in React:

  * a task TIMELINE (one lane per worker, spans from each record's
    state_ts transitions — the dashboard-embedded flavor of `ray-tpu
    timeline`'s Chrome-trace export);
  * per-node CPU utilization SPARKLINES + a cluster utilization strip,
    built client-side from the poll history (the reference's
    Grafana-backed metrics charts, without Grafana).
"""

PAGE = """<!doctype html>
<html>
<head>
<meta charset="utf-8">
<title>ray_tpu dashboard</title>
<style>
  body { font-family: -apple-system, "Segoe UI", Roboto, sans-serif;
         margin: 0; background: #f7f7f9; color: #1a1a2e; }
  header { background: #1a1a2e; color: #fff; padding: 10px 24px;
           display: flex; align-items: baseline; gap: 16px; }
  header h1 { font-size: 18px; margin: 0; }
  header .sub { color: #9aa; font-size: 12px; }
  main { padding: 16px 24px; max-width: 1200px; margin: 0 auto; }
  .cards { display: flex; gap: 12px; flex-wrap: wrap; margin: 12px 0; }
  .card { background: #fff; border-radius: 8px; padding: 12px 18px;
          box-shadow: 0 1px 3px rgba(0,0,0,.08); min-width: 130px; }
  .card .v { font-size: 24px; font-weight: 600; }
  .card .k { font-size: 12px; color: #667; }
  h2 { font-size: 14px; margin: 18px 0 6px; color: #334; }
  table { border-collapse: collapse; width: 100%; background: #fff;
          border-radius: 8px; overflow: hidden; font-size: 13px;
          box-shadow: 0 1px 3px rgba(0,0,0,.08); }
  th { text-align: left; background: #eef; padding: 6px 10px;
       font-size: 12px; }
  td { padding: 5px 10px; border-top: 1px solid #f0f0f4;
       font-family: ui-monospace, monospace; font-size: 12px; }
  .ALIVE, .RUNNING, .SUCCEEDED, .FINISHED { color: #0a7d33; }
  .DEAD, .FAILED { color: #c0262d; }
  .PENDING, .RESTARTING { color: #b26a00; }
  #err { color: #c0262d; font-size: 12px; }
  tbody tr { cursor: pointer; }
  #panel { position: fixed; top: 0; right: 0; width: 46%; height: 100%;
           background: #fff; box-shadow: -4px 0 16px rgba(0,0,0,.25);
           padding: 14px 18px; overflow: auto; display: none;
           z-index: 10; }
  #panel.open { display: block; }
  #panel h3 { margin: 4px 0 10px; font-size: 14px; }
  #panel pre { background: #16161f; color: #d8d8e8; padding: 10px;
               border-radius: 6px; font-size: 11px; overflow: auto;
               max-height: 45vh; white-space: pre-wrap; }
  #panel .close { float: right; cursor: pointer; font-size: 18px;
                  color: #667; }
  #panel .loglink { color: #2a5bd7; cursor: pointer; display: block;
                    font-family: ui-monospace, monospace; font-size: 12px;
                    padding: 1px 0; }
</style>
</head>
<body>
<header><h1>ray_tpu</h1><span class="sub" id="addr"></span>
<span class="sub" id="ts"></span><span id="err"></span></header>
<div id="panel"><span class="close" onclick="closePanel()">&times;</span>
  <div id="panel-body"></div></div>
<main>
  <div class="cards" id="cards"></div>
  <h2>Cluster CPU utilization (last 5 min)</h2>
  <canvas id="util" width="1160" height="60"
          style="background:#fff;border-radius:8px;width:100%"></canvas>
  <h2>Nodes</h2><table id="nodes"></table>
  <h2>Task timeline (last 60 s, one lane per worker)</h2>
  <canvas id="timeline" width="1160" height="160"
          style="background:#fff;border-radius:8px;width:100%"></canvas>
  <h2>Actors</h2><table id="actors"></table>
  <h2>Jobs</h2><table id="jobs"></table>
  <h2>Serve deployments</h2><table id="serve"></table>
  <h2>Train runs</h2><table id="train"></table>
  <h2>Placement groups</h2><table id="pgs"></table>
  <h2>Recent task events</h2><table id="tasks"></table>
  <h2>Cluster events</h2><table id="events"></table>
</main>
<script>
const fmt = (x) => x === null || x === undefined ? "" :
  (typeof x === "object" ? JSON.stringify(x) : String(x));
const esc = (s) => s.replace(/&/g, "&amp;").replace(/</g, "&lt;")
  .replace(/>/g, "&gt;").replace(/"/g, "&quot;");
const RAW = Symbol("raw-html");  // unforgeable marker for page-built cells
const drill = {};   // table id -> row click handler (drill-down panel)
function table(el, rows, cols) {
  const t = document.getElementById(el);
  if (!rows || !rows.length) { t.innerHTML = "<tr><td>none</td></tr>"; return; }
  t._rows = rows.slice(0, 50);
  if (drill[el] && !t._wired) {
    t._wired = true;
    t.addEventListener("click", ev => {
      const tr = ev.target.closest("tr");
      if (!tr || !tr.parentNode) return;
      const i = [...tr.parentNode.children].indexOf(tr) - 1; // header row
      if (i >= 0 && t._rows && t._rows[i]) drill[el](t._rows[i]);
    });
  }
  let h = "<tr>" + cols.map(c => `<th>${esc(c)}</th>`).join("") + "</tr>";
  for (const r of rows.slice(0, 50)) {
    h += "<tr>" + cols.map(c => {
      // ONLY cells built in this page may carry raw markup — keyed on a
      // Symbol, which is unforgeable through JSON (server data can never
      // produce it, so no column name or value shape reinstates the
      // stored-XSS sink); everything else is escaped BEFORE
      // interpolation: entrypoints / actor names / error strings are
      // workload-controlled
      if (r[c] && typeof r[c] === "object" && r[c][RAW] !== undefined)
        return `<td>${r[c][RAW]}</td>`;
      const v = fmt(r[c]);
      const cls = /^(ALIVE|DEAD|PENDING|RESTARTING|RUNNING|SUCCEEDED|FAILED|FINISHED)$/.test(v) ? ` class="${v}"` : "";
      return `<td${cls}>${esc(v.slice(0, 80))}</td>`;
    }).join("") + "</tr>";
  }
  t.innerHTML = h;
}
async function j(path) {
  const r = await fetch(path);
  if (!r.ok) throw new Error(`${path}: ${r.status} ${await r.text()}`);
  return r.json();
}
function drillSafe(fn) {   // surface drill-down failures in the panel
  return async row => {
    try { await fn(row); }
    catch (e) { panel("error", `<pre>${esc(String(e))}</pre>`); }
  };
}

// ---- metrics history (client-side: each tick appends one sample) ----
const hist = [];            // {t, used, total, perNode: {id: frac}}
function pushSample(cs, nodes) {
  const total = (cs.total_resources || {}).CPU || 0;
  const avail = (cs.available_resources || {}).CPU || 0;
  const perNode = {};
  for (const n of nodes || []) {
    const t = (n.total || {}).CPU || 0, a = (n.available || {}).CPU || 0;
    if (t > 0) perNode[n.node_id] = (t - a) / t;
  }
  hist.push({t: Date.now() / 1000, used: total - avail, total, perNode});
  while (hist.length && hist[0].t < Date.now() / 1000 - 300) hist.shift();
}
function drawUtil() {
  const c = document.getElementById("util"), g = c.getContext("2d");
  g.clearRect(0, 0, c.width, c.height);
  if (hist.length < 2) return;
  const t1 = Date.now() / 1000, t0 = t1 - 300;
  g.beginPath(); g.strokeStyle = "#3b6fd4"; g.lineWidth = 2;
  g.fillStyle = "rgba(59,111,212,.15)";
  const pts = hist.map(h => [
    (h.t - t0) / 300 * c.width,
    c.height - 4 - (h.total ? h.used / h.total : 0) * (c.height - 10)]);
  g.moveTo(pts[0][0], pts[0][1]);
  for (const [x, y] of pts) g.lineTo(x, y);
  g.stroke();
  g.lineTo(pts[pts.length-1][0], c.height); g.lineTo(pts[0][0], c.height);
  g.closePath(); g.fill();
  const h = hist[hist.length - 1];
  g.fillStyle = "#334"; g.font = "11px monospace";
  g.fillText(`${h.used.toFixed(1)}/${h.total} CPU busy`, 8, 14);
}
function sparkline(nodeId) {  // tiny inline chart per node row
  const w = 90, hgt = 18;
  const cv = document.createElement("canvas");
  cv.width = w; cv.height = hgt;
  const g = cv.getContext("2d");
  g.strokeStyle = "#3b6fd4"; g.beginPath();
  const samples = hist.slice(-45);
  samples.forEach((h, i) => {
    const f = h.perNode[nodeId] ?? 0;
    const x = i / Math.max(1, samples.length - 1) * w;
    const y = hgt - 2 - f * (hgt - 4);
    i ? g.lineTo(x, y) : g.moveTo(x, y);
  });
  g.stroke();
  return `<img src="${cv.toDataURL()}" width="${w}" height="${hgt}">`;
}

// ---- task timeline: lanes per worker, spans from state_ts ----
const STATE_COLOR = {FINISHED: "#0a7d33", FAILED: "#c0262d",
                     RUNNING: "#3b6fd4"};
function drawTimeline(records, serverNow) {
  const c = document.getElementById("timeline"), g = c.getContext("2d");
  g.clearRect(0, 0, c.width, c.height);
  // anchor to the SERVER clock: event ts are cluster-host time, and a
  // skewed viewer clock would shift or blank the chart
  const t1 = serverNow || Date.now() / 1000, t0 = t1 - 60;
  const lanes = new Map();  // worker_id -> lane index
  const spans = [];
  for (const r of records || []) {
    const st = r.state_ts || {};
    const start = st.RUNNING ?? st.PENDING_ARGS_AVAIL ?? null;
    if (start === null) continue;
    const end = st.FINISHED ?? st.FAILED ?? t1;  // still running: now
    if (end < t0) continue;
    const key = r.worker_id || r.actor_id || "driver";
    if (!lanes.has(key)) lanes.set(key, lanes.size);
    spans.push({lane: lanes.get(key), s: Math.max(start, t0),
                e: Math.min(end, t1), state: r.state, name: r.name || ""});
  }
  const nl = Math.max(1, Math.min(lanes.size, 12));
  const lh = Math.floor((c.height - 18) / nl);
  g.font = "10px monospace"; g.fillStyle = "#99a";
  for (let m = 0; m <= 6; m++) {  // 10s gridlines
    const x = m / 6 * c.width;
    g.fillRect(x, 0, 1, c.height - 14);
    g.fillText(`-${60 - m * 10}s`, Math.min(x + 2, c.width - 30),
               c.height - 3);
  }
  for (const sp of spans) {
    if (sp.lane >= nl) continue;
    const x0 = (sp.s - t0) / 60 * c.width;
    const x1 = Math.max(x0 + 2, (sp.e - t0) / 60 * c.width);
    g.fillStyle = STATE_COLOR[sp.state] || "#b26a00";
    g.globalAlpha = 0.85;
    g.fillRect(x0, sp.lane * lh + 3, x1 - x0, lh - 6);
    g.globalAlpha = 1;
    if (x1 - x0 > 60) {
      g.fillStyle = "#fff";
      g.fillText(sp.name.slice(0, Math.floor((x1-x0)/7)),
                 x0 + 3, sp.lane * lh + lh / 2 + 3);
    }
  }
  let li = 0;
  g.fillStyle = "#667";
  for (const [k] of lanes) {
    if (li >= nl) break;
    g.fillText(k.slice(0, 10), 2, li * lh + 12);
    li++;
  }
}
// ---- drill-down panel (node detail / actor detail / task record / logs)
function closePanel() { document.getElementById("panel").classList.remove("open"); }
function panel(title, html) {
  document.getElementById("panel-body").innerHTML =
    `<h3>${esc(title)}</h3>` + html;
  document.getElementById("panel").classList.add("open");
}
function miniTable(rows, cols) {
  if (!rows || !rows.length) return "<div>none</div>";
  let h = "<table><tr>" + cols.map(c => `<th>${esc(c)}</th>`).join("") + "</tr>";
  for (const r of rows.slice(0, 40))
    h += "<tr>" + cols.map(c => `<td>${esc(fmt(r[c]).slice(0, 60))}</td>`).join("") + "</tr>";
  return h + "</table>";
}
async function openNode(n) {
  const d = await j("/api/node?node_id=" + encodeURIComponent(n.node_id));
  panel("node " + d.node_id,
    `<pre>${esc(JSON.stringify({addr: d.addr, state: d.state, total: d.total,
      available: d.available, labels: d.labels}, null, 1))}</pre>` +
    "<h3>workers</h3>" + miniTable(d.workers || [],
      ["worker_id", "pid", "state", "actor_id", "blocked"]) +
    "<h3>leases</h3>" + miniTable(d.leases || [],
      ["worker_id", "state", "lease_resources", "bundle_key"]) +
    "<h3>logs</h3>" + (d.logs || []).map(lg =>
      `<span class="loglink" data-log="${esc(lg.name)}">${esc(lg.name)} ` +
      `(${lg.size_bytes ?? "?"} B)</span>`).join("") +
    `<pre id="logview" style="display:none"></pre>`);
  document.getElementById("panel-body").querySelectorAll(".loglink")
    .forEach(a => a.addEventListener("click", async () => {
      const v = document.getElementById("logview");
      v.style.display = "block";
      try {
        const r = await j("/api/log_tail?node_id=" +
          encodeURIComponent(n.node_id) + "&name=" +
          encodeURIComponent(a.dataset.log));
        // textContent: no HTML sink
        v.textContent = r.error ? "ERROR: " + r.error
                                : (r.text || "(empty)");
      } catch (e) { v.textContent = "ERROR: " + e; }
    }));
}
async function openActor(a) {
  const d = await j("/api/actor?actor_id=" + encodeURIComponent(a.actor_id));
  const evs = d.task_events || [];
  delete d.task_events;
  panel("actor " + a.actor_id,
    `<pre>${esc(JSON.stringify(d, null, 1))}</pre>` +
    "<h3>recent tasks</h3>" +
    miniTable(evs, ["task_id", "name", "state", "error"]));
}
function openTask(t) {
  panel("task " + t.task_id, `<pre>${esc(JSON.stringify(t, null, 1))}</pre>`);
}
drill.nodes = drillSafe(openNode); drill.actors = drillSafe(openActor);
drill.tasks = drillSafe(openTask);
document.addEventListener("keydown", e => { if (e.key === "Escape") closePanel(); });

async function tick() {
  try {
    const [cs, nodes, actors, jobs, pgs, tasks, events, ver, serve,
           train] =
      await Promise.all([
      j("/api/cluster_status"), j("/api/nodes"), j("/api/actors"),
      j("/api/jobs"), j("/api/placement_groups"),
      j("/api/tasks?limit=50"), j("/api/events?limit=30"),
      j("/api/version"), j("/api/serve"), j("/api/train")]);
    document.getElementById("addr").textContent = ver.control_address;
    const total = cs.total_resources || {}, avail = cs.available_resources || {};
    const card = (k, v) => `<div class="card"><div class="v">${v}</div><div class="k">${k}</div></div>`;
    document.getElementById("cards").innerHTML =
      card("alive nodes", cs.alive_nodes) +
      card("CPU free/total", `${avail.CPU ?? 0}/${total.CPU ?? 0}`) +
      card("TPU free/total", `${avail.TPU ?? 0}/${total.TPU ?? 0}`) +
      card("actors", actors.length) + card("jobs", jobs.length) +
      card("placement groups", pgs.length);
    pushSample(cs, nodes);
    drawUtil();
    drawTimeline(tasks.records || [], tasks.now);
    for (const n of nodes || []) n.util = {[RAW]: sparkline(n.node_id)};
    table("nodes", nodes, ["node_id", "addr", "state", "total", "available", "util", "labels"]);
    table("actors", actors, ["actor_id", "class_name", "name", "state", "node_id", "restarts"]);
    table("jobs", jobs, ["submission_id", "entrypoint", "status", "message"]);
    const srows = [];
    for (const a of (serve.apps || []))
      for (const d of (a.deployments || []))
        srows.push({app: a.app, route: a.route_prefix, ...d,
                    app_status: a.status});
    table("serve", srows, ["app", "route", "deployment", "status",
                           "replicas", "ongoing", "message"]);
    const trows = (train || []).map(r => ({...r,
      metrics: r.last_metrics ? JSON.stringify(r.last_metrics).slice(0, 70) : ""}));
    table("train", trows, ["name", "trial", "status", "workers",
                           "rounds", "metrics"]);
    table("pgs", pgs, ["pg_id", "name", "state", "bundles", "strategy"]);
    table("tasks", tasks.records || [], ["task_id", "name", "state", "actor_id", "error"]);
    const evs = (events || []).slice().reverse().map(e => ({
      ...e, when: new Date(e.ts * 1000).toLocaleTimeString()}));
    table("events", evs, ["when", "severity", "source", "event_type", "entity_id", "message"]);
    document.getElementById("ts").textContent = new Date().toLocaleTimeString();
    document.getElementById("err").textContent = "";
  } catch (e) { document.getElementById("err").textContent = " " + e; }
}
tick(); setInterval(tick, 2000);
</script>
</body>
</html>
"""
