"""Dashboard head: HTTP API over cluster state + Prometheus metrics.

Analog of the reference's dashboard backend (reference:
python/ray/dashboard/dashboard.py + head.py + modules/): a separate
process on the head node serving JSON state endpoints and the Prometheus
scrape target.  Stdlib http.server (threaded) instead of aiohttp — the
data volumes are controlplane-sized, and it keeps the daemon
dependency-free.

Endpoints (mirroring the reference's dashboard REST surface):
  GET /                         live HTML dashboard (static.py — the
                                dependency-free stand-in for the
                                reference's React client)
  GET /api/version              build/version info
  GET /api/cluster_status       nodes + resource totals (reference: /api/cluster_status)
  GET /api/nodes                node table
  GET /api/actors               actor table
  GET /api/tasks                task events
  GET /api/jobs                 submitted jobs (reference: /api/jobs/)
  POST /api/jobs                submit a job (reference:
                                modules/job/job_head.py submit_job)
  GET /api/jobs/<id>            one job's info
  GET /api/jobs/<id>/logs       job driver logs
  POST /api/jobs/<id>/stop      stop a job
  GET /api/placement_groups     placement groups
  GET /api/objects              object-store summary
  GET /metrics                  Prometheus exposition (reference: agent scrape)
  GET /healthz                  liveness (reference: modules/healthz)
"""

from __future__ import annotations

import argparse
import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse

logger = logging.getLogger(__name__)


class DashboardHead:
    def __init__(self, control_address: str, host: str = "127.0.0.1",
                 port: int = 8265):
        from ray_tpu._private.protocol import Client

        chost, cport = control_address.rsplit(":", 1)
        self.control = Client((chost, int(cport)), name="dashboard")
        self.control_address = control_address
        self.host = host
        self.port = port
        self._server: Optional[ThreadingHTTPServer] = None
        self._jobs_lock = threading.Lock()

    # -- data providers ----------------------------------------------------

    def _state_dump(self) -> Dict[str, Any]:
        return self.control.call("state_dump", {}, timeout=10.0)

    def _node_calls(self, node: Dict[str, Any], *calls):
        """Several RPCs to a node's raylet over ONE connection
        (drill-down endpoints); each call is (method, payload).
        Returns a list of results; a failed call yields None and the
        error string is returned as the trailing element."""
        from ray_tpu._private.protocol import Client

        out = []
        err = None
        try:
            c = Client(tuple(node["addr"]), name="dashboard-node")
            try:
                for method, payload in calls:
                    try:
                        out.append(c.call(method, payload or {},
                                          timeout=10.0))
                    except Exception as e:
                        out.append(None)
                        err = err or str(e)
            finally:
                c.close()
        except Exception as e:
            err = str(e)
            out += [None] * (len(calls) - len(out))
        return out + [err]

    def _job_client(self):
        """Lazy full driver connection for job submission (reference: the
        job head submits through an internal JobSubmissionClient).
        Locked: handler threads race on first use."""
        with self._jobs_lock:
            cli = getattr(self, "_jobs", None)
            if cli is None:
                from ray_tpu.job.job_manager import JobSubmissionClient

                cli = self._jobs = JobSubmissionClient(self.control_address)
            return cli

    def route_post(self, path: str, body: Dict[str, Any]
                   ) -> Tuple[int, str, str]:
        """POST routes: job submission + stop (reference:
        modules/job/job_head.py)."""
        try:
            if path in ("/api/jobs", "/api/jobs/"):
                entrypoint = (body or {}).get("entrypoint")
                if not entrypoint:
                    return 400, "text/plain", "entrypoint required"
                sid = self._job_client().submit_job(
                    entrypoint=entrypoint,
                    runtime_env=body.get("runtime_env"),
                    submission_id=body.get("submission_id"),
                    metadata=body.get("metadata"))
                return self._json({"submission_id": sid})
            if path.startswith("/api/jobs/") and path.endswith("/stop"):
                sid = path[len("/api/jobs/"):-len("/stop")]
                return self._json(
                    {"stopped": self._job_client().stop_job(sid)})
            return 404, "text/plain", f"no POST route {path}"
        except Exception as e:
            logger.exception("dashboard POST %s failed", path)
            return 500, "text/plain", f"error: {e}"

    def route(self, path: str, query: Dict[str, Any]) -> Tuple[int, str, str]:
        """Returns (status, content_type, body)."""
        try:
            if path in ("/", "/index.html"):
                from .static import PAGE

                return 200, "text/html", PAGE
            if path == "/healthz":
                return 200, "text/plain", "success"
            if path == "/api/version":
                import ray_tpu

                return self._json({"ray_tpu_version": ray_tpu.__version__,
                                   "control_address": self.control_address})
            if path == "/api/cluster_status":
                dump = self._state_dump()
                res = self.control.call("cluster_resources", {},
                                        timeout=10.0)
                return self._json({
                    "nodes": dump["nodes"],
                    "total_resources": res["total"],
                    "available_resources": res["available"],
                    "alive_nodes": sum(1 for n in dump["nodes"]
                                       if n["state"] == "ALIVE"),
                })
            if path == "/api/nodes":
                return self._json(self._state_dump()["nodes"])
            if path == "/api/actors":
                return self._json(self._state_dump()["actors"])
            if path == "/api/placement_groups":
                return self._json(self._state_dump()["pgs"])
            if path == "/api/jobs":
                from ray_tpu.job.job_manager import JOB_NS

                keys = self.control.call(
                    "kv_keys", {"ns": JOB_NS, "prefix": ""}, timeout=10.0)
                jobs = []
                for k in keys:
                    raw = self.control.call(
                        "kv_get", {"ns": JOB_NS, "key": k}, timeout=10.0)
                    if raw:
                        jobs.append(json.loads(raw))
                return self._json(jobs)
            if path.startswith("/api/jobs/"):
                rest = path[len("/api/jobs/"):]
                if rest.endswith("/logs"):
                    sid = rest[:-len("/logs")]
                    return self._json(
                        {"logs": self._job_client().get_job_logs(sid)})
                info = self._job_client().get_job_info(rest)
                if info is None:
                    return 404, "text/plain", f"no job {rest}"
                return self._json(info)
            if path == "/api/train":
                # run states the trainer publishes (train/trainer.py
                # _publish_state); newest first.  Fetches are BOUNDED —
                # run keys accumulate over a cluster's life, and the page
                # polls this every tick inside one Promise.all, so an
                # unbounded N+1 here would stall every other panel.
                keys = self.control.call(
                    "kv_keys", {"ns": "train", "prefix": ""}, timeout=10.0)
                runs = []
                for k in list(keys)[-200:]:
                    raw = self.control.call(
                        "kv_get", {"ns": "train", "key": k}, timeout=10.0)
                    if raw:
                        runs.append(json.loads(raw))
                runs.sort(key=lambda r: -(r.get("ts") or 0))
                return self._json(runs[:100])
            if path == "/api/train/timeline":
                # flight-recorder rings -> Chrome trace-event JSON (loads
                # straight into Perfetto); ?trial= filters to one run and
                # overlays that run's remediation markers
                from ray_tpu.telemetry.timeline import (chrome_trace,
                                                        collect_remediations,
                                                        collect_snapshots)

                trial = (query.get("trial") or [None])[0]
                snaps = collect_snapshots(self.control, trial=trial)
                rems = collect_remediations(self.control, trial=trial) \
                    if trial else []
                from ray_tpu.telemetry.timeline import \
                    collect_device_workers

                # compile slices are cluster-wide, but a ?trial= that
                # matches no run must stay a truly empty trace
                dev = collect_device_workers(self.control) \
                    if (not trial or snaps) else {}
                return self._json(chrome_trace(snaps, remediations=rems,
                                               device_workers=dev))
            if path == "/api/train/remediations":
                # a run's cause→action→effect self-healing log (see
                # elastic/remediation.py); ?trial= selects the run
                from ray_tpu.elastic.remediation import fetch_records

                trial = (query.get("trial") or [""])[0]
                return self._json(fetch_records(self.control, trial))
            if path == "/api/serve":
                # snapshot the serve controller publishes each reconcile
                # pass (serve/_controller.py _publish_status)
                raw = self.control.call(
                    "kv_get", {"ns": "serve", "key": "status"},
                    timeout=10.0)
                return self._json(json.loads(raw) if raw
                                  else {"ts": None, "apps": []})
            if path == "/api/events":
                # structured cluster events (reference: dashboard
                # modules/event); ?severity=&source=&limit=
                return self._json(self.control.call("list_events", {
                    "severity": (query.get("severity") or [None])[0],
                    "source": (query.get("source") or [None])[0],
                    "limit": int((query.get("limit") or ["200"])[0]),
                }, timeout=10.0))
            if path == "/api/tasks":
                limit = int(query.get("limit", ["1000"])[0])
                out = self.control.call("list_task_events",
                                        {"limit": limit}, timeout=10.0)
                return self._json(out)
            if path == "/api/node":
                # per-node drill-down: the node view + its raylet's live
                # worker/lease tables + log file list (reference: the
                # dashboard's node detail page)
                nid = (query.get("node_id") or [None])[0]
                node = next((n for n in self._state_dump()["nodes"]
                             if n["node_id"] == nid), None)
                if node is None:
                    return 404, "text/plain", f"no node {nid}"
                detail = dict(node)
                if node["state"] == "ALIVE":
                    workers, leases, logs, err = self._node_calls(
                        node, ("list_workers", None),
                        ("list_leases", None), ("list_logs", None))
                    # endpoint contract: these fields are LISTS; raylet
                    # failures ride a separate error key
                    detail["workers"] = workers \
                        if isinstance(workers, list) else []
                    detail["leases"] = leases \
                        if isinstance(leases, list) else []
                    detail["logs"] = logs.get("logs", []) \
                        if isinstance(logs, dict) else []
                    if err:
                        detail["error"] = err
                return self._json(detail)
            if path == "/api/actor":
                aid = (query.get("actor_id") or [None])[0]
                actor = self.control.call("get_actor",
                                          {"actor_id": aid}, timeout=10.0)
                if actor is None:
                    return 404, "text/plain", f"no actor {aid}"
                # this actor's task events round out the drill-down
                evs = self.control.call(
                    "list_task_events",
                    {"limit": 100, "filters": {"actor_id": aid}},
                    timeout=10.0)
                return self._json({**actor, "task_events":
                                   (evs or {}).get("records", [])})
            if path == "/api/log_tail":
                nid = (query.get("node_id") or [None])[0]
                name = (query.get("name") or [None])[0]
                tail = int((query.get("tail_bytes") or ["65536"])[0])
                node = next((n for n in self._state_dump()["nodes"]
                             if n["node_id"] == nid), None)
                if node is None or not name:
                    return 404, "text/plain", "need node_id and name"
                text, err = self._node_calls(
                    node, ("read_log", {"name": name,
                                        "tail_bytes": tail}))
                if text is None and not err:
                    # the raylet rejected the name (traversal guard)
                    err = f"log {name!r} not readable"
                out = {"node_id": nid, "name": name,
                       "text": text if isinstance(text, str) else ""}
                if err:
                    out["error"] = err
                return self._json(out)
            if path == "/api/objects":
                from ray_tpu.util.state.api import StateApiClient

                c = StateApiClient(self.control_address)
                try:
                    return self._json(c.per_node("store_stats"))
                finally:
                    c.close()
            if path == "/api/workers":
                from ray_tpu.util.state.api import StateApiClient

                c = StateApiClient(self.control_address)
                try:
                    return self._json(c.per_node("list_workers"))
                finally:
                    c.close()
            if path in ("/api/profile/stacks", "/api/profile/cpu",
                        "/api/profile/memory"):
                # worker=host:port of the target's core server (from
                # /api/workers).  Reference: reporter agent's py-spy /
                # memray endpoints (profile_manager.py:82,:189).
                from ray_tpu._private.protocol import Client
                from ray_tpu.util.state.api import StateApiClient

                waddr = query.get("worker", [""])[0]
                try:
                    whost, wport = waddr.rsplit(":", 1)
                    target = (whost, int(wport))
                    dur = float(query.get("duration", ["2"])[0])
                except ValueError:
                    return 400, "text/plain", "need worker=host:port"
                # only relay to addresses that ARE cluster workers — the
                # dashboard must not be an arbitrary connect-and-call proxy
                sc = StateApiClient(self.control_address)
                try:
                    known = {tuple(w["addr"])
                             for ws in sc.per_node("list_workers").values()
                             for w in ws if w.get("addr")}
                finally:
                    sc.close()
                if target not in known:
                    return 404, "text/plain", \
                        f"{waddr} is not a cluster worker"
                cli = Client(target, name="dash-profile")
                try:
                    if path.endswith("stacks"):
                        out = cli.call("dump_stacks", timeout=15.0)
                    elif path.endswith("memory"):
                        out = cli.call("memory_summary", timeout=15.0)
                    else:
                        out = cli.call("profile_cpu", {"duration": dur},
                                       timeout=dur + 15.0)
                finally:
                    cli.close()
                return 200, "text/plain", out
            if path == "/api/usage_stats":
                from ray_tpu._private.usage_stats import usage_report

                return self._json(usage_report(self.control))
            if path == "/api/control/stats":
                return self._json(
                    self.control.call("control_stats", {}, timeout=10.0))
            if path == "/api/device/stats":
                # cluster-wide XLA compilation ledger + device-memory
                # census (telemetry/device.py): per-program compile /
                # recompile counts, last recompile cause diffs, storm
                # advisories, live HBM bytes and KV page occupancy
                from ray_tpu.telemetry.device import collect_device_stats

                return self._json(collect_device_stats(self.control))
            if path.startswith("/api/traces"):
                # distributed traces from the span collector: /api/traces
                # lists ids, /api/traces/<id> returns the reassembled
                # trace (span tree + critical-path attribution); add
                # ?format=chrome for Perfetto trace-event JSON
                from ray_tpu.telemetry import trace_assembly as ta

                rest = path[len("/api/traces"):].strip("/")
                if not rest:
                    return self._json({"traces": ta.list_trace_ids(
                        self.control)})
                spans = ta.fetch_trace(self.control, rest)
                if not spans:
                    return 404, "text/plain", f"no trace {rest}"
                if (query.get("format") or [""])[0] == "chrome":
                    return self._json(ta.chrome_trace(spans))
                return self._json(ta.analyze(spans))
            if path == "/metrics":
                from ray_tpu.util.metrics import (collect_cluster_metrics,
                                                  control_stats_metrics,
                                                  prometheus_text)

                merged = collect_cluster_metrics(self.control)
                # the control daemon has no flusher of its own: synthesize
                # its ray_tpu_control_* series from the control_stats RPC
                try:
                    merged.extend(control_stats_metrics(
                        self.control.call("control_stats", {},
                                          timeout=10.0)))
                except Exception:
                    pass
                return 200, "text/plain; version=0.0.4", \
                    prometheus_text(merged)
            return 404, "text/plain", f"no route {path}"
        except Exception as e:
            logger.exception("dashboard route %s failed", path)
            return 500, "text/plain", f"error: {e}"

    def _json(self, obj) -> Tuple[int, str, str]:
        return 200, "application/json", json.dumps(obj, default=str)

    # -- server ------------------------------------------------------------

    def start(self, block: bool = False):
        head = self

        class Handler(BaseHTTPRequestHandler):
            def _reply(self, status, ctype, body):
                data = body.encode()
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                parsed = urlparse(self.path)
                self._reply(*head.route(parsed.path,
                                        parse_qs(parsed.query)))

            def do_POST(self):
                parsed = urlparse(self.path)
                n = int(self.headers.get("Content-Length") or 0)
                raw = self.rfile.read(n) if n else b""
                try:
                    body = json.loads(raw) if raw else {}
                except ValueError:
                    self._reply(400, "text/plain", "invalid JSON body")
                    return
                self._reply(*head.route_post(parsed.path, body))

            def log_message(self, fmt, *args):
                logger.debug("http: " + fmt, *args)

        self._server = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._server.server_address[1]
        if block:
            self._server.serve_forever()
        else:
            t = threading.Thread(target=self._server.serve_forever,
                                 name="dashboard-http", daemon=True)
            t.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self):
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        self.control.close()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--control", required=True, help="host:port")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8265)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s dashboard %(levelname)s "
                               "%(message)s")
    head = DashboardHead(args.control, args.host, args.port)
    logger.info("dashboard serving at %s", head.url)
    head.start(block=True)


if __name__ == "__main__":
    main()
