"""Dashboard backend (reference: python/ray/dashboard/)."""

from .head import DashboardHead

__all__ = ["DashboardHead"]
